#!/usr/bin/env python
"""Reference-equivalent PyTorch throughput baseline at bench.py's shapes.

The reference repo publishes no numbers and ships no dataset (SURVEY.md
§6), so the comparison anchor must be established here: an independent
PyTorch implementation of the same architecture (M parallel contextual-
gated-LSTM branches over K-support graph convolutions, summed fusion,
linear head) trained with Adam+L2 at identical shapes. Runs on whatever
torch device is available (CPU in this image; pass a CUDA device on a GPU
host to anchor the >=10x target of BASELINE.json).

Writes ``benchmarks/baseline.json``; ``bench.py`` reads it for
``vs_baseline``.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import torch
from torch import nn

ROWS = 16
SERIAL, DAILY, WEEKLY = 10, 1, 1
BATCH = 64
WARMUP = 2
ITERS = 10


class KSupportConv(nn.Module):
    """y = relu(cat_k(A_k x) W + b), one weight across the K propagations."""

    def __init__(self, k: int, d_in: int, d_out: int):
        super().__init__()
        self.proj = nn.Linear(k * d_in, d_out)

    def forward(self, supports, x):  # (K,N,N), (B,N,F)
        mixed = torch.einsum("knm,bmf->bnkf", supports, x).flatten(2)
        return torch.relu(self.proj(mixed))


class GatedBranch(nn.Module):
    """One graph view: temporal gate (paper eqs. 6-9) -> shared LSTM -> conv."""

    def __init__(self, k: int, seq_len: int, d_in: int, d_rnn: int, layers: int, d_gcn: int):
        super().__init__()
        self.time_conv = KSupportConv(k, seq_len, seq_len)
        self.gate_fc = nn.Linear(seq_len, seq_len)
        self.rnn = nn.LSTM(d_in, d_rnn, num_layers=layers, batch_first=True)
        self.out_conv = KSupportConv(k, d_rnn, d_gcn)

    def forward(self, supports, seq):  # (B,T,N,C)
        b, t, n, c = seq.shape
        hist = seq.sum(-1).transpose(1, 2)  # (B,N,T)
        ctx = hist + self.time_conv(supports, hist)
        gate = torch.sigmoid(self.gate_fc(torch.relu(self.gate_fc(ctx.mean(1)))))
        gated = seq * gate[:, :, None, None]
        flat = gated.transpose(1, 2).reshape(b * n, t, c)
        states, _ = self.rnn(flat)
        region_state = states[:, -1].reshape(b, n, -1)
        return self.out_conv(supports, region_state)


class MultiGraphForecaster(nn.Module):
    def __init__(self, m: int, k: int, seq_len: int, d_in: int,
                 d_rnn: int = 64, layers: int = 3, d_gcn: int = 64):
        super().__init__()
        self.branches = nn.ModuleList(
            GatedBranch(k, seq_len, d_in, d_rnn, layers, d_gcn) for _ in range(m)
        )
        self.head = nn.Linear(d_gcn, d_in)

    def forward(self, supports_stack, seq):  # (M,K,N,N), (B,T,N,C)
        total = sum(br(supports_stack[i], seq) for i, br in enumerate(self.branches))
        return self.head(total)


def main() -> None:
    # the anchor is a measurement like any other: serialize on the host
    # bench lock and carry load provenance so anchor and candidate are
    # comparable same-host, same-regime (stmgcn_tpu/utils/hostload.py)
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from stmgcn_tpu.utils.hostload import BenchLock, host_load_snapshot

    lock_path = os.environ.get("STMGCN_BENCH_LOCK_PATH")
    lock = BenchLock(lock_path) if lock_path else BenchLock()
    lock.acquire(wait_s=float(os.environ.get("STMGCN_BENCH_LOCK_WAIT", 300)))
    load_before = host_load_snapshot()

    device = "cuda" if torch.cuda.is_available() else "cpu"
    torch.manual_seed(0)
    seq_len = SERIAL + DAILY + WEEKLY
    n = ROWS * ROWS
    rng = np.random.default_rng(0)
    supports = torch.tensor(
        (rng.standard_normal((3, 3, n, n)) * 0.1).astype(np.float32), device=device
    )
    x = torch.tensor(rng.standard_normal((BATCH, seq_len, n, 1)).astype(np.float32),
                     device=device)
    y = torch.tensor(rng.standard_normal((BATCH, n, 1)).astype(np.float32) * 0.1,
                     device=device)

    model = MultiGraphForecaster(m=3, k=3, seq_len=seq_len, d_in=1).to(device)
    opt = torch.optim.Adam(model.parameters(), lr=2e-3, weight_decay=1e-4)
    crit = nn.MSELoss()

    def step():
        opt.zero_grad()
        loss = crit(model(supports, x), y)
        loss.backward()
        opt.step()
        return loss

    for _ in range(WARMUP):
        step()
    if device == "cuda":
        torch.cuda.synchronize()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step()
    if device == "cuda":
        torch.cuda.synchronize()
    dt = (time.perf_counter() - t0) / ITERS

    value = BATCH * seq_len * n / dt
    out = {
        "torch_cpu_region_ts_per_sec": value,
        "device": device,
        "torch_version": torch.__version__,
        "threads": torch.get_num_threads(),
        "shapes": {"rows": ROWS, "seq_len": seq_len, "batch": BATCH,
                   "m_graphs": 3, "n_supports": 3},
        "step_seconds": dt,
        "final_loss": float(loss.detach()),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_load": {
            "before": load_before,
            "after": host_load_snapshot(),
            "lock": lock.record(),
        },
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    lock.release()


if __name__ == "__main__":
    main()
