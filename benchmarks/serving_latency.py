#!/usr/bin/env python
"""Serving-path latency at the canonical shapes (one chip or CPU).

Training throughput is bench.py's story; this measures the OTHER path a
user of the reference cannot even take (the reference has no inference
entry point at all — SURVEY.md C12 covers test-time scoring only):

- ``forecaster``: :class:`stmgcn_tpu.inference.Forecaster` — checkpoint
  -> rebuilt model -> jitted predict (normalize, forward, denormalize).
- ``exported``: :class:`stmgcn_tpu.export.ExportedForecaster` — the AOT
  serving artifact, loaded WITHOUT the model stack in a fresh process.

Both measured at batch 1 (interactive latency) and the training batch
(throughput serving), at the default preset's shapes (16x16 grid,
T=5), after a warmup call (compile excluded — serving processes are
long-lived). Trains a
2-epoch throwaway checkpoint first; accuracy is irrelevant here, only
the compiled prediction path's wall-clock. Writes
``benchmarks/serving_latency.json`` with lock + host-load provenance
(cpu-fallback records never overwrite an on-chip record).

Usage: python benchmarks/serving_latency.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "benchmarks", "serving_latency.json")


def _timed(fn, warmup=2, iters=20) -> float:
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main() -> None:
    from stmgcn_tpu.utils.hostload import (
        host_load_snapshot,
        measurement_preamble,
        probe_backend_child,
    )

    lock, load_before = measurement_preamble()
    on_tpu = probe_backend_child() == "tpu"
    if not on_tpu:
        from stmgcn_tpu.utils import force_host_platform

        force_host_platform("cpu")

    import numpy as np

    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_trainer

    cfg = preset("default")
    cfg.data.rows = 16
    cfg.data.n_timesteps = 24 * 7 * 2 + 64
    cfg.train.epochs = 2
    cfg.train.batch_size = 16
    tmp = tempfile.mkdtemp(prefix="stmgcn_serving_")
    cfg.train.out_dir = tmp
    trainer = build_trainer(cfg, verbose=False)
    trainer.train()

    from stmgcn_tpu.export import ExportedForecaster, export_forecaster
    from stmgcn_tpu.inference import Forecaster

    fc = Forecaster.from_checkpoint(os.path.join(tmp, "best.ckpt"))
    export_path = os.path.join(tmp, "model.stmgx")
    export_forecaster(fc, export_path)
    ex = ExportedForecaster.load(export_path)
    ds = trainer.dataset
    supports = np.asarray(cfg.model.support_config.build_all(ds.adjs.values()))
    seq_len, n, c = cfg.data.seq_len, ds.n_nodes, ds.n_feats
    rng = np.random.default_rng(0)

    legs = {}
    for batch in (1, cfg.train.batch_size):
        history = (rng.random((batch, seq_len, n, c)) * 50).astype(np.float32)
        for name, predictor in (("forecaster", fc), ("exported", ex)):
            s = _timed(lambda p=predictor, h=history: p.predict(supports, h))
            legs[f"{name}/b{batch}"] = {
                "ms": round(s * 1e3, 3),
                "predictions_per_sec": round(batch / s, 1),
            }

    record = {
        "operating_point": f"serving-16x16-T{seq_len}",
        "platform": "tpu" if on_tpu else "cpu-fallback",
        "legs": legs,
        "host_load": {
            "before": load_before,
            "after": host_load_snapshot(),
            "lock": lock.record(),
        },
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    from stmgcn_tpu.utils.hostload import persist_measurement

    persist_measurement(OUT, record, on_tpu, "serving_latency")
    print(json.dumps(record))
    lock.release()


if __name__ == "__main__":
    main()
