#!/usr/bin/env python
"""Serving-path throughput/latency at the canonical shapes (one chip or CPU).

Training throughput is bench.py's story; this is the inference side —
since the serving-engine PR it measures three generations of the path
via :func:`stmgcn_tpu.serving.bench.run_serve_bench`:

- ``forecaster``/``exported``: the naive per-call predictors (the r05
  legs whose batch-16 throughput sat *below* batch-1);
- ``engine``: the shape-bucketed AOT programs, direct dispatch;
- ``engine/microbatchN``: concurrent batch-1 clients coalesced by the
  dynamic micro-batcher.

Every leg reports mean/p50/p95/p99 with warmup excluded; the record adds
the engine's per-bucket telemetry (queue-wait vs device-time split, pad
waste) and the two acceptance ratios (``speedup.b16_vs_b1``,
``speedup.microbatch_vs_sequential_b1``). Trains a 2-epoch throwaway
checkpoint first (accuracy irrelevant — only the compiled path's
wall-clock). Writes ``benchmarks/serving_latency.json`` with lock +
host-load provenance (cpu-fallback records never overwrite an on-chip
record). Prints EXACTLY one JSON line on stdout.

Operating point: 4x4 grid (N=16), slim hidden dims, ladder topped at
the client count — the dispatch-dominated regime serving engines exist
for (see ``stmgcn_tpu.serving.bench.train_throwaway``). At r05's 16x16
the full model is memory-bound on this 1-core host and *no* software
path can make batch-16 beat batch-1 per-row; shapes ride in the record
either way. Env knobs (for the slow-tier contract test):
STMGCN_SERVE_ROWS, STMGCN_SERVE_BATCH, STMGCN_SERVE_CLIENTS,
STMGCN_SERVE_PER_CLIENT, STMGCN_SERVE_ITERS, STMGCN_SERVE_OUT.

Usage: python benchmarks/serving_latency.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.environ.get(
    "STMGCN_SERVE_OUT", os.path.join(REPO, "benchmarks", "serving_latency.json")
)


def main() -> None:
    from stmgcn_tpu.utils.hostload import (
        host_load_snapshot,
        measurement_preamble,
        persist_measurement,
        probe_backend_child,
    )

    lock, load_before = measurement_preamble()
    on_tpu = probe_backend_child() == "tpu"
    if not on_tpu:
        from stmgcn_tpu.utils import force_host_platform

        force_host_platform("cpu")

    # the record line must stay alone on stdout — training/compile chatter
    # from the throwaway run lands on stderr
    record_stream = sys.stdout
    sys.stdout = sys.stderr
    try:
        from stmgcn_tpu.serving.bench import run_serve_bench, train_throwaway

        rows = int(os.environ.get("STMGCN_SERVE_ROWS", "4"))
        batch = int(os.environ.get("STMGCN_SERVE_BATCH", "16"))
        # one temp dir holds the throwaway checkpoint AND the export
        # artifact through the measurement, then vanishes — both used to
        # leak (mkdtemp'd dirs nothing ever removed)
        with tempfile.TemporaryDirectory(prefix="stmgcn_serve_") as tmp:
            fc, supports = train_throwaway(
                rows=rows, out_dir=os.path.join(tmp, "ckpt")
            )
            body = run_serve_bench(
                fc,
                supports,
                batch=batch,
                # top rung = the large-batch point = peak client concurrency,
                # so saturated micro-batch dispatches run back-to-back
                buckets=(1, 4, batch),
                clients=int(os.environ.get("STMGCN_SERVE_CLIENTS", "16")),
                per_client=int(os.environ.get("STMGCN_SERVE_PER_CLIENT", "40")),
                iters=int(os.environ.get("STMGCN_SERVE_ITERS", "30")),
                artifact_path=os.path.join(tmp, "model.stmgx"),
            )
        record = {
            "operating_point": f"serving-{rows}x{rows}-T{fc.seq_len}",
            "platform": "tpu" if on_tpu else "cpu-fallback",
            **body,
            "host_load": {
                "before": load_before,
                "after": host_load_snapshot(),
                "lock": lock.record(),
            },
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        persist_measurement(OUT, record, on_tpu, "serving_latency")
    finally:
        sys.stdout = record_stream
    print(json.dumps(record))
    lock.release()


if __name__ == "__main__":
    main()
