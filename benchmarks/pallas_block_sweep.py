#!/usr/bin/env python
"""On-chip row-block sweep for the fused Pallas LSTM (TPU only).

Measures the FULL flagship training step with ``lstm_backend="pallas"``
across forward/backward row-block sizes (``STMGCN_PALLAS_FWD_ROWS`` /
``STMGCN_PALLAS_BWD_ROWS`` env knobs read by ``ops/pallas_lstm.py``),
plus the tuned XLA scan as the line to beat. One JSON line per point.

The sweep restarts a fresh subprocess per point: the block sizes are
read at trace time, so they must be set before the kernel is traced,
and a wedged tunnel must not take the whole sweep down with it.

Usage: python benchmarks/pallas_block_sweep.py [dtype]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

POINTS = [
    # (fwd_rows, bwd_rows); None = the derived default
    (None, None),
    (128, 64),
    (128, 128),
    (256, 256),
    (512, 128),
    (512, 256),
]


def main() -> None:
    dtype = sys.argv[1] if len(sys.argv) > 1 else "bfloat16"
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = []

    # caller-exported block overrides would silently retune every point
    # (including the 'auto' one) — each point fully owns these knobs
    base_env = {
        k: v for k, v in os.environ.items() if not k.startswith("STMGCN_PALLAS_")
    }

    # the line to beat: the tuned XLA scan at the same shapes
    env = dict(
        base_env,
        STMGCN_BENCH_DTYPE=dtype,
        STMGCN_BENCH_LSTM_FUSED="1",
        STMGCN_BENCH_LSTM_UNROLL="0",
    )
    results.append(("xla-tuned", _run(here, env)))

    for fwd, bwd in POINTS:
        env = dict(
            base_env,
            STMGCN_BENCH_DTYPE=dtype,
            STMGCN_BENCH_LSTM_BACKEND="pallas",
        )
        if fwd is not None:
            env["STMGCN_PALLAS_FWD_ROWS"] = str(fwd)
            env["STMGCN_PALLAS_BWD_ROWS"] = str(bwd)
        results.append((f"pallas-{fwd or 'auto'}/{bwd or 'auto'}", _run(here, env)))

    print("\n| leg | region-ts/s | step ms | mfu |")
    print("|---|---|---|---|")
    for name, r in results:
        if r is None:
            print(f"| {name} | failed | | |")
            continue
        print(f"| {name} | {r['value']} | {r['step_ms']} | {r.get('mfu')} |")


def _run(repo_root: str, env: dict):
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(repo_root, "bench.py")],
            env=env,
            capture_output=True,
            timeout=3000,
            check=True,
        )
        rec = json.loads(out.stdout.decode().strip().splitlines()[-1])
        print(json.dumps(rec), flush=True)
        if rec.get("platform") == "cpu-fallback" or rec.get("value", 0) <= 0:
            return None
        return rec
    except Exception as e:  # noqa: BLE001 — per-point isolation is the point
        print(f"sweep point failed: {type(e).__name__}: {e}", file=sys.stderr)
        return None


if __name__ == "__main__":
    main()
