#!/usr/bin/env python
"""On-chip row-block sweep for the fused Pallas LSTM (TPU only).

Measures the FULL flagship training step with ``lstm_backend="pallas"``
across forward/backward row-block sizes (``STMGCN_PALLAS_FWD_ROWS`` /
``STMGCN_PALLAS_BWD_ROWS`` env knobs read by ``ops/pallas_lstm.py``),
plus the tuned XLA scan as the line to beat. One JSON line per point.

The sweep runs a fresh ``bench.py`` subprocess per point: the block
sizes are read at trace time, so they must be set before the kernel is
traced, and a wedged tunnel must not take the whole sweep down with it.
Points that did not measure on a real TPU (cpu-fallback, refusal
records, hosts whose probe resolves to CPU) are reported failed —
a CPU number must never pose as the line to beat.

Usage: python benchmarks/pallas_block_sweep.py [dtype]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from variants import run_bench  # noqa: E402 — the one bench-parsing contract

POINTS = [
    # (fwd_rows, bwd_rows); None = the derived default
    (None, None),
    (128, 64),
    (128, 128),
    (256, 256),
    (512, 128),
    (512, 256),
]


def main() -> None:
    dtype = sys.argv[1] if len(sys.argv) > 1 else "bfloat16"
    # caller-exported block overrides would silently retune every point
    # (including the 'auto' one) — each point fully owns these knobs
    base_env = {
        k: v for k, v in os.environ.items() if not k.startswith("STMGCN_PALLAS_")
    }
    results = []

    # the line to beat: the tuned XLA scan at the same shapes
    results.append((
        "xla-tuned",
        _tpu_point(
            {
                "STMGCN_BENCH_DTYPE": dtype,
                "STMGCN_BENCH_LSTM_FUSED": "1",
                "STMGCN_BENCH_LSTM_UNROLL": "0",
            },
            base_env,
        ),
    ))

    for fwd, bwd in POINTS:
        extra = {"STMGCN_BENCH_DTYPE": dtype, "STMGCN_BENCH_LSTM_BACKEND": "pallas"}
        if fwd is not None:
            extra["STMGCN_PALLAS_FWD_ROWS"] = str(fwd)
            extra["STMGCN_PALLAS_BWD_ROWS"] = str(bwd)
        results.append(
            (f"pallas-{fwd or 'auto'}/{bwd or 'auto'}", _tpu_point(extra, base_env))
        )

    print("\n| leg | region-ts/s | step ms | mfu |")
    print("|---|---|---|---|")
    for name, r in results:
        if r is None:
            print(f"| {name} | failed | | |")
            continue
        print(f"| {name} | {r['value']} | {r['step_ms']} | {r.get('mfu')} |")


def _tpu_point(env_extra: dict, base_env: dict):
    rec = run_bench(env_extra, base_env=base_env, timeout=3000)
    print(json.dumps(rec), flush=True)
    if rec.get("platform") == "cpu-fallback" or rec.get("value", 0) <= 0:
        return None
    # the probe can succeed on CPU (plugin absent / pinned platform) with
    # no error field — only a real TPU device_kind counts as a data point
    if "tpu" not in str(rec.get("device", "")).lower():
        return None
    return rec


if __name__ == "__main__":
    main()
