#!/usr/bin/env python
"""Compile the fused Pallas LSTM under REAL Mosaic — no chip required.

The axon terminal compiles TPU programs through a chipless AOT helper
(``TpuAotCompiler`` behind ``remote_compile``), reachable via JAX's
topology API (``jax.experimental.topologies.get_topology_desc``) even
when device init is wedged — which is how round 4's driver bench left
the two concrete kernel failures this tool exists to chase
(``bench_stderr.log``, 2026-07-29):

- fp32 forward kernel: VMEM stack OOM — 18.04 MB scoped allocation vs
  the 16 MB limit at the pre-packing 128-row block calibration
  (addressed: ``_block_rows`` halved its bases, see ops/pallas_lstm.py;
  ``stmgcn lint``'s static Pallas pass — ``analysis/pallas_check.py`` —
  is calibrated to reproduce this exact 18.04 MB estimate from source
  alone, so the regression is caught on CPU without the tunnel);
- bf16: ``infer-vector-layout: unsupported shape cast``
  (``vector<128x64xbf16> -> vector<1x1x128x1x64xbf16>``) somewhere in
  the vmapped lowering of the packed kernel.

Each configuration {bf16, fp32} x {fwd, grad} x {plain, vmapped M=3}
compiles in a KILLABLE child process under the bench lock (the compile
rides the same tunnel that wedges, and concurrent libtpu inits fight
over /tmp/libtpu_lockfile), one JSON line per config with the tail of
the compiler error on failure. Two extra configs compile the tiled-
sparse SpMM program (``ops/tiling.py`` plan -> ``spmm_stack`` fwd/grad
at tile=128, the bench largeN path's on-chip kernel) so the probe loop
captures on-chip evidence for it the moment hardware returns. Exit 0
iff every configuration compiles.

Run it the moment the tunnel's compile path answers — it settles "does
the kernel build under real Mosaic" in minutes, before the chip itself
is even usable for timing. The recovery watcher pre-gates every cycle
with ``--probe`` (a trivial-kernel compile, cheap fail-fast) and runs
the full check the moment the compile path answers, independent of
device recovery. Any run that produced at least one REAL verdict (a
success or an actual compiler error, not a pure timeout) persists
``benchmarks/mosaic_compile_verdict.json``.

Usage: python benchmarks/mosaic_compile_check.py [timeout_s_per_config]
       python benchmarks/mosaic_compile_check.py --probe   # path check
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
VERDICT_PATH = os.path.join(REPO, "benchmarks", "mosaic_compile_verdict.json")
TIMEOUT_MSG = "compile did not finish"

PROBE_COMPILE_SRC = """
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import pallas as pl

topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2x1")
mesh = Mesh(np.array(topo.devices[:1]), ("d",))

def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def f(x):
    return pl.pallas_call(
        kern, out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32)
    )(x)

x = jax.ShapeDtypeStruct(
    (256, 256), jnp.float32, sharding=NamedSharding(mesh, P())
)
jax.jit(f).lower(x).compile()
print("PROBE_COMPILE_OK")
"""

CHILD_SRC = """
import sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, {repo!r})
from stmgcn_tpu.ops.pallas_lstm import fused_lstm

dtype = jnp.bfloat16 if {dtype!r} == "bfloat16" else jnp.float32
mode, vmapped = {mode!r}, {vmapped!r} == "vmap"

topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2x1")
mesh = Mesh(np.array(topo.devices[:1]), ("d",))
sh = NamedSharding(mesh, P())

M, R, T, L, H = 3, 16384, 12, 3, 64

def one(xp, wh, wx, b):
    hs, hf, cf = fused_lstm(xp, wh, wx, b)
    return jnp.sum(hs.astype(jnp.float32) ** 2) + jnp.sum(hf.astype(jnp.float32))

def scalar(*args):
    if vmapped:
        return jnp.sum(jax.vmap(one)(*args))
    return one(*args)

fn = jax.grad(lambda a: scalar(*a)) if mode == "grad" else scalar
lead = (M,) if vmapped else ()
args = tuple(
    jax.ShapeDtypeStruct(lead + s, dtype, sharding=sh)
    for s in ((R, T, 4 * H), (L, H, 4 * H), (L - 1, H, 4 * H), (L - 1, 4 * H))
)
jax.jit(fn).lower(args if mode == "grad" else args[0],
                  *(() if mode == "grad" else args[1:])).compile()
print("COMPILE_OK")
"""


TILED_SRC = """
import sys
import numpy as np
import jax, jax.numpy as jnp
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

sys.path.insert(0, {repo!r})
from stmgcn_tpu.data.synthetic import grid_adjacency
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.ops.spmm import spmm_stack
from stmgcn_tpu.ops.tiling import plan_tiling

mode = {mode!r}

topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2x1")
mesh = Mesh(np.array(topo.devices[:1]), ("d",))
sh = NamedSharding(mesh, P())

# tile=128 at the shipped kernel regime: per-grid-step VMEM depends only
# on the tile and the m<=256 column ceiling, never on how many blocks the
# plan keeps, so a small-N plan compiles the same program shape the
# bench's N=8192 largeN path runs on chip
dense = SupportConfig("chebyshev", 2).build_all([grid_adjacency(16)] * 3)
plan = plan_tiling(np.asarray(dense, np.float32), tile=128)
stack = plan[0].as_stack()

def fwd(x):
    return spmm_stack(stack, x)

def loss(x):
    return jnp.sum(fwd(x).astype(jnp.float32) ** 2)

fn = jax.grad(loss) if mode == "grad" else fwd
x = jax.ShapeDtypeStruct((plan.n, 256), jnp.float32, sharding=sh)
jax.jit(fn).lower(x).compile()
print("COMPILE_OK")
"""


def _run_child(src: str, config: str, timeout_s: int) -> dict:
    rec = {"config": config}
    try:
        out = subprocess.run(
            [sys.executable, "-c", src], timeout=timeout_s, capture_output=True
        )
    except subprocess.TimeoutExpired:
        rec["ok"] = False
        rec["error"] = f"{TIMEOUT_MSG} in {timeout_s}s (tunnel wedged?)"
        return rec
    rec["ok"] = out.returncode == 0 and b"COMPILE_OK" in out.stdout
    if not rec["ok"]:
        err = out.stderr.decode(errors="replace")
        # surface the Mosaic/VMEM line if present, else the tail
        key_lines = [
            ln for ln in err.splitlines()
            if "Mosaic" in ln or "vmem" in ln.lower() or "Error" in ln
        ]
        rec["error"] = ("\n".join(key_lines[-4:]) or err[-500:])[-800:]
    return rec


def check(dtype: str, mode: str, vmapped: str, timeout_s: int) -> dict:
    src = CHILD_SRC.format(repo=REPO, dtype=dtype, mode=mode, vmapped=vmapped)
    return _run_child(src, f"{dtype}/{mode}/{vmapped}", timeout_s)


def check_tiled(mode: str, timeout_s: int) -> dict:
    """AOT-compile the tiled SpMM program (fwd or grad) under real Mosaic."""
    src = TILED_SRC.format(repo=REPO, mode=mode)
    return _run_child(src, f"float32/{mode}/tiled-spmm", timeout_s)


def _real_error(err: str) -> bool:
    """A compiler verdict, as opposed to tunnel/infra trouble."""
    infra = (TIMEOUT_MSG, "UNAVAILABLE", "initialize backend", "libtpu_lockfile")
    return bool(err) and not any(marker in err for marker in infra)


def probe_compile_path(timeout_s: int = 150) -> bool:
    """Cheap gate: does the chipless AOT compile path answer at all?"""
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_COMPILE_SRC],
            timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and b"PROBE_COMPILE_OK" in out.stdout


def main() -> None:
    import time

    from stmgcn_tpu.utils.hostload import measurement_preamble

    if len(sys.argv) > 1 and sys.argv[1] == "--probe":
        lock, _ = measurement_preamble()
        ok = probe_compile_path()
        lock.release()
        print(json.dumps({"compile_path": "up" if ok else "down"}))
        sys.exit(0 if ok else 1)

    timeout_s = int(sys.argv[1]) if len(sys.argv) > 1 else 900
    lock, _ = measurement_preamble()  # libtpu lockfile + 1-core serialization
    ok_all, results = True, []
    for dtype in ("bfloat16", "float32"):
        for mode in ("fwd", "grad"):
            for vmapped in ("plain", "vmap"):
                rec = check(dtype, mode, vmapped, timeout_s)
                ok_all &= rec["ok"]
                results.append(rec)
                print(json.dumps(rec), flush=True)
    for mode in ("fwd", "grad"):
        rec = check_tiled(mode, timeout_s)
        ok_all &= rec["ok"]
        results.append(rec)
        print(json.dumps(rec), flush=True)
    lock.release()
    # a run that produced at least one REAL verdict (success or an actual
    # compiler error — not a timeout and not tunnel-infrastructure
    # trouble like 'UNAVAILABLE ... initialize backend') is evidence
    real = [r for r in results if r["ok"] or _real_error(r.get("error", ""))]
    if real:
        with open(VERDICT_PATH, "w") as f:
            json.dump(
                {
                    "captured_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                    ),
                    "all_ok": ok_all,
                    "configs": results,
                },
                f,
                indent=1,
            )
    sys.exit(0 if ok_all else 1)


if __name__ == "__main__":
    main()
