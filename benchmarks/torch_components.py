#!/usr/bin/env python
"""Torch-side component timings matching step_breakdown.py's legs (CPU).

Round-5 re-anchoring found torch 28-37% faster than the XLA:CPU
fallback at the canonical point on the current host. This script times
the torch implementation's components at the SAME shapes as
``step_breakdown.py``'s JAX legs — the pairing attributes the gap to a
primitive (oneDNN's fused RNN vs the XLA scan; GEMM conv vs einsum)
instead of leaving it a mystery ratio:

- ``torch/lstm``: M branches' ``nn.LSTM`` fwd+bwd at the model's folded
  shapes (R = B*N rows, T steps, 1 feature in, H hidden, L layers) —
  the component the analytic model says is ~93% of step FLOPs.
- ``torch/conv``: the K-support einsum + projection fwd+bwd at both
  conv sites' shapes.
- ``torch/step``: the full train step (same as torch_baseline.py, fewer
  iters) for the denominator.

One JSON line per measurement, lock + host-load provenance in a trailer
record. Shapes come from bench.py's canonical constants so the pairing
cannot drift.

Usage: python benchmarks/torch_components.py
Env: STMGCN_BENCH_{ROWS,BATCH,WARMUP,ITERS} narrow the point (as in
bench.py); STMGCN_BENCH_LOCK_PATH/_LOCK_WAIT as everywhere.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as bench_mod  # noqa: E402 — the one canonical-point definition

ROWS, BATCH = bench_mod.ROWS, bench_mod.BATCH
T = bench_mod.SERIAL + bench_mod.DAILY + bench_mod.WEEKLY
H, L = bench_mod.LSTM_HIDDEN, bench_mod.LSTM_LAYERS
M, K = bench_mod.M_GRAPHS, bench_mod.K_SUPPORTS
GCN_HIDDEN = bench_mod.GCN_HIDDEN
WARMUP = int(os.environ.get("STMGCN_BENCH_WARMUP", 2))
ITERS = int(os.environ.get("STMGCN_BENCH_ITERS", 5))


def _time(fn, warmup=WARMUP, iters=ITERS) -> float:
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def _emit(name: str, seconds: float, extra=None) -> None:
    rec = {"component": name, "dtype": "float32", "ms": round(seconds * 1e3, 3)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def main() -> None:
    from stmgcn_tpu.utils.hostload import BenchLock, host_load_snapshot

    lock_path = os.environ.get("STMGCN_BENCH_LOCK_PATH")
    lock = BenchLock(lock_path) if lock_path else BenchLock()
    lock.acquire(wait_s=float(os.environ.get("STMGCN_BENCH_LOCK_WAIT", 300)))
    load_before = host_load_snapshot()

    import numpy as np
    import torch
    from torch import nn

    torch.manual_seed(0)
    n = ROWS * ROWS
    rows = BATCH * n  # the model folds nodes into batch for the LSTM
    rng = np.random.default_rng(0)

    # --- lstm: M branches' fused oneDNN recurrence, fwd + bwd.
    # Mirrors step_breakdown.measure_lstm EXACTLY: input feature dim
    # GCN_HIDDEN (the breakdown's chosen width, not the model's d_in=1),
    # loss = sum of ALL timesteps' outputs squared, M real branch passes.
    lstms = [nn.LSTM(GCN_HIDDEN, H, num_layers=L, batch_first=True) for _ in range(M)]
    xs = torch.tensor(rng.standard_normal((rows, T, GCN_HIDDEN)).astype(np.float32))

    def lstm_leg():
        total = 0.0
        for rnn in lstms:
            rnn.zero_grad()
            out, _ = rnn(xs)
            loss = out.square().sum()
            loss.backward()
            total += float(loss.detach())
        return total

    _emit(
        "torch/lstm",
        _time(lstm_leg),
        {"rows": rows, "T": T, "d_in": GCN_HIDDEN, "H": H, "L": L, "m_branches": M},
    )

    # --- conv: M branches' K-support einsum + (K*f -> GCN_HIDDEN) matmul,
    # fwd + bwd — same contraction, projection width, and loss as
    # step_breakdown.measure_conv (no bias/relu there either)
    sup_b = torch.tensor((rng.standard_normal((M, K, n, n)) * 0.1).astype(np.float32))
    for site, f_in in (("seq", T), ("hidden", H)):
        ws = [
            torch.tensor(
                (rng.standard_normal((K * f_in, GCN_HIDDEN)) * 0.1).astype(np.float32),
                requires_grad=True,
            )
            for _ in range(M)
        ]
        sig = torch.tensor(
            rng.standard_normal((M, BATCH, n, f_in)).astype(np.float32)
        )

        def conv_leg():
            total = 0.0
            for m in range(M):
                if ws[m].grad is not None:
                    ws[m].grad = None
                kx = torch.einsum("kij,bjf->bikf", sup_b[m], sig[m]).flatten(2)
                loss = (kx @ ws[m]).square().sum()
                loss.backward()
                total += float(loss.detach())
            return total

        _emit(
            f"torch/conv-{site}",
            _time(conv_leg),
            {"batch": BATCH, "n_nodes": n, "f_in": f_in,
             "f_out": GCN_HIDDEN, "m_branches": M},
        )

    # --- full step (torch_baseline's model; same warmup/iters as the
    # component legs so component-vs-step arithmetic is meaningful) ---
    from torch_baseline import MultiGraphForecaster

    model = MultiGraphForecaster(m=M, k=K, seq_len=T, d_in=1)
    opt = torch.optim.Adam(model.parameters(), lr=2e-3, weight_decay=1e-4)
    crit = nn.MSELoss()
    sup_stack = torch.tensor(
        (rng.standard_normal((M, K, n, n)) * 0.1).astype(np.float32)
    )
    x = torch.tensor(rng.standard_normal((BATCH, T, n, 1)).astype(np.float32))
    y = torch.tensor(rng.standard_normal((BATCH, n, 1)).astype(np.float32) * 0.1)

    def step():
        opt.zero_grad()
        loss = crit(model(sup_stack, x), y)
        loss.backward()
        opt.step()
        return loss

    _emit("torch/step", _time(step))

    print(
        json.dumps(
            {
                "component": "provenance",
                "torch_version": torch.__version__,
                "threads": torch.get_num_threads(),
                "host_load": {
                    "before": load_before,
                    "after": host_load_snapshot(),
                    "lock": lock.record(),
                },
            }
        )
    )
    lock.release()


if __name__ == "__main__":
    main()
