#!/usr/bin/env python
"""Time-budget decomposition of the flagship training step (one chip).

The analytic FLOPs model says the shared LSTM is ~93% of step FLOPs
(``stmgcn_tpu/utils/flops.py``), but FLOPs don't decide wall-clock on a
TPU — the MXU runs matmuls while the VPU runs the gate transcendentals
and the HBM moves the scan's intermediates. This script times each
component in isolation at the canonical operating point so the
optimization target is measured, not guessed:

- ``step/tuned`` and ``step/pallas``: the full train step (fwd+bwd+Adam)
  under the tuned XLA scan and the fused Pallas kernel.
- ``lstm/scan`` and ``lstm/pallas``: ONLY the M-branch LSTM recurrence
  (value+grad of a scalar readout), same shapes the model runs
  (``R = B*N`` rows folded, vmapped over M branches).
- ``conv``: ONLY the fused K-support graph conv einsum (value+grad),
  both conv sites' shapes.
- ``gate``: ONLY the contextual-gate elementwise chain (value+grad) —
  sigmoid/relu/tanh VPU work with trivial matmuls.

Interpretation: if ``lstm/*`` ~= ``step/*`` the LSTM is the whole story;
if ``lstm`` legs barely move between fp32/bf16 the recurrence is
VPU/HBM-bound (the MXU would be ~2x faster in bf16); if
``sum(parts) << step`` the un-timed glue (transposes, fusion boundaries)
is the gap. One JSON line per measurement.

Usage: python benchmarks/step_breakdown.py [dtype] (default bfloat16)
Env: STMGCN_BENCH_{ROWS,BATCH,WARMUP,ITERS,PLATFORM} as in bench.py.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as bench_mod  # noqa: E402 — the one canonical-point definition

ROWS, BATCH = bench_mod.ROWS, bench_mod.BATCH
WARMUP, ITERS = bench_mod.WARMUP, bench_mod.ITERS
T = bench_mod.SERIAL + bench_mod.DAILY + bench_mod.WEEKLY
H, L = bench_mod.LSTM_HIDDEN, bench_mod.LSTM_LAYERS
M, K = bench_mod.M_GRAPHS, bench_mod.K_SUPPORTS
GCN_HIDDEN = bench_mod.GCN_HIDDEN


def _emit(name: str, dtype: str, step_s: float, extra=None) -> None:
    rec = {"component": name, "dtype": dtype, "ms": round(step_s * 1e3, 3)}
    if extra:
        rec.update(extra)
    print(json.dumps(rec), flush=True)


def measure_steps(dtype: str) -> None:
    """Full train step, tuned scan vs pallas backend (on TPU) — built by
    ``bench.build_canonical_step`` so this measures exactly the headline
    model."""
    import jax

    from stmgcn_tpu.utils import time_chained

    for sched, kwargs in (
        ("tuned", dict(fused=True, unroll=0)),
        ("pallas", dict(backend="pallas")),
    ):
        if kwargs.get("backend") == "pallas" and not _on_tpu():
            continue
        fns, sup, x, y, mask, fk = bench_mod.build_canonical_step(dtype, **kwargs)
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        state = {"params": params, "opt_state": opt_state}

        def step():
            state["params"], state["opt_state"], loss = fns.train_step(
                state["params"], state["opt_state"], sup, x, y, mask
            )
            return loss

        s = time_chained(step, iters=ITERS, warmup=WARMUP)
        _emit(f"step/{sched}", dtype, s, {"n_nodes": fk["n_nodes"], "batch": BATCH})


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def measure_lstm(dtype: str) -> None:
    """The M-branch LSTM recurrence alone, scan vs pallas."""
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.ops.lstm import StackedLSTM
    from stmgcn_tpu.utils import time_chained

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    R = BATCH * ROWS * ROWS
    x = jax.random.normal(jax.random.key(0), (M, R, T, GCN_HIDDEN), dt)

    for name, kwargs in (
        ("scan", dict(fused_scan=True, unroll=0)),
        ("pallas", dict(backend="pallas")),
    ):
        if kwargs.get("backend") == "pallas" and not _on_tpu():
            continue
        mod = StackedLSTM(hidden_dim=H, num_layers=L, dtype=dt, **kwargs)
        params = jax.vmap(lambda xb: mod.init(jax.random.key(1), xb))(x)

        def loss(p, xb):
            out, _ = jax.vmap(mod.apply)(p, xb)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        vg = jax.jit(jax.value_and_grad(loss))
        state = {"g": None}

        def step():
            val, state["g"] = vg(params, x)
            return val

        s = time_chained(step, iters=ITERS, warmup=WARMUP)
        _emit(f"lstm/{name}", dtype, s, {"rows": R})


def measure_conv(dtype: str) -> None:
    """The fused K-support conv einsum alone (both call sites' shapes)."""
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.utils import time_chained

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    N = ROWS * ROWS
    sup = jax.random.normal(jax.random.key(0), (M, K, N, N), dt) * 0.1
    # site 1: temporal-as-feature (B, N, T); site 2: LSTM output (B, N, H)
    for site, feat in (("conv/seq", T), ("conv/hidden", H)):
        xb = jax.random.normal(jax.random.key(1), (M, BATCH, N, feat), dt)
        w = jax.random.normal(jax.random.key(2), (M, K * feat, GCN_HIDDEN), dt) * 0.1

        def loss(w, xb):
            def one(sup_m, x_m, w_m):
                kx = jnp.einsum("kij,bjf->bikf", sup_m, x_m)
                kx = kx.reshape(kx.shape[0], kx.shape[1], -1)
                return jnp.sum((kx @ w_m).astype(jnp.float32) ** 2)

            return jnp.sum(jax.vmap(one)(sup, xb, w))

        vg = jax.jit(jax.value_and_grad(loss))
        state = {}

        def step():
            val, state["g"] = vg(w, xb)
            return val

        s = time_chained(step, iters=ITERS, warmup=WARMUP)
        _emit(site, dtype, s, {"n_nodes": N, "feat": feat})


def measure_gate(dtype: str) -> None:
    """The contextual-gate elementwise chain alone (VPU-dominated)."""
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.utils import time_chained

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    N = ROWS * ROWS
    x = jax.random.normal(jax.random.key(0), (M, BATCH, T, N, 1), dt)
    xh = jax.random.normal(jax.random.key(1), (M, BATCH, N, T), dt)
    wf = jax.random.normal(jax.random.key(2), (M, T, T), dt) * 0.1

    def loss(wf, x, xh):
        def one(x_m, xh_m, w_m):
            z = jnp.mean(jax.nn.relu(xh_m + xh_m), axis=1)  # (B, T) pool
            s = jax.nn.sigmoid(jax.nn.relu(z @ w_m) @ w_m)
            gated = jnp.einsum("btnf,bt->btnf", x_m, s)
            return jnp.sum(gated.astype(jnp.float32) ** 2)

        return jnp.sum(jax.vmap(one)(x, xh, wf))

    vg = jax.jit(jax.value_and_grad(loss))
    state = {}

    def step():
        val, state["g"] = vg(wf, x, xh)
        return val

    s = time_chained(step, iters=ITERS, warmup=WARMUP)
    _emit("gate", dtype, s, {"n_nodes": N})


def main() -> None:
    dtype = sys.argv[1] if len(sys.argv) > 1 else "bfloat16"
    pinned = os.environ.get("STMGCN_BENCH_PLATFORM")
    if pinned:
        from stmgcn_tpu.utils import force_host_platform

        force_host_platform(pinned)
    measure_steps(dtype)
    measure_lstm(dtype)
    measure_conv(dtype)
    measure_gate(dtype)


if __name__ == "__main__":
    main()
