#!/usr/bin/env python
"""The accuracy leg at scale: train BASELINE config 3 once, record RMSE/PCC.

The iso-RMSE pairing behind the north star (BASELINE.json: >= 10x
samples/sec *at iso-RMSE*) has only ever been measured at the 16x16
point; this script produces the scaled-point accuracy row — the
N=2500 sparse preset trained with the full reference recipe (patience
early stop) on whatever single chip JAX exposes — and writes
``benchmarks/scaled_accuracy.json`` with the metrics, wall-clock,
device, and host-load provenance.

Intended to run on a real TPU (the tunnel-recovery loop runs it as its
final leg); off-TPU it still works but labels the record cpu-fallback
and shrinks the problem so the result arrives this side of forever.
Epoch cap via STMGCN_SCALED_ACC_EPOCHS (default 40: early stop usually
fires first; the cap bounds a wedged-tunnel worst case).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "benchmarks", "scaled_accuracy.json")


def main() -> None:
    from stmgcn_tpu.utils.hostload import (
        host_load_snapshot,
        measurement_preamble,
        probe_backend_child,
    )

    lock, load_before = measurement_preamble()
    on_tpu = probe_backend_child() == "tpu"
    if not on_tpu:
        from stmgcn_tpu.utils import force_host_platform

        force_host_platform("cpu")

    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_trainer

    cfg = preset("scaled")
    cfg.model.sparse = True
    cfg.mesh.dp = cfg.mesh.region = 1  # one chip; the sharded story is MULTICHIP's
    cfg.mesh.region_strategy = "gspmd"
    cfg.train.epochs = int(os.environ.get("STMGCN_SCALED_ACC_EPOCHS", 40))
    if not on_tpu:  # CPU can't train N=2500 in useful time; shrink honestly
        cfg.data.rows = 10
        cfg.train.epochs = min(cfg.train.epochs, 5)
        cfg.train.batch_size = 8
    cfg.data.n_timesteps = 24 * 7 * 8  # 8 weeks of synthetic demand

    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="stmgcn_scaled_acc_") as out_dir:
        cfg.train.out_dir = out_dir
        trainer = build_trainer(cfg, verbose=True)
        history = trainer.train()
        results = trainer.test(modes=("test",))
    record = {
        "operating_point": f"scaled-n{cfg.data.rows ** 2}",
        "sparse": cfg.model.sparse,
        "dtype": cfg.model.dtype,
        "epochs_run": len(history["train"]),
        "epoch_cap": cfg.train.epochs,
        "best_val_loss": min(history["validate"]),
        "test": {k: float(v) for k, v in results["test"].items()},
        "wallclock_s": round(time.time() - t0, 1),
        "platform": "tpu" if on_tpu else "cpu-fallback",
        "host_load": {
            "before": load_before,
            "after": host_load_snapshot(),
            "lock": lock.record(),
        },
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    from stmgcn_tpu.utils.hostload import persist_measurement

    persist_measurement(OUT, record, on_tpu, "scaled_accuracy")
    print(json.dumps(record))
    lock.release()


if __name__ == "__main__":
    main()
