#!/usr/bin/env python
"""Sweep bench variants on the current backend; print a table + JSON lines.

Runs the flagship training step at the canonical operating point across
the performance levers that need on-hardware numbers:

- dtype: float32 vs bfloat16
- LSTM scan schedule: plain layered scan / unroll=T / fused / fused+unroll
  (numerically identical — equality pinned in tests/test_lstm_variants.py)

``bench.py`` itself measures the {plain, tuned} x {fp32, bf16} grid in one
run (its ``variants`` table); this harness adds the intermediate schedules
(unroll-only, fused-only) as separate subprocess runs through bench's env
knobs — one backend and compile-cache namespace per run, inheriting
bench's fail-open behavior. Use ``--tiny`` to validate the sweep logic on
a slow host.

Usage::

    python benchmarks/variants.py            # canonical shapes
    python benchmarks/variants.py --tiny     # logic check (small, CPU ok)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

#: extra single-schedule runs beyond bench's built-in plain/tuned pair;
#: both env vars are always set explicitly so the pair means exactly this
EXTRA_VARIANTS = [
    ("unroll=T", {"STMGCN_BENCH_LSTM_UNROLL": "0", "STMGCN_BENCH_LSTM_FUSED": "0"}),
    ("fused", {"STMGCN_BENCH_LSTM_UNROLL": "1", "STMGCN_BENCH_LSTM_FUSED": "1"}),
]


def run_bench(
    env_extra: dict, tiny: bool = False, *, base_env: dict = None, timeout: float = None
) -> dict:
    """Run ``bench.py`` in a subprocess and parse its one-line record.

    The single bench-stdout parsing contract — every sweep script
    (this one, ``pallas_block_sweep.py``) goes through here. ``base_env``
    replaces the inherited environment (callers that must strip
    ambient overrides); ``timeout`` bounds the child.
    """
    env = dict(os.environ if base_env is None else base_env)
    env.update(env_extra)
    if tiny:
        env.update(
            STMGCN_BENCH_ROWS="4",
            STMGCN_BENCH_BATCH="8",
            STMGCN_BENCH_WARMUP="1",
            STMGCN_BENCH_ITERS="3",
            STMGCN_BENCH_PLATFORM="cpu",
        )
    bench = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "bench.py")
    try:
        out = subprocess.run(
            [sys.executable, bench],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"bench timed out after {timeout}s"}
    if not out.stdout.strip():
        # a crashed child with nothing on stdout must surface its
        # traceback, not parse as an empty record
        return {
            "error": f"bench exited {out.returncode} with no output: "
            + out.stderr.strip()[-300:]
        }
    line = out.stdout.strip().splitlines()[-1]
    try:
        return json.loads(line)
    except json.JSONDecodeError:
        return {"error": f"unparsable bench output: {line[-200:]}"}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true", help="small shapes, CPU pinned")
    args = ap.parse_args()

    records = []
    for label, env_extra in [("plain+tuned", {})] + EXTRA_VARIANTS:
        rec = run_bench(env_extra, args.tiny)
        rec["sweep_variant"] = label
        records.append(rec)
        print(json.dumps(rec), flush=True)

    # flatten every record's per-leg table into (schedule, dtype, leg) rows
    rows = []
    for rec in records:
        for key, leg in (rec.get("variants") or {}).items():
            dtype, sched = key.split("/", 1)
            label = rec["sweep_variant"] if sched == "custom" else sched
            rows.append((label, dtype, leg))

    def fmt(v):
        return "-" if v is None else (f"{v:.4f}" if isinstance(v, float) and v < 1 else f"{v:,.1f}")

    print(f"\n{'schedule':<14} {'dtype':<9} {'r-ts/s':>14} {'step ms':>9} {'mfu':>9}")
    for label, dtype, leg in rows:
        print(f"{label:<14} {dtype:<9} {fmt(leg.get('value')):>14} "
              f"{fmt(leg.get('step_ms')):>9} {fmt(leg.get('mfu')):>9}")
    if any("error" in r for r in records):
        print("\nnote: some runs recorded errors (see JSON lines above)")


if __name__ == "__main__":
    main()
