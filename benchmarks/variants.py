#!/usr/bin/env python
"""Sweep bench variants on the current backend; print a table + JSON lines.

Runs the flagship training step at the canonical operating point across
the performance levers that need on-hardware numbers:

- dtype: float32 vs bfloat16
- LSTM scan schedule: layered / unroll=T / fused / fused+unroll
  (numerically identical — equality pinned in tests/test_lstm_variants.py)

Each variant runs in a fresh subprocess (one backend, one compile cache
namespace, no cross-variant donation hazards) through ``bench.py`` with
its env knobs, so this harness inherits bench's fail-open behavior. Use
``--tiny`` to validate the sweep logic on a slow host.

Usage::

    python benchmarks/variants.py            # canonical shapes
    python benchmarks/variants.py --tiny     # logic check (small, CPU ok)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

VARIANTS = [
    # (label, extra env)
    ("layered", {}),
    ("unroll=T", {"STMGCN_BENCH_LSTM_UNROLL": "12"}),
    ("fused", {"STMGCN_BENCH_LSTM_FUSED": "1"}),
    ("fused+unroll", {"STMGCN_BENCH_LSTM_FUSED": "1", "STMGCN_BENCH_LSTM_UNROLL": "4"}),
]


def run_variant(label: str, env_extra: dict, tiny: bool) -> dict:
    env = dict(os.environ)
    env.update(env_extra)
    if tiny:
        env.update(
            STMGCN_BENCH_ROWS="4",
            STMGCN_BENCH_BATCH="8",
            STMGCN_BENCH_WARMUP="1",
            STMGCN_BENCH_ITERS="3",
            STMGCN_BENCH_PLATFORM="cpu",
        )
    bench = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "bench.py")
    out = subprocess.run(
        [sys.executable, bench], env=env, capture_output=True, text=True
    )
    line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
    try:
        record = json.loads(line)
    except json.JSONDecodeError:
        record = {"error": f"unparsable bench output: {line[-200:]}"}
    record["variant"] = label
    return record


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiny", action="store_true", help="small shapes, CPU pinned")
    args = ap.parse_args()

    records = []
    for label, env_extra in VARIANTS:
        rec = run_variant(label, env_extra, args.tiny)
        records.append(rec)
        print(json.dumps(rec), flush=True)

    def fmt(v):
        return "-" if v is None else (f"{v:.4f}" if isinstance(v, float) and v < 1 else f"{v:,.1f}")

    print(f"\n{'variant':<14} {'fp32 r-ts/s':>14} {'fp32 ms':>9} {'fp32 mfu':>9} "
          f"{'bf16 r-ts/s':>14} {'bf16 ms':>9} {'bf16 mfu':>9}")
    for rec in records:
        bf = rec.get("bf16") or {}
        print(f"{rec['variant']:<14} {fmt(rec.get('value')):>14} "
              f"{fmt(rec.get('step_ms')):>9} {fmt(rec.get('mfu')):>9} "
              f"{fmt(bf.get('value')):>14} {fmt(bf.get('step_ms')):>9} "
              f"{fmt(bf.get('mfu')):>9}")
    if any("error" in r for r in records):
        print("\nnote: some variants recorded errors (see JSON lines above)")


if __name__ == "__main__":
    main()
