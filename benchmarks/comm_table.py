#!/usr/bin/env python
"""Per-step collective wire volume for every sharding plan, from compiled HLO.

Builds the full training step (forward + grad + Adam) for each of the
framework's distributed execution plans at the scaled operating point and
tallies the collectives XLA actually emitted
(``stmgcn_tpu.utils.comm.step_comm_report`` — measured program text, not
an analytic model). Runs entirely on the 8-virtual-device CPU mesh: HLO
collective structure is a function of the sharding annotations, not of
which backend executes them, so the table holds for a TPU mesh of the
same shape (byte counts; achieved bandwidth obviously differs).

Plans (the communication layer the reference lacks outright — SURVEY.md
§2 "no NCCL/distributed anywhere"):

- ``dp8``            batch sharded 8 ways; gradient all-reduce
- ``region8-gspmd``  node axis sharded; XLA's automatic conv plan
- ``region8-auto``   banded branches on the explicit halo plan
                     (collective-permute), the rest GSPMD
- ``region8-sparse`` block-CSR row strips per shard
- ``branch3``        graph branches sharded; sum fusion becomes one psum
- ``branch2-dense``  (dp=2, region=2, branch=2): branch parallelism
                     composed with region sharding, dense GSPMD supports
- ``branch2-sparse`` same mesh, branch-stacked block-CSR strips (round
                     5: the vmapped branch axis shards the stacked
                     operand; each branch group all-gathers the signal
                     over its region ring)
- ``hetero-region``  heterogeneous city pair on a (dp, region) mesh with
                     per-city node padding; reports the padded city's
                     compiled step (each city shape compiles its own)

Usage: python benchmarks/comm_table.py [rows] [batch]
Emits one JSON line per plan plus a markdown table on stdout.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_plan(name: str, rows: int, batch: int):
    from stmgcn_tpu.config import preset

    # base preset first, shared settings once after — so every plan
    # (hetero included) measures the same dtype/shapes
    if name == "hetero-region":
        cfg = preset("multicity")
        # second city one row smaller -> its N needs padding on region=2
        cfg.data.override(
            city_rows=(rows, rows - 1),
            city_timesteps=(24 * 7 * 2 + 2 * batch, 24 * 7 * 2 + 2 * batch),
        )
        cfg.mesh.dp, cfg.mesh.region = 4, 2
    else:
        cfg = preset("scaled")
        cfg.data.rows = rows
        cfg.data.n_timesteps = 24 * 7 * 2 + 2 * batch
        if name == "dp8":
            cfg.mesh.dp, cfg.mesh.region = 8, 1
            cfg.mesh.region_strategy = "gspmd"
        elif name == "region8-gspmd":
            cfg.mesh.region, cfg.mesh.region_strategy = 8, "gspmd"
        elif name == "region8-auto":
            cfg.mesh.region, cfg.mesh.region_strategy = 8, "auto"
        elif name == "region8-sparse":
            cfg.mesh.region, cfg.mesh.region_strategy = 8, "gspmd"
            cfg.model.sparse = True
        elif name == "branch3":
            cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 1, 1, 3
            cfg.mesh.region_strategy = "gspmd"
        elif name in ("branch2-dense", "branch2-sparse"):
            # the branch extent must divide m_graphs; 2 of the 3
            # synthetic graphs keep the step architecturally complete
            cfg.model.m_graphs = 2
            cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 2, 2, 2
            cfg.mesh.region_strategy = "gspmd"
            cfg.model.sparse = name == "branch2-sparse"
        else:
            raise ValueError(name)
    cfg.train.batch_size = batch
    cfg.train.out_dir = f"/tmp/comm_table_{name}"
    cfg.train.epochs = 1
    # keep the measurement about sharding, not scan scheduling or dtype
    cfg.model.dtype = "bfloat16"
    return cfg


def measure(name: str, rows: int, batch: int) -> dict:
    from stmgcn_tpu.experiment import build_trainer
    from stmgcn_tpu.utils.comm import step_comm_report

    cfg = build_plan(name, rows, batch)
    tr = build_trainer(cfg, verbose=False)
    gen = tr._placed_batches("train", with_arrays=True)
    batch_obj, (x, y, mask) = next(gen)
    if name == "hetero-region":
        # report the PADDED city's compiled step — the one whose plan the
        # per-city padding machinery shapes
        for batch_obj, (x, y, mask) in gen:
            if tr._pad_for(batch_obj.city):
                break
    # the full train step always carries HLO while loops (scanned LSTM,
    # sparse/halo paths) — accept lower-bound counts; while_count marks
    # every row so readers know the numbers don't multiply through loops
    stats = step_comm_report(
        tr._fns(batch_obj.city).train_step,
        tr.params,
        tr.opt_state,
        tr._supports_for(batch_obj),
        x,
        y,
        mask,
        allow_loops=True,
    )
    return {
        "plan": name,
        "rows": rows,
        "batch": batch,
        "n_nodes": x.shape[2],
        **{
            op: stats[op]
            for op in (
                "all-gather",
                "all-reduce",
                "collective-permute",
                "reduce-scatter",
                "all-to-all",
            )
        },
        "total_bytes": stats["total_bytes"],
        "while_count": stats["while_count"],
    }


PLANS = (
    "dp8",
    "region8-gspmd",
    "region8-auto",
    "region8-sparse",
    "branch3",
    "branch2-dense",
    "branch2-sparse",
    "hetero-region",
)


def main() -> None:
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 16

    from stmgcn_tpu.utils import force_host_platform

    force_host_platform("cpu", n_devices=8)

    results = []
    for name in PLANS:
        try:
            r = measure(name, rows, batch)
        except Exception as e:  # report per-plan, keep the rest of the table
            r = {"plan": name, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(r), flush=True)
        results.append(r)

    print(
        "\n| plan | all-gather | all-reduce | permute | reduce-scatter "
        "| total/step (>=) | while loops |"
    )
    print("|---|---|---|---|---|---|---|")
    for r in results:
        if "error" in r:
            print(f"| {r['plan']} | error: {r['error'][:60]} | | | | | |")
            continue

        def mb(op):
            return f"{r[op]['bytes'] / 1e6:.2f} MB x{r[op]['count']}"

        print(
            f"| {r['plan']} | {mb('all-gather')} | {mb('all-reduce')} | "
            f"{mb('collective-permute')} | {mb('reduce-scatter')} | "
            f"{r['total_bytes'] / 1e6:.2f} MB | {r['while_count']} |"
        )


if __name__ == "__main__":
    main()
