#!/usr/bin/env python
"""Tunnel-recovery watcher: probe the TPU, run the evidence runbook once.

The TPU behind this container's tunnel wedges for hours at a time
(backend init blocks inside native code). This loop turns the first
minutes of a recovery window into committed evidence without manual
driving, executing TPU_RUNBOOK.md's order:

1. probe the backend in a killable child (cheap 8x8 matmul, bounded).
   Independently, while no Mosaic compile verdict exists, pre-gate the
   CHIPLESS AOT compile path each cycle (``mosaic_compile_check.py
   --probe``) and run the full compile check the moment it answers —
   the compile helper can recover before (or without) the devices, and
   the kernel-compiles-under-real-Mosaic question needs no chip;
2. on device-probe success: ``bench.py`` canonical ->
   ``STMGCN_BENCH_MODE=scaled`` -> ``step_breakdown.py`` ->
   ``pallas_block_sweep.py`` -> ``serving_latency.py`` ->
   ``scaled_accuracy.py``, each leg logged (timeouts keep the child's
   partial stdout). If the canonical leg fails to land
   ``benchmarks/tpu_last_good.json`` (tunnel re-wedged mid-leg), the
   later legs are skipped and the watcher re-arms for the next window —
   up to ``MAX_PASSES`` total runbook passes, so a persistent
   non-tunnel failure cannot re-run the multi-hour runbook forever;
3. after a pass whose canonical evidence landed (or the pass budget is
   spent), write a done-marker and exit; the evidence files
   (benchmarks/tpu*_last_good.json, mosaic_compile_verdict.json,
   breakdown/sweep logs) are then committed by a human (or the
   driver's end-of-round sweep).

Contention discipline (BASELINE.md round 4: concurrent probe children
depressed the driver's own record 4-20% on this 1-core host): every
probe happens ONLY while holding the host-wide bench lock
(`stmgcn_tpu.utils.hostload.BenchLock`), and the lock is RELEASED before
spawning ``bench.py`` — bench takes the same lock itself, so the loop
can never measure against itself, and a driver-invoked bench always
serializes with (never races) this loop.

Usage: ``nohup python benchmarks/tpu_probe_loop.py >/tmp/probe_loop.log
2>&1 &``. State: ``/tmp/stmgcn_probe_done`` marks a completed pass
(delete it to re-arm); the log is self-describing.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from stmgcn_tpu.utils.hostload import (  # noqa: E402
    BenchLock,
    probe_backend_child,
)

DONE_MARKER = "/tmp/stmgcn_probe_done"
PROBE_TIMEOUT_S = int(os.environ.get("STMGCN_PROBE_TIMEOUT", 120))
SLEEP_S = int(os.environ.get("STMGCN_PROBE_SLEEP", 600))
#: total runbook passes before giving up (re-arm cap: a healthy-looking
#: probe with a persistently failing canonical leg must not re-run the
#: multi-hour runbook forever on this 1-core host)
MAX_PASSES = int(os.environ.get("STMGCN_PROBE_MAX_PASSES", 3))


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def probe_once() -> bool:
    """One killable backend probe under the bench lock. True iff the
    resolved backend is a real TPU (a plugin-less host 'succeeds' on CPU
    and must not trigger the runbook). The probe itself is the shared
    ``probe_backend_child`` — one implementation everywhere, and immune
    to a rc=0 child with empty stdout killing the watcher."""
    lock = BenchLock()
    if not lock.acquire(wait_s=30):
        log(f"bench lock held by pid {lock.holder_pid()}; standing down")
        return False
    try:
        backend = probe_backend_child(timeout_s=PROBE_TIMEOUT_S)
        if backend is None:
            log(f"probe failed or timed out after {PROBE_TIMEOUT_S}s (tunnel wedged)")
            return False
        log(f"probe resolved backend: {backend}")
        return backend == "tpu"
    finally:
        lock.release()


_mosaic_attempts = 0


def maybe_mosaic_check() -> None:
    """While no Mosaic compile verdict exists, pre-gate the chipless AOT
    compile path (cheap trivial-kernel compile, fail-fast) and run the
    full kernel compile check the moment it answers. Both the probe and
    the full check take the bench lock themselves. Full checks are
    capped: a flapping tunnel that passes the gate but starves the big
    compiles must not grind the host forever (observed 2026-07-30: the
    gate compiled in ~2 min while every kernel config timed out)."""
    global _mosaic_attempts
    verdict = os.path.join(REPO, "benchmarks", "mosaic_compile_verdict.json")
    if os.path.exists(verdict) or _mosaic_attempts >= 3:
        return
    py = sys.executable
    gate = [py, "benchmarks/mosaic_compile_check.py", "--probe"]
    # The child's measurement_preamble waits up to 300s (default) for the
    # bench lock BEFORE its 150s probe compile — under a default parent
    # timeout the lock wait alone could eat the whole budget and a healthy
    # compile path read as "down". Cap the child's lock wait short and
    # size the parent timeout to the child's actual worst case:
    # lock wait + probe compile + startup/teardown margin.
    gate_lock_wait_s = 30
    gate_compile_s = 150  # probe_compile_path(timeout_s=150) in the child
    try:
        out = subprocess.run(
            gate, cwd=REPO,
            timeout=gate_lock_wait_s + gate_compile_s + 60,
            capture_output=True,
            env={**os.environ, "STMGCN_BENCH_LOCK_WAIT": str(gate_lock_wait_s)},
        )
    except subprocess.TimeoutExpired:
        log("mosaic gate: compile path down (probe timed out)")
        return
    if out.returncode != 0:
        log("mosaic gate: compile path down")
        return
    _mosaic_attempts += 1
    log(
        "mosaic gate: compile path UP — running the full kernel check "
        f"(attempt {_mosaic_attempts}/3)"
    )
    run_leg(
        "mosaic-compile",
        [py, "benchmarks/mosaic_compile_check.py", "400"],
        {},
        4200,
        False,
    )


def run_leg(
    name: str, argv: list[str], env_extra: dict, timeout_s: int, take_lock: bool
) -> bool:
    """Run one runbook leg. ``take_lock`` legs (tools that don't acquire
    the bench lock themselves) run while THIS process holds it, so a
    driver-invoked ``bench.py`` serializes behind them instead of
    measuring contended-but-reporting-clean. ``bench.py`` legs must NOT
    be spawned under the lock — bench takes it itself and would deadlock
    against its own parent."""
    env = dict(os.environ, **env_extra)
    log(f"leg {name}: {' '.join(argv)}")
    lock = BenchLock() if take_lock else None
    if lock is not None and not lock.acquire(wait_s=600):
        log(f"leg {name}: bench lock busy (pid {lock.holder_pid()}); skipping")
        return False
    if lock is not None:
        # take_lock legs (breakdown/sweep) have no preamble of their own:
        # drain lingering probe children for them like bench.py does for
        # itself, or they measure against the wedged child
        from stmgcn_tpu.utils.hostload import wait_for_probe_children

        wait_for_probe_children()
    try:
        out = subprocess.run(
            argv, cwd=REPO, env=env, timeout=timeout_s, capture_output=True
        )
    except subprocess.TimeoutExpired as e:
        # keep whatever the leg printed before dying — for a multi-config
        # tool that is most of the evidence
        partial = (e.stdout or b"").decode(errors="replace")[-2000:]
        log(f"leg {name}: TIMED OUT after {timeout_s}s\n{partial}")
        return False
    finally:
        if lock is not None:
            lock.release()
    tail = out.stdout.decode()[-2000:]
    log(f"leg {name}: rc={out.returncode}\n{tail}")
    if out.returncode != 0:
        log(f"leg {name} stderr: {out.stderr.decode()[-1000:]}")
    return out.returncode == 0


def _canonical_evidence_since(t0: float) -> bool:
    """Whether THIS pass's canonical leg landed its evidence file — a
    last-good file surviving from an earlier recovery window must not
    count."""
    evidence = os.path.join(REPO, "benchmarks", "tpu_last_good.json")
    return os.path.exists(evidence) and os.path.getmtime(evidence) >= t0


def runbook() -> bool:
    """TPU_RUNBOOK.md order — canonical first (settles >= baseline), each
    later leg strictly optional. Logs land next to the evidence files.
    Returns True iff the canonical leg produced its evidence file — the
    one outcome that makes a pass worth retiring the watcher for. When
    it didn't (tunnel re-wedged mid-leg), the later legs are pointless
    multi-hour grinds against a dead backend and are skipped so the
    watcher re-arms within one leg's timeout."""
    t0 = time.time()
    py = sys.executable
    legs = [
        ("canonical", [py, "bench.py"], {}, 1800, False),
        ("scaled", [py, "bench.py"], {"STMGCN_BENCH_MODE": "scaled"}, 2400, False),
        (
            "breakdown-bf16",
            [py, "benchmarks/step_breakdown.py", "bfloat16"],
            {},
            1800,
            True,
        ),
        (
            "sweep-bf16",
            [py, "benchmarks/pallas_block_sweep.py", "bfloat16"],
            {},
            3600,
            True,
        ),
        # these two take the bench lock themselves (they ARE measurement
        # processes like bench.py) — spawning them under the parent's
        # hold would deadlock
        (
            "serving-latency",
            [py, "benchmarks/serving_latency.py"],
            {},
            1800,
            False,
        ),
        (
            "scaled-accuracy",
            [py, "benchmarks/scaled_accuracy.py"],
            {},
            7200,
            False,
        ),
    ]
    for name, argv, env_extra, timeout_s, take_lock in legs:
        run_leg(name, argv, env_extra, timeout_s, take_lock)
        if name == "canonical" and not _canonical_evidence_since(t0):
            log("canonical leg landed no evidence; skipping later legs")
            return False
    return _canonical_evidence_since(t0)


def main() -> None:
    if os.path.exists(DONE_MARKER):
        log(f"{DONE_MARKER} exists; runbook already completed — exiting")
        return
    log(
        f"watching for tunnel recovery (probe timeout {PROBE_TIMEOUT_S}s, "
        f"sleep {SLEEP_S}s)"
    )
    passes = 0
    while True:
        maybe_mosaic_check()
        if probe_once():
            passes += 1
            log(f"TPU answered — executing runbook (pass {passes}/{MAX_PASSES})")
            if runbook():
                with open(DONE_MARKER, "w") as f:
                    f.write(time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
                log("runbook pass complete; marker written — exiting")
                return
            if passes >= MAX_PASSES:
                log(
                    f"{passes} runbook passes without canonical evidence — "
                    "the failure is not transient; exiting WITHOUT marker "
                    "(delete nothing to re-arm: just restart the loop)"
                )
                return
            # the tunnel answered the probe but wedged again before the
            # canonical leg landed evidence: stay armed for the next window
            log("runbook pass produced no canonical evidence; re-arming")
        time.sleep(SLEEP_S)


if __name__ == "__main__":
    main()
