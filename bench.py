#!/usr/bin/env python
"""Benchmark the flagship training step; prints ONE JSON line and exits 0.

Metric: region-timesteps/sec/chip — ``batch * seq_len * n_nodes`` demand
points advanced per second of steady-state training step (forward + grad +
Adam update), on whatever single chip JAX exposes. The record also carries
``mfu`` (analytic-FLOPs model utilization vs the chip's bf16 peak — see
``stmgcn_tpu/utils/flops.py``) and a ``variants`` table covering
{fp32, bf16} x {plain scan, tuned fused/unrolled scan, fused Pallas
kernel} plus ``float32/superstep`` (S train steps fused into one
``lax.scan`` dispatch with on-device batch gather, per-step numbers) —
all numerically equivalent schedules of the same step; the headline is
the fastest leg. A ``precision_superstep`` rider measures the
lint-certified bf16 twin program against the fp32 superstep at smoke
shapes (throughput ratio, final-loss delta, nonfinite census) — the
ratio is chip evidence only when ``bf16_native`` is true.
Timing methodology is chained-steps with a single readback fence
(``stmgcn_tpu.utils.time_chained``): on this image's tunneled TPU backend,
``block_until_ready`` does not actually fence and a per-step sync costs a
~68 ms round-trip, so per-step "fenced" timing is wrong in both
directions.

``vs_baseline`` compares against the reference-equivalent PyTorch
implementation's throughput at identical shapes (the reference repo itself
ships no numbers or data — SURVEY.md §6); the anchor's provenance (device,
threads, value — it is a single-thread CPU torch run, NOT a like-for-like
accelerator) is embedded in the printed record as ``baseline``. A record
measured with competing Python processes on the host carries
``"contended": true``: the measurement is still printed, but its baseline
ratios are nulled and it never overwrites last-good evidence. The
``data_residency`` block reports the window-free resident footprint vs
materialized windows and the dataset build-time split.

Failure policy: this script never fails closed on *environment* trouble.
A wedged TPU tunnel is probed with retries + backoff; on persistent
failure it falls back to a CPU measurement (labeled ``platform:
cpu-fallback`` with an ``error`` field) so the driver parses a real record
with ``value > 0`` whenever the configuration is valid. Invalid operator
configuration (bad ``STMGCN_BENCH_DTYPE``) exits nonzero instead; any
other unexpected exception emits a ``value: 0.0`` record with the error
attached rather than producing no parsable line at all.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

# Benchmark operating point ("Didi-Chengdu, 12-step" scale, BASELINE.json):
# 16x16 region grid, 12-step observation window, batch 64, full M=3 ST-MGCN.
# Env overrides (STMGCN_BENCH_*) let the script's logic be validated on
# slow hosts without changing the canonical TPU operating point.
#: "canonical" measures the 16x16 flagship point; "scaled" measures
#: BASELINE config 3 (50x50 grid -> N=2500, K=3, bf16, batch 16) as a
#: dense-vs-sparse support-representation table on one chip. "fleet"
#: measures an 8-city heterogeneous fleet (two shape classes) as a
#: fused-fleet-superstep vs materialized-per-city-loop epoch-throughput
#: table on one chip. "largeN" measures a metro-scale N=8192 city as a
#: tiled-sparse vs dense support-representation table (offline
#: reorder/condense plan + MXU-tile SpMM, ROADMAP item 2).
#: Scaled/fleet/largeN runs persist their own last-good TPU evidence
#: (benchmarks/tpu_{scaled,fleet,largen}_last_good.json), which
#: canonical records embed as ``scaled_tpu`` so the driver-captured
#: record carries both stories.
MODE = os.environ.get("STMGCN_BENCH_MODE", "canonical")
ROWS = int(os.environ.get("STMGCN_BENCH_ROWS", 16))
SERIAL, DAILY, WEEKLY = 10, 1, 1
BATCH = int(os.environ.get("STMGCN_BENCH_BATCH", 64))
DTYPE = os.environ.get("STMGCN_BENCH_DTYPE", "both")  # float32 | bfloat16 | both
WARMUP = int(os.environ.get("STMGCN_BENCH_WARMUP", 5))
ITERS = int(os.environ.get("STMGCN_BENCH_ITERS", 30))
# LSTM scheduling levers (numerically identical; see ops/lstm.py and
# ops/pallas_lstm.py). By default the bench measures THREE schedules: the
# plain scan (unroll=1), the tuned scan (single fused scan over all
# layers, fully unrolled — 0 means unroll=T), and the hand-written fused
# Pallas kernel (backend=pallas; whole T x L recurrence in one kernel
# pair, VMEM-resident states). Setting any env var replaces the set with
# that one custom schedule. An unset var keeps its plain-schedule value
# so a partial override still means what it always meant.
LSTM_UNROLL = int(os.environ.get("STMGCN_BENCH_LSTM_UNROLL", 1))
LSTM_FUSED = os.environ.get("STMGCN_BENCH_LSTM_FUSED", "0") == "1"
LSTM_BACKEND = os.environ.get("STMGCN_BENCH_LSTM_BACKEND", "xla")
#: S for the float32/superstep leg: S train steps fused into one lax.scan
#: dispatch with on-device batch gather (train/step.py make_superstep_fns),
#: measured over the tuned LSTM schedule so the delta vs float32/tuned is
#: pure dispatch amortization. Overriding moves the run off the canonical
#: point (it changes what the superstep leg measures).
SUPERSTEP = int(os.environ.get("STMGCN_BENCH_SUPERSTEP", 8))
#: S for the fleet superstep (fleet mode): fused steps per dispatch on
#: the per-class path. Overriding moves the run off the canonical point.
FLEET_S = int(os.environ.get("STMGCN_BENCH_FLEET_S", 8))
CUSTOM_SCHEDULE = (
    "STMGCN_BENCH_LSTM_UNROLL" in os.environ
    or "STMGCN_BENCH_LSTM_FUSED" in os.environ
    or "STMGCN_BENCH_LSTM_BACKEND" in os.environ
)
LSTM_HIDDEN, LSTM_LAYERS, GCN_HIDDEN, M_GRAPHS, K_SUPPORTS = 64, 3, 64, 3, 3
#: any STMGCN_BENCH_* override moves the run off the canonical operating
#: point (shape, iteration count, or schedule set) — such a run must never
#: overwrite a last-good TPU evidence file (canonical or scaled). The
#: watchdog/platform vars only tune backend *probing*, MODE only selects
#: which operating point runs, and the LOCK_* vars only tune measurement
#: *serialization* — none move the point itself, so they don't count (a
#: platform other than tpu never reaches the writes).
CANONICAL_POINT = not any(
    (
        k.startswith("STMGCN_BENCH_")
        and k
        not in (
            "STMGCN_BENCH_WATCHDOG",
            "STMGCN_BENCH_PLATFORM",
            "STMGCN_BENCH_MODE",
            "STMGCN_BENCH_LOCK_WAIT",
            "STMGCN_BENCH_LOCK_PATH",
        )
    )
    # Pallas block-size knobs (ops/pallas_lstm.py) are schedule overrides
    # too — a block-sweep leftover must not become canonical evidence
    or k.startswith("STMGCN_PALLAS_")
    for k in os.environ
)
#: evidence files live next to the baseline anchor
BENCH_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks")


#: the real stdout, captured before measurement aliases sys.stdout to
#: stderr (below): the driver parses stdout as EXACTLY one JSON line, so
#: every other write — retry diagnostics, library chatter, stray prints
#: in anything bench imports — must land on stderr. The in-repo prints
#: all say ``file=sys.stderr`` already; the alias is the backstop for
#: code this script doesn't control.
_RECORD_STREAM = sys.stdout


def _emit(record: dict) -> None:
    """Print the one-line JSON record and exit 0 (driver parses stdout)."""
    try:
        # observability rider: when STMGCN_TRACE_OUT armed the tracer (see
        # main), export the timeline and fold the JAX telemetry into the
        # record. best-effort — obs must never cost the run its record.
        from stmgcn_tpu.obs import jaxmon
        from stmgcn_tpu.obs import trace as obs_trace

        trc = obs_trace.active_tracer()
        if trc is not None or jaxmon.installed():
            record["obs"] = jaxmon.snapshot()
            path = os.environ.get("STMGCN_TRACE_OUT")
            if trc is not None and path:
                record["obs"]["trace_path"] = path
                record["obs"]["trace_spans"] = trc.export_jsonl(path)
    except Exception as e:  # noqa: BLE001 — never block the record line
        print(f"bench: obs rider failed: {e}", file=sys.stderr)
    print(json.dumps(record), file=_RECORD_STREAM, flush=True)
    sys.exit(0)


def _provenance(lock, load_before: dict) -> dict:
    """Host-load provenance for the record: load regime before/after the
    measurement plus the bench-lock outcome. On this 1-core host a
    concurrent probe child depresses throughput 4-20% (BASELINE.md round
    4); this field makes a contended ``vs_baseline`` machine-verifiable
    instead of a prose caveat."""
    from stmgcn_tpu.utils.hostload import host_load_snapshot

    return {
        "before": load_before,
        "after": host_load_snapshot(),
        "lock": lock.record(),
    }


def _probe_backend() -> tuple[Optional[str], Optional[str]]:
    """Probe backend init in a killable child; retry with backoff.

    A wedged TPU tunnel can block the first device op indefinitely *inside
    native code* (signal handlers never run), so the probe happens in a
    child process the parent can time out and kill. Returns
    ``(error, backend_name)``: ``(None, "tpu"|"cpu"|...)`` when the
    backend is healthy (the name is what ``jax.default_backend()``
    resolves to — a host without the TPU plugin probes *successfully* on
    CPU, and callers must not mistake that for a chip), else
    ``(final error string, None)``.
    ``STMGCN_BENCH_WATCHDOG=0`` disables it; any other integer scales the
    first attempt's timeout (later attempts grow: t, 2t, 3t).
    """
    import subprocess

    base = int(os.environ.get("STMGCN_BENCH_WATCHDOG", 45))
    if base <= 0:
        return None, None
    from stmgcn_tpu.utils.hostload import PROBE_SRC as probe

    err = "backend probe never ran"
    timeouts = (base, 2 * base, 3 * base)
    for attempt, timeout_s in enumerate(timeouts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                timeout=timeout_s,
                check=True,
                capture_output=True,
            )
            return None, out.stdout.decode().strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            err = f"backend did not initialize within {timeout_s}s (attempt {attempt + 1})"
        except subprocess.CalledProcessError as e:
            err = "backend probe failed: " + e.stderr.decode()[-300:]
        if attempt + 1 < len(timeouts):
            print(f"bench: {err}; retrying", file=sys.stderr)
            time.sleep(2**attempt)
    return err, None


def _measure(
    dtype: str, unroll: int, fused: bool, backend: str, warmup: int, iters: int
) -> dict:
    """Measure the training step at the canonical point, one schedule/dtype.

    Methodology: ``time_chained`` — N chained steps, one readback fence at
    the end. Per-step ``block_until_ready`` fencing is wrong twice over on
    this image's tunneled TPU: it does not actually wait (measured 1 ms
    "step times" for an 82 ms step), and an honest per-step sync pays a
    ~68 ms tunnel round-trip that is not the device's cost. See
    ``stmgcn_tpu/utils/profiling.py``.
    """
    fns, sup, x, y, mask, flops_kwargs = build_canonical_step(
        dtype, unroll=unroll, fused=fused, backend=backend
    )
    return _run_leg(fns, sup, x, y, mask, warmup, iters, **flops_kwargs)


def _canonical_parts(dtype: str, unroll: int, fused: bool, backend: str):
    """Model/optimizer/dataset at the canonical point — the ONE
    construction shared by the per-step legs (``build_canonical_step``)
    and the superstep leg, so neither can measure a different model."""
    import jax.numpy as jnp

    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import make_optimizer

    seq_len = SERIAL + DAILY + WEEKLY
    data = synthetic_dataset(rows=ROWS, n_timesteps=24 * 7 * 2 + 4 * BATCH, seed=0)
    dataset = DemandDataset(data, WindowSpec(SERIAL, DAILY, WEEKLY, 24))
    supports = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(
        m_graphs=M_GRAPHS,
        n_supports=K_SUPPORTS,
        seq_len=seq_len,
        input_dim=dataset.n_feats,
        lstm_hidden_dim=LSTM_HIDDEN,
        lstm_num_layers=LSTM_LAYERS,
        gcn_hidden_dim=GCN_HIDDEN,
        lstm_unroll=unroll,
        lstm_fused_scan=fused,
        lstm_backend=backend,
        dtype=jnp.bfloat16 if dtype == "bfloat16" else None,
    )
    optimizer = make_optimizer(2e-3, 1e-4)
    sup = jnp.asarray(supports)
    flops_kwargs = dict(
        batch=BATCH,
        seq_len=seq_len,
        n_nodes=dataset.n_nodes,
        n_feats=dataset.n_feats,
        m_graphs=M_GRAPHS,
        n_supports=K_SUPPORTS,
        lstm_hidden_dim=LSTM_HIDDEN,
        lstm_num_layers=LSTM_LAYERS,
        gcn_hidden_dim=GCN_HIDDEN,
    )
    return model, optimizer, dataset, sup, flops_kwargs


def build_canonical_step(
    dtype: str, unroll: int = 1, fused: bool = False, backend: str = "xla"
):
    """The flagship train step's pieces at the canonical operating point.

    Returns ``(fns, sup, x, y, mask, flops_kwargs)`` — the ONE
    construction of the benchmark model/shapes, shared by this script's
    legs and the decomposition/sweep tools under ``benchmarks/`` so they
    can never measure a different model than the headline does.
    """
    import jax.numpy as jnp

    from stmgcn_tpu.train import make_step_fns

    model, optimizer, dataset, sup, flops_kwargs = _canonical_parts(
        dtype, unroll, fused, backend
    )
    fns = make_step_fns(model, optimizer, "mse")

    batch = next(dataset.batches("train", BATCH, pad_last=True))
    x = jnp.asarray(batch.x)
    y = jnp.asarray(batch.y)
    mask = jnp.ones(BATCH, jnp.float32)
    return fns, sup, x, y, mask, flops_kwargs


def _run_leg(fns, sup, x, y, mask, warmup, iters, **flops_kwargs) -> dict:
    """Time one training-step leg (chained-steps methodology, see
    ``_measure``) and assemble its throughput/MFU record. Shared by the
    canonical and scaled modes so the timing methodology cannot diverge."""
    from stmgcn_tpu.utils import (
        device_peak_flops,
        mfu,
        region_timesteps_per_sec,
        stmgcn_step_flops,
        time_chained,
    )
    import jax

    params, opt_state = fns.init(jax.random.key(0), sup, x)
    state = {"params": params, "opt_state": opt_state, "loss": None}

    def step():
        state["params"], state["opt_state"], state["loss"] = fns.train_step(
            state["params"], state["opt_state"], sup, x, y, mask
        )
        return state["loss"]

    step_s = time_chained(step, iters=iters, warmup=warmup)
    return _leg_record(step_s, float(state["loss"]), **flops_kwargs)


def _leg_record(step_s: float, final_loss: float, **flops_kwargs) -> dict:
    """Assemble one leg's throughput/MFU record from its per-step seconds."""
    from stmgcn_tpu.utils import (
        device_peak_flops,
        mfu,
        region_timesteps_per_sec,
        stmgcn_step_flops,
    )

    flops = stmgcn_step_flops(**flops_kwargs)
    peak = device_peak_flops()
    util = mfu(flops, step_s, peak)
    batch, seq_len, n_nodes = (
        flops_kwargs["batch"], flops_kwargs["seq_len"], flops_kwargs["n_nodes"],
    )
    return {
        "value": round(region_timesteps_per_sec(batch, seq_len, n_nodes, step_s), 1),
        "step_ms": round(step_s * 1e3, 3),
        "mfu": round(util, 4) if util is not None else None,
        "model_flops_per_step": flops,
        "peak_flops_bf16": peak,
        "final_loss": final_loss,
    }


def _measure_superstep(dtype: str, warmup: int, iters: int, s_steps: int) -> dict:
    """The superstep leg: S fused train steps per dispatch, tuned schedule.

    Uses the tuned LSTM schedule (unroll=0, fused scan — the best XLA
    per-step leg) so the delta vs ``<dtype>/tuned`` isolates dispatch
    amortization: same math, S-fold fewer host round-trips. The data
    path is the trainer's window-free default: only the raw ``(T, N, C)``
    series plus int32 target/offset vectors stay device-resident, and
    each scan step reconstructs its microbatch on device
    (``gather_window_batch`` from an ``(S, B)`` index block) — exactly
    the ``steps_per_superstep`` path, at ~``seq_len``x less resident
    HBM than materialized windows. ``step_ms``/``value`` are per *train
    step* (superstep time / S) so the variants table stays comparable.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from stmgcn_tpu.train import (
        gather_window_batch,
        make_series_superstep_fns,
        make_step_fns,
    )
    from stmgcn_tpu.utils import time_chained

    if s_steps < 1:
        raise ValueError(f"STMGCN_BENCH_SUPERSTEP must be >= 1, got {s_steps}")
    model, optimizer, dataset, sup, flops_kwargs = _canonical_parts(
        dtype, unroll=0, fused=True, backend="xla"
    )
    horizon = dataset.window.horizon
    fns = make_step_fns(model, optimizer, "mse")
    sfns = make_series_superstep_fns(model, optimizer, "mse", horizon=horizon)

    series = jnp.asarray(dataset.series_stack())
    targets = jnp.asarray(dataset.mode_targets("train"))
    offsets = jnp.asarray(np.asarray(dataset.window.offsets, np.int32))
    index_rows = [
        np.asarray(b.indices, np.int32)
        for b in dataset.batches("train", BATCH, pad_last=True, with_arrays=False)
    ]
    idx_block = jnp.asarray(
        np.stack([index_rows[i % len(index_rows)] for i in range(s_steps)])
    )
    mask_block = jnp.ones((s_steps, BATCH), jnp.float32)

    x0, _ = gather_window_batch(series, targets, offsets, idx_block[0], horizon)
    params, opt_state = fns.init(jax.random.key(0), sup, x0)
    state = {"params": params, "opt_state": opt_state, "loss": None}

    def superstep():
        state["params"], state["opt_state"], state["loss"] = sfns.train_superstep(
            state["params"], state["opt_state"], sup, series, targets, offsets,
            idx_block, mask_block,
        )
        return state["loss"]

    superstep_s = time_chained(superstep, iters=iters, warmup=warmup)
    leg = _leg_record(
        superstep_s / s_steps, float(state["loss"][-1]), **flops_kwargs
    )
    leg["s_steps"] = s_steps
    return leg


def _health_rider() -> dict:
    """Numeric-health rider: the fused superstep with on-device health
    statistics (grad/update norms, nonfinite counts, per-group norms) as
    extra scan outputs vs the plain superstep at the same smoke-scale
    shapes — wall overhead of ``every_k=1`` instrumentation plus
    bit-parity of the trained params. Smoke shapes on purpose: the
    contract under test is "cheap enough to leave on" (<3% wall) and
    "bit-identical when on", not canonical throughput; the canonical
    legs above stay un-instrumented."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import (
        make_optimizer,
        make_series_superstep_fns,
        make_step_fns,
    )
    from stmgcn_tpu.utils import time_chained

    s_steps, batch = 4, 8
    data = synthetic_dataset(rows=5, n_timesteps=24 * 7 * 2 + 4 * batch, seed=0)
    dataset = DemandDataset(data, WindowSpec(SERIAL, DAILY, WEEKLY, 24))
    supports = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(
        m_graphs=M_GRAPHS, n_supports=K_SUPPORTS,
        seq_len=SERIAL + DAILY + WEEKLY, input_dim=dataset.n_feats,
        lstm_hidden_dim=16, lstm_num_layers=1, gcn_hidden_dim=16,
    )
    opt = make_optimizer(2e-3, 1e-4)
    fns = make_step_fns(model, opt, "mse")
    horizon = dataset.window.horizon
    plain = make_series_superstep_fns(model, opt, "mse", horizon=horizon)
    instr = make_series_superstep_fns(
        model, opt, "mse", horizon=horizon, health=True
    )

    series = jnp.asarray(dataset.series_stack())
    targets = jnp.asarray(dataset.mode_targets("train"))
    offsets = jnp.asarray(np.asarray(dataset.window.offsets, np.int32))
    index_rows = [
        np.asarray(b.indices, np.int32)
        for b in dataset.batches("train", batch, pad_last=True, with_arrays=False)
    ]
    idx = jnp.asarray(
        np.stack([index_rows[i % len(index_rows)] for i in range(s_steps)])
    )
    mask = jnp.ones((s_steps, batch), jnp.float32)

    from stmgcn_tpu.train import gather_window_batch

    x0, _ = gather_window_batch(series, targets, offsets, idx[0], horizon)
    params0, opt0 = fns.init(jax.random.key(0), jnp.asarray(supports), x0)
    sup = jnp.asarray(supports)

    # bit-parity: both compiled programs advanced from identical state
    # (copies — the superstep donates its carry)
    def run(step_fn, n=3):
        p = jax.tree.map(jnp.copy, params0)
        o = jax.tree.map(jnp.copy, opt0)
        out = None
        for _ in range(n):
            out = step_fn(p, o, sup, series, targets, offsets, idx, mask)
            p, o = out[0], out[1]
        return jax.device_get(p)

    p_off = run(lambda *a: plain.train_superstep(*a))
    p_on = run(lambda *a: instr.train_superstep(*a))
    parity = all(
        np.array_equal(a, b, equal_nan=True)
        for a, b in zip(jax.tree.leaves(p_off), jax.tree.leaves(p_on))
    )

    def timed(step_fn):
        state = {
            "p": jax.tree.map(jnp.copy, params0),
            "o": jax.tree.map(jnp.copy, opt0),
        }

        def step():
            out = step_fn(
                state["p"], state["o"], sup, series, targets, offsets, idx, mask
            )
            state["p"], state["o"] = out[0], out[1]
            return out[2]

        return time_chained(step, iters=10, warmup=2)

    t_off = timed(lambda *a: plain.train_superstep(*a))
    t_on = timed(lambda *a: instr.train_superstep(*a))
    return {
        "parity": parity,
        "every_k": 1,
        "s_steps": s_steps,
        "superstep_ms_off": round(t_off * 1e3, 3),
        "superstep_ms_on": round(t_on * 1e3, 3),
        "overhead_pct": round(100.0 * (t_on - t_off) / t_off, 2),
    }


def _precision_rider() -> dict:
    """Precision-census rider: the canonical train step dtype-walked
    abstractly (no device execution) by the same engine ``stmgcn lint``
    certifies the contract programs with — bytes/FLOPs by dtype, cast
    count, classified-site count, and the parameter tree's dtype census
    at the headline operating point. The record carries what the
    hardware was actually asked to compute in, so a bf16 migration
    shows up in the bench evidence as a census shift, not a footnote."""
    import jax

    from stmgcn_tpu.analysis.dtype_flow import flow_program
    from stmgcn_tpu.models.params import leaf_dtype_census

    operating = "bfloat16" if DTYPE == "bfloat16" else "float32"
    fns, sup, x, y, mask, _ = build_canonical_step(
        operating, unroll=LSTM_UNROLL, fused=LSTM_FUSED, backend="xla"
    )
    params, opt_state = jax.eval_shape(fns.init, jax.random.key(0), sup, x)
    closed = jax.make_jaxpr(fns.train_step)(params, opt_state, sup, x, y, mask)
    flow = flow_program("bench_train_step", closed)
    return {
        "program": "train_step",
        "operating_dtype": operating,
        "bytes_by_dtype": flow.census["bytes"],
        "flops_by_dtype": flow.census["flops"],
        "casts": flow.census["casts"],
        "sites": len(flow.sites),
        "param_census": leaf_dtype_census(params),
    }


def _precision_superstep_leg(native_tpu: bool) -> dict:
    """The mixed-precision leg: the fused window-free superstep at
    ``precision="bf16"`` (train/step.py's lint-certified twin — bf16
    matmul operands, f32 accumulation islands, f32 master params) vs the
    byte-identical-to-before fp32 program, same shapes, same data, same
    initial state. Reports the per-superstep throughput ratio, the
    final-loss delta after a short training run, and a nonfinite count
    over the bf16 run's losses and trained params. Smoke-scale shapes
    for the same reason as :func:`_health_rider`: the contract is
    "bf16 twins train stably and cheaply", measurable on any host; the
    *speedup* claim only means something where bf16 math is real
    hardware (``native_tpu``) — a CPU host emulates bf16 through f32,
    so its ratio is recorded with ``bf16_native: false`` and the
    record-level ``contended`` flag, never as chip evidence."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import (
        gather_window_batch,
        make_optimizer,
        make_series_superstep_fns,
        make_step_fns,
    )
    from stmgcn_tpu.utils import time_chained

    s_steps, batch = 4, 8
    data = synthetic_dataset(rows=5, n_timesteps=24 * 7 * 2 + 4 * batch, seed=0)
    dataset = DemandDataset(data, WindowSpec(SERIAL, DAILY, WEEKLY, 24))
    supports = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(
        m_graphs=M_GRAPHS, n_supports=K_SUPPORTS,
        seq_len=SERIAL + DAILY + WEEKLY, input_dim=dataset.n_feats,
        lstm_hidden_dim=16, lstm_num_layers=1, gcn_hidden_dim=16,
    )
    opt = make_optimizer(2e-3, 1e-4)
    fns = make_step_fns(model, opt, "mse")
    horizon = dataset.window.horizon
    twins = {
        p: make_series_superstep_fns(
            model, opt, "mse", horizon=horizon, precision=p
        )
        for p in ("fp32", "bf16")
    }

    series = jnp.asarray(dataset.series_stack())
    targets = jnp.asarray(dataset.mode_targets("train"))
    offsets = jnp.asarray(np.asarray(dataset.window.offsets, np.int32))
    index_rows = [
        np.asarray(b.indices, np.int32)
        for b in dataset.batches("train", batch, pad_last=True, with_arrays=False)
    ]
    idx = jnp.asarray(
        np.stack([index_rows[i % len(index_rows)] for i in range(s_steps)])
    )
    mask = jnp.ones((s_steps, batch), jnp.float32)
    x0, _ = gather_window_batch(series, targets, offsets, idx[0], horizon)
    params0, opt0 = fns.init(jax.random.key(0), jnp.asarray(supports), x0)
    sup = jnp.asarray(supports)

    # short training run from identical state (copies — the superstep
    # donates its carry): final loss + nonfinite census per precision
    def train(sfns, n=5):
        p = jax.tree.map(jnp.copy, params0)
        o = jax.tree.map(jnp.copy, opt0)
        losses = []
        for _ in range(n):
            p, o, block = sfns.train_superstep(
                p, o, sup, series, targets, offsets, idx, mask
            )
            losses.append(np.asarray(block))
        return float(losses[-1][-1]), np.concatenate(losses), jax.device_get(p)

    loss32, all32, _ = train(twins["fp32"])
    loss16, all16, p16 = train(twins["bf16"])
    nonfinite = int(np.sum(~np.isfinite(all16))) + sum(
        int(np.sum(~np.isfinite(np.asarray(leaf, np.float32))))
        for leaf in jax.tree.leaves(p16)
    )

    def timed(sfns):
        state = {
            "p": jax.tree.map(jnp.copy, params0),
            "o": jax.tree.map(jnp.copy, opt0),
        }

        def step():
            state["p"], state["o"], loss = sfns.train_superstep(
                state["p"], state["o"], sup, series, targets, offsets, idx, mask
            )
            return loss

        return time_chained(step, iters=10, warmup=2)

    t32 = timed(twins["fp32"])
    t16 = timed(twins["bf16"])
    return {
        "s_steps": s_steps,
        "bf16_native": native_tpu,
        "superstep_ms_fp32": round(t32 * 1e3, 3),
        "superstep_ms_bf16": round(t16 * 1e3, 3),
        "throughput_ratio": round(t32 / t16, 3),
        "final_loss_fp32": loss32,
        "final_loss_bf16": loss16,
        "final_loss_delta": round(abs(loss16 - loss32), 6),
        "nonfinite": nonfinite,
        "master_param_dtypes": sorted(
            {str(np.asarray(leaf).dtype) for leaf in jax.tree.leaves(p16)}
        ),
    }


def _data_residency() -> dict:
    """The canonical point's data-residency story: window-free resident
    bytes vs materialized windows, and the dataset build time with and
    without window materialization. Pure numpy on the host — valid on
    any platform, so it rides along even in cpu-fallback records."""
    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset

    data = synthetic_dataset(rows=ROWS, n_timesteps=24 * 7 * 2 + 4 * BATCH, seed=0)
    t0 = time.perf_counter()
    dataset = DemandDataset(data, WindowSpec(SERIAL, DAILY, WEEKLY, 24))
    build_s = time.perf_counter() - t0
    resident = int(dataset.resident_nbytes)
    t0 = time.perf_counter()
    dataset.materialize()
    materialize_s = time.perf_counter() - t0
    return {
        "resident_bytes": resident,
        "materialized_bytes": int(dataset.nbytes),
        "bytes_ratio": round(dataset.nbytes / resident, 1),
        "build_seconds_window_free": round(build_s, 4),
        "build_seconds_materialized": round(build_s + materialize_s, 4),
    }


def _measure_scaled(sparse: bool, warmup: int, iters: int) -> dict:
    """BASELINE config 3's training step on one chip, dense or block-CSR
    sparse supports (the N=2500 representation crossover — SURVEY.md §7
    hard part 1). Built from ``preset("scaled")`` itself so the measured
    config stays the shipped config (mesh forced single-device: this
    script measures one chip; the sharded story is MULTICHIP's)."""
    import jax
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_dataset, build_model, build_supports
    from stmgcn_tpu.train import make_optimizer, make_step_fns

    cfg = preset("scaled")
    cfg.data.rows = ROWS if "STMGCN_BENCH_ROWS" in os.environ else 50
    if "STMGCN_BENCH_BATCH" in os.environ:
        cfg.train.batch_size = BATCH
    cfg.data.n_timesteps = 24 * 7 * 2 + 4 * cfg.train.batch_size
    cfg.model.sparse = sparse
    cfg.mesh.dp = cfg.mesh.region = 1
    cfg.mesh.region_strategy = "gspmd"

    dataset = build_dataset(cfg)
    supports = build_supports(cfg, dataset)
    model = build_model(cfg, dataset.n_feats)
    fns = make_step_fns(model, make_optimizer(cfg.train.lr, cfg.train.weight_decay), "mse")
    batch = next(dataset.batches("train", cfg.train.batch_size, pad_last=True))
    sup = jax.tree.map(jnp.asarray, supports)
    x, y = jnp.asarray(batch.x), jnp.asarray(batch.y)
    mask = jnp.ones(cfg.train.batch_size, jnp.float32)
    leg = _run_leg(
        fns, sup, x, y, mask, warmup, iters,
        batch=cfg.train.batch_size,
        seq_len=cfg.data.seq_len,
        n_nodes=dataset.n_nodes,
        n_feats=dataset.n_feats,
        m_graphs=cfg.model.m_graphs,
        n_supports=cfg.model.n_supports,
        lstm_hidden_dim=cfg.model.lstm_hidden_dim,
        lstm_num_layers=cfg.model.lstm_num_layers,
        gcn_hidden_dim=cfg.model.gcn_hidden_dim,
    )
    leg.update(
        n_nodes=dataset.n_nodes,
        batch=cfg.train.batch_size,
        dtype=cfg.model.dtype,
    )
    return leg


#: the fleet operating point: 8 heterogeneous cities in two shape
#: classes at the default waste budget — six cities share the N=16 rung
#: (worst member N=14 pads 2/16 of its nodes), two share the N=6 rung
#: exactly. Near-equal member sizes keep rung-padding overcompute small,
#: so the fleet-vs-loop ratio measures what bucketing actually buys
#: (program count + dispatch amortization), not pad arithmetic.
FLEET_CITY_DIMS = (
    (4, 4), (4, 4), (5, 3), (3, 5), (7, 2), (2, 7), (3, 2), (2, 3)
)

#: short serial window for the fleet legs (the canonical point keeps
#: SERIAL=10): a slim forward keeps per-step device compute small so the
#: measured ratio isolates dispatch/loop overhead — the cost the fleet
#: path exists to amortize
FLEET_SERIAL = 3


def _build_fleet_trainer(out_dir: str, *, superstep: int, fleet, window_free):
    """One 8-city heterogeneous trainer at the fleet operating point.

    Slim hidden dims for the same reason as serve-bench's throwaway
    model: the fleet path's win is dispatch amortization (one fused
    program per class instead of a per-city per-step loop), and tiny
    forwards are the regime where dispatch dominates."""
    from stmgcn_tpu.data import HeteroCityDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import CitySupports, Trainer

    datas = [
        synthetic_dataset(rows=r, cols=c, n_timesteps=24 * 7 * 4 + 12 * i,
                          seed=i + 1)
        for i, (r, c) in enumerate(FLEET_CITY_DIMS)
    ]
    dataset = HeteroCityDataset(
        datas, WindowSpec(FLEET_SERIAL, DAILY, WEEKLY, 24)
    )
    sup = CitySupports(
        SupportConfig("chebyshev", 2).build_all(d.adjs.values()) for d in datas
    )
    # slim hidden dims + small batches: the dispatch-dominated regime
    # (serve-bench's throwaway-model rationale) — per-step compute is
    # microseconds, per-step host round-trips are what the per-city loop
    # dies on, and the fused per-class scan removes S of them at a time
    model = STMGCN(
        m_graphs=3, n_supports=3, seq_len=FLEET_SERIAL + DAILY + WEEKLY,
        input_dim=1, horizon=1, lstm_hidden_dim=8, lstm_num_layers=1,
        gcn_hidden_dim=8,
    )
    return Trainer(
        model, dataset, sup, n_epochs=1, batch_size=2,
        steps_per_superstep=superstep, fleet=fleet,
        window_free=window_free, out_dir=out_dir, verbose=False,
    )


def _fleet_leg(trainer, epochs: int) -> dict:
    """Epoch-throughput of one training path: one warmup epoch (compiles
    every program the path needs), then ``epochs`` timed epochs. The
    epoch's final loss reduction reads back on host, so each epoch is
    naturally fenced. Throughput counts REAL demand points — samples x
    seq_len x the city's real node count — so padded rungs never inflate
    the fleet leg's numerator."""
    seq_len = FLEET_SERIAL + DAILY + WEEKLY
    work = sum(
        len(trainer.dataset.mode_targets("train", c)) * seq_len
        * trainer.dataset.city_n_nodes[c]
        for c in range(trainer.dataset.n_cities)
    )
    trainer._run_epoch("train", True)  # warmup: compile + first dispatches
    t0 = time.perf_counter()
    for _ in range(epochs):
        loss = trainer._run_epoch("train", True)
    epoch_s = (time.perf_counter() - t0) / epochs
    return {
        "value": round(work / epoch_s, 1),
        "epoch_ms": round(epoch_s * 1e3, 1),
        "final_loss": round(float(loss), 6),
        "train_path": trainer.train_path,
        "fallback_reason": trainer.fallback_reason,
    }


def _fleet_main(probe_err, native_tpu, lock, load_before) -> None:
    """Fleet-mode record: the fused per-class superstep vs the
    materialized per-city loop on the same 8-city fleet.

    Both trainers consume identical data with identical math (the loop
    IS the fleet path's bit-parity oracle, tests/test_fleet.py), so the
    throughput ratio isolates what shape-class bucketing buys: one
    compiled program per class + S fused steps per dispatch, against one
    program per city dispatched per step."""
    import shutil
    import tempfile

    import jax

    from stmgcn_tpu.utils.hostload import is_contended

    results, measure_err = {}, None
    epochs = 3 if native_tpu else 1
    tmp = tempfile.mkdtemp(prefix="stmgcn_fleet_bench_")
    plan = None
    try:
        for name, kwargs in (
            ("fleet_superstep", dict(superstep=FLEET_S, fleet=None,
                                     window_free=None)),
            ("per_city_loop", dict(superstep=1, fleet=False,
                                   window_free=False)),
        ):
            try:
                t = _build_fleet_trainer(
                    os.path.join(tmp, name), **kwargs
                )
                if name == "fleet_superstep":
                    plan = t._fleet_plan
                results[name] = _fleet_leg(t, epochs)
            except Exception as e:
                measure_err = f"{name}: {type(e).__name__}: {e}"
                print(f"bench: fleet measurement failed for {measure_err}",
                      file=sys.stderr)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if not results:
        raise RuntimeError(measure_err or "no fleet configuration measured")

    host_load = _provenance(lock, load_before)
    contended = is_contended(host_load)
    fast = results.get("fleet_superstep")
    slow = results.get("per_city_loop")
    record = {
        "metric": "region-timesteps/sec/chip",
        "operating_point": "fleet-8city",
        "value": (fast or slow)["value"],
        "unit": "region-timesteps/s",
        # the torch anchor exists only at the canonical 16x16 point; this
        # record's comparison axis is fused-fleet vs per-city loop
        "vs_baseline": None,
        "fleet_vs_per_city": (
            round(fast["value"] / slow["value"], 2) if fast and slow else None
        ),
        "s_steps": FLEET_S,
        "n_cities": len(FLEET_CITY_DIMS),
        "shape_classes": (
            [
                {
                    "n_nodes": c.n_nodes,
                    "cities": list(c.cities),
                    "node_waste": round(c.node_waste, 4),
                }
                for c in plan.classes
            ]
            if plan is not None
            else None
        ),
        "pad_waste": round(plan.node_waste, 4) if plan is not None else None,
        "device": jax.devices()[0].device_kind,
        "variants": results,
        "host_load": host_load,
        "contended": contended,
    }
    if probe_err is not None:
        record["platform"] = "cpu-fallback"
        record["error"] = probe_err
    elif measure_err is not None:
        record["error"] = measure_err
    path = os.path.join(BENCH_DIR, "tpu_fleet_last_good.json")
    if (
        native_tpu
        and len(results) == 2
        and measure_err is None
        and CANONICAL_POINT
        and lock.acquired
        and not contended
    ):
        # same host-contention policy as the canonical/scaled snapshots:
        # only a clean on-chip table at the shipped operating point,
        # measured while holding the bench lock with no competing
        # process, becomes last-good evidence
        snapshot = dict(record)
        snapshot["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        snapshot["measurement"] = {"epochs": epochs}
        try:
            with open(path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError as e:
            print(f"bench: could not persist fleet last-good: {e}",
                  file=sys.stderr)
    _emit(record)


#: multichip legs: one dp fleet preset + one banded region preset, the
#: two 8-device shapes parallel/compose.py certifies
MULTICHIP_PRESETS = ("multicity", "scaled")


def _multichip_leg(trainer, epochs: int) -> dict:
    """Epoch-throughput of one composed (or single-device twin) trainer:
    one warmup epoch compiles the program, then ``epochs`` timed epochs.
    Work counts REAL demand points — samples x seq_len x node count, per
    city on the hetero fleet — so padded rungs never inflate the ratio."""
    ds = trainer.dataset
    seq_len = ds.window.seq_len
    if hasattr(ds, "city_n_nodes"):
        work = sum(
            len(ds.mode_targets("train", c)) * seq_len * ds.city_n_nodes[c]
            for c in range(ds.n_cities)
        )
    else:
        work = len(ds.mode_targets("train")) * seq_len * ds.n_nodes
    trainer._run_epoch("train", True)  # warmup: compile + first dispatches
    t0 = time.perf_counter()
    for _ in range(epochs):
        loss = trainer._run_epoch("train", True)
    epoch_s = (time.perf_counter() - t0) / epochs
    return {
        "value": round(work / epoch_s, 1),
        "epoch_ms": round(epoch_s * 1e3, 1),
        "final_loss": round(float(loss), 6),
        "train_path": trainer.train_path,
        "fallback_reason": trainer.fallback_reason,
    }


def _multichip_main(probe_err, native_tpu, lock, load_before) -> None:
    """Multichip-mode record: the composed mesh programs (dp-sharded
    fleet + banded region) vs single-device builds of the same configs.

    Off-TPU the 8 "chips" are XLA virtual host devices time-slicing one
    CPU core, so ``vs_single_device`` is expected < 1.0 there — recorded
    honestly with ``n_devices``/``virtual_devices`` provenance, and kept
    out of ``vs_baseline`` until an on-chip run exists (the same policy
    that keeps contended host runs out of the baseline table)."""
    import shutil
    import tempfile

    import jax

    from stmgcn_tpu.config import MeshConfig
    from stmgcn_tpu.experiment import build_trainer
    from stmgcn_tpu.parallel.compose import composed_config, composed_trainer
    from stmgcn_tpu.utils.hostload import is_contended

    results, measure_err = {}, None
    epochs = 3 if native_tpu else 1
    tmp = tempfile.mkdtemp(prefix="stmgcn_multichip_bench_")
    try:
        for name in MULTICHIP_PRESETS:
            try:
                mesh_t = composed_trainer(
                    name, out_dir=os.path.join(tmp, f"{name}_mesh")
                )
                # the single-device leg reuses the composed config with the
                # mesh cleared: same data, same model dims, the program the
                # trainer would dispatch on one chip (for banded presets
                # this is NOT a bit-parity twin — compose.parity_twin_kind
                # — but it IS the deployment question the ratio answers)
                cfg = composed_config(name)
                cfg.mesh = MeshConfig()
                cfg.train.out_dir = os.path.join(tmp, f"{name}_single")
                single_t = build_trainer(cfg, verbose=False)
                legs = {
                    "composed": _multichip_leg(mesh_t, epochs),
                    "single_device": _multichip_leg(single_t, epochs),
                }
                legs["composed"]["program"] = mesh_t.train_path
                legs["mesh"] = {
                    k: int(v) for k, v in mesh_t.placement.mesh.shape.items()
                }
                legs["vs_single_device"] = round(
                    legs["composed"]["value"] / legs["single_device"]["value"],
                    3,
                )
                results[name] = legs
            except Exception as e:
                measure_err = f"{name}: {type(e).__name__}: {e}"
                print(
                    f"bench: multichip measurement failed for {measure_err}",
                    file=sys.stderr,
                )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if not results:
        raise RuntimeError(measure_err or "no multichip configuration measured")

    host_load = _provenance(lock, load_before)
    contended = is_contended(host_load)
    first = results.get(MULTICHIP_PRESETS[0]) or next(iter(results.values()))
    record = {
        "metric": "region-timesteps/sec/chip",
        "operating_point": "multichip-8dev",
        "value": first["composed"]["value"],
        "unit": "region-timesteps/s",
        # the torch anchor exists only at the canonical single-device
        # point; this record's comparison axis is composed-mesh vs
        # single-device, and it joins the baseline table only on-chip
        "vs_baseline": None,
        "n_devices": jax.device_count(),
        "virtual_devices": not native_tpu,
        "device": jax.devices()[0].device_kind,
        "variants": results,
        "host_load": host_load,
        "contended": contended,
    }
    if probe_err is not None:
        record["platform"] = "cpu-fallback"
        record["error"] = probe_err
    elif measure_err is not None:
        record["error"] = measure_err
    path = os.path.join(BENCH_DIR, "tpu_multichip_last_good.json")
    if (
        native_tpu
        and len(results) == len(MULTICHIP_PRESETS)
        and measure_err is None
        and lock.acquired
        and not contended
    ):
        # same host-contention policy as the other snapshots: only a
        # clean on-chip 8-device table, measured under the bench lock,
        # becomes last-good evidence
        snapshot = dict(record)
        snapshot["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        snapshot["measurement"] = {"epochs": epochs}
        try:
            with open(path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError as e:
            print(f"bench: could not persist multichip last-good: {e}",
                  file=sys.stderr)
    _emit(record)


def _scaled_main(probe_err, native_tpu, lock, load_before) -> None:
    """Scaled-mode record: dense vs block-CSR sparse at BASELINE config 3.

    Off-TPU the sparse leg is dropped entirely — its block-CSR SpMM would
    run in Pallas interpret mode at N=2500 (orders of magnitude slow), the
    same reason canonical mode drops its pallas leg — and the dense leg
    runs with tiny warmup/iters. Measured legs key off ``native_tpu``, not
    just the probe result: a host without the TPU plugin probes
    *successfully* on CPU.
    """
    results, measure_err = {}, None
    warmup, iters = (WARMUP, ITERS) if native_tpu else (1, 2)
    reps = ("dense", "sparse") if native_tpu else ("dense",)
    for rep in reps:
        try:
            results[rep] = _measure_scaled(rep == "sparse", warmup, iters)
        except Exception as e:
            measure_err = f"{rep}: {type(e).__name__}: {e}"
            print(f"bench: scaled measurement failed for {measure_err}", file=sys.stderr)
    if not results:
        raise RuntimeError(measure_err or "no scaled configuration measured")
    import jax

    from stmgcn_tpu.utils.hostload import is_contended

    head = max(results, key=lambda k: results[k]["value"])
    host_load = _provenance(lock, load_before)
    contended = is_contended(host_load)
    record = {
        "metric": "region-timesteps/sec/chip",
        "operating_point": "scaled-n2500",
        "value": results[head]["value"],
        "unit": "region-timesteps/s",
        # the torch anchor exists only at the canonical 16x16 point; this
        # record's comparison axis is dense-vs-sparse at N=2500
        "vs_baseline": None,
        "support_representation": head,
        "step_ms": results[head]["step_ms"],
        "mfu": results[head]["mfu"],
        "device": jax.devices()[0].device_kind,
        "variants": results,
        "host_load": host_load,
        "contended": contended,
    }
    if probe_err is not None:
        record["platform"] = "cpu-fallback"
        record["error"] = probe_err
    elif measure_err is not None:
        record["error"] = measure_err
    path = os.path.join(BENCH_DIR, "tpu_scaled_last_good.json")
    if (
        native_tpu
        and len(results) == 2
        and measure_err is None
        and CANONICAL_POINT
        and lock.acquired
        and not contended
    ):
        # same rule as the canonical snapshot: a clean on-chip table AT THE
        # SHIPPED OPERATING POINT (no STMGCN_BENCH_* shape/iter overrides),
        # measured while HOLDING the bench lock with no competing process
        # (a known-contended run must not overwrite good evidence),
        # becomes evidence
        snapshot = dict(record)
        snapshot["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        snapshot["measurement"] = {"warmup": warmup, "iters": iters}
        try:
            with open(path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError as e:
            print(f"bench: could not persist scaled last-good: {e}", file=sys.stderr)
    _emit(record)


#: largeN operating point: one metro-scale city on a ``rows x 2*rows``
#: region grid — the default 64x128 grid is N=8192, the "whole-metro-
#: area" city class ROADMAP item 2 names. STMGCN_BENCH_LARGEN_ROWS
#: shrinks it for validating the mode's logic on slow hosts (any
#: override moves the run off the canonical point, so it never
#: overwrites last-good evidence).
LARGEN_ROWS = int(os.environ.get("STMGCN_BENCH_LARGEN_ROWS", 64))
#: the shipped plan tile: one MXU-native (128, 128) block per kept tile
LARGEN_TILE = 128
#: tiny batch + short serial window: at N=8192 one dense support apply
#: is ~1e9 MACs per timestep per branch, so the dense oracle leg is only
#: measurable on the CPU-fallback host if everything else stays slim
LARGEN_BATCH = 2
LARGEN_SERIAL = 3


def _largen_city(rows: int, cols: int, n_timesteps: int, seed: int = 0):
    """Synthetic metro city with three STRUCTURED sparse graphs.

    ``synthetic_dataset``'s transport graph draws uniform random links —
    fine for training tests, fatal for a bandwidth-reducing reorder: a
    handful of uniform long-range edges weld distant grid regions
    together and the condensed plan degenerates toward dense (the same
    reason tests/test_tiling.py's condensation fixtures are noise-free).
    Real metro graphs are not uniform — transit lines follow corridors
    and functional similarity clusters by district — so this builder
    generates that structure:

    - spatial: grid rook adjacency (degree <= 4);
    - transport: transit lines along every 8th row/column with stops
      every 4 cells, consecutive stops linked — sparse corridor paths;
    - similarity: top-3 demand-profile similarity *within 8x8 districts*
      — functionally similar regions cluster spatially.
    """
    import numpy as np

    from stmgcn_tpu.data.loader import ADJ_KEYS, DemandData
    from stmgcn_tpu.data.synthetic import grid_adjacency, synthetic_demand

    n = rows * cols
    demand = synthetic_demand(n_timesteps, n, 1, 24, seed)

    trans = np.zeros((n, n), np.float32)

    def _line(ids):
        for a, b in zip(ids, ids[1:]):
            trans[a, b] = trans[b, a] = 1.0

    for r in range(0, rows, 8):
        _line([r * cols + c for c in range(0, cols, 4)])
    for c in range(0, cols, 8):
        _line([r * cols + c for r in range(0, rows, 4)])

    profile = demand[:, :, 0].T  # (N, T)
    profile = profile - profile.mean(axis=1, keepdims=True)
    norms = np.linalg.norm(profile, axis=1, keepdims=True)
    norms[norms == 0] = 1.0
    profile = profile / norms
    sim = np.zeros((n, n), np.float32)
    for r0 in range(0, rows, 8):
        for c0 in range(0, cols, 8):
            ids = np.array(
                [r * cols + c
                 for r in range(r0, min(r0 + 8, rows))
                 for c in range(c0, min(c0 + 8, cols))]
            )
            s = profile[ids] @ profile[ids].T
            np.fill_diagonal(s, -np.inf)
            top = np.argsort(s, axis=1)[:, -3:]
            for i, js in enumerate(top):
                sim[ids[i], ids[js]] = 1.0
    sim = np.maximum(sim, sim.T)

    return DemandData(
        demand=demand,
        adjs={
            ADJ_KEYS[0]: grid_adjacency(rows, cols),
            ADJ_KEYS[1]: trans,
            ADJ_KEYS[2]: sim,
        },
    )


def _build_largen_trainer(out_dir: str, dataset, supports, *, tiled: bool):
    """One large-N trainer; identical model/optimizer/step path for both
    support representations, so the epoch ratio isolates the support
    apply. Slim LSTM/GCN hidden dims: at N=8192 the K-support
    propagation dominates the step regardless, and slim everything-else
    keeps the dense oracle leg measurable on the CPU-fallback host."""
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.train import Trainer

    model = STMGCN(
        m_graphs=M_GRAPHS, n_supports=K_SUPPORTS,
        seq_len=LARGEN_SERIAL + DAILY + WEEKLY, input_dim=1, horizon=1,
        lstm_hidden_dim=4, lstm_num_layers=1, gcn_hidden_dim=4,
        support_modes=("tiled",) * M_GRAPHS if tiled else None,
    )
    return Trainer(
        model, dataset, supports, n_epochs=1, batch_size=LARGEN_BATCH,
        steps_per_superstep=2, window_free=True, out_dir=out_dir,
        verbose=False,
    )


def _largen_leg(trainer, epochs: int) -> dict:
    """Epoch-throughput of one support representation — same fencing and
    demand-point accounting as :func:`_fleet_leg` (one warmup epoch
    compiles every program, the epoch's final loss readback fences each
    timed epoch)."""
    seq_len = LARGEN_SERIAL + DAILY + WEEKLY
    work = (
        len(trainer.dataset.mode_targets("train")) * seq_len
        * trainer.dataset.n_nodes
    )
    trainer._run_epoch("train", True)  # warmup: compile + first dispatches
    t0 = time.perf_counter()
    for _ in range(epochs):
        loss = trainer._run_epoch("train", True)
    epoch_s = (time.perf_counter() - t0) / epochs
    return {
        "value": round(work / epoch_s, 1),
        "epoch_ms": round(epoch_s * 1e3, 1),
        "final_loss": round(float(loss), 6),
        "train_path": trainer.train_path,
        "fallback_reason": trainer.fallback_reason,
    }


def _largen_main(probe_err, native_tpu, lock, load_before) -> None:
    """largeN-mode record: tiled-sparse vs dense supports at metro scale.

    One N=8192 city with structured sparse graphs (:func:`_largen_city`),
    one offline :func:`~stmgcn_tpu.ops.tiling.plan_tiling` pass covering
    all M x K supports, then the SAME window-free superstep trainer once
    per support representation — the epoch ratio is the tiled path's
    claim (ROADMAP item 2): support-apply work proportional to kept
    blocks, not N^2. A serve leg times the compiled forward program each
    representation dispatches per serving rung, and a parity probe pins
    the tiled forward against the dense oracle at shared params (the
    bit-level engine parity is tests/test_tiling.py's job; the bench
    records max |delta| at this operating point). Off-TPU both legs run
    the gathered-tiles XLA path — pallas would be interpret-mode — which
    is exactly the measurable CPU-host comparison the acceptance bar
    names; on a real chip the tiled leg routes to the fused Pallas
    ``spmm_stack`` kernel automatically (``backend="auto"``)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from stmgcn_tpu.data import DemandDataset, WindowSpec
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.ops.tiling import plan_tiling
    from stmgcn_tpu.utils import time_chained
    from stmgcn_tpu.utils.hostload import is_contended

    rows, cols = LARGEN_ROWS, 2 * LARGEN_ROWS
    # just enough history for a weekly window + a handful of train steps:
    # at N=8192 every extra dense train step costs ~1e11 FLOPs of
    # measurement wall-clock on the CPU-fallback host
    data = _largen_city(rows, cols, n_timesteps=24 * 7 + 14)
    dataset = DemandDataset(data, WindowSpec(LARGEN_SERIAL, DAILY, WEEKLY, 24))
    dense = np.asarray(
        SupportConfig("chebyshev", K_SUPPORTS - 1).build_all(
            dataset.adjs.values()
        ),
        np.float32,
    )
    plan = plan_tiling(dense, tile=LARGEN_TILE)
    stats = plan.tile_stats()

    results, trainers, measure_err = {}, {}, None
    epochs = 3 if native_tpu else 1
    serve_warmup, serve_iters = (WARMUP, ITERS) if native_tpu else (1, 2)
    hist = None
    tmp = tempfile.mkdtemp(prefix="stmgcn_largen_bench_")
    try:
        for name in ("tiled", "dense"):
            try:
                sup = plan if name == "tiled" else jnp.asarray(dense)
                t = _build_largen_trainer(
                    os.path.join(tmp, name), dataset, sup,
                    tiled=name == "tiled",
                )
                leg = _largen_leg(t, epochs)
                if hist is None:
                    hist = jnp.asarray(next(iter(dataset.batches(
                        "validate", LARGEN_BATCH, pad_last=True
                    ))).x)
                apply = jax.jit(t.model.apply)
                apply(t.params, sup, hist).block_until_ready()  # compile
                serve_s = time_chained(
                    lambda: apply(t.params, sup, hist),
                    iters=serve_iters, warmup=serve_warmup,
                )
                leg["serve_ms"] = round(serve_s * 1e3, 2)
                results[name] = leg
                trainers[name] = (t, sup, apply)
            except Exception as e:
                measure_err = f"{name}: {type(e).__name__}: {e}"
                print(f"bench: largeN measurement failed for {measure_err}",
                      file=sys.stderr)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if not results:
        raise RuntimeError(measure_err or "no largeN configuration measured")

    parity = None
    if len(trainers) == 2 and hist is not None:
        # the DENSE-trained params through BOTH representations: the
        # tiled serving clone (models/params.to_tiled_serving, the same
        # converter the serving engine uses for a tiled city) unstacks
        # the vmapped dense checkpoint to the loop layout, so the output
        # delta is purely the support representation
        from stmgcn_tpu.models.params import to_tiled_serving

        t_dense, sup_d, apply_d = trainers["dense"]
        model_t, params_t = to_tiled_serving(
            t_dense.model, t_dense.params, M_GRAPHS
        )
        parity = float(jnp.max(jnp.abs(
            apply_d(t_dense.params, sup_d, hist)
            - jax.jit(model_t.apply)(params_t, plan, hist)
        )))

    host_load = _provenance(lock, load_before)
    contended = is_contended(host_load)
    fast, slow = results.get("tiled"), results.get("dense")
    ratio = round(fast["value"] / slow["value"], 2) if fast and slow else None
    serve_ratio = (
        round(slow["serve_ms"] / fast["serve_ms"], 2) if fast and slow else None
    )
    density = stats["density"]
    flop_reduction = round(1.0 / stats["flops_ratio"], 2)
    record = {
        "metric": "region-timesteps/sec/chip",
        "operating_point": f"largeN-n{dataset.n_nodes}",
        "value": (fast or slow)["value"],
        "unit": "region-timesteps/s",
        # the torch anchor exists only at the canonical 16x16 point; this
        # record's comparison axis is tiled-sparse vs dense at metro N
        "vs_baseline": None,
        "tiled_vs_dense": ratio,
        "serve_tiled_vs_dense": serve_ratio,
        "parity_max_abs": parity,
        "tile": LARGEN_TILE,
        "tile_stats": {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in stats.items()
        },
        "support_apply_flop_reduction": flop_reduction,
        # ISSUE 13 acceptance: the tiled leg must beat dense by >= half
        # the density ratio in support-apply FLOPs, and by >= 3x wall on
        # the CPU host when the plan is <=10% dense (scaled down pro rata
        # for denser plans)
        "acceptance": {
            "required_flop_reduction": round(0.5 / density, 2),
            "met_flops": bool(flop_reduction >= 0.5 / density),
            "required_wall_ratio": round(min(3.0, 0.5 / density), 2),
            "met_wall": (
                None if ratio is None
                else bool(ratio >= min(3.0, 0.5 / density))
            ),
        },
        "device": jax.devices()[0].device_kind,
        "variants": results,
        "host_load": host_load,
        "contended": contended,
    }
    if probe_err is not None:
        record["platform"] = "cpu-fallback"
        record["error"] = probe_err
    elif measure_err is not None:
        record["error"] = measure_err
    path = os.path.join(BENCH_DIR, "tpu_largen_last_good.json")
    if (
        native_tpu
        and len(results) == 2
        and measure_err is None
        and CANONICAL_POINT
        and lock.acquired
        and not contended
    ):
        # same host-contention policy as the canonical/scaled/fleet
        # snapshots: only a clean on-chip table at the shipped operating
        # point, measured while holding the bench lock with no competing
        # process, becomes last-good evidence
        snapshot = dict(record)
        snapshot["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        snapshot["measurement"] = {
            "epochs": epochs, "serve_iters": serve_iters,
        }
        try:
            with open(path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError as e:
            print(f"bench: could not persist largeN last-good: {e}",
                  file=sys.stderr)
    _emit(record)


def main() -> None:
    if MODE not in ("canonical", "scaled", "fleet", "largeN", "multichip"):
        raise SystemExit(
            f"STMGCN_BENCH_MODE must be canonical|scaled|fleet|largeN|"
            f"multichip, got {MODE!r}"
        )
    if DTYPE not in ("float32", "bfloat16", "both"):
        raise SystemExit(
            f"STMGCN_BENCH_DTYPE must be float32|bfloat16|both, got {DTYPE!r}"
        )
    from stmgcn_tpu.utils import force_host_platform
    from stmgcn_tpu.utils.hostload import measurement_preamble

    if os.environ.get("STMGCN_TRACE_OUT"):
        # STMGCN_TRACE_OUT (deliberately not STMGCN_BENCH_*: tracing does
        # not move the operating point, and we prove <=2% overhead) arms
        # the span ring + jax.monitoring before the first compile; _emit
        # exports the timeline and adds record["obs"]
        from stmgcn_tpu.obs import jaxmon
        from stmgcn_tpu.obs import trace as obs_trace

        obs_trace.configure()
        jaxmon.install()

    # Serialize against the tunnel-probe loop (and any other bench) before
    # measuring anything: on this 1-core host the competing process IS the
    # measurement error. The shared preamble acquires the host-wide lock
    # (proceeding flagged-but-unblocked on timeout — lock.record() says
    # who held it), drains lingering — possibly unkillable D-state —
    # probe children (one depressed the round-5 driver sim ~10%; its
    # host_load field caught it), and snapshots the load regime.
    lock, load_before = measurement_preamble()

    # STMGCN_BENCH_PLATFORM=cpu pins the host platform (skipping the TPU
    # probe entirely) — for validating the full success path on hosts
    # where the axon plugin would otherwise be dialed.
    pinned = os.environ.get("STMGCN_BENCH_PLATFORM")
    if pinned:
        force_host_platform(pinned)
        probe_err, probed_backend = None, pinned
    else:
        probe_err, probed_backend = _probe_backend()
    if probe_err is not None:
        # TPU unreachable: measure on the host CPU instead of recording nothing.
        force_host_platform("cpu")
    if MODE == "multichip" and probed_backend != "tpu":
        # The multichip legs need 8 devices; off-TPU they run on the
        # 8-virtual-device host substrate (same as tests/conftest.py),
        # which must be pinned before the in-process backend initializes.
        force_host_platform("cpu", n_devices=8)

    dtypes = ("float32", "bfloat16") if DTYPE == "both" else (DTYPE,)
    # The pallas leg is only a measurement on a real TPU: anywhere else the
    # kernel runs in interpret mode (correct but orders of magnitude slow).
    # Keyed off the *resolved* backend the probe child reported (or the
    # pinned platform): a host whose probe succeeds on CPU because the TPU
    # plugin is absent must drop the leg just like a pinned-CPU run.
    if probed_backend is None and probe_err is None:
        # Watchdog disabled (STMGCN_BENCH_WATCHDOG=0): no probe child ran,
        # so resolve the backend in-process — disabling the watchdog must
        # not change which schedules get measured on a real TPU.
        import jax

        probed_backend = jax.default_backend()
    native_tpu = probe_err is None and probed_backend == "tpu"
    if MODE == "scaled":
        _scaled_main(probe_err, native_tpu, lock, load_before)  # emits + exits
        return
    if MODE == "fleet":
        _fleet_main(probe_err, native_tpu, lock, load_before)  # emits + exits
        return
    if MODE == "largeN":
        _largen_main(probe_err, native_tpu, lock, load_before)  # emits + exits
        return
    if MODE == "multichip":
        _multichip_main(probe_err, native_tpu, lock, load_before)  # emits + exits
        return
    if CUSTOM_SCHEDULE:
        if LSTM_BACKEND == "pallas" and not native_tpu:
            # interpret-mode pallas at the canonical shapes never finishes;
            # emit a parsable refusal instead of hanging the caller
            _emit(
                {
                    "metric": "region-timesteps/sec/chip",
                    "value": 0.0,
                    "unit": "region-timesteps/s",
                    "vs_baseline": None,
                    "error": "STMGCN_BENCH_LSTM_BACKEND=pallas needs a real "
                    f"TPU (resolved backend: {probed_backend!r}); the kernel "
                    "would run in interpret mode here",
                }
            )
        schedules = {"custom": (LSTM_UNROLL, LSTM_FUSED, LSTM_BACKEND)}
    else:
        schedules = {
            "plain": (1, False, "xla"),
            "tuned": (0, True, "xla"),
        }
        if native_tpu:
            schedules["pallas"] = (1, False, "pallas")
    if probe_err is not None:
        # CPU fallback: fp32 only (unless asked), but keep BOTH XLA
        # schedules — recording only the untuned leg made round 2's
        # fallback record understate even the CPU capability.
        if "STMGCN_BENCH_DTYPE" not in os.environ:
            dtypes = ("float32",)

    results = {}
    measure_err = None
    for d in dtypes:
        for sched, (unroll, fused, backend) in schedules.items():
            # CPU fallback: 5 iters (not 3) tightens the ~11 s/step legs
            # from ±5% to ~±2% for one extra minute of wall-clock
            warmup, iters = (1, 5) if probe_err is not None else (WARMUP, ITERS)
            try:
                results[f"{d}/{sched}"] = _measure(
                    d, unroll, fused, backend, warmup, iters
                )
            except Exception as e:  # keep surviving legs: one bad leg must
                measure_err = f"{d}/{sched}: {type(e).__name__}: {e}"  # not void all
                print(f"bench: measurement failed for {measure_err}", file=sys.stderr)
    if not CUSTOM_SCHEDULE and "float32" in dtypes:
        # the superstep leg (S fused steps per dispatch over the tuned
        # schedule); iteration counts scale down by S so the leg runs a
        # comparable number of real train steps to the per-step legs
        warmup, iters = (1, 2) if probe_err is not None else (2, max(2, ITERS // SUPERSTEP))
        try:
            results["float32/superstep"] = _measure_superstep(
                "float32", warmup, iters, SUPERSTEP
            )
        except Exception as e:
            measure_err = f"float32/superstep: {type(e).__name__}: {e}"
            print(f"bench: measurement failed for {measure_err}", file=sys.stderr)
    if not results:
        raise RuntimeError(measure_err or "no configuration measured")

    # Headline: the fastest measured leg. Schedules are numerically
    # identical; dtypes are not (bf16 vs fp32) — the headline's dtype is
    # recorded and a like-for-like fp32 ratio is emitted alongside.
    head_key = max(results, key=lambda k: results[k]["value"])
    primary = results[head_key]
    head_dtype, head_sched = head_key.split("/")

    # Post-measurement load regime, captured BEFORE the ratio math: a
    # contended record keeps its measurements but its baseline ratios are
    # nulled — on this 1-core host a competing process depresses
    # throughput 4-20%, so the ratio would compare against the anchor
    # with a thumb on the scale.
    from stmgcn_tpu.utils.hostload import is_contended

    host_load = _provenance(lock, load_before)
    contended = is_contended(host_load)

    vs_baseline = None
    vs_baseline_fp32 = None
    baseline = None
    baseline_path = os.path.join(BENCH_DIR, "baseline.json")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        ref = base.get("torch_cpu_region_ts_per_sec")
        baseline = {
            "device": base.get("device"),
            "threads": base.get("threads"),
            "dtype": "float32",
            "value": round(ref, 1) if ref else None,
        }
        shapes = base.get("shapes", {})
        shapes_match = (
            shapes.get("rows") == ROWS
            and shapes.get("batch") == BATCH
            and shapes.get("seq_len") == SERIAL + DAILY + WEEKLY
        )
        if ref and shapes_match and not contended:
            # headline ratio may cross dtypes (bf16 chip leg vs fp32 torch
            # anchor — a real capability of the hardware, and the record
            # carries both dtypes); the like-for-like fp32 ratio is
            # reported alongside so neither reading is ambiguous.
            vs_baseline = primary["value"] / ref
            fp32_best = max(
                (r["value"] for k, r in results.items() if k.startswith("float32/")),
                default=None,
            )
            vs_baseline_fp32 = fp32_best / ref if fp32_best else None

    import math

    import jax

    loss = primary["final_loss"]
    record = {
        "metric": "region-timesteps/sec/chip",
        "value": primary["value"],
        "unit": "region-timesteps/s",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline is not None else None,
        "vs_baseline_fp32": (
            round(vs_baseline_fp32, 2) if vs_baseline_fp32 is not None else None
        ),
        "dtype": head_dtype,
        "lstm_schedule": head_sched,
        "step_ms": primary["step_ms"],
        "mfu": primary["mfu"],
        "device": jax.devices()[0].device_kind,
        "model_flops_per_step": primary["model_flops_per_step"],
        "peak_flops_bf16": primary["peak_flops_bf16"],
        # bare NaN/Inf would make the one output line unparsable to strict
        # JSON readers — exactly the failure this script must never have
        "final_loss": loss if math.isfinite(loss) else None,
        "baseline": baseline,
        "variants": {
            k: {
                "value": r["value"], "step_ms": r["step_ms"], "mfu": r["mfu"],
                **({"s_steps": r["s_steps"]} if "s_steps" in r else {}),
            }
            for k, r in results.items()
        },
        "host_load": host_load,
        "contended": contended,
    }
    try:
        record["data_residency"] = _data_residency()
    except Exception as e:  # the residency story must not void the record
        print(f"bench: data_residency failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # numeric-health contract evidence: every_k=1 instrumentation
        # overhead + bit-parity at smoke shapes (see _health_rider)
        record["health"] = _health_rider()
    except Exception as e:  # the health story must not void the record
        print(f"bench: health rider failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # precision-census evidence: the canonical step's dtype census at
        # the headline operating point (see _precision_rider)
        record["precision"] = _precision_rider()
    except Exception as e:  # the precision story must not void the record
        print(f"bench: precision rider failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    try:
        # mixed-precision training evidence: bf16-twin vs fp32 superstep
        # throughput ratio + final-loss delta + nonfinite census (see
        # _precision_superstep_leg; a CPU host's ratio carries
        # bf16_native: false and the record's contended flag)
        record["precision_superstep"] = _precision_superstep_leg(native_tpu)
    except Exception as e:  # must not void the record
        print(f"bench: precision superstep leg failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    if probe_err is not None:
        record["platform"] = "cpu-fallback"
        record["error"] = probe_err
    elif measure_err is not None:
        record["error"] = measure_err

    # Evidence persistence: a successful on-chip measurement is written to
    # benchmarks/tpu_last_good.json so a later wedged tunnel cannot erase
    # the round's TPU numbers; any non-TPU record carries the last good
    # on-chip table inline (with its own timestamp + device provenance).
    last_good_path = os.path.join(BENCH_DIR, "tpu_last_good.json")
    if (
        native_tpu
        and results
        and measure_err is None
        and CANONICAL_POINT
        and lock.acquired
        and not contended
    ):
        # only a fully-clean on-chip run AT THE CANONICAL OPERATING POINT,
        # measured while HOLDING the bench lock AND free of competing
        # processes, becomes canonical evidence — a run with failed legs,
        # STMGCN_BENCH_* shape/schedule overrides, or known host
        # contention must not overwrite the last good one (later
        # cpu-fallback records inline this file)
        snapshot = dict(record)
        snapshot["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        snapshot["operating_point"] = {
            "rows": ROWS,
            "batch": BATCH,
            "seq_len": SERIAL + DAILY + WEEKLY,
            "warmup": WARMUP,
            "iters": ITERS,
        }
        try:
            with open(last_good_path, "w") as f:
                json.dump(snapshot, f, indent=1)
        except OSError as e:  # never let evidence-keeping break the record
            print(f"bench: could not persist last-good: {e}", file=sys.stderr)
    elif os.path.exists(last_good_path):
        try:
            with open(last_good_path) as f:
                record["last_good_tpu"] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench: could not read last-good: {e}", file=sys.stderr)
    # the scaled-point (N=2500 dense-vs-sparse) evidence rides along in
    # every canonical record once a clean on-chip scaled run has landed
    scaled_path = os.path.join(BENCH_DIR, "tpu_scaled_last_good.json")
    if os.path.exists(scaled_path):
        try:
            with open(scaled_path) as f:
                record["scaled_tpu"] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench: could not read scaled last-good: {e}", file=sys.stderr)
    # compact summaries of the other evidence files (accuracy at scale,
    # serving latency) so the driver's one record carries the round's
    # whole measurement story with their platform provenance attached
    for key, fname, fields in (
        ("scaled_accuracy", "scaled_accuracy.json", ("test", "platform", "captured_at")),
        ("serving", "serving_latency.json",
         ("legs", "speedup", "engine_stats", "platform", "captured_at")),
    ):
        path = os.path.join(BENCH_DIR, fname)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    ev = json.load(f)
                if isinstance(ev, dict):  # a mangled file must not void
                    record[key] = {k: ev.get(k) for k in fields}  # the record
            except (OSError, json.JSONDecodeError) as e:
                print(f"bench: could not read {fname}: {e}", file=sys.stderr)
    _emit(record)


if __name__ == "__main__":
    sys.stdout = sys.stderr  # backstop: only _emit writes the record stream
    try:
        main()
    except SystemExit:
        raise
    except Exception as e:  # never fail closed: the driver needs a parsable line
        _emit(
            {
                "metric": "region-timesteps/sec/chip",
                "value": 0.0,
                "unit": "region-timesteps/s",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}",
            }
        )
