#!/usr/bin/env python
"""Benchmark the flagship training step; prints ONE JSON line.

Metric: region-timesteps/sec/chip — ``batch * seq_len * n_nodes`` demand
points advanced per second of steady-state training step (forward + grad +
Adam update), on whatever single chip JAX exposes.

``vs_baseline`` compares against the reference-equivalent PyTorch
implementation's throughput at identical shapes (see
``benchmarks/torch_baseline.py``; the reference repo itself ships no
numbers or data — SURVEY.md §6). The stored baseline in
``benchmarks/baseline.json`` records the hardware it was measured on.
"""

from __future__ import annotations

import json
import os
from typing import Optional

# Benchmark operating point ("Didi-Chengdu, 12-step" scale, BASELINE.json):
# 16x16 region grid, 12-step observation window, batch 64, full M=3 ST-MGCN.
# Env overrides (STMGCN_BENCH_*) let the script's logic be validated on
# slow hosts without changing the canonical TPU operating point.
ROWS = int(os.environ.get("STMGCN_BENCH_ROWS", 16))
SERIAL, DAILY, WEEKLY = 10, 1, 1
BATCH = int(os.environ.get("STMGCN_BENCH_BATCH", 64))
DTYPE = os.environ.get("STMGCN_BENCH_DTYPE", "float32")  # or bfloat16
WARMUP = int(os.environ.get("STMGCN_BENCH_WARMUP", 5))
ITERS = int(os.environ.get("STMGCN_BENCH_ITERS", 30))


def _backend_watchdog(seconds: Optional[int] = None) -> None:
    """Fail fast (to stderr, nonzero exit) if backend init hangs.

    A wedged TPU tunnel can block the first device op indefinitely *inside
    native code* (signal handlers never run), so the probe happens in a
    child process the parent can time out and kill. Costs one extra
    backend startup per run; ``STMGCN_BENCH_WATCHDOG=0`` disables it on
    trusted hosts, any other integer overrides the timeout (seconds).
    """
    import subprocess
    import sys

    if seconds is None:
        seconds = int(os.environ.get("STMGCN_BENCH_WATCHDOG", 180))
    if seconds <= 0:
        return
    probe = (
        "import jax, jax.numpy as jnp; "
        "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()"
    )
    try:
        subprocess.run(
            [sys.executable, "-c", probe],
            timeout=seconds,
            check=True,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        print(
            f"bench: backend did not initialize within {seconds}s "
            "(TPU tunnel unavailable?)",
            file=sys.stderr,
        )
        sys.exit(2)
    except subprocess.CalledProcessError as e:
        print(
            "bench: backend probe failed:\n" + e.stderr.decode()[-500:],
            file=sys.stderr,
        )
        sys.exit(2)


def main() -> None:
    _backend_watchdog()
    import jax
    import numpy as np

    from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
    from stmgcn_tpu.models import STMGCN
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.train import make_optimizer, make_step_fns

    seq_len = SERIAL + DAILY + WEEKLY
    data = synthetic_dataset(rows=ROWS, n_timesteps=24 * 7 * 2 + 4 * BATCH, seed=0)
    dataset = DemandDataset(data, WindowSpec(SERIAL, DAILY, WEEKLY, 24))
    supports = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    import jax.numpy as jnp

    if DTYPE not in ("float32", "bfloat16"):
        raise ValueError(f"STMGCN_BENCH_DTYPE must be float32 or bfloat16, got {DTYPE!r}")
    model = STMGCN(
        m_graphs=3,
        n_supports=3,
        seq_len=seq_len,
        input_dim=dataset.n_feats,
        lstm_hidden_dim=64,
        lstm_num_layers=3,
        gcn_hidden_dim=64,
        dtype=jnp.bfloat16 if DTYPE == "bfloat16" else None,
    )
    fns = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse")

    batch = next(dataset.batches("train", BATCH, pad_last=True))
    sup = jnp.asarray(supports)
    x = jnp.asarray(batch.x)
    y = jnp.asarray(batch.y)
    mask = jnp.ones(BATCH, jnp.float32)
    params, opt_state = fns.init(jax.random.key(0), sup, x)

    from stmgcn_tpu.utils import StepTimer, region_timesteps_per_sec

    timer = StepTimer(warmup=WARMUP)
    for _ in range(WARMUP + ITERS):
        params, opt_state, loss = timer.measure(
            fns.train_step, params, opt_state, sup, x, y, mask
        )

    value = region_timesteps_per_sec(BATCH, seq_len, dataset.n_nodes, timer.mean)

    # vs_baseline only compares like dtypes: the stored torch anchor is fp32
    vs_baseline = None
    baseline_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "benchmarks", "baseline.json")
    if DTYPE == "float32" and os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
        ref = base.get("torch_cpu_region_ts_per_sec")
        if ref:
            vs_baseline = value / ref

    record = {
        "metric": "region-timesteps/sec/chip",
        "value": round(value, 1),
        "unit": "region-timesteps/s",
        "vs_baseline": round(vs_baseline, 2) if vs_baseline is not None else None,
    }
    if DTYPE != "float32":
        record["dtype"] = DTYPE
    print(json.dumps(record))


if __name__ == "__main__":
    main()
