"""Divergence guard: non-finite-loss detection with bounded patience.

A single poisoned or pathological batch can NaN the Adam moments and
silently destroy a run hours in — every later step multiplies NaN into
the params, and the failure surfaces (if at all) as a flat loss curve.
The guard is the cheap runtime tripwire: the trainer checks each step's
(host-fetched) loss for finiteness, and on a trip rolls ``params`` /
``opt_state`` back to an in-memory last-good snapshot taken just before
the step, then skips or defers the offending batch. This class holds the
policy and trip accounting; the rollback mechanics (snapshots under
buffer donation, superstep block re-runs) live in the trainer.

Off by default: detection costs a device sync per step on the per-step
path (one per S-step block on the superstep path), and the production
loop keeps losses on device until the epoch ends.
"""

from __future__ import annotations

from typing import Optional

from stmgcn_tpu.obs.registry import REGISTRY

__all__ = ["DivergenceError", "DivergenceGuard"]

ACTIONS = ("skip", "defer")


class DivergenceError(RuntimeError):
    """Too many consecutive non-finite steps — the divergence is not a
    single bad batch, and skipping forward would train on garbage."""


class DivergenceGuard:
    """Policy + accounting for non-finite-loss trips.

    - ``action`` — what happens to the offending batch after rollback:
      ``"skip"`` drops it from the epoch (its loss never enters the epoch
      mean, exactly as if the batch were never drawn); ``"defer"``
      re-queues it once at the end of the epoch (re-ordering instead of
      losing data; a second trip then skips it).
    - ``patience`` — abort after this many *consecutive* trips by raising
      :class:`DivergenceError`: persistent non-finiteness means the
      params/data are bad, not one batch.
    - ``lr_cut`` — optional factor in (0, 1); each trip multiplies the
      learning rate by it (the trainer rebuilds its optimizer at the new
      scale, keeping the optimizer state).
    """

    def __init__(
        self,
        action: str = "skip",
        patience: int = 3,
        lr_cut: Optional[float] = None,
    ):
        if action not in ACTIONS:
            raise ValueError(f"divergence action must be one of {ACTIONS}, got {action!r}")
        if patience < 1:
            raise ValueError(f"divergence patience must be >= 1, got {patience}")
        if lr_cut is not None and not 0.0 < lr_cut < 1.0:
            raise ValueError(f"divergence lr_cut must be in (0, 1), got {lr_cut}")
        self.action = action
        self.patience = patience
        self.lr_cut = lr_cut
        self.consecutive = 0
        self.total = 0

    def trip(self, loss: float, epoch: int, step: int) -> None:
        """Record a non-finite step; raise after ``patience`` consecutive.

        Called *after* the trainer has rolled back to the last-good
        snapshot, so even the aborting raise leaves finite live state
        behind (and a final checkpoint write stays loadable).
        """
        self.consecutive += 1
        self.total += 1
        REGISTRY.counter("train.divergence_trips").inc()
        if self.consecutive >= self.patience:
            raise DivergenceError(
                f"{self.consecutive} consecutive non-finite losses "
                f"(last {loss!r} at epoch {epoch}, step {step}) — params "
                "were rolled back to the last finite snapshot, but this is "
                "not a single bad batch. Re-run with --checkify nan to "
                "locate the op producing the first NaN, or lower the "
                "learning rate (--divergence-lr-cut cuts it automatically)."
            )

    def ok(self) -> None:
        """A finite step landed — reset the consecutive-trip counter."""
        self.consecutive = 0
