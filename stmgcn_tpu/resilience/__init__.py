"""Preemption-safe training: fault injection, divergence rollback.

Training on preemptible TPU slices means workers die mid-epoch, disks
truncate files, and one bad batch can NaN the params hours in. This
package holds the pieces the trainer threads through its hot loop —
behind no-op defaults, so the production code paths are exactly the
tested paths:

- :class:`FaultPlan` / :class:`FaultSpec` (:mod:`.faults`) — a
  deterministic fault-injection harness: raise in the step, deliver
  SIGTERM, poison a batch's loss mask with NaN/Inf, drop a batch, or
  truncate/bit-flip a checkpoint write, each at a configured
  (epoch, step) index or write ordinal. Every resilience claim in the
  test suite is driven through it, not reproduced anecdotally.
- :class:`DivergenceGuard` (:mod:`.guard`) — non-finite-loss detection
  with rollback to an in-memory last-good snapshot, skip/defer of the
  offending batch, optional LR cut, and abort after N consecutive trips.
- :class:`Preempted` — raised at a safe step boundary after SIGTERM once
  the emergency checkpoint has landed; a ``BaseException`` so broad
  ``except Exception`` recovery code cannot swallow a shutdown request.
- :class:`ServeFaultPlan` / :class:`ServeFaultSpec` — the serving-side
  mirror: dispatch-addressed raise/slow/hang faults, batcher-thread
  death (:class:`BatcherKilled`), at-rest checkpoint corruption for
  the hot-swap watcher, and promotion-gate raises, so every
  shed/degrade/swap/promote path of the serving engine is exercised
  deterministically too.
- :class:`IngestFaultPlan` / :class:`IngestFaultSpec` — the live-feed
  mirror for the continual loop: a deterministic stream transformer
  (gap / out-of-order / duplicate / nonfinite / SIGTERM by source-row
  ordinal) applied before rows reach the device-resident ingest ring.
- :class:`FederationFaultPlan` / :class:`FederationFaultSpec` — the
  tier-level mirror for the serving federation: replica kill by scatter
  ordinal, hang-on-drain, thundering-herd city spikes, and at-rest
  candidate poisoning before the tier promotion gate, so the
  kill/re-shard/herd/rejection drills of ``serve-bench --federation``
  are deterministic too.

The verified-checkpoint side (CRC32 format v2, ``load_latest_verified``
recovery chain) lives in :mod:`stmgcn_tpu.train.checkpoint`.
"""

from stmgcn_tpu.resilience.faults import (
    BatcherKilled,
    FaultPlan,
    FaultSpec,
    FederationFaultPlan,
    FederationFaultSpec,
    IngestFaultPlan,
    IngestFaultSpec,
    InjectedFault,
    Preempted,
    ServeFaultPlan,
    ServeFaultSpec,
)
from stmgcn_tpu.resilience.guard import DivergenceError, DivergenceGuard

__all__ = [
    "BatcherKilled",
    "DivergenceError",
    "DivergenceGuard",
    "FaultPlan",
    "FaultSpec",
    "FederationFaultPlan",
    "FederationFaultSpec",
    "IngestFaultPlan",
    "IngestFaultSpec",
    "InjectedFault",
    "Preempted",
    "ServeFaultPlan",
    "ServeFaultSpec",
]
