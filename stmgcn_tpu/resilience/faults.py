"""Deterministic fault injection for the training loop.

A :class:`FaultPlan` is a set of :class:`FaultSpec` triggers the trainer
consults at fixed points of its hot loop — before each step (or fused
S-step block), when building each batch's loss mask, and when handing
serialized checkpoint bytes to the writer. The empty plan is the
default and every hook returns immediately, so production runs exercise
*exactly* the code paths the fault drills test; there is no
"instrumented build".

Step faults address batches by ``(epoch, step)`` where ``step`` is the
0-based ordinal of the batch **within its epoch, counting consumed
batches** (guard-skipped and dropped batches advance it, like the resume
cursor in checkpoint meta). This makes triggers reproducible across the
per-step and superstep paths and across a divergence-guard rollback
re-run: the re-run revisits the same ordinals, so a ``poison`` fault
re-fires on exactly the batch it poisoned before (``poison``/``drop``
are pure matches; ``raise``/``sigterm``/write faults fire once).

Write faults address checkpoint writes by filename glob + ordinal among
the matching writes, and corrupt the serialized bytes *before* they
reach the atomic writer — simulating disk-level truncation/bit rot of a
file that did land, the case ``os.replace`` atomicity cannot cover.

The serving side gets the same treatment (:class:`ServeFaultPlan` /
:class:`ServeFaultSpec`): faults address the micro-batcher's *dispatch
ordinal* (0-based count of coalesced dispatches) instead of training
steps, plus an at-rest checkpoint corruption hook the hot-swap watcher
consults and a promotion-gate hook the continual-learning gate consults
— so every shed/degrade/swap/promote path in the serving engine is
exercised deterministically, and the empty plan is again a production
no-op.

The closed continual loop adds the last two stages. Ingest faults
(:class:`IngestFaultPlan` / :class:`IngestFaultSpec`) are a
deterministic *stream transformer* addressed by source-row ordinal:
drop a row (gap), hold one back (out-of-order arrival), replay one
(duplicate), poison one with NaN, or deliver SIGTERM mid-ingest —
applied to the ``(timestamp, values)`` stream *before* it reaches the
ring, because that is where real feeds break. Daemon faults reuse
:class:`FaultPlan` with the retrain ordinal as the "epoch": raise /
hang / poison mid-fine-tune plus the write kinds against candidate
checkpoints, including ``torn-write`` — a crash *between* the tmp-file
write and the atomic rename, the one window ``os.replace`` atomicity
cannot cover from inside the process.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import signal
from typing import Optional, Tuple

from stmgcn_tpu.obs.registry import REGISTRY

__all__ = [
    "BatcherKilled",
    "FEDERATION_KINDS",
    "FaultPlan",
    "FaultSpec",
    "FederationFaultPlan",
    "FederationFaultSpec",
    "INGEST_KINDS",
    "IngestFaultPlan",
    "IngestFaultSpec",
    "InjectedFault",
    "Preempted",
    "SERVE_KINDS",
    "ServeFaultPlan",
    "ServeFaultSpec",
]

_STEP_KINDS = ("raise", "sigterm", "hang", "poison", "drop")
_WRITE_KINDS = ("truncate-write", "corrupt-write", "torn-write")
KINDS = _STEP_KINDS + _WRITE_KINDS
SERVE_KINDS = (
    "dispatch-raise",
    "dispatch-slow",
    "dispatch-hang",
    "batcher-die",
    "corrupt-checkpoint",
    "promotion-raise",
)
INGEST_KINDS = ("gap", "out-of-order", "duplicate", "nonfinite", "sigterm")
FEDERATION_KINDS = (
    "replica-kill",
    "hang-on-drain",
    "herd-spike",
    "poisoned-candidate",
)


def _count_fault(kind: str) -> None:
    """Registry tally of faults that actually FIRED (never armed specs —
    the empty-plan hooks short-circuit before reaching this)."""
    REGISTRY.counter("faults.injected", {"kind": kind}).inc()


class InjectedFault(RuntimeError):
    """Raised by a ``kind="raise"`` fault — a stand-in for the step fn
    dying mid-epoch (driver crash, XLA error, host OOM)."""


class Preempted(BaseException):
    """SIGTERM was delivered and the emergency checkpoint has landed.

    Deliberately a ``BaseException`` (like ``KeyboardInterrupt``): broad
    ``except Exception`` retry/recovery code must not swallow a shutdown
    request — the process has been asked to die and should exit after
    unwinding. ``--resume auto`` continues the run bit-exactly.
    """


class BatcherKilled(BaseException):
    """Raised by a ``kind="batcher-die"`` serve fault at dispatch entry.

    Deliberately a ``BaseException``: the micro-batcher's dispatch error
    handling catches ``Exception`` (a dying *dispatch* releases its
    waiters and the worker lives on), so this escapes that handler and
    kills the worker thread itself — the wedged-batcher scenario the
    engine's degrade-to-direct fallback exists for.
    """


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic trigger in a :class:`FaultPlan`.

    Step kinds (addressed by ``epoch``/``step``):

    - ``"raise"``    — raise :class:`InjectedFault` before the step runs.
    - ``"sigterm"``  — deliver SIGTERM to this process before the step
      (``signal.raise_signal``): exercises the trainer's grace-window
      handler, emergency checkpoint, and :class:`Preempted` unwind.
    - ``"hang"``     — sleep ``hang_ms`` before the step (one-shot): the
      stalled-device / wedged-host stand-in for the continual daemon's
      supervision drills — a fine-tune that hangs must never block the
      serving path.
    - ``"poison"``   — inject ``payload`` (default NaN) into the batch's
      loss mask: the loss and every gradient go non-finite exactly as
      they would for NaN input data, tripping checkify/the divergence
      guard at that one step.
    - ``"drop"``     — consume the batch without stepping. The control
      for divergence drills: a guard-skip run must end bit-identical to
      a drop run that never saw the poisoned batch.

    Write kinds (addressed by ``path_glob``/``write_index``):

    - ``"truncate-write"`` — keep only the first ``keep_fraction`` of the
      serialized bytes.
    - ``"corrupt-write"``  — flip one bit of byte ``flip_byte``
      (-1 = middle of the file).
    - ``"torn-write"``     — crash between the tmp-file write and the
      atomic rename: the first ``keep_fraction`` of the bytes land in
      the ``*.tmp.<pid>`` file, :class:`InjectedFault` fires before
      ``os.replace``, and the destination file is never touched — the
      window ``os.replace`` atomicity cannot cover, left as a documented
      gap by the original write-fault harness.
    """

    kind: str
    epoch: Optional[int] = None  # step faults: epoch to fire in (None = any)
    step: Optional[int] = None  # step faults: batch ordinal in the epoch
    payload: float = float("nan")
    hang_ms: float = 0.0
    path_glob: str = "*.ckpt"
    write_index: int = 0
    keep_fraction: float = 0.5
    flip_byte: int = -1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"fault kind must be one of {KINDS}, got {self.kind!r}")
        if self.kind in ("poison", "drop") and self.step is None:
            raise ValueError(f"{self.kind!r} faults need an explicit step ordinal")
        if self.kind == "hang" and self.hang_ms <= 0:
            raise ValueError("hang faults need hang_ms > 0")
        if not 0.0 < self.keep_fraction < 1.0:
            raise ValueError(
                f"keep_fraction must be in (0, 1), got {self.keep_fraction}"
            )

    def _matches_step(self, epoch: int, start: int, stop: int) -> bool:
        if self.epoch is not None and self.epoch != epoch:
            return False
        step = self.step if self.step is not None else start
        return start <= step < stop


class FaultPlan:
    """A deterministic set of faults, consulted by the trainer's hot loop.

    The empty plan (``FaultPlan()``) is the production default: every
    hook short-circuits on ``self.specs`` being empty. One-shot state
    (which ``raise``/``sigterm``/write faults already fired, per-glob
    write counters) lives on the plan instance, so reusing a plan across
    trainers re-arms it only if you build a fresh plan.
    """

    def __init__(self, *specs: FaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], FaultSpec):
            specs = tuple(specs[0])  # accept FaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, FaultSpec):
                raise TypeError(f"FaultPlan takes FaultSpecs, got {type(s).__name__}")
        self.specs: Tuple[FaultSpec, ...] = tuple(specs)
        self._fired: set = set()
        self._write_counts: dict = {}

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def before_step(self, epoch: int, start: int, stop: Optional[int] = None) -> None:
        """Fire any one-shot ``raise``/``sigterm``/``hang`` fault addressed
        to a batch ordinal in ``[start, stop)`` of ``epoch`` (a superstep
        block passes its full range: the fault lands at the block boundary,
        the same safe point the emergency checkpoint uses)."""
        if not self.specs:
            return
        stop = start + 1 if stop is None else stop
        for i, spec in enumerate(self.specs):
            if spec.kind not in ("raise", "sigterm", "hang"):
                continue
            key = ("step", i)
            if key in self._fired or not spec._matches_step(epoch, start, stop):
                continue
            self._fired.add(key)
            _count_fault(spec.kind)
            if spec.kind == "sigterm":
                signal.raise_signal(signal.SIGTERM)
            elif spec.kind == "hang":
                import time

                time.sleep(spec.hang_ms / 1e3)
            else:
                raise InjectedFault(
                    f"injected fault at epoch {epoch}, step {spec.step}"
                )

    def poison_value(self, epoch: int, step: int) -> Optional[float]:
        """The NaN/Inf payload to inject at this batch, or ``None``.

        A pure match (no one-shot state): a rollback re-run that revisits
        this ordinal must poison it again, or the re-run would train on a
        batch the original pass skipped.
        """
        for spec in self.specs:
            if spec.kind == "poison" and spec._matches_step(epoch, step, step + 1):
                _count_fault("poison")
                return spec.payload
        return None

    def should_drop(self, epoch: int, step: int) -> bool:
        """Whether this batch is consumed without an optimizer step."""
        hit = any(
            spec.kind == "drop" and spec._matches_step(epoch, step, step + 1)
            for spec in self.specs
        )
        if hit:
            _count_fault("drop")
        return hit

    def any_drop(self, epoch: int, start: int, stop: int) -> bool:
        """Whether any ordinal in ``[start, stop)`` carries a drop fault —
        a fused block containing one falls back to the per-step path."""
        return any(
            spec.kind == "drop" and spec._matches_step(epoch, start, stop)
            for spec in self.specs
        )

    def mutate_write(self, path: str, data: bytes) -> bytes:
        """Corrupt checkpoint bytes bound for ``path`` per any matching
        one-shot write fault (counted per spec over writes whose basename
        matches its glob). ``torn-write`` is NOT handled here — it is not
        a byte mutation but a crash inside the atomic writer, so it lives
        in :meth:`torn_write`, consulted by ``write_checkpoint_bytes``
        itself."""
        if not self.specs:
            return data
        name = os.path.basename(path)
        for i, spec in enumerate(self.specs):
            if spec.kind not in ("truncate-write", "corrupt-write"):
                continue
            if not fnmatch.fnmatch(name, spec.path_glob):
                continue
            key = ("write", i)
            count = self._write_counts.get(key, 0)
            self._write_counts[key] = count + 1
            if count != spec.write_index or key in self._fired:
                continue
            self._fired.add(key)
            _count_fault(spec.kind)
            if spec.kind == "truncate-write":
                data = data[: max(1, int(len(data) * spec.keep_fraction))]
            else:
                idx = spec.flip_byte if spec.flip_byte >= 0 else len(data) // 2
                mutated = bytearray(data)
                mutated[idx] ^= 0x01
                data = bytes(mutated)
        return data

    def torn_write(self, path: str, data: bytes, tmp: str) -> None:
        """Crash the atomic writer between tmp write and rename.

        Consulted by ``write_checkpoint_bytes`` *before* it writes the
        tmp file: a matching one-shot ``torn-write`` spec leaves the
        first ``keep_fraction`` of ``data`` in ``tmp`` and raises
        :class:`InjectedFault` — the destination ``path`` is never
        replaced, exactly what a crash between ``f.write`` and
        ``os.replace`` leaves behind (stale-but-intact destination plus
        a partial ``*.tmp.<pid>`` orphan). Write ordinals are counted
        per spec over writes whose basename matches its glob, same
        addressing as :meth:`mutate_write`.
        """
        if not self.specs:
            return
        name = os.path.basename(path)
        for i, spec in enumerate(self.specs):
            if spec.kind != "torn-write":
                continue
            if not fnmatch.fnmatch(name, spec.path_glob):
                continue
            key = ("torn", i)
            count = self._write_counts.get(key, 0)
            self._write_counts[key] = count + 1
            if count != spec.write_index or key in self._fired:
                continue
            self._fired.add(key)
            _count_fault("torn-write")
            with open(tmp, "wb") as f:
                f.write(data[: max(1, int(len(data) * spec.keep_fraction))])
            raise InjectedFault(
                f"injected torn write: crashed before renaming {tmp} "
                f"over {path}"
            )


@dataclasses.dataclass(frozen=True)
class ServeFaultSpec:
    """One deterministic serving-side trigger in a :class:`ServeFaultPlan`.

    Dispatch kinds (addressed by ``dispatch``, the 0-based ordinal of
    coalesced micro-batch dispatches; ``None`` = every dispatch):

    - ``"dispatch-raise"`` — raise :class:`InjectedFault` at dispatch
      entry (one-shot): the XLA-error/driver-crash stand-in. The batcher
      must wrap it per waiter and the worker must survive.
    - ``"dispatch-slow"``  — sleep ``slow_ms`` before the dispatch (pure
      match): sustained device slowdown, the regime that backs the queue
      up and makes admission control shed.
    - ``"dispatch-hang"``  — sleep ``hang_ms`` before the dispatch (pure
      match): a long stall; queued requests' deadlines expire behind it
      and must be shed at dispatch time, not served late.
    - ``"batcher-die"``    — raise :class:`BatcherKilled` at dispatch
      entry (one-shot): kills the worker thread itself; pending and
      future submits must fail fast (``BatcherWedged``) and the engine
      must degrade to its inline path.

    Checkpoint kind (addressed by ``path_glob``):

    - ``"corrupt-checkpoint"`` — flip one bit of byte ``flip_byte`` of a
      matching checkpoint file *at rest* (one-shot per spec), before the
      hot-swap watcher reads it: the mid-watch bit-rot drill. The
      watcher must quarantine and keep serving the old params.

    Promotion kind (addressed by ``dispatch`` as the 0-based ordinal of
    promotion-gate evaluations):

    - ``"promotion-raise"`` — raise :class:`InjectedFault` at gate entry
      (one-shot): the gate's own evaluation dying mid-decision. The gate
      must quarantine the candidate with a typed ``gate-error`` reason
      and the engine must keep serving its current generation.
    """

    kind: str
    dispatch: Optional[int] = None
    slow_ms: float = 0.0
    hang_ms: float = 0.0
    path_glob: str = "latest.ckpt"
    flip_byte: int = -1

    def __post_init__(self):
        if self.kind not in SERVE_KINDS:
            raise ValueError(
                f"serve fault kind must be one of {SERVE_KINDS}, got "
                f"{self.kind!r}"
            )
        if self.kind == "dispatch-slow" and self.slow_ms <= 0:
            raise ValueError("dispatch-slow faults need slow_ms > 0")
        if self.kind == "dispatch-hang" and self.hang_ms <= 0:
            raise ValueError("dispatch-hang faults need hang_ms > 0")
        if (
            self.kind in ("dispatch-raise", "batcher-die", "promotion-raise")
            and self.dispatch is None
        ):
            raise ValueError(
                f"{self.kind!r} faults need an explicit dispatch ordinal"
            )

    def _matches_dispatch(self, ordinal: int) -> bool:
        return self.dispatch is None or self.dispatch == ordinal


class ServeFaultPlan:
    """Deterministic serving faults, consulted by the micro-batch worker
    at dispatch entry and by the hot-swap watcher before each poll.

    Same contract as :class:`FaultPlan`: the empty plan is the
    production default and every hook short-circuits immediately — the
    engine has no instrumented build. One-shot state lives on the plan
    instance.
    """

    def __init__(self, *specs: ServeFaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], ServeFaultSpec):
            specs = tuple(specs[0])  # accept ServeFaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, ServeFaultSpec):
                raise TypeError(
                    f"ServeFaultPlan takes ServeFaultSpecs, got "
                    f"{type(s).__name__}"
                )
        self.specs: Tuple[ServeFaultSpec, ...] = tuple(specs)
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def before_dispatch(self, ordinal: int) -> None:
        """Fire any fault addressed to this dispatch ordinal. Sleeps for
        slow/hang kinds; raises for raise/die kinds (one-shot)."""
        if not self.specs:
            return
        import time

        for i, spec in enumerate(self.specs):
            if not spec._matches_dispatch(ordinal):
                continue
            if spec.kind == "dispatch-slow":
                _count_fault("dispatch-slow")
                time.sleep(spec.slow_ms / 1e3)
            elif spec.kind == "dispatch-hang":
                _count_fault("dispatch-hang")
                time.sleep(spec.hang_ms / 1e3)
            elif spec.kind in ("dispatch-raise", "batcher-die"):
                key = ("dispatch", i)
                if key in self._fired:
                    continue
                self._fired.add(key)
                _count_fault(spec.kind)
                if spec.kind == "batcher-die":
                    raise BatcherKilled(
                        f"injected batcher death at dispatch {ordinal}"
                    )
                raise InjectedFault(
                    f"injected dispatch fault at dispatch {ordinal}"
                )

    def before_promotion(self, ordinal: int) -> None:
        """Fire any one-shot ``promotion-raise`` fault addressed to this
        promotion-gate evaluation ordinal (the gate catches it and
        quarantines the candidate with a ``gate-error`` reason)."""
        if not self.specs:
            return
        for i, spec in enumerate(self.specs):
            if spec.kind != "promotion-raise":
                continue
            if not spec._matches_dispatch(ordinal):
                continue
            key = ("promotion", i)
            if key in self._fired:
                continue
            self._fired.add(key)
            _count_fault("promotion-raise")
            raise InjectedFault(
                f"injected promotion-gate fault at evaluation {ordinal}"
            )

    def corrupt_checkpoints(self, out_dir: str) -> list:
        """Flip bytes at rest in checkpoint files matching any one-shot
        ``corrupt-checkpoint`` spec; returns the corrupted paths. Called
        by the hot-swap watcher at poll start, BEFORE verification — the
        drill is bit rot landing between writer and reader."""
        if not self.specs:
            return []
        hit = []
        for i, spec in enumerate(self.specs):
            if spec.kind != "corrupt-checkpoint":
                continue
            key = ("ckpt", i)
            if key in self._fired:
                continue
            try:
                names = sorted(os.listdir(out_dir))
            except OSError:
                continue
            for name in names:
                if not fnmatch.fnmatch(name, spec.path_glob):
                    continue
                path = os.path.join(out_dir, name)
                try:
                    with open(path, "rb") as f:
                        data = bytearray(f.read())
                    if not data:
                        continue
                    idx = (
                        spec.flip_byte
                        if spec.flip_byte >= 0
                        else len(data) // 2
                    )
                    data[idx] ^= 0x01
                    with open(path, "wb") as f:
                        f.write(bytes(data))
                except OSError:
                    continue
                self._fired.add(key)
                _count_fault("corrupt-checkpoint")
                hit.append(path)
                break
        return hit


@dataclasses.dataclass(frozen=True)
class IngestFaultSpec:
    """One deterministic source-stream trigger in an
    :class:`IngestFaultPlan`, addressed by ``row`` — the 0-based ordinal
    of rows the *source* offers (faulted rows still advance it, so a
    plan reads like a script of the feed).

    - ``"gap"``          — the source never delivers this row: the ring
      sees a timestamp jump at the next arrival and must forward-fill.
    - ``"out-of-order"`` — hold this row back and deliver it after the
      next ``delay`` rows: a late arrival inside (or beyond) the ring's
      reorder window.
    - ``"duplicate"``    — deliver this row twice back to back: the
      at-least-once transport case the ring must dedupe.
    - ``"nonfinite"``    — overwrite the row's first cell with
      ``payload`` (default NaN): a sensor glitch the ring must
      quarantine instead of letting onto the device.
    - ``"sigterm"``      — deliver SIGTERM to this process before the
      row: the mid-ingest preemption drill (the ring must stay
      consistent — every committed row fully written, bookkeeping
      matching the device state).
    """

    kind: str
    row: int
    delay: int = 1
    payload: float = float("nan")

    def __post_init__(self):
        if self.kind not in INGEST_KINDS:
            raise ValueError(
                f"ingest fault kind must be one of {INGEST_KINDS}, got "
                f"{self.kind!r}"
            )
        if self.row < 0:
            raise ValueError(f"row ordinal must be >= 0, got {self.row}")
        if self.kind == "out-of-order" and self.delay < 1:
            raise ValueError("out-of-order faults need delay >= 1")


class IngestFaultPlan:
    """Deterministic ingest-stream transformer for the live-feed drills.

    Sits between the observation source and :class:`~stmgcn_tpu.data
    .ring.SeriesRing`: :meth:`feed` takes each source row and returns
    the rows that actually *arrive* (possibly none, possibly several,
    possibly mutated or reordered) — the empty plan passes every row
    through untouched, so production ingest runs exactly the drilled
    code path. One-shot state (held back rows, which specs fired) lives
    on the plan instance.
    """

    def __init__(self, *specs: IngestFaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], IngestFaultSpec):
            specs = tuple(specs[0])  # accept IngestFaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, IngestFaultSpec):
                raise TypeError(
                    f"IngestFaultPlan takes IngestFaultSpecs, got "
                    f"{type(s).__name__}"
                )
        self.specs: Tuple[IngestFaultSpec, ...] = tuple(specs)
        self._seen = 0
        #: held back out-of-order rows: [rows_remaining, ts, values]
        self._held: list = []

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def feed(self, ts, values) -> list:
        """Transform one source row into the rows that arrive now.

        Returns ``[(ts, values), ...]`` in arrival order. Held-back rows
        release *after* the current row once their delay has elapsed, so
        an ``out-of-order`` spec turns into a genuinely late arrival.
        """
        if not self.specs:
            return [(ts, values)]
        ordinal = self._seen
        self._seen += 1
        out = [(ts, values)]
        for spec in self.specs:
            if spec.row != ordinal:
                continue
            _count_fault(f"ingest-{spec.kind}")
            if spec.kind == "gap":
                out = []
            elif spec.kind == "duplicate":
                out = [(ts, values), (ts, values)]
            elif spec.kind == "nonfinite":
                import numpy as np

                poisoned = np.array(values, copy=True)
                poisoned.reshape(-1)[0] = spec.payload
                out = [(ts, poisoned)]
            elif spec.kind == "out-of-order":
                self._held.append([spec.delay, ts, values])
                out = []
            elif spec.kind == "sigterm":
                signal.raise_signal(signal.SIGTERM)
        released = []
        for h in self._held:
            h[0] -= 1
            if h[0] <= 0:
                released.append((h[1], h[2]))
        self._held = [h for h in self._held if h[0] > 0]
        return out + released


@dataclasses.dataclass(frozen=True)
class FederationFaultSpec:
    """One deterministic tier-level trigger in a
    :class:`FederationFaultPlan`, addressed by the federation router's
    *scatter ordinal* — the 0-based count of multi-city scatter/gather
    operations the router has run (every scatter advances it, so a plan
    reads like a script of tier traffic).

    - ``"replica-kill"`` — at scatter ordinal ``dispatch``, the router
      hard-kills replica ``replica`` mid-traffic (one-shot): the handle
      goes dead, its in-flight cities come back as typed per-city errors
      (never a hung caller), and the router must re-shard the dead
      replica's cities onto survivors.
    - ``"hang-on-drain"`` — the next drain of replica ``replica`` stalls
      ``hang_ms`` before its in-flight work flushes (one-shot): the
      bounded-handover drill — a drain must report a wedged replica
      within its timeout instead of blocking the tier forever.
    - ``"herd-spike"`` — at scatter ordinal ``dispatch``, the open-loop
      schedule injects ``burst`` extra back-to-back requests for
      ``city`` (one-shot): the thundering-herd drill — one city's
      replica saturates and must shed typed errors while the rest of
      the tier keeps its SLO.
    - ``"poisoned-candidate"`` — flip one bit of byte ``flip_byte`` of
      the next candidate checkpoint whose basename matches
      ``path_glob``, before the tier promotion gate evaluates it
      (one-shot): the tier-wide-rejection drill — the gate must
      quarantine the candidate exactly once, not once per replica.
    """

    kind: str
    replica: Optional[int] = None
    dispatch: Optional[int] = None
    hang_ms: float = 0.0
    city: Optional[int] = None
    burst: int = 0
    path_glob: str = "candidate-*.ckpt"
    flip_byte: int = -1

    def __post_init__(self):
        if self.kind not in FEDERATION_KINDS:
            raise ValueError(
                f"federation fault kind must be one of {FEDERATION_KINDS}, "
                f"got {self.kind!r}"
            )
        if self.kind == "replica-kill" and (
            self.replica is None or self.dispatch is None
        ):
            raise ValueError(
                "replica-kill faults need explicit replica and dispatch "
                "ordinals"
            )
        if self.kind == "hang-on-drain":
            if self.replica is None:
                raise ValueError("hang-on-drain faults need a replica")
            if self.hang_ms <= 0:
                raise ValueError("hang-on-drain faults need hang_ms > 0")
        if self.kind == "herd-spike" and (
            self.city is None or self.dispatch is None or self.burst < 1
        ):
            raise ValueError(
                "herd-spike faults need a city, a dispatch ordinal, and "
                "burst >= 1"
            )


class FederationFaultPlan:
    """Deterministic tier-level faults, consulted by the federation
    router at scatter entry and drain entry, and by the tier promotion
    gate before each evaluation.

    Same contract as :class:`FaultPlan`: the empty plan is the
    production default and every hook short-circuits immediately — the
    router has no instrumented build. One-shot state lives on the plan
    instance.
    """

    def __init__(self, *specs: FederationFaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], FederationFaultSpec):
            specs = tuple(specs[0])  # accept FederationFaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, FederationFaultSpec):
                raise TypeError(
                    f"FederationFaultPlan takes FederationFaultSpecs, got "
                    f"{type(s).__name__}"
                )
        self.specs: Tuple[FederationFaultSpec, ...] = tuple(specs)
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def kill_at_scatter(self, ordinal: int) -> Optional[int]:
        """The replica id to hard-kill at this scatter ordinal, or None
        (one-shot). The router runs its own kill path on the returned
        id so the drill exercises exactly the production code."""
        if not self.specs:
            return None
        for i, spec in enumerate(self.specs):
            if spec.kind != "replica-kill" or spec.dispatch != ordinal:
                continue
            key = ("kill", i)
            if key in self._fired:
                continue
            self._fired.add(key)
            _count_fault("replica-kill")
            return spec.replica
        return None

    def on_drain(self, replica: int) -> None:
        """Stall a drain of ``replica`` per any one-shot hang-on-drain
        spec — the router's drain timeout must bound the stall."""
        if not self.specs:
            return
        for i, spec in enumerate(self.specs):
            if spec.kind != "hang-on-drain" or spec.replica != replica:
                continue
            key = ("drain", i)
            if key in self._fired:
                continue
            self._fired.add(key)
            _count_fault("hang-on-drain")
            import time

            time.sleep(spec.hang_ms / 1e3)

    def herd_burst(self, ordinal: int) -> list:
        """``[(city, burst), ...]`` spikes scheduled at this scatter
        ordinal (each one-shot) — the open-loop driver injects them as
        extra back-to-back arrivals for the city."""
        if not self.specs:
            return []
        out = []
        for i, spec in enumerate(self.specs):
            if spec.kind != "herd-spike" or spec.dispatch != ordinal:
                continue
            key = ("herd", i)
            if key in self._fired:
                continue
            self._fired.add(key)
            _count_fault("herd-spike")
            out.append((spec.city, spec.burst))
        return out

    def poison_candidate(self, path: str) -> bool:
        """Flip a byte of ``path`` at rest per any matching one-shot
        poisoned-candidate spec; True when the file was corrupted.
        Called by the tier promotion gate before evaluation."""
        if not self.specs:
            return False
        name = os.path.basename(path)
        for i, spec in enumerate(self.specs):
            if spec.kind != "poisoned-candidate":
                continue
            if not fnmatch.fnmatch(name, spec.path_glob):
                continue
            key = ("poison", i)
            if key in self._fired:
                continue
            try:
                with open(path, "rb") as f:
                    data = bytearray(f.read())
                if not data:
                    continue
                idx = spec.flip_byte if spec.flip_byte >= 0 else len(data) // 2
                data[idx] ^= 0x01
                with open(path, "wb") as f:
                    f.write(bytes(data))
            except OSError:
                continue
            self._fired.add(key)
            _count_fault("poisoned-candidate")
            return True
        return False
