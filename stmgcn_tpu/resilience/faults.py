"""Deterministic fault injection for the training loop.

A :class:`FaultPlan` is a set of :class:`FaultSpec` triggers the trainer
consults at fixed points of its hot loop — before each step (or fused
S-step block), when building each batch's loss mask, and when handing
serialized checkpoint bytes to the writer. The empty plan is the
default and every hook returns immediately, so production runs exercise
*exactly* the code paths the fault drills test; there is no
"instrumented build".

Step faults address batches by ``(epoch, step)`` where ``step`` is the
0-based ordinal of the batch **within its epoch, counting consumed
batches** (guard-skipped and dropped batches advance it, like the resume
cursor in checkpoint meta). This makes triggers reproducible across the
per-step and superstep paths and across a divergence-guard rollback
re-run: the re-run revisits the same ordinals, so a ``poison`` fault
re-fires on exactly the batch it poisoned before (``poison``/``drop``
are pure matches; ``raise``/``sigterm``/write faults fire once).

Write faults address checkpoint writes by filename glob + ordinal among
the matching writes, and corrupt the serialized bytes *before* they
reach the atomic writer — simulating disk-level truncation/bit rot of a
file that did land, the case ``os.replace`` atomicity cannot cover.

The serving side gets the same treatment (:class:`ServeFaultPlan` /
:class:`ServeFaultSpec`): faults address the micro-batcher's *dispatch
ordinal* (0-based count of coalesced dispatches) instead of training
steps, plus an at-rest checkpoint corruption hook the hot-swap watcher
consults — so every shed/degrade/swap path in the serving engine is
exercised deterministically, and the empty plan is again a production
no-op.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
import signal
from typing import Optional, Tuple

from stmgcn_tpu.obs.registry import REGISTRY

__all__ = [
    "BatcherKilled",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "Preempted",
    "SERVE_KINDS",
    "ServeFaultPlan",
    "ServeFaultSpec",
]

_STEP_KINDS = ("raise", "sigterm", "poison", "drop")
_WRITE_KINDS = ("truncate-write", "corrupt-write")
KINDS = _STEP_KINDS + _WRITE_KINDS
SERVE_KINDS = (
    "dispatch-raise",
    "dispatch-slow",
    "dispatch-hang",
    "batcher-die",
    "corrupt-checkpoint",
)


def _count_fault(kind: str) -> None:
    """Registry tally of faults that actually FIRED (never armed specs —
    the empty-plan hooks short-circuit before reaching this)."""
    REGISTRY.counter("faults.injected", {"kind": kind}).inc()


class InjectedFault(RuntimeError):
    """Raised by a ``kind="raise"`` fault — a stand-in for the step fn
    dying mid-epoch (driver crash, XLA error, host OOM)."""


class Preempted(BaseException):
    """SIGTERM was delivered and the emergency checkpoint has landed.

    Deliberately a ``BaseException`` (like ``KeyboardInterrupt``): broad
    ``except Exception`` retry/recovery code must not swallow a shutdown
    request — the process has been asked to die and should exit after
    unwinding. ``--resume auto`` continues the run bit-exactly.
    """


class BatcherKilled(BaseException):
    """Raised by a ``kind="batcher-die"`` serve fault at dispatch entry.

    Deliberately a ``BaseException``: the micro-batcher's dispatch error
    handling catches ``Exception`` (a dying *dispatch* releases its
    waiters and the worker lives on), so this escapes that handler and
    kills the worker thread itself — the wedged-batcher scenario the
    engine's degrade-to-direct fallback exists for.
    """


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One deterministic trigger in a :class:`FaultPlan`.

    Step kinds (addressed by ``epoch``/``step``):

    - ``"raise"``    — raise :class:`InjectedFault` before the step runs.
    - ``"sigterm"``  — deliver SIGTERM to this process before the step
      (``signal.raise_signal``): exercises the trainer's grace-window
      handler, emergency checkpoint, and :class:`Preempted` unwind.
    - ``"poison"``   — inject ``payload`` (default NaN) into the batch's
      loss mask: the loss and every gradient go non-finite exactly as
      they would for NaN input data, tripping checkify/the divergence
      guard at that one step.
    - ``"drop"``     — consume the batch without stepping. The control
      for divergence drills: a guard-skip run must end bit-identical to
      a drop run that never saw the poisoned batch.

    Write kinds (addressed by ``path_glob``/``write_index``):

    - ``"truncate-write"`` — keep only the first ``keep_fraction`` of the
      serialized bytes.
    - ``"corrupt-write"``  — flip one bit of byte ``flip_byte``
      (-1 = middle of the file).
    """

    kind: str
    epoch: Optional[int] = None  # step faults: epoch to fire in (None = any)
    step: Optional[int] = None  # step faults: batch ordinal in the epoch
    payload: float = float("nan")
    path_glob: str = "*.ckpt"
    write_index: int = 0
    keep_fraction: float = 0.5
    flip_byte: int = -1

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"fault kind must be one of {KINDS}, got {self.kind!r}")
        if self.kind in ("poison", "drop") and self.step is None:
            raise ValueError(f"{self.kind!r} faults need an explicit step ordinal")
        if not 0.0 < self.keep_fraction < 1.0:
            raise ValueError(
                f"keep_fraction must be in (0, 1), got {self.keep_fraction}"
            )

    def _matches_step(self, epoch: int, start: int, stop: int) -> bool:
        if self.epoch is not None and self.epoch != epoch:
            return False
        step = self.step if self.step is not None else start
        return start <= step < stop


class FaultPlan:
    """A deterministic set of faults, consulted by the trainer's hot loop.

    The empty plan (``FaultPlan()``) is the production default: every
    hook short-circuits on ``self.specs`` being empty. One-shot state
    (which ``raise``/``sigterm``/write faults already fired, per-glob
    write counters) lives on the plan instance, so reusing a plan across
    trainers re-arms it only if you build a fresh plan.
    """

    def __init__(self, *specs: FaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], FaultSpec):
            specs = tuple(specs[0])  # accept FaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, FaultSpec):
                raise TypeError(f"FaultPlan takes FaultSpecs, got {type(s).__name__}")
        self.specs: Tuple[FaultSpec, ...] = tuple(specs)
        self._fired: set = set()
        self._write_counts: dict = {}

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def before_step(self, epoch: int, start: int, stop: Optional[int] = None) -> None:
        """Fire any one-shot ``raise``/``sigterm`` fault addressed to a
        batch ordinal in ``[start, stop)`` of ``epoch`` (a superstep block
        passes its full range: the fault lands at the block boundary, the
        same safe point the emergency checkpoint uses)."""
        if not self.specs:
            return
        stop = start + 1 if stop is None else stop
        for i, spec in enumerate(self.specs):
            if spec.kind not in ("raise", "sigterm"):
                continue
            key = ("step", i)
            if key in self._fired or not spec._matches_step(epoch, start, stop):
                continue
            self._fired.add(key)
            _count_fault(spec.kind)
            if spec.kind == "sigterm":
                signal.raise_signal(signal.SIGTERM)
            else:
                raise InjectedFault(
                    f"injected fault at epoch {epoch}, step {spec.step}"
                )

    def poison_value(self, epoch: int, step: int) -> Optional[float]:
        """The NaN/Inf payload to inject at this batch, or ``None``.

        A pure match (no one-shot state): a rollback re-run that revisits
        this ordinal must poison it again, or the re-run would train on a
        batch the original pass skipped.
        """
        for spec in self.specs:
            if spec.kind == "poison" and spec._matches_step(epoch, step, step + 1):
                _count_fault("poison")
                return spec.payload
        return None

    def should_drop(self, epoch: int, step: int) -> bool:
        """Whether this batch is consumed without an optimizer step."""
        hit = any(
            spec.kind == "drop" and spec._matches_step(epoch, step, step + 1)
            for spec in self.specs
        )
        if hit:
            _count_fault("drop")
        return hit

    def any_drop(self, epoch: int, start: int, stop: int) -> bool:
        """Whether any ordinal in ``[start, stop)`` carries a drop fault —
        a fused block containing one falls back to the per-step path."""
        return any(
            spec.kind == "drop" and spec._matches_step(epoch, start, stop)
            for spec in self.specs
        )

    def mutate_write(self, path: str, data: bytes) -> bytes:
        """Corrupt checkpoint bytes bound for ``path`` per any matching
        one-shot write fault (counted per spec over writes whose basename
        matches its glob)."""
        if not self.specs:
            return data
        name = os.path.basename(path)
        for i, spec in enumerate(self.specs):
            if spec.kind not in _WRITE_KINDS:
                continue
            if not fnmatch.fnmatch(name, spec.path_glob):
                continue
            key = ("write", i)
            count = self._write_counts.get(key, 0)
            self._write_counts[key] = count + 1
            if count != spec.write_index or key in self._fired:
                continue
            self._fired.add(key)
            _count_fault(spec.kind)
            if spec.kind == "truncate-write":
                data = data[: max(1, int(len(data) * spec.keep_fraction))]
            else:
                idx = spec.flip_byte if spec.flip_byte >= 0 else len(data) // 2
                mutated = bytearray(data)
                mutated[idx] ^= 0x01
                data = bytes(mutated)
        return data


@dataclasses.dataclass(frozen=True)
class ServeFaultSpec:
    """One deterministic serving-side trigger in a :class:`ServeFaultPlan`.

    Dispatch kinds (addressed by ``dispatch``, the 0-based ordinal of
    coalesced micro-batch dispatches; ``None`` = every dispatch):

    - ``"dispatch-raise"`` — raise :class:`InjectedFault` at dispatch
      entry (one-shot): the XLA-error/driver-crash stand-in. The batcher
      must wrap it per waiter and the worker must survive.
    - ``"dispatch-slow"``  — sleep ``slow_ms`` before the dispatch (pure
      match): sustained device slowdown, the regime that backs the queue
      up and makes admission control shed.
    - ``"dispatch-hang"``  — sleep ``hang_ms`` before the dispatch (pure
      match): a long stall; queued requests' deadlines expire behind it
      and must be shed at dispatch time, not served late.
    - ``"batcher-die"``    — raise :class:`BatcherKilled` at dispatch
      entry (one-shot): kills the worker thread itself; pending and
      future submits must fail fast (``BatcherWedged``) and the engine
      must degrade to its inline path.

    Checkpoint kind (addressed by ``path_glob``):

    - ``"corrupt-checkpoint"`` — flip one bit of byte ``flip_byte`` of a
      matching checkpoint file *at rest* (one-shot per spec), before the
      hot-swap watcher reads it: the mid-watch bit-rot drill. The
      watcher must quarantine and keep serving the old params.
    """

    kind: str
    dispatch: Optional[int] = None
    slow_ms: float = 0.0
    hang_ms: float = 0.0
    path_glob: str = "latest.ckpt"
    flip_byte: int = -1

    def __post_init__(self):
        if self.kind not in SERVE_KINDS:
            raise ValueError(
                f"serve fault kind must be one of {SERVE_KINDS}, got "
                f"{self.kind!r}"
            )
        if self.kind == "dispatch-slow" and self.slow_ms <= 0:
            raise ValueError("dispatch-slow faults need slow_ms > 0")
        if self.kind == "dispatch-hang" and self.hang_ms <= 0:
            raise ValueError("dispatch-hang faults need hang_ms > 0")
        if self.kind in ("dispatch-raise", "batcher-die") and self.dispatch is None:
            raise ValueError(
                f"{self.kind!r} faults need an explicit dispatch ordinal"
            )

    def _matches_dispatch(self, ordinal: int) -> bool:
        return self.dispatch is None or self.dispatch == ordinal


class ServeFaultPlan:
    """Deterministic serving faults, consulted by the micro-batch worker
    at dispatch entry and by the hot-swap watcher before each poll.

    Same contract as :class:`FaultPlan`: the empty plan is the
    production default and every hook short-circuits immediately — the
    engine has no instrumented build. One-shot state lives on the plan
    instance.
    """

    def __init__(self, *specs: ServeFaultSpec):
        if len(specs) == 1 and not isinstance(specs[0], ServeFaultSpec):
            specs = tuple(specs[0])  # accept ServeFaultPlan([spec, ...])
        for s in specs:
            if not isinstance(s, ServeFaultSpec):
                raise TypeError(
                    f"ServeFaultPlan takes ServeFaultSpecs, got "
                    f"{type(s).__name__}"
                )
        self.specs: Tuple[ServeFaultSpec, ...] = tuple(specs)
        self._fired: set = set()

    @property
    def active(self) -> bool:
        return bool(self.specs)

    def before_dispatch(self, ordinal: int) -> None:
        """Fire any fault addressed to this dispatch ordinal. Sleeps for
        slow/hang kinds; raises for raise/die kinds (one-shot)."""
        if not self.specs:
            return
        import time

        for i, spec in enumerate(self.specs):
            if not spec._matches_dispatch(ordinal):
                continue
            if spec.kind == "dispatch-slow":
                _count_fault("dispatch-slow")
                time.sleep(spec.slow_ms / 1e3)
            elif spec.kind == "dispatch-hang":
                _count_fault("dispatch-hang")
                time.sleep(spec.hang_ms / 1e3)
            elif spec.kind in ("dispatch-raise", "batcher-die"):
                key = ("dispatch", i)
                if key in self._fired:
                    continue
                self._fired.add(key)
                _count_fault(spec.kind)
                if spec.kind == "batcher-die":
                    raise BatcherKilled(
                        f"injected batcher death at dispatch {ordinal}"
                    )
                raise InjectedFault(
                    f"injected dispatch fault at dispatch {ordinal}"
                )

    def corrupt_checkpoints(self, out_dir: str) -> list:
        """Flip bytes at rest in checkpoint files matching any one-shot
        ``corrupt-checkpoint`` spec; returns the corrupted paths. Called
        by the hot-swap watcher at poll start, BEFORE verification — the
        drill is bit rot landing between writer and reader."""
        if not self.specs:
            return []
        hit = []
        for i, spec in enumerate(self.specs):
            if spec.kind != "corrupt-checkpoint":
                continue
            key = ("ckpt", i)
            if key in self._fired:
                continue
            try:
                names = sorted(os.listdir(out_dir))
            except OSError:
                continue
            for name in names:
                if not fnmatch.fnmatch(name, spec.path_glob):
                    continue
                path = os.path.join(out_dir, name)
                try:
                    with open(path, "rb") as f:
                        data = bytearray(f.read())
                    if not data:
                        continue
                    idx = (
                        spec.flip_byte
                        if spec.flip_byte >= 0
                        else len(data) // 2
                    )
                    data[idx] ^= 0x01
                    with open(path, "wb") as f:
                        f.write(bytes(data))
                except OSError:
                    continue
                self._fired.add(key)
                _count_fault("corrupt-checkpoint")
                hit.append(path)
                break
        return hit
