"""Command line entry point.

Supersedes the reference's ``Main.py`` argparse (``Main.py:21-34``): same
user-facing knobs (``-date``, ``-cpt``, data path, loss) plus preset
selection for the five baseline configs and full hyperparameter override.
No ``-device`` flag — JAX owns device selection, and multi-device execution
is a mesh config, not a flag.

Usage::

    python -m stmgcn_tpu.cli --preset smoke
    python -m stmgcn_tpu.cli --preset default --data ./data/data_dict.npz \
        -date 0101 0630 0701 0731 -cpt 3 1 1
    python -m stmgcn_tpu.cli --preset default --test-only --out-dir output
    python -m stmgcn_tpu.cli lint --format json   # static analysis gate
    python -m stmgcn_tpu.cli serve-bench          # serving-engine benchmark
    python -m stmgcn_tpu.cli obs trace.jsonl      # span-trace report
"""

from __future__ import annotations

import argparse
import json
import sys

from stmgcn_tpu.config import PRESETS, preset

__all__ = ["build_parser", "main"]


def _positive_int(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn",
        description="TPU-native ST-MGCN: spatiotemporal multi-graph demand forecasting",
    )
    p.add_argument("--preset", choices=sorted(PRESETS), default="default",
                   help="baseline config to start from")
    p.add_argument("--data", type=str, default=None,
                   help="path to a data_dict.npz archive (default: synthetic)")
    p.add_argument("-date", "--dates", type=str, nargs=4, default=None,
                   metavar=("TRAIN_S", "TRAIN_E", "TEST_S", "TEST_E"),
                   help="MMDD split dates, e.g. -date 0101 0630 0701 0731")
    p.add_argument("-cpt", "--obs-len", type=int, nargs=3, default=None,
                   metavar=("SERIAL", "DAILY", "WEEKLY"),
                   help="observation window lengths, e.g. -cpt 3 1 1")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr-schedule", choices=("none", "cosine"), default=None,
                   help="constant lr (reference parity) or warmup+cosine "
                        "decay sized to the full run")
    p.add_argument("--warmup-epochs", type=float, default=None,
                   help="linear warmup extent for --lr-schedule cosine")
    p.add_argument("--min-lr-fraction", type=float, default=None,
                   help="cosine floor as a fraction of --lr")
    p.add_argument("--weight-decay", type=float, default=None)
    p.add_argument("--grad-clip-norm", type=float, default=None,
                   help="global-norm gradient clipping (off by default)")
    p.add_argument("--loss", choices=("mse", "mae", "huber"), default=None)
    p.add_argument("--patience", type=int, default=None)
    p.add_argument("--top-k", type=int, default=None,
                   help="keep the k best improvement snapshots (best_eN.ckpt) "
                        "alongside best/latest")
    p.add_argument("--shuffle", action="store_true", default=None,
                   help="shuffle training batches (reference default is off)")
    p.add_argument("--m-graphs", type=int, default=None)
    p.add_argument("--kernel", choices=("chebyshev", "localpool", "random_walk_diffusion"),
                   default=None)
    p.add_argument("--cheb-k", type=int, default=None, help="max polynomial order K")
    p.add_argument("--dtype", choices=("float32", "bfloat16"), default=None)
    p.add_argument("--precision", choices=("fp32", "bf16"), default=None,
                   help="step-program compute precision: fp32 (default — "
                        "bit-identical to the pre-mixed-precision programs) "
                        "or bf16 (lint-certified mixed-precision twins: bf16 "
                        "matmul operands, f32 accumulation islands, f32 "
                        "master params in the optimizer and checkpoints)")
    p.add_argument("--sr-seed", type=int, default=None, metavar="SEED",
                   help="stochastically round the master->bf16 param casts "
                        "with this seed (bf16 only; default: deterministic "
                        "round-to-nearest-even)")
    p.add_argument("--lstm-backend", choices=("xla", "pallas"), default=None,
                   help="LSTM recurrence implementation: lax.scan (xla) or "
                        "the fused Pallas TPU kernel (pallas)")
    p.add_argument("--lstm-unroll", type=int, default=None,
                   help="lax.scan unroll factor for the LSTM recurrence")
    p.add_argument("--lstm-fused", action="store_true", default=None,
                   help="run all LSTM layers inside one scan over time")
    p.add_argument("--sparse", action="store_true", default=None,
                   help="use the Pallas block-CSR SpMM path for graph convs")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--out-dir", type=str, default=None)
    p.add_argument("--data-placement", choices=("auto", "resident", "stream"),
                   default=None,
                   help="batch data residency: upload splits once and gather "
                        "on device (resident), upload per batch with "
                        "prefetch (stream), or pick by device/size (auto)")
    p.add_argument("--steps-per-superstep", type=_positive_int, default=None,
                   metavar="S",
                   help="fuse S train steps into one jitted lax.scan "
                        "dispatch with on-device batch gather (needs "
                        "resident data + shared graphs; bit-identical "
                        "results, S-fold fewer host dispatches; default 1)")
    p.add_argument("--window-free", dest="window_free", action="store_true",
                   default=None,
                   help="require the window-free resident path: keep the raw "
                        "(T, N, C) series on device and gather each batch's "
                        "windows inside the jitted step (~seq_len x less "
                        "resident HBM; default: on wherever it can hold)")
    p.add_argument("--no-window-free", dest="window_free",
                   action="store_false",
                   help="force materialized window arrays (the bit-parity "
                        "oracle / streaming-hetero fallback path)")
    p.add_argument("--fleet", dest="fleet", action="store_true", default=None,
                   help="require fleet shape-class training: heterogeneous "
                        "cities grouped into node-count rungs, one fused "
                        "superstep program per class (default: auto when "
                        "--steps-per-superstep > 1 and the dataset is viable)")
    p.add_argument("--no-fleet", dest="fleet", action="store_false",
                   help="never group cities into shape classes (the "
                        "materialized per-city loop — the parity oracle)")
    p.add_argument("--fleet-max-classes", type=_positive_int, default=None,
                   metavar="C",
                   help="most shape classes the fleet planner may open "
                        "(default 8); cities fitting none run per-step")
    p.add_argument("--fleet-max-pad-waste", type=float, default=None,
                   metavar="F",
                   help="max padded-node fraction of a rung a city may "
                        "waste before it is excluded from the class "
                        "(default 0.5)")
    p.add_argument("--normalize", choices=("minmax", "std", "none"), default=None,
                   help="demand normalization (reference parity: minmax to "
                        "[-1,1]; stats travel inside checkpoints either way)")
    p.add_argument("--val-ratio", type=float, default=None,
                   help="validation fraction carved off the end of train "
                        "(reference default 0.2)")
    p.add_argument("--horizon", type=int, default=None,
                   help="forecast steps per sample (default 1, next-step)")
    p.add_argument("--rows", type=int, default=None,
                   help="synthetic city grid rows (N = rows^2)")
    p.add_argument("--timesteps", type=int, default=None,
                   help="synthetic demand length in timesteps")
    p.add_argument("--platform", choices=("tpu", "cpu"), default=None,
                   help="force a JAX platform (default: auto)")
    p.add_argument("--virtual-devices", type=int, default=None, metavar="N",
                   help="emulate N devices on CPU (for mesh dry-runs; implies "
                        "--platform cpu)")
    p.add_argument("--branch-parallel", type=_positive_int, default=None,
                   metavar="B",
                   help="shard the M graph branches over a 'branch' mesh "
                        "axis of extent B (composes with dense GSPMD, "
                        "banded, and sparse supports; B must divide "
                        "m_graphs)")
    p.add_argument("--region-strategy", choices=("gspmd", "banded", "auto"),
                   default=None,
                   help="region-sharded conv plan: XLA's automatic (gspmd), "
                        "explicit halo exchange for banded graphs (banded), "
                        "or per-branch routing (auto)")
    p.add_argument("--halo", type=int, default=None,
                   help="halo budget for the banded region strategy "
                        "(default: tightest, capped at shard_size/2 for auto)")
    p.add_argument("--matmul-precision", choices=("default", "high", "highest"),
                   default=None,
                   help="jax default matmul precision (TPU fp32 matmuls use "
                        "fast bf16 passes under 'default'; 'highest' for "
                        "iso-accuracy comparisons)")
    p.add_argument("--distributed", action="store_true",
                   help="join a multi-host job (jax.distributed.initialize; "
                        "TPU pods auto-discover the coordinator)")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans (fail fast at the op producing NaN)")
    p.add_argument("--checkify", choices=("nan", "index", "float", "all"),
                   default=None, dest="checks",
                   help="functional sanitizer on the train/eval steps "
                        "(jax.experimental.checkify): fails at the step "
                        "producing the bad value (nan: NaNs; index: OOB "
                        "gathers/scatters; float: nan+div0; all: "
                        "everything), works under jit+donation on TPU; "
                        "costs a device sync per step")
    p.add_argument("--profile", type=str, default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the run into DIR")
    p.add_argument("--trace-out", type=str, default=None, metavar="PATH",
                   help="record wall-clock spans (host pack / upload / "
                        "device superstep / checkpoint) plus JAX compile "
                        "telemetry and write the schema-versioned JSONL "
                        "timeline to PATH; inspect with `stmgcn obs PATH`")
    p.add_argument("--health-out", type=str, default=None, metavar="PATH",
                   help="enable numeric-health telemetry and write the "
                        "schema-versioned health.jsonl (loss / grad norm / "
                        "update ratio / nonfinite counts / per-group and "
                        "per-city attribution) to PATH; inspect with "
                        "`stmgcn health PATH`")
    p.add_argument("--health-every-k", type=_positive_int, default=None,
                   metavar="K",
                   help="health sampling cadence: instrument every K-th "
                        "step (per-step path) or superstep block (fused "
                        "paths); implies health telemetry on (default 1)")
    p.add_argument("--resume", nargs="?", const="strict", default=None,
                   choices=("strict", "auto"),
                   help="resume before training from the newest *verified* "
                        "checkpoint in <out-dir> (latest -> rotated previous "
                        "-> best snapshots; corrupt files are quarantined). "
                        "Bare --resume errors when nothing resumable exists; "
                        "--resume auto starts fresh instead (preemptible-job "
                        "restart loops). Mid-epoch checkpoints continue "
                        "bit-exactly from the step they were written at")
    p.add_argument("--checkpoint-every-steps", type=int, default=None,
                   metavar="K",
                   help="additionally rewrite latest.ckpt every K optimizer "
                        "steps with the exact mid-epoch resume cursor "
                        "(default 0: epoch boundaries only)")
    p.add_argument("--divergence-guard", action="store_true", default=None,
                   help="check each step's loss for NaN/Inf; on a trip, roll "
                        "params/optimizer back to the pre-step snapshot and "
                        "skip (or defer) the batch. Costs a device sync per "
                        "step")
    p.add_argument("--divergence-action", choices=("skip", "defer"),
                   default=None,
                   help="what the guard does with an offending batch: drop "
                        "it (skip) or retry it once at epoch end (defer)")
    p.add_argument("--divergence-patience", type=_positive_int, default=None,
                   help="abort after this many consecutive guard trips "
                        "(default 3) — persistent divergence is not a "
                        "single bad batch; see --checkify nan")
    p.add_argument("--divergence-lr-cut", type=float, default=None,
                   metavar="F",
                   help="multiply the learning rate by F in (0,1) on each "
                        "guard trip")
    p.add_argument("--export", type=str, default=None, metavar="PATH",
                   help="after training/testing, write the best checkpoint "
                        "as a self-contained AOT serving artifact "
                        "(serialized StableHLO + normalizer; see "
                        "stmgcn_tpu.export)")
    p.add_argument("--test-only", action="store_true",
                   help="skip training; evaluate <out-dir>/best.ckpt")
    p.add_argument("--print-config", action="store_true",
                   help="print the resolved config as JSON and exit")
    return p


def config_from_args(args) -> "ExperimentConfig":
    cfg = preset(args.preset)
    if args.data is not None:
        cfg.data.path = args.data
    if args.dates is not None:
        cfg.data.dates = tuple(args.dates)
    if args.obs_len is not None:
        cfg.data.serial_len, cfg.data.daily_len, cfg.data.weekly_len = args.obs_len
    if args.val_ratio is not None:
        # val_ratio is the fraction carved off *train* (date path,
        # Data_Container.py:106-108 semantics: train shrinks by the carve).
        # Mirror that on the fraction path: the original train block splits
        # into train' = train*(1-r) and val = train*r; test is untouched.
        cfg.data.val_ratio = args.val_ratio
        cfg.data.val_frac = cfg.data.train_frac * args.val_ratio
        cfg.data.train_frac = cfg.data.train_frac * (1.0 - args.val_ratio)
    if args.horizon is not None:
        cfg.data.horizon = args.horizon
    if args.normalize is not None:
        cfg.data.normalize = args.normalize
    if args.rows is not None:
        cfg.data.rows = args.rows
    if args.timesteps is not None:
        cfg.data.n_timesteps = args.timesteps
    for field, attr in [
        ("epochs", "epochs"), ("batch_size", "batch_size"), ("lr", "lr"),
        ("lr_schedule", "lr_schedule"), ("warmup_epochs", "warmup_epochs"),
        ("min_lr_fraction", "min_lr_fraction"),
        ("weight_decay", "weight_decay"), ("grad_clip_norm", "grad_clip_norm"),
        ("loss", "loss"),
        ("patience", "patience"), ("top_k", "top_k"), ("seed", "seed"),
        ("checks", "checks"),
        ("out_dir", "out_dir"), ("data_placement", "data_placement"),
        ("window_free", "window_free"),
        ("steps_per_superstep", "steps_per_superstep"),
        ("fleet", "fleet"),
        ("fleet_max_classes", "fleet_max_classes"),
        ("fleet_max_pad_waste", "fleet_max_pad_waste"),
        ("checkpoint_every_steps", "checkpoint_every_steps"),
        ("divergence_action", "divergence_action"),
        ("divergence_patience", "divergence_patience"),
        ("divergence_lr_cut", "divergence_lr_cut"),
        ("precision", "precision"), ("sr_seed", "sr_seed"),
    ]:
        val = getattr(args, field)
        if val is not None:
            setattr(cfg.train, attr, val)
    if args.shuffle:
        cfg.train.shuffle = True
    if args.divergence_guard:
        cfg.train.divergence_guard = True
    if args.m_graphs is not None:
        cfg.model.m_graphs = args.m_graphs
    if args.kernel is not None:
        cfg.model.kernel_type = args.kernel
    if args.cheb_k is not None:
        cfg.model.K = args.cheb_k
    if args.dtype is not None:
        cfg.model.dtype = args.dtype
    if args.sparse:
        cfg.model.sparse = True
    if args.lstm_unroll is not None:
        cfg.model.lstm_unroll = args.lstm_unroll
    if args.lstm_fused:
        cfg.model.lstm_fused_scan = True
    if args.lstm_backend is not None:
        cfg.model.lstm_backend = args.lstm_backend
    if args.branch_parallel is not None:
        cfg.mesh.branch = args.branch_parallel
    if args.region_strategy is not None:
        cfg.mesh.region_strategy = args.region_strategy
    if args.halo is not None:
        cfg.mesh.halo = args.halo
    if args.health_out is not None or args.health_every_k is not None:
        cfg.health.enabled = True
        if args.health_out is not None:
            cfg.health.out = args.health_out
        if args.health_every_k is not None:
            cfg.health.every_k = args.health_every_k
    return cfg


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "lint":
        # static-analysis subcommand: no training imports, no JAX backend
        # unless the contract pass runs (and then CPU-pinned)
        from stmgcn_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "serve-bench":
        # serving-engine benchmark: naive vs AOT-bucketed vs micro-batched
        # prediction throughput; one JSON record line on stdout
        from stmgcn_tpu.serving.bench import main as serve_bench_main

        return serve_bench_main(argv[1:])
    if argv and argv[0] == "obs":
        # span-trace report: pure stdlib, no JAX backend initialization
        from stmgcn_tpu.obs.cli import main as obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "health":
        # numeric-health report: stdlib+numpy, no JAX backend initialization
        from stmgcn_tpu.obs.cli import health_main

        return health_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.print_config:
        print(json.dumps(cfg.to_dict(), indent=2))
        return 0

    # Platform selection must land before the JAX backend initializes (no
    # jax array op has run yet at this point).
    if args.virtual_devices:
        args.platform = args.platform or "cpu"
    if args.platform:
        from stmgcn_tpu.utils import force_host_platform

        force_host_platform(args.platform, n_devices=args.virtual_devices)
    if args.debug_nans:
        import jax

        jax.config.update("jax_debug_nans", True)
    if args.matmul_precision:
        import jax

        jax.config.update("jax_default_matmul_precision", args.matmul_precision)
    if args.distributed:
        from stmgcn_tpu.parallel import init_distributed

        init_distributed()
    if args.trace_out:
        # after platform forcing (no backend op has run), before the first
        # compile — so the jax.monitoring listener sees every compilation
        from stmgcn_tpu.obs import jaxmon
        from stmgcn_tpu.obs import trace as obs_trace

        cfg.obs.trace = True
        cfg.obs.trace_path = args.trace_out
        obs_trace.configure(capacity=cfg.obs.ring_capacity)
        jaxmon.install()

    from stmgcn_tpu.experiment import build_trainer  # defer heavy imports

    try:
        trainer = build_trainer(cfg)
    except ValueError as e:
        # configuration errors (mesh size, divisibility, splits) — no traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    except FileNotFoundError as e:
        print(f"error: {e.filename or e} not found", file=sys.stderr)
        return 1
    from stmgcn_tpu.resilience import Preempted

    try:
        if args.resume == "auto":
            # resume-if-possible: the restart-loop mode for preemptible
            # jobs — an empty/corrupt-beyond-recovery out_dir starts fresh
            meta = trainer.restore_auto()
            if meta is None:
                print("No resumable checkpoint found — starting fresh")
            else:
                print(f"Resumed from epoch {meta['epoch']} "
                      f"(best val {meta['best_val']:.5})")
        elif args.resume:
            meta = trainer.restore()
            print(f"Resumed from epoch {meta['epoch']} (best val {meta['best_val']:.5})")
        import contextlib

        with contextlib.ExitStack() as stack:
            if args.profile:
                from stmgcn_tpu.utils import trace

                stack.enter_context(trace(args.profile))
            if not args.test_only:
                trainer.train()
            results = trainer.test(modes=("train", "test"))
        if args.profile:
            print(f"profiler trace written to {args.profile}")
    except Preempted as e:
        # the emergency checkpoint already landed; exit with SIGTERM's
        # conventional code so supervisors treat it as a clean preemption
        print(f"preempted: {e}", file=sys.stderr)
        return 143
    except FileNotFoundError as e:
        print(f"error: {e.filename or e} not found"
              + (" — train first or check --out-dir" if args.test_only or args.resume else ""),
              file=sys.stderr)
        return 1
    import jax

    if jax.process_index() == 0:  # one JSON line per job, not per host
        print(json.dumps({"preset": cfg.name, "results": results}))
    if args.trace_out and jax.process_index() == 0:
        from stmgcn_tpu.obs import jaxmon
        from stmgcn_tpu.obs import trace as obs_trace

        trc = obs_trace.active_tracer()
        if trc is not None:
            n = trc.export_jsonl(args.trace_out)
            mon = jaxmon.snapshot()
            print(
                f"trace written to {args.trace_out} ({n} spans, "
                f"{mon['compilations']} compilations, "
                f"{mon['recompiles_after_warmup']} recompiles after warmup)"
                " — inspect with `stmgcn obs " + args.trace_out + "`",
                file=sys.stderr,
            )

    # Export last: a failed export must not cost the run its results line.
    if args.export:
        ok = True
        if jax.process_index() == 0:
            import os

            from stmgcn_tpu.export import export_forecaster
            from stmgcn_tpu.inference import Forecaster

            try:
                fc = Forecaster.from_checkpoint(
                    os.path.join(cfg.train.out_dir, "best.ckpt")
                )
                if getattr(fc, "normalizers", None) is not None:
                    # heterogeneous multi-city: one fixed-N artifact per
                    # city (each bakes that city's normalizer)
                    root, ext = os.path.splitext(args.export)
                    for c in range(len(fc.normalizers)):
                        city_path = f"{root}.city{c}{ext}"
                        export_forecaster(fc, city_path, city=c)
                        print(f"serving artifact written to {city_path}")
                else:
                    export_forecaster(fc, args.export)
                    print(f"serving artifact written to {args.export}")
            except Exception as e:  # noqa: BLE001 — host 0 must reach the
                # broadcast below no matter how export dies, or every other
                # host blocks forever in the collective
                print(f"error: export failed: {type(e).__name__}: {e}", file=sys.stderr)
                ok = False
        if jax.process_count() > 1:
            # every host must exit with the same code — a launcher
            # aggregating per-host codes must see the failure everywhere
            import numpy as np
            from jax.experimental import multihost_utils

            ok = bool(multihost_utils.broadcast_one_to_all(np.asarray(ok)))
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
