"""Region-sharded block-sparse support application.

Composes the Pallas block-CSR SpMM (:mod:`stmgcn_tpu.ops.spmm`) with the
``(dp, region)`` mesh: each region shard stores only its **row strip** of
every support in block-CSR form (``O(nnz / n_shards)`` memory — the point
of sparsity at N=2500, where dense ``(K, N, N)`` supports are the
quadratic blowup SURVEY.md §2 quirk 8 flags), all-gathers the node axis
of the signal over the region ring, and runs ONE fused-K kernel launch on
its strip. The batch axis stays partitioned over ``dp`` throughout.

Communication is the same as GSPMD's dense plan (one signal all-gather
per conv — arbitrary graph structure can touch any column); compute and
support memory are sparse. For *banded* graphs the halo plan
(:mod:`stmgcn_tpu.parallel.banded`) moves strictly less data; ``auto``
region routing prefers it where it applies.

The backward pass needs no hand-written collective: the kernel's custom
VJP produces this shard's column-contribution ``A_s^T @ g_s`` and
``shard_map`` transposes the tiled all-gather into the matching
``psum_scatter`` automatically.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from stmgcn_tpu.utils.platform import shard_map
from stmgcn_tpu.ops.spmm import (
    TILE,
    BlockSparseStack,
    _assemble_blocks,
    _scan_blocks,
    spmm_stack,
)

__all__ = [
    "ShardedBlockSparse",
    "branch_stack_sparse",
    "sharded_from_dense",
    "sharded_spmm_apply",
]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class ShardedBlockSparse:
    """Per-shard row-strip :class:`BlockSparseStack` s, stacked on a leading
    shard axis (shardable over ``region`` with one ``NamedSharding``).

    ``data`` ``(S, K, R_loc, C, tile, tile)``, ``idx`` ``(S, K, R_loc, C)``;
    transpose structure likewise (each strip's ``(N, n_local)`` transpose).

    The branch-stacked form used by branch-parallel meshes
    (:func:`branch_stack_sparse`) carries a leading graph axis — ``data``
    ``(M, S, K, R_loc, C, tile, tile)`` with one common block-column
    width: ``nn.vmap`` over the model's branch axis then maps axis 0,
    handing each branch the ordinary form. Shape properties index from
    the end so both forms answer correctly.
    """

    data: jnp.ndarray
    idx: jnp.ndarray
    data_t: jnp.ndarray
    idx_t: jnp.ndarray
    n: int  # global node count
    tile: int

    def tree_flatten(self):
        return (self.data, self.idx, self.data_t, self.idx_t), (self.n, self.tile)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, idx, data_t, idx_t = children
        n, tile = aux
        return cls(data=data, idx=idx, data_t=data_t, idx_t=idx_t, n=n, tile=tile)

    @property
    def n_shards(self) -> int:
        return self.data.shape[-6]

    @property
    def n_supports(self) -> int:
        return self.data.shape[-5]

    @property
    def branch_stacked(self) -> bool:
        return self.data.ndim == 7

    @property
    def n_local(self) -> int:
        return self.n // self.n_shards

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.idx.nbytes + self.data_t.nbytes + self.idx_t.nbytes


def sharded_from_dense(mats, n_shards: int, tile: int = TILE) -> ShardedBlockSparse:
    """Split dense ``(K, N, N)`` supports into per-shard block-CSR strips.

    All shards share one ``(c_max, c_max_t)`` so the stacked arrays are
    uniform (padding rows keep index 0 with zero data, harmless).
    """
    data, idx, data_t, idx_t, n = _sharded_np(mats, n_shards, tile)
    return ShardedBlockSparse(
        data=jnp.asarray(data),
        idx=jnp.asarray(idx),
        data_t=jnp.asarray(data_t),
        idx_t=jnp.asarray(idx_t),
        n=n,
        tile=tile,
    )


def _sharded_np(mats, n_shards: int, tile: int):
    """Host-side assembly of :func:`sharded_from_dense`'s arrays (numpy) —
    shared with :func:`branch_stack_sparse`, which must pad and re-stack
    before anything is uploaded to a device."""
    mats = np.asarray(mats, dtype=np.float32)
    k, n, n2 = mats.shape
    if n != n2:
        raise ValueError(f"supports must be (K, N, N), got {mats.shape}")
    if n % n_shards:
        raise ValueError(f"N={n} not divisible by {n_shards} shards")
    n_local = n // n_shards
    # one scan per (shard, support, direction); shared c_max across all
    # shards and supports so the stacked arrays are uniform, then one
    # assembly pass (padding rows keep index 0 with zero data, harmless)
    fwd_scan, bwd_scan = [], []
    for s in range(n_shards):
        rows = slice(s * n_local, (s + 1) * n_local)
        fwd_scan.append([_scan_blocks(mats[ki, rows, :], tile) for ki in range(k)])
        bwd_scan.append(
            [_scan_blocks(np.ascontiguousarray(mats[ki, rows, :].T), tile)
             for ki in range(k)]
        )
    occupancy = lambda scans: max(  # noqa: E731 — local helper
        max(int(nz.sum(axis=1).max()), 1) for per_shard in scans for _, nz in per_shard
    )
    c_max, c_max_t = occupancy(fwd_scan), occupancy(bwd_scan)

    def assemble(scans, width):
        pairs = [
            [_assemble_blocks(b, nz, width, tile) for b, nz in per_shard]
            for per_shard in scans
        ]
        data = np.stack([np.stack([d for d, _ in per]) for per in pairs])
        idx = np.stack([np.stack([i for _, i in per]) for per in pairs])
        return data, idx

    data, idx = assemble(fwd_scan, c_max)
    data_t, idx_t = assemble(bwd_scan, c_max_t)
    return data, idx, data_t, idx_t, n


def branch_stack_sparse(
    dense_stack, n_shards: int, tile: int = TILE
) -> ShardedBlockSparse:
    """Stack M branches' ``(K, N, N)`` dense supports into ONE
    branch-stacked :class:`ShardedBlockSparse`.

    Branch model parallelism shards the model's vmapped branch axis over
    the mesh; the sparse supports must then be a single stacked operand.
    Each branch keeps its own block-CSR content, but the block-column
    axis pads to the *max* occupancy across branches so the stacked
    arrays are uniform — padding blocks keep index 0 with zero data, the
    same harmless convention :func:`sharded_from_dense` uses for padded
    rows. The sparse analogue of :func:`~stmgcn_tpu.parallel.banded.
    branch_stack`'s common halo."""
    dense_stack = np.asarray(dense_stack, dtype=np.float32)
    per = [
        _sharded_np(dense_stack[m], n_shards, tile)  # host-side numpy:
        for m in range(dense_stack.shape[0])  # pad+stack before upload
    ]

    def pad_c(a, width):
        extra = width - a.shape[3]
        if extra == 0:
            return a
        widths = [(0, 0)] * a.ndim
        widths[3] = (0, extra)
        return np.pad(a, widths)

    c_max = max(data.shape[3] for data, _, _, _, _ in per)
    c_max_t = max(data_t.shape[3] for _, _, data_t, _, _ in per)
    return ShardedBlockSparse(
        data=jnp.asarray(np.stack([pad_c(d, c_max) for d, _, _, _, _ in per])),
        idx=jnp.asarray(np.stack([pad_c(i, c_max) for _, i, _, _, _ in per])),
        data_t=jnp.asarray(np.stack([pad_c(dt, c_max_t) for _, _, dt, _, _ in per])),
        idx_t=jnp.asarray(np.stack([pad_c(it, c_max_t) for _, _, _, it, _ in per])),
        n=per[0][4],
        tile=tile,
    )


def sharded_spmm_apply(
    mesh: Mesh,
    ssp: ShardedBlockSparse,
    x,
    axis_name: str = "region",
    batch_axis: str = "dp",
) -> jnp.ndarray:
    """``out[k,b,i,f] = sum_j A_k[i,j] x[b,j,f]`` with node axis sharded and
    supports stored as per-shard sparse strips. ``x``: ``(B, N, F)``;
    returns ``(K, B, N, F)`` float32, node axis sharded over ``axis_name``.
    """
    b_ax = batch_axis if batch_axis in mesh.shape and mesh.shape[batch_axis] > 1 else None
    n, n_local = ssp.n, ssp.n_local
    tile = ssp.tile

    def local(data, idx, data_t, idx_t, x_loc):
        # leading shard axis arrives as a size-1 block; x_loc: (b, n_loc, F)
        bss = BlockSparseStack(
            data=data[0], idx=idx[0], data_t=data_t[0], idx_t=idx_t[0],
            n_rows=n_local, n_cols=n, tile=tile,
        )
        x_full = jax.lax.all_gather(x_loc, axis_name, axis=1, tiled=True)  # (b, N, F)
        b, _, f = x_full.shape
        x_mat = x_full.transpose(1, 0, 2).reshape(n, b * f)
        out = spmm_stack(bss, x_mat)  # (K, n_loc, b*F)
        return out.reshape(-1, n_local, b, f).transpose(0, 2, 1, 3)  # (K, b, n_loc, F)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(
            P(axis_name, None, None, None, None, None),
            P(axis_name, None, None, None),
            P(axis_name, None, None, None, None, None),
            P(axis_name, None, None, None),
            P(b_ax, axis_name, None),
        ),
        out_specs=P(None, b_ax, axis_name, None),
        # the Pallas call's out_shape carries no varying-mesh-axes metadata,
        # so shard_map's vma checker cannot see through it
        check_vma=False,
    )
    return fn(ssp.data, ssp.idx, ssp.data_t, ssp.idx_t, jnp.asarray(x))
