"""Array placement rules over a ``(dp, region[, branch])`` mesh.

One object answers "where does this array live": model/optimizer state is
replicated, batches are split over ``dp``, the graph-node axis over
``region``. Handing arrays placed this way to the (unchanged) jitted step
functions is all GSPMD needs — it propagates shardings through the model
and inserts the collectives (node all-gather in each graph conv, gradient
``psum`` over dp) automatically. This replaces the communication backend
the reference never had (SURVEY.md §5.h).

Array-kind conventions (shapes as in the model):

- ``supports`` ``(M, K, N, N)`` — rows (output nodes) sharded:
  ``P(None, None, 'region', None)``; with a ``branch`` mesh axis the
  graph axis shards too: ``P('branch', None, 'region', None)``
- ``x`` ``(B, T, N, C)`` — ``P('dp', None, 'region', None)``
- ``y`` ``(B, N, C)`` — ``P('dp', 'region', None)``; the seq2seq
  ``(B, H, N, C)`` form shards the node axis: ``P('dp', None, 'region',
  None)`` (the horizon axis is never sharded)
- ``mask`` ``(B,)`` — ``P('dp')``; node-padded ``(B, N)`` —
  ``P('dp', 'region')``
- ``state`` (params / optimizer) — replicated ``P()``; with a ``branch``
  axis, leaves under the vmapped ``branches`` subtree shard their leading
  ``(M, ...)`` axis over it (the fusion sum becomes a ``psum``) — branch
  model parallelism, the expert-parallel analogue for this model family

Window-free resident-series kinds (the composed multi-chip fast path —
the fused superstep consumes the resident series through
``gather_window_batch`` instead of placed window arrays):

- ``series`` ``(T, N, C)`` — ``P(None, 'region', None)``: the node axis
  shards; time stays whole so every shard's window gather is local
- ``index`` — int vectors/blocks that select *samples*: ``(B,)`` →
  ``P('dp')``, superstep ``(S, B)`` blocks → ``P(None, 'dp')``
- ``mask_block`` — superstep mask stacks: ``(S, B)`` → ``P(None, 'dp')``,
  node-padded ``(S, B, N)`` → ``P(None, 'dp', 'region')``
- ``replicated`` — small int vectors every shard needs whole (window
  target/offset tables, fleet slot ids) — ``P()``
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["BRANCH_FUSION", "DP_GRAD_SYNC", "GSPMD_REGION", "MeshPlacement"]


def _decl(kind, axes, required=False, reason=""):
    from stmgcn_tpu.parallel.manifest import CollectiveDecl

    return CollectiveDecl(kind=kind, axes=axes, required=required, reason=reason)


#: collective signature of the data-parallel placement: with batches
#: split over ``dp`` and params replicated, GSPMD syncs gradients and the
#: loss mean with ``all-reduce`` over ``dp`` — the plan-defining op of
#: every ``dp > 1`` training program (see :mod:`.manifest`)
DP_GRAD_SYNC = (
    _decl("all-reduce", "dp", required=True,
          reason="gradient + loss-mean psum over the batch axis"),
)

#: collective signature of dense region sharding: each graph conv's
#: node-axis contraction all-gathers the signal over ``region``
GSPMD_REGION = (
    _decl("all-gather", "region", required=True,
          reason="node-axis signal gather in the dense graph convs"),
)

#: collective signature of branch model parallelism: the branch-fusion
#: sum (and replicated-param grad sync) is an ``all-reduce`` over
#: ``branch``
BRANCH_FUSION = (
    _decl("all-reduce", "branch", required=True,
          reason="branch-fusion psum / replicated-param grad sync"),
)


class MeshPlacement:
    """Places arrays onto a mesh by kind; usable as the Trainer's placement."""

    SPECS = {
        "supports": P(None, None, "region", None),
        "x": P("dp", None, "region", None),
        "y": P("dp", "region", None),
        "mask": P("dp",),
        "state": P(),
        "series": P(None, "region", None),
        "index": P("dp",),
        "mask_block": P(None, "dp"),
        "replicated": P(),
    }

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def _spec(self, kind: str, ndim: int) -> P:
        if kind not in self.SPECS:
            raise ValueError(f"unknown array kind {kind!r}; known: {sorted(self.SPECS)}")
        if kind == "y" and ndim == 4:
            # seq2seq targets (B, H, N, C): region stays on the node axis
            return P("dp", None, "region", None)
        if kind == "mask" and ndim == 2:
            # (B, N) sample x node mask (node-padded meshes)
            return P("dp", "region")
        if kind == "index" and ndim == 2:
            # (S, B) superstep index blocks: steps stay whole, batch shards
            return P(None, "dp")
        if kind == "mask_block" and ndim == 3:
            # (S, B, N) node-padded superstep mask stacks
            return P(None, "dp", "region")
        return self.SPECS[kind]

    def sharding(self, kind: str, ndim: int = 3) -> NamedSharding:
        return NamedSharding(self.mesh, self._spec(kind, ndim))

    def put(self, tree, kind: str):
        """Place every array leaf of ``tree`` according to ``kind``.

        Batch axes must divide the mesh extents they shard over (use
        ``pad_last`` batching for static, divisible batch shapes).
        ``kind="supports"`` additionally understands the routed per-branch
        forms (see :func:`stmgcn_tpu.experiment.route_supports`).
        """
        if kind not in self.SPECS:
            raise ValueError(f"unknown array kind {kind!r}; known: {sorted(self.SPECS)}")
        if kind == "supports":
            return self._put_supports(tree)
        if kind == "state" and "branch" in self.mesh.shape:
            return self._put_state_branched(tree)
        return jax.tree.map(
            lambda a: jax.device_put(
                jnp.asarray(a), self.sharding(kind, jnp.ndim(a))
            ),
            tree,
        )

    def _put_state_branched(self, tree):
        """State placement with branch model parallelism: leaves under the
        vmapped ``branches`` subtree shard their leading (M, ...) axis over
        the ``branch`` mesh axis; everything else replicates."""
        from jax.tree_util import DictKey, tree_map_with_path

        def place(path, leaf):
            in_branches = any(
                isinstance(k, DictKey) and k.key == "branches" for k in path
            )
            leaf = jnp.asarray(leaf)
            spec = (
                P("branch", *([None] * (leaf.ndim - 1))) if in_branches else P()
            )
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))

        return tree_map_with_path(place, tree)

    def _put_supports(self, supports):
        """Dense ``(M, K, N, N)`` stack, per-branch ``(K, N, N)`` arrays,
        :class:`~stmgcn_tpu.parallel.banded.BandedSupports` strips, or
        :class:`~stmgcn_tpu.parallel.sparse.ShardedBlockSparse` strips
        (leading shard axis over region either way)."""
        from stmgcn_tpu.parallel.banded import BandedSupports
        from stmgcn_tpu.parallel.sparse import ShardedBlockSparse

        if isinstance(supports, (tuple, list)):
            return tuple(self._put_supports(s) for s in supports)
        if isinstance(supports, BandedSupports):
            # branch-stacked strips (M, shards, K, nl, nl+2h) shard the
            # graph axis over 'branch' too; plain strips lead with shards
            spec = (
                P("branch", "region", None, None, None)
                if supports.branch_stacked and "branch" in self.mesh.shape
                else P(*([None] * (supports.strips.ndim - 4)), "region", None, None, None)
            )
            strips = jax.device_put(
                jnp.asarray(supports.strips), NamedSharding(self.mesh, spec)
            )
            return BandedSupports(strips=strips, halo=supports.halo, n=supports.n)
        if isinstance(supports, ShardedBlockSparse):
            def shard_leading(a):
                if supports.branch_stacked:
                    # (M, S, ...): graph axis leads; shard it over 'branch'
                    # when the mesh has that axis, never over 'region'
                    lead = ("branch",) if "branch" in self.mesh.shape else (None,)
                    spec = P(*lead, "region", *([None] * (a.ndim - 2)))
                else:  # (S, ...): shard axis leads
                    spec = P("region", *([None] * (a.ndim - 1)))
                return jax.device_put(jnp.asarray(a), NamedSharding(self.mesh, spec))

            return ShardedBlockSparse(
                data=shard_leading(supports.data),
                idx=shard_leading(supports.idx),
                data_t=shard_leading(supports.data_t),
                idx_t=shard_leading(supports.idx_t),
                n=supports.n,
                tile=supports.tile,
            )
        arr = jnp.asarray(supports)
        if arr.ndim == 4:  # (M, K, N, N): output-node rows sharded
            spec = (
                P("branch", None, "region", None)
                if "branch" in self.mesh.shape
                else self.SPECS["supports"]
            )
        elif arr.ndim == 3:  # per-branch (K, N, N)
            spec = P(None, "region", None)
        else:
            raise ValueError(
                f"supports must be (M, K, N, N) or (K, N, N), got shape {arr.shape}"
            )
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def check_divisibility(
        self, batch_size: int, n_nodes: int, m_graphs: int | None = None
    ) -> None:
        dp = self.mesh.shape["dp"]
        region = self.mesh.shape["region"]
        if batch_size % dp:
            raise ValueError(f"batch_size {batch_size} not divisible by dp={dp}")
        if n_nodes % region:
            raise ValueError(f"n_nodes {n_nodes} not divisible by region={region}")
        branch = self.mesh.shape.get("branch", 1)
        if branch > 1 and m_graphs is not None and m_graphs % branch:
            raise ValueError(f"m_graphs {m_graphs} not divisible by branch={branch}")
