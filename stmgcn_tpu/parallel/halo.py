"""Ring halo exchange over the region axis (``shard_map`` + ``ppermute``).

For *banded* graphs — grid cities, where node ``i`` only neighbors nodes
within a fixed index distance ``w`` — a region-sharded graph convolution
does not need the full-node all-gather GSPMD inserts for dense supports:
each shard only needs ``w`` boundary rows from its ring neighbors. This
module provides that exchange as an explicit XLA collective pattern
(``ppermute`` rides neighbor ICI links; the TPU analogue of the halo
exchanges in ring attention / stencil codes).

The reference has no counterpart (single device, SURVEY.md §2); this is
forward-looking infrastructure for the K-hop-partitioned SpMM path
(SURVEY.md §7 "hard parts" (2)).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from stmgcn_tpu.utils.platform import axis_size

__all__ = ["halo_exchange"]


def halo_exchange(x: jnp.ndarray, halo: int, axis_name: str) -> jnp.ndarray:
    """Pad a node-axis shard with its ring neighbors' boundary rows.

    Must be called inside ``shard_map`` over ``axis_name``. ``x`` is this
    shard's ``(n_local, ...)`` block of the node axis; returns
    ``(halo + n_local + halo, ...)`` where the leading rows are the left
    neighbor's last ``halo`` rows and the trailing rows the right
    neighbor's first ``halo`` rows. Boundary shards receive zeros
    (non-periodic — matches a banded adjacency with no wraparound).
    """
    if halo <= 0:
        raise ValueError(f"halo must be positive, got {halo}")
    if x.shape[0] < halo:
        raise ValueError(f"shard has {x.shape[0]} rows < halo {halo}")
    n_shards = axis_size(axis_name)
    # left halo: shard i receives shard i-1's trailing rows
    from_left = jax.lax.ppermute(
        x[-halo:], axis_name, perm=[(i, i + 1) for i in range(n_shards - 1)]
    )
    # right halo: shard i receives shard i+1's leading rows
    from_right = jax.lax.ppermute(
        x[:halo], axis_name, perm=[(i + 1, i) for i in range(n_shards - 1)]
    )
    return jnp.concatenate([from_left, x, from_right], axis=0)
