"""Distributed execution: device meshes, shardings, halo exchange.

The reference has **no** parallelism or communication layer (SURVEY.md §2:
no torch.distributed/NCCL/MPI anywhere; one device picked by a CLI flag).
This package is its TPU-native replacement, built on ``jax.sharding``:

- :mod:`mesh` — ``Mesh`` construction over a ``dp x region`` axis grid
  (data parallelism over the batch, graph-node parallelism over the region
  axis — the spatial analogue of sequence parallelism for this model).
- :mod:`placement` — ``NamedSharding`` placement rules for every array kind
  (params replicated, batch dp-sharded, supports/nodes region-sharded).
  With inputs placed, ``jit``/GSPMD propagates shardings through the model
  and inserts the XLA collectives (gradient ``psum`` over dp, node
  all-gathers over region) that ride ICI — no hand-written NCCL analogue.
- :mod:`halo` — explicit ``shard_map`` + ``ppermute`` ring halo exchange
  for banded (grid) graphs, exchanging only boundary nodes instead of
  all-gathering the full node axis.

Multi-host: the same mesh axes extend over ``jax.distributed``-initialized
process groups; collectives within a slice ride ICI and across slices DCN.
"""

from stmgcn_tpu.parallel.banded import (
    BandedSpec,
    BandedSupports,
    ShardSpec,
    banded_decompose,
    bandwidth,
    branch_stack,
    sharded_banded_apply,
    strip_decompose,
)
from stmgcn_tpu.parallel.halo import halo_exchange
from stmgcn_tpu.parallel.manifest import (
    CollectiveDecl,
    CollectiveManifest,
    manifest_for_config,
)
from stmgcn_tpu.parallel.mesh import build_mesh, init_distributed, mesh_from_config
from stmgcn_tpu.parallel.placement import MeshPlacement
from stmgcn_tpu.parallel.sparse import (
    ShardedBlockSparse,
    branch_stack_sparse,
    sharded_from_dense,
    sharded_spmm_apply,
)

__all__ = [
    "BandedSpec",
    "BandedSupports",
    "CollectiveDecl",
    "CollectiveManifest",
    "manifest_for_config",
    "MeshPlacement",
    "ShardSpec",
    "ShardedBlockSparse",
    "banded_decompose",
    "branch_stack",
    "branch_stack_sparse",
    "bandwidth",
    "build_mesh",
    "halo_exchange",
    "init_distributed",
    "mesh_from_config",
    "sharded_banded_apply",
    "sharded_from_dense",
    "sharded_spmm_apply",
    "strip_decompose",
]
