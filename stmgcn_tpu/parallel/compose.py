"""Composed multi-chip programs: one shrunk trainer per mesh preset.

The trainer's fused superstep programs and the mesh presets used to live
in different worlds — ``analysis/spmd_check`` certified probe programs it
built itself, while the trainer executed unsharded twins. This module is
the splice point: for every multi-device preset it builds a dryrun-scale
trainer through the REAL assembly path (``build_dataset`` →
``route_supports`` → ``build_model`` → ``Trainer``) whose fused
window-free superstep engages on the preset's mesh, so

- :mod:`stmgcn_tpu.analysis.spmd_check` lowers
  :meth:`~stmgcn_tpu.train.trainer.Trainer.composed_program` for the
  static SPMD audit (the audited program IS the executed program),
- ``scripts/lint_gate.sh``'s ``spmd_exec`` section executes one smoke
  superstep of the same program on the 8-virtual-device substrate,
- ``bench.py``'s ``multichip`` leg and ``dryrun_multichip`` time/parity
  the same program against its single-device (or per-step) twin.

Shrinks keep each preset's mesh axes and routing decisions — the
collective vocabulary (kind x mesh axes) is shrink-invariant — while
fitting CPU-compile seconds:

========== ================== =========================================
preset      mesh               composed program
========== ================== =========================================
multicity   dp=8               ``fleet_superstep`` (hetero city pair)
scaled      region=8 (auto)    ``series_superstep``, mixed banded/dense
branchpar   dp=2 x branch=3    ``series_superstep``, branch-sharded
bandedbranch dp=2 x region=2    ``series_superstep``, branch-stacked
            x branch=2          banded strips (injected banded adjs)
========== ================== =========================================

Parity twins: dense presets (``multicity``/``branchpar``) have a true
single-device twin — same config with the mesh removed, identical param
init (the vmapped layout does not depend on mesh extents). The banded
presets' layout/routing *is* a function of the mesh config, so their
twin is the per-step loop on the SAME mesh (``steps_per_superstep=1``)
— fusion parity rather than device-count parity; dp device-count parity
is covered by the dense presets.
"""

from __future__ import annotations

__all__ = [
    "COMPOSED_PRESETS",
    "banded_meta",
    "composed_config",
    "composed_program_names",
    "composed_trainer",
    "parity_twin_kind",
]

#: every multi-device preset with a composed program (must stay in sync
#: with ``analysis/spmd_check.PROGRAM_SPECS`` — coverage is checked there)
COMPOSED_PRESETS = ("multicity", "scaled", "branchpar", "bandedbranch")

#: twin kind per preset: "single" = true 1-device twin (same layout),
#: "per_step" = per-step loop on the same mesh (banded layouts are
#: mesh-config-derived, so removing the mesh changes the param tree)
_TWIN = {
    "multicity": "single",
    "scaled": "per_step",
    "branchpar": "single",
    "bandedbranch": "per_step",
}


def _band_adj(n: int, w: int, seed: int):
    """Symmetric adjacency with every edge within index distance ``w``."""
    import numpy as np

    rng = np.random.default_rng(seed)
    a = np.zeros((n, n), np.float32)
    for d in range(1, w + 1):
        band = (rng.random(n - d) < 0.7).astype(np.float32)
        a += np.diag(band, d) + np.diag(band, -d)
    return a


def _shrink_model(cfg) -> None:
    cfg.model.lstm_hidden_dim = 8
    cfg.model.lstm_num_layers = 1
    cfg.model.gcn_hidden_dim = 8
    # float32 throughout: the wire budgets and the dp-psum/halo analytic
    # models assume 4-byte elements (spmd_check._ITEMSIZE), and parity
    # twins compare loss histories at f32 resolution
    cfg.model.dtype = "float32"


def composed_config(name: str):
    """The preset's dryrun-scale config whose fused path engages on the
    preset's mesh. Mesh axes and routing strategy are the preset's own;
    data/model dims shrink; the window-free resident superstep is opted
    in explicitly (``data_placement="resident"``, ``window_free=True``,
    ``steps_per_superstep=2``)."""
    from stmgcn_tpu.config import preset

    if name not in COMPOSED_PRESETS:
        raise ValueError(
            f"no composed program for preset {name!r}; "
            f"known: {COMPOSED_PRESETS}"
        )
    cfg = preset(name)
    _shrink_model(cfg)
    cfg.train.epochs = 2
    cfg.train.steps_per_superstep = 2
    cfg.train.window_free = True
    cfg.train.data_placement = "resident"
    if name == "multicity":
        # hetero city pair, both cities in one fleet shape class (rows
        # 4/3 both rung-pad to 16 nodes); batch 16 = dp x 2
        cfg.data.rows = 4
        cfg.data.city_rows = (4, 3)
        cfg.data.n_timesteps = 24 * 7 * 2 + 40
        cfg.data.city_timesteps = (24 * 7 * 2 + 40, 24 * 7 * 2 + 30)
        cfg.train.batch_size = 16
    elif name == "scaled":
        # 32x2 grid, cheb-K2: grid bandwidth K*cols = 4 <= n_local//2 = 4
        # (the 50x50/K=3 original routes the same way at preset scale);
        # the random transport/similarity branches rightly stay dense —
        # the preset's mixed banded/dense plan
        cfg.data.rows, cfg.data.cols = 32, 2
        cfg.data.n_timesteps = 24 * 7 + 64
        cfg.model.K = 2
        cfg.train.batch_size = 4
    elif name == "branchpar":
        cfg.data.rows = 4
        cfg.data.n_timesteps = 24 * 7 + 64
        cfg.train.batch_size = 4
    else:  # bandedbranch
        cfg.data.rows = 4
        cfg.data.n_timesteps = 24 * 7 + 64
        cfg.train.batch_size = 4
        cfg.mesh.halo = 4
    return cfg


def parity_twin_kind(name: str) -> str:
    return _TWIN[name]


def composed_program_names() -> dict:
    """``preset -> {"train": ..., "serve": ...}`` — which fused program
    each preset's composed trainer dispatches (hetero fleets the
    per-class ``fleet_superstep``, homogeneous series presets the
    ``series_superstep``; serving always lowers ``serve_bucket``). Pure
    config — no dataset build, no trace — so record writers can stamp
    manifests without touching a backend."""
    return {
        p: {
            "train": (
                "fleet_superstep"
                if composed_config(p).data.hetero
                else "series_superstep"
            ),
            "serve": "serve_bucket",
        }
        for p in COMPOSED_PRESETS
    }


def composed_trainer(
    name: str,
    *,
    twin: str | None = None,
    out_dir: str | None = None,
    epochs: int | None = None,
    fault_plan=None,
    verbose: bool = False,
):
    """Build the preset's composed trainer (or its parity twin).

    ``twin=None`` builds the mesh-composed trainer;
    ``twin="single"`` the 1-device twin (dense presets only — banded
    layouts are functions of the mesh config); ``twin="per_step"`` the
    per-step loop on the same mesh. Both twins share the composed
    trainer's param init bit-for-bit.
    """
    from stmgcn_tpu.config import MeshConfig
    from stmgcn_tpu.experiment import build_dataset, build_trainer

    cfg = composed_config(name)
    if epochs is not None:
        cfg.train.epochs = epochs
    if out_dir is not None:
        cfg.train.out_dir = out_dir
    if twin == "single":
        if _TWIN[name] != "single":
            raise ValueError(
                f"{name!r} has no single-device twin (its banded routing/"
                "param layout derives from the mesh config); use "
                'twin="per_step"'
            )
        cfg.mesh = MeshConfig()
    elif twin == "per_step":
        cfg.train.steps_per_superstep = 1
    elif twin is not None:
        raise ValueError(f'twin must be None, "single", or "per_step", got {twin!r}')
    dataset = None
    if name == "bandedbranch":
        # the preset's synthetic transport graph is unbandable by design
        # (see the preset docstring) — stand in banded city adjacencies so
        # the branch-stacked halo composition actually engages, as it does
        # on real banded city pairs
        dataset = build_dataset(cfg)
        n = dataset.n_nodes
        dataset.adjs = {"g0": _band_adj(n, 1, 1), "g1": _band_adj(n, 2, 2)}
    trainer = build_trainer(
        cfg, verbose=verbose, fault_plan=fault_plan, dataset=dataset
    )
    if name in ("scaled", "bandedbranch") and twin is None:
        banded = [
            s
            for s in (
                trainer.supports
                if isinstance(trainer.supports, tuple)
                else (trainer.supports,)
            )
            if hasattr(s, "halo")
        ]
        if not banded:
            raise RuntimeError(
                f"composed {name!r}: routing did not engage the banded "
                "plan — the shrink no longer matches the router's "
                "bandwidth budget"
            )
    return trainer


def banded_meta(trainer, cfg) -> dict:
    """Analytic wire-model inputs for a banded composed program
    (``spmd_check``'s halo permute bound): measured halo from the routed
    strips plus per-shard batch/graph/feature extents from the config.
    Empty for dense programs."""
    banded = [
        s
        for s in (
            trainer.supports
            if isinstance(trainer.supports, tuple)
            else (trainer.supports,)
        )
        if hasattr(s, "halo")
    ]
    if not banded:
        return {}
    f_cap = (
        cfg.data.serial_len
        + cfg.data.daily_len
        + cfg.data.weekly_len
        + 2 * cfg.model.lstm_hidden_dim
        + cfg.model.gcn_hidden_dim
    )
    return {
        "halo": max(s.halo for s in banded),
        "b_local": cfg.train.batch_size // cfg.mesh.dp,
        "m_local": max(1, cfg.model.m_graphs // cfg.mesh.branch),
        "f_cap": f_cap,
    }
