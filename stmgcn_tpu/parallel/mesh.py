"""Device mesh construction.

A logical mesh of up to three axes ``(dp, region, branch)``:

- ``dp`` — data parallelism (batch sharding + gradient all-reduce);
- ``region`` — graph-node model parallelism for large-N configs
  (BASELINE config 3's 50x50 grid);
- ``branch`` — graph-branch model parallelism: the M graph views are
  independent until the sum fusion (``STMGCN.py:112-116`` in the
  reference runs them *sequentially*), so their stacked parameters and
  supports shard over this axis and the fusion becomes one ``psum`` —
  the expert-parallel analogue for this model family.

On real hardware the mesh should be laid out so the high-traffic axis
(``region``: node all-gathers every conv) maps to the faster ICI links;
``jax.experimental.mesh_utils`` does this when available. The ``branch``
axis is omitted from the mesh when its extent is 1, so 2-D callers are
unaffected.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "init_distributed", "mesh_from_config"]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host JAX job (the reference has no multi-node story).

    Wraps ``jax.distributed.initialize``: on TPU pods all arguments are
    discovered from the environment, so a bare ``init_distributed()`` per
    host is enough; on other platforms pass the coordinator explicitly.
    After this, ``jax.devices()`` spans every host and :func:`build_mesh`
    lays the ``(dp, region)`` axes across the whole slice — XLA routes
    collectives over ICI within a slice and DCN across slices. Call before
    any other JAX operation.
    """
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


def build_mesh(
    dp: int = 1,
    region: int = 1,
    branch: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(dp, region[, branch])`` mesh from the first devices.

    The ``branch`` axis only appears in the mesh when its extent is > 1.
    """
    if devices is None:
        devices = jax.devices()
    extents = {"dp": dp, "region": region, "branch": branch}
    if any(e < 1 for e in extents.values()):
        raise ValueError(f"mesh extents must be positive, got {extents}")
    shape = (dp, region) if branch == 1 else (dp, region, branch)
    names = ("dp", "region") if branch == 1 else ("dp", "region", "branch")
    need = dp * region * branch
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices ({' x '.join(f'{n}={e}' for n, e in zip(names, shape))}) "
            f"but only {len(devices)} are visible"
        )
    if need > 1:
        try:  # physical-topology-aware layout on real TPU slices
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(shape, devices=devices[:need])
        except Exception:
            arr = np.asarray(devices[:need]).reshape(shape)
    else:
        arr = np.asarray(devices[:need]).reshape(shape)
    return Mesh(arr, axis_names=names)


def mesh_from_config(mesh_cfg, devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """``MeshConfig -> Mesh``, or ``None`` for the single-device 1x1 case."""
    if mesh_cfg.n_devices <= 1:
        return None
    return build_mesh(mesh_cfg.dp, mesh_cfg.region, mesh_cfg.branch, devices=devices)
