"""Device mesh construction.

A 2-D logical mesh ``(dp, region)``: the ``dp`` axis carries data
parallelism (batch sharding + gradient all-reduce), the ``region`` axis
carries graph-node parallelism for large-N configs (BASELINE config 3's
50x50 grid). On real hardware the mesh should be laid out so ``region``
(the high-traffic axis: node all-gathers every layer) maps to the faster
ICI links; ``jax.experimental.mesh_utils`` does this when available.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "init_distributed", "mesh_from_config"]


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host JAX job (the reference has no multi-node story).

    Wraps ``jax.distributed.initialize``: on TPU pods all arguments are
    discovered from the environment, so a bare ``init_distributed()`` per
    host is enough; on other platforms pass the coordinator explicitly.
    After this, ``jax.devices()`` spans every host and :func:`build_mesh`
    lays the ``(dp, region)`` axes across the whole slice — XLA routes
    collectives over ICI within a slice and DCN across slices. Call before
    any other JAX operation.
    """
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


def build_mesh(dp: int = 1, region: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(dp, region)`` mesh from the first ``dp*region`` devices."""
    if devices is None:
        devices = jax.devices()
    need = dp * region
    if need < 1:
        raise ValueError(f"mesh extents must be positive, got dp={dp}, region={region}")
    if len(devices) < need:
        raise ValueError(
            f"mesh needs {need} devices (dp={dp} x region={region}) but only "
            f"{len(devices)} are visible"
        )
    if need > 1:
        try:  # physical-topology-aware layout on real TPU slices
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh((dp, region), devices=devices[:need])
        except Exception:
            arr = np.asarray(devices[:need]).reshape(dp, region)
    else:
        arr = np.asarray(devices[:need]).reshape(dp, region)
    return Mesh(arr, axis_names=("dp", "region"))


def mesh_from_config(mesh_cfg, devices: Optional[Sequence] = None) -> Optional[Mesh]:
    """``MeshConfig -> Mesh``, or ``None`` for the single-device 1x1 case."""
    if mesh_cfg.n_devices <= 1:
        return None
    return build_mesh(mesh_cfg.dp, mesh_cfg.region, devices=devices)
