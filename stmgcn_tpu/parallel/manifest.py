"""Declared collective manifests: what a sharding plan promises to move.

Every parallel plan in this package implies a communication signature —
the data-parallel placement psums gradients over ``dp``, the banded halo
plan ring-permutes boundary rows over ``region``, GSPMD's dense region
sharding all-gathers the node axis, branch model parallelism psums the
fusion over ``branch``. A :class:`CollectiveManifest` writes that
signature down as data: the collective kinds and mesh axes a compiled
step program is *allowed* (and, for the plan-defining ones, *required*)
to contain.

The declarations live as fragment tuples next to the code they describe
(``placement.DP_GRAD_SYNC``, ``banded.HALO_EXCHANGE``, ...);
:func:`manifest_for_config` composes a config's fragments into the
per-program manifest the :mod:`stmgcn_tpu.analysis.spmd_check` contract
pass diffs against the compiled HLO. An observed collective with no
matching declaration is implicit GSPMD resharding the plan never asked
for; a required declaration with no observed op means the plan never
engaged — both are ``spmd-collective-manifest`` errors.

``max_count`` bounds the *static* op count in the compiled module
(``None`` = unbounded): collectives inside an HLO ``while`` body count
once, so the bound is per-program structure, not per-step wire volume —
bytes are budgeted separately (``spmd-wire-budget``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

__all__ = ["CollectiveDecl", "CollectiveManifest", "manifest_for_config"]


@dataclasses.dataclass(frozen=True)
class CollectiveDecl:
    """One permitted collective: HLO kind x mesh axes (``"+"``-joined).

    ``required=True`` marks a plan-defining op — its absence from the
    compiled program means the plan silently never engaged (e.g. the
    banded path fell back to dense GSPMD).
    """

    kind: str
    axes: str
    required: bool = False
    max_count: Optional[int] = None
    reason: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class CollectiveManifest:
    """The full declared signature of one compiled program."""

    program: str
    decls: Tuple[CollectiveDecl, ...]

    def lookup(self, kind: str, axes: str) -> Optional[CollectiveDecl]:
        for d in self.decls:
            if d.kind == kind and d.axes == axes:
                return d
        return None

    def to_dict(self) -> dict:
        return {
            "program": self.program,
            "decls": [d.to_dict() for d in self.decls],
        }


def manifest_for_config(
    cfg, program: str = "train", banded: bool = False
) -> CollectiveManifest:
    """Compose a config's plan fragments into one program manifest.

    ``program`` is ``"train"`` (grads + optimizer: every axis the loss
    and parameters span syncs) or ``"serve"`` (forward only: no gradient
    traffic; a ``dp``-only mesh serves with *zero* collectives, and any
    observed op is implicit resharding). ``banded=True`` declares the
    explicit halo plan for the region axis — permutes required — which
    is exactly when routing produced banded strips; otherwise a
    ``region`` axis gets GSPMD's dense signature (node all-gathers).
    """
    from stmgcn_tpu.parallel.banded import HALO_EXCHANGE
    from stmgcn_tpu.parallel.placement import (
        BRANCH_FUSION,
        DP_GRAD_SYNC,
        GSPMD_REGION,
    )

    train = program == "train"
    decls: list = []
    if cfg.mesh.dp > 1 and train:
        decls.extend(DP_GRAD_SYNC)
    if cfg.mesh.region > 1:
        if banded:
            decls.extend(HALO_EXCHANGE)
        # dense-branch signal gathers (and, in banded programs, the
        # backward-pass transposes and node-pooling reductions) ride
        # GSPMD's region signature either way
        decls.extend(
            dataclasses.replace(d, required=d.required and not banded)
            for d in GSPMD_REGION
        )
        decls.append(
            CollectiveDecl(
                "all-reduce", "region", required=False,
                reason="node-pooling (gate context) and, in training, "
                "loss-mean / weight-grad reductions over the "
                "region-sharded node axis",
            )
        )
    if cfg.mesh.branch > 1:
        decls.extend(BRANCH_FUSION)
        if train:
            decls.append(
                CollectiveDecl(
                    "all-gather", "branch", required=False,
                    reason="optimizer re-gather of branch-sharded "
                    "parameter updates",
                )
            )
    return CollectiveManifest(program=program, decls=tuple(decls))
