"""Region-sharded banded support application with ring halo exchange.

For banded graphs (grid cities: all support nonzeros within index distance
``w``), GSPMD's default plan for a region-sharded graph convolution
all-gathers the *entire* node axis of the signal on every device. This
module implements the cheaper explicit plan (SURVEY.md §7, hard part 2):

1. offline, each shard keeps only its **strip** of every support — its
   ``n_local`` rows restricted to the ``n_local + 2w`` columns they can
   touch (:func:`strip_decompose`);
2. at apply time, each shard ``ppermute``s just ``w`` boundary rows of the
   signal with its ring neighbors (:func:`~stmgcn_tpu.parallel.halo.
   halo_exchange`) and contracts its strip locally — communication is
   ``O(w)`` per shard instead of ``O(N)``.

Numerically identical to the dense contraction
``einsum('kij,bjf->kbif')`` — note the ``(K, B, N, F)`` output layout —
for any support whose bandwidth fits the halo (validated at
decomposition time).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from stmgcn_tpu.parallel.halo import halo_exchange
from stmgcn_tpu.utils.platform import shard_map

__all__ = [
    "BandedSpec",
    "ShardSpec",
    "BandedSupports",
    "bandwidth",
    "banded_decompose",
    "branch_stack",
    "sharded_banded_apply",
    "strip_decompose",
]


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Static routing info for mesh-aware graph convs (flax module
    attribute): which mesh to ``shard_map`` over and the name of its
    region axis. Shared by the banded halo plan and the sharded sparse
    plan (:mod:`stmgcn_tpu.parallel.sparse`)."""

    mesh: Mesh
    axis_name: str = "region"


#: back-compat alias (the banded plan named it first)
BandedSpec = ShardSpec


def _halo_exchange_decls():
    from stmgcn_tpu.parallel.manifest import CollectiveDecl

    return (
        CollectiveDecl(
            kind="collective-permute", axes="region", required=True,
            reason="±1 ring halo exchange of boundary signal rows "
            "(halo_exchange) — the op that replaces GSPMD's full "
            "node-axis gather",
        ),
    )


#: collective signature of the halo plan: boundary rows ride ``ppermute``
#: over the ring — the plan-defining op a banded program must contain
#: (its absence means routing silently fell back to dense GSPMD)
HALO_EXCHANGE = _halo_exchange_decls()
__all__.append("HALO_EXCHANGE")


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BandedSupports:
    """Supports in strip form: the banded analogue of the dense
    ``(K, N, N)`` stack. ``strips`` is :func:`strip_decompose` output
    ``(n_shards, K, n_local, n_local + 2*halo)``; ``halo`` and the
    global node count ``n`` are static metadata.

    The branch-stacked form used by branch-parallel meshes
    (:func:`branch_stack`) carries a leading graph axis:
    ``(M, n_shards, K, n_local, n_local + 2*halo)`` with ONE common halo
    — ``nn.vmap`` over the model's branch axis then maps ``strips``'s
    axis 0, handing each branch the ordinary 4-d form. Shape properties
    index from the end so both forms answer correctly."""

    strips: jnp.ndarray
    halo: int
    n: int

    def tree_flatten(self):
        return (self.strips,), (self.halo, self.n)

    @classmethod
    def tree_unflatten(cls, aux, children):
        (strips,) = children
        halo, n = aux
        return cls(strips=strips, halo=halo, n=n)

    @property
    def n_supports(self) -> int:
        return self.strips.shape[-3]

    @property
    def n_shards(self) -> int:
        return self.strips.shape[-4]

    @property
    def branch_stacked(self) -> bool:
        return self.strips.ndim == 5


def branch_stack(
    per_branch_supports, n_shards: int, halo: int | None = None
) -> BandedSupports:
    """Stack M branches' ``(K, N, N)`` dense supports into ONE
    branch-stacked :class:`BandedSupports` at their common (max) halo.

    Branch model parallelism shards the model's vmapped branch axis over
    the mesh; the supports must then be a single stacked operand rather
    than a per-branch Python tuple. A common halo costs the
    narrower-band branches a few extra exchanged rows but buys one
    uniform strip shape — the same trade the per-city node padding makes
    for heterogeneous meshes. Pass ``halo`` when the caller already
    scanned the bandwidths (``strip_decompose`` still validates it);
    ``None`` computes the max here."""
    mats = [np.asarray(s, dtype=np.float32) for s in per_branch_supports]
    if halo is None:
        halo = max(
            max(bandwidth(m[k]) for k in range(m.shape[0])) for m in mats
        )
    stacked = np.stack([strip_decompose(m, n_shards, halo) for m in mats])
    return BandedSupports(strips=jnp.asarray(stacked), halo=halo, n=mats[0].shape[1])


def banded_decompose(supports, n_shards: int, halo: int | None = None) -> BandedSupports:
    """``(K, N, N)`` dense supports -> :class:`BandedSupports`.

    ``halo=None`` uses the tightest halo: the max bandwidth over the K
    supports (still subject to the ``halo <= n_local`` strip limit).
    """
    supports = np.asarray(supports, dtype=np.float32)
    if halo is None:
        halo = max(bandwidth(supports[k]) for k in range(supports.shape[0]))
    return BandedSupports(
        strips=jnp.asarray(strip_decompose(supports, n_shards, halo)),
        halo=halo,
        n=supports.shape[1],
    )


def bandwidth(mat) -> int:
    """Largest ``|i - j|`` with a nonzero entry (0 for diagonal/empty)."""
    rows, cols = np.nonzero(np.asarray(mat))
    if rows.size == 0:
        return 0
    return int(np.abs(rows - cols).max())


def strip_decompose(supports, n_shards: int, halo: int) -> np.ndarray:
    """Split ``(K, N, N)`` supports into per-shard row strips.

    Returns ``(n_shards, K, n_local, n_local + 2*halo)`` where strip ``s``
    holds rows ``[s*n_local, (s+1)*n_local)`` restricted to columns
    ``[s*n_local - halo, (s+1)*n_local + halo)`` (zero-padded at the
    boundaries). Raises if any support's bandwidth exceeds ``halo`` (the
    exchange would silently drop neighbors) or if ``N`` is not divisible
    by ``n_shards``.
    """
    supports = np.asarray(supports, dtype=np.float32)
    k, n, _ = supports.shape
    if n % n_shards:
        raise ValueError(f"N={n} not divisible by {n_shards} shards")
    n_local = n // n_shards
    if halo > n_local:
        raise ValueError(f"halo {halo} exceeds shard size {n_local}")
    for ki in range(k):
        bw = bandwidth(supports[ki])
        if bw > halo:
            raise ValueError(
                f"support {ki} has bandwidth {bw} > halo {halo}; boundary "
                "neighbors would be dropped"
            )
    padded = np.zeros((k, n, n + 2 * halo), dtype=np.float32)
    padded[:, :, halo : halo + n] = supports
    strips = np.empty((n_shards, k, n_local, n_local + 2 * halo), dtype=np.float32)
    for s in range(n_shards):
        lo = s * n_local
        strips[s] = padded[:, lo : lo + n_local, lo : lo + n_local + 2 * halo]
    return strips


def sharded_banded_apply(
    mesh: Mesh,
    strips,
    x,
    halo: int,
    axis_name: str = "region",
    batch_axis: str = "dp",
) -> jnp.ndarray:
    """``out[k,b,i,f] = sum_j A_k[i,j] x[b,j,f]`` with the node axis sharded.

    ``strips``: :func:`strip_decompose` output; ``x``: ``(B, N, F)``.
    Returns ``(K, B, N, F)`` with ``N`` sharded over ``axis_name``; each
    shard exchanges only ``halo`` boundary rows.

    When the mesh also has a ``batch_axis`` (data parallelism), ``x``'s
    batch dimension stays partitioned over it inside the ``shard_map`` —
    otherwise SPMD would replicate the activations across dp at the manual
    boundary (an involuntary full rematerialization) just to run a
    computation that is elementwise-parallel over batch anyway.
    """
    b_ax = batch_axis if batch_axis in mesh.shape and mesh.shape[batch_axis] > 1 else None

    def local(strip, x_loc):
        # strip: (1, K, nl, nl+2h) — leading shard axis; x_loc: (b_loc, nl, F)
        if halo > 0:
            xp = x_loc.swapaxes(0, 1)
            xp = halo_exchange(xp, halo, axis_name)  # (nl+2h, b_loc, F)
        else:  # diagonal-only supports: nothing to exchange
            xp = x_loc.swapaxes(0, 1)
        # contract local rows against the padded neighborhood
        return jnp.einsum("knm,mbf->kbnf", strip[0], xp)

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name, None, None, None), P(b_ax, axis_name, None)),
        out_specs=P(None, b_ax, axis_name, None),
        # under the branch-stacked layout (outer vmap with
        # spmd_axis_name='branch') the replication checker sees mismatched
        # varying-axes sets on the einsum operands and rejects a correct
        # program; disable it like sparse.py / pallas_lstm.py do
        check_vma=False,
    )
    return fn(jnp.asarray(strips), jnp.asarray(x))
