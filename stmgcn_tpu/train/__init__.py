"""Training/eval layer: optimization, jitted steps, checkpointing, metrics.

Counterpart of the reference's ``Model_Trainer.py`` (L4 in SURVEY.md §1),
rebuilt for JAX: the per-batch work is a single jitted ``train_step`` (grad +
Adam-with-L2 update) instead of an eager autograd loop, checkpoints are
self-sufficient single-file pytrees (params + optimizer state + step +
normalizer statistics), and the best-on-validation / patience early-stop
semantics match the reference exactly (``Model_Trainer.py:47-60``).
"""

from stmgcn_tpu.train.checkpoint import (
    CorruptCheckpointError,
    load_checkpoint,
    load_latest_verified,
    save_checkpoint,
    verify_checkpoint,
)
from stmgcn_tpu.train.continual import (
    ContinualDaemon,
    ContinualTrainer,
    closed_loop_smoke,
    make_holdout_eval,
)
from stmgcn_tpu.train.metrics import MAE, MAPE, MSE, PCC, RMSE, regression_report
from stmgcn_tpu.train.step import (
    FleetSuperstepFns,
    SeriesSuperstepFns,
    StepFns,
    SuperstepFns,
    gather_window_batch,
    health_group_names,
    make_fleet_superstep_fns,
    make_optimizer,
    make_series_superstep_fns,
    make_step_fns,
    make_superstep_fns,
)
from stmgcn_tpu.train.trainer import CitySupports, Trainer

__all__ = [
    "CitySupports",
    "ContinualDaemon",
    "ContinualTrainer",
    "CorruptCheckpointError",
    "FleetSuperstepFns",
    "MAE",
    "MAPE",
    "MSE",
    "PCC",
    "RMSE",
    "SeriesSuperstepFns",
    "StepFns",
    "SuperstepFns",
    "Trainer",
    "closed_loop_smoke",
    "gather_window_batch",
    "make_holdout_eval",
    "health_group_names",
    "load_checkpoint",
    "load_latest_verified",
    "make_fleet_superstep_fns",
    "make_optimizer",
    "make_series_superstep_fns",
    "make_step_fns",
    "make_superstep_fns",
    "regression_report",
    "save_checkpoint",
    "verify_checkpoint",
]
