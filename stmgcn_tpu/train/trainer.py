"""Training loop with best-on-validation checkpointing and early stopping.

Counterpart of the reference's ``ModelTrainer`` (``Model_Trainer.py:8-98``)
with the same control semantics, restructured for JAX:

- epoch loop in Python, per-batch work in the jitted step functions;
- validation improvement test is ``val <= best`` with the patience counter
  (default 10) reset on improvement (``Model_Trainer.py:47-60``);
- the best checkpoint is rewritten on every improvement and a ``latest``
  checkpoint every epoch, each self-sufficient for resume (params,
  optimizer state, epoch, best val, patience, normalizer stats);
- ``test()`` reloads the best checkpoint and reports denormalized
  MSE/RMSE/MAE/MAPE/PCC (``Model_Trainer.py:68-98``) — under
  ``jax.eval_shape``-free pure eval (the reference forgot ``no_grad``,
  quirk 5);
- per-epoch JSONL records land in ``<out_dir>/history.jsonl`` in addition
  to stdout prints (SURVEY.md §5.e);
- batch data placement: ``data_placement="resident"`` keeps the data on
  device once and gathers batches by index on device (per-batch
  host->device copies leave the epoch entirely; single-device only),
  ``"stream"`` uploads per batch with ``prefetch`` overlap, ``"auto"``
  (default) picks resident on a single device when the resident payload
  fits comfortably in HBM. The resident payload is **window-free** by
  default (``window_free``): ONE normalized ``(T, N, C)`` series per
  city plus per-mode int32 target vectors stay resident, and every
  train/eval batch is reconstructed on device as
  ``series[target + offsets]`` (``train/step.py gather_window_batch``)
  — ~``seq_len``x fewer resident bytes than the materialized windows,
  bit-identical results (the gather is a pure copy). ``window_free=
  False`` keeps the materialized-window resident path (the parity
  oracle); heterogeneous datasets always use it.

Preemption safety (stmgcn_tpu/resilience): a ``FaultPlan`` threads
deterministic fault injection through this loop behind a no-op default;
SIGTERM gets a grace-window emergency checkpoint and a ``Preempted``
unwind at the next safe step boundary; ``checkpoint_every_steps`` adds a
mid-epoch ``latest`` cadence whose meta carries the exact resume cursor
(batch-in-epoch, data-order state, partial epoch losses) so
``restore_auto()`` continues bit-exactly from step k; an optional
``DivergenceGuard`` rolls params/opt_state back to an in-memory last-good
snapshot when a step's loss goes non-finite.

Multi-host note: only the lead process touches ``out_dir`` — writes
always, and in multi-process jobs reads too: ``restore()``/``test()``
load the checkpoint on process 0 and **broadcast** the state (params,
optimizer state, JSON metadata) to every other process via
``jax.experimental.multihost_utils``, so ``out_dir`` may live on
host-local disk. A shared filesystem is only needed if non-lead hosts
should also see the files themselves.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from stmgcn_tpu.data.pipeline import DemandDataset
from stmgcn_tpu.obs import jaxmon
from stmgcn_tpu.obs import trace as obs_trace
from stmgcn_tpu.obs.health import HealthWriter, publish_train_health
from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.resilience.faults import FaultPlan, Preempted
from stmgcn_tpu.resilience.guard import DivergenceGuard
from stmgcn_tpu.train.checkpoint import (
    load_checkpoint,
    load_latest_verified,
    serialize_checkpoint,
    write_checkpoint_bytes,
)
from stmgcn_tpu.train.metrics import regression_report
from stmgcn_tpu.utils.profiling import fence
from stmgcn_tpu.train.step import (
    PRECISIONS,
    StepFns,
    gather_window_batch,
    health_group_names,
    make_fleet_superstep_fns,
    make_optimizer,
    make_series_superstep_fns,
    make_step_fns,
    make_superstep_fns,
)

__all__ = ["Trainer"]


class CitySupports:
    """Per-city support stacks for multi-city training with differing
    graphs (BASELINE config 4: real city pairs do not share adjacencies).

    Batches never mix cities (``Batch.city``); the trainer applies
    ``for_city(batch.city)`` per step. City stacks share shapes, so one
    compiled step serves every city.
    """

    def __init__(self, per_city):
        self.per_city = tuple(per_city)
        if not self.per_city:
            raise ValueError("need at least one city's supports")

    def __len__(self) -> int:
        return len(self.per_city)

    def for_city(self, city: int):
        return self.per_city[city]

    def map(self, fn) -> "CitySupports":
        return CitySupports(fn(s) for s in self.per_city)


@dataclasses.dataclass(frozen=True)
class _FleetCity:
    """One fleet city's place in its shape class (trainer-internal)."""

    cls: int  # shape-class index in the plan
    slot: int  # member slot in the class's stacked supports
    rung: int  # class node count N_c every member pads to
    n_real: int  # real node rows (traced gate-pooling divisor)
    pad: int  # rung - n_real
    t_offset: int  # city's time offset in the class's concatenated series


def _contains_blocksparse(supports) -> bool:
    """Single-device block-CSR forms (mesh-shardable ShardedBlockSparse
    passes; see stmgcn_tpu/parallel/sparse.py)."""
    from stmgcn_tpu.ops.spmm import BlockSparse, BlockSparseStack

    if isinstance(supports, (BlockSparse, BlockSparseStack)):
        return True
    if isinstance(supports, (tuple, list)):
        return any(_contains_blocksparse(s) for s in supports)
    return False


class _DefaultPlacement:
    """Single-device placement: plain ``jnp.asarray``; state left in place."""

    def put(self, tree, kind: str):
        if kind == "state":
            return tree
        return jax.tree.map(jnp.asarray, tree)


class Trainer:
    """Drives training of a flax model over a :class:`DemandDataset`."""

    #: "auto" data placement goes resident up to this many windowed-array
    #: bytes when the device doesn't report its memory (the conservative
    #: fallback; see :meth:`_resident_cap_bytes` for the device-derived cap)
    RESIDENT_CAP_BYTES = 1 << 30

    def _resident_cap_bytes(self) -> int:
        """Byte budget for "auto" resident data placement.

        Derived from the device's own ``memory_stats()`` when available —
        half of the currently-free device memory (leaving the other half
        for params, optimizer state, activations, and XLA scratch) — with
        :data:`RESIDENT_CAP_BYTES` as the floor/fallback so hosts and
        backends that report nothing keep the old conservative behavior.
        """
        try:
            stats = jax.devices()[0].memory_stats() or {}
        except Exception:  # backends without memory_stats raise various types
            return self.RESIDENT_CAP_BYTES
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if not limit:
            return self.RESIDENT_CAP_BYTES
        return max(self.RESIDENT_CAP_BYTES, (limit - in_use) // 2)

    def __init__(
        self,
        model,
        dataset: DemandDataset,
        supports,
        *,
        lr: float = 2e-3,
        weight_decay: float = 1e-4,
        lr_schedule: str = "none",
        warmup_epochs: float = 0.0,
        min_lr_fraction: float = 0.0,
        grad_clip_norm: Optional[float] = None,
        loss: str = "mse",
        checks: Optional[str] = None,
        precision: str = "fp32",
        sr_seed: Optional[int] = None,
        n_epochs: int = 100,
        batch_size: int = 32,
        patience: int = 10,
        shuffle: bool = False,
        seed: int = 0,
        out_dir: str = "output",
        top_k: int = 1,
        prefetch: int = 1,
        node_pad=0,
        data_placement: str = "auto",
        window_free: Optional[bool] = None,
        steps_per_superstep: int = 1,
        fleet: Optional[bool] = None,
        fleet_max_classes: int = 8,
        fleet_max_pad_waste: float = 0.5,
        async_checkpoint: bool = True,
        checkpoint_every_steps: int = 0,
        divergence_guard: bool = False,
        divergence_action: str = "skip",
        divergence_patience: int = 3,
        divergence_lr_cut: Optional[float] = None,
        fault_plan: Optional[FaultPlan] = None,
        health: bool = False,
        health_every_k: int = 1,
        health_out: Optional[str] = None,
        health_baseline: bool = True,
        health_sketch_size: int = 64,
        placement=None,
        extra_meta: Optional[dict] = None,
        verbose: bool = True,
    ):
        self.model = model
        self.dataset = dataset
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}, got {precision!r}"
            )
        if sr_seed is not None and precision != "bf16":
            raise ValueError(
                "sr_seed (stochastic rounding) requires precision='bf16'"
            )
        #: step-program compute precision: "fp32" is bit-identical to the
        #: pre-mixed-precision programs; "bf16" runs the lint-certified
        #: mixed-precision twins. Either way the params the Trainer owns,
        #: the optimizer state, and every checkpoint payload are f32
        #: masters — precision never changes the checkpoint format.
        self.precision = precision
        self.sr_seed = sr_seed
        self.n_epochs = n_epochs
        self.batch_size = batch_size
        self.patience = patience
        self.shuffle = shuffle
        self.seed = seed
        self.out_dir = out_dir
        if prefetch < 0:
            raise ValueError("prefetch must be >= 0 (batches placed ahead)")
        self.prefetch = prefetch
        hetero = getattr(dataset, "heterogeneous", False)
        n_cities = getattr(dataset, "n_cities", 1)
        if isinstance(node_pad, (tuple, list)):
            pads = tuple(int(p) for p in node_pad)
            if len(pads) != n_cities:
                raise ValueError(
                    f"node_pad sequence must list one pad per city "
                    f"(n_cities={n_cities}), got {node_pad!r}"
                )
        else:
            if node_pad and hetero:
                raise ValueError(
                    "heterogeneous cities have per-city region counts — "
                    "node_pad must be a per-city sequence, not a scalar"
                )
            pads = (int(node_pad),) * n_cities
        if min(pads) < 0:
            raise ValueError("node_pad must be >= 0 (padded node rows)")
        #: extra zero nodes appended per city so N divides the mesh's
        #: region axis; padded rows are isolated (zero supports), excluded
        #: from the gate pooling (model.n_real_nodes / city_n_real) and
        #: masked out of the loss/metrics
        self._node_pads = pads
        #: scalar for the homogeneous case (all cities share one pad);
        #: per-city tuple otherwise
        self.node_pad = pads[0] if len(set(pads)) == 1 else pads
        if data_placement not in ("auto", "resident", "stream"):
            raise ValueError(
                f"data_placement must be auto|resident|stream, got {data_placement!r}"
            )
        self.data_placement = data_placement
        if steps_per_superstep < 1:
            raise ValueError(
                f"steps_per_superstep must be >= 1, got {steps_per_superstep}"
            )
        #: S optimizer steps fused into one jitted lax.scan dispatch
        #: (train/step.py make_superstep_fns). 1 = the per-step loop.
        #: >1 engages only where the superstep can gather on device:
        #: resident data, one shared support stack, no per-city models —
        #: anything else silently falls back to the per-step loop, which
        #: is bit-identical anyway.
        self.steps_per_superstep = steps_per_superstep
        if checkpoint_every_steps < 0:
            raise ValueError(
                f"checkpoint_every_steps must be >= 0, got {checkpoint_every_steps}"
            )
        #: 0 = epoch-cadence latest writes only; K > 0 additionally rewrites
        #: ``latest.ckpt`` every K optimizer steps, carrying the mid-epoch
        #: resume cursor in its meta
        self.checkpoint_every_steps = checkpoint_every_steps
        #: deterministic fault injection (tests); the empty default plan
        #: makes every hook a no-op, so this *is* the production code path
        self.fault_plan = fault_plan if fault_plan is not None else FaultPlan()
        if health_every_k < 1:
            raise ValueError(
                f"health_every_k must be >= 1, got {health_every_k}"
            )
        if health_sketch_size < 1:
            raise ValueError(
                f"health_sketch_size must be >= 1, got {health_sketch_size}"
            )
        #: numeric health telemetry: on a cadence (every K dispatch units —
        #: steps on the per-step path, blocks on the fused paths) the
        #: health-instrumented step/superstep variants run instead of the
        #: plain ones, returning on-device stats (grad norms, update
        #: ratio, nonfinite counts, fleet per-city loss attribution) that
        #: one device_get downloads into health.jsonl + the registry.
        #: Params stay bit-identical; off, the plain programs are the
        #: byte-same jaxprs as before (see train/step.py).
        self.health = bool(health)
        self.health_every_k = health_every_k
        self.health_sketch_size = health_sketch_size
        self._health_out = health_out
        self._health_baseline_on = bool(health_baseline)
        self._health_counter = 0
        self._health_writer: Optional[HealthWriter] = None
        self._health_baseline_cache: Optional[dict] = None
        self._guard = (
            DivergenceGuard(
                action=divergence_action,
                patience=divergence_patience,
                lr_cut=divergence_lr_cut,
            )
            if divergence_guard
            else None
        )
        #: optimizer steps taken across the whole run (survives resume)
        self.global_step = 0
        # mid-epoch resume machinery: the cursor of consumed batches within
        # the current epoch, the skip count a restored checkpoint asks for,
        # and the partial per-batch loss/count accumulators the epoch
        # reduction reads (persisted in mid-epoch checkpoint meta)
        self._batch_in_epoch = 0
        self._resume_skip = 0
        self._epoch_losses: list = []
        self._epoch_counts: list = []
        # guard action="defer" end-of-epoch retries, as (ordinal, batch)
        # pairs — the ordinal (position in the epoch's deterministic batch
        # order) is what mid-epoch checkpoints persist, so a resumed run
        # can re-materialize the pending retries bit-exactly
        self._deferred: list = []
        self._resume_deferred: list = []  # ordinals restored from meta
        self._preempted = False  # SIGTERM arrived; unwind at next safe point
        self._last_cadence_step = 0
        self._lr_scale = 1.0  # cumulative divergence-guard LR cut
        self._resident_cache: dict = {}
        # window-free residency: the per-city device series, the per-(mode,
        # city) device target vectors, and the window's offset table
        self._resident_series_cache: dict = {}
        self._resident_targets_cache: dict = {}
        self._offsets_dev = None
        window = getattr(dataset, "window", None)
        self._horizon = window.horizon if window is not None else 1
        #: serialize on the training thread (device->host snapshot), write
        #: the file from a background worker — IO leaves the epoch's
        #: critical path. Reads (restore/test) flush pending writes first.
        self.async_checkpoint = async_checkpoint
        self._write_queue = None
        self._writer = None
        self._writer_error: Optional[BaseException] = None
        self.verbose = verbose
        self.extra_meta = extra_meta or {}
        # device placement hook; stmgcn_tpu.parallel.MeshPlacement shards over
        # a mesh, the default puts everything on the default device
        self.placement = placement or _DefaultPlacement()
        # supports: dense (M, K, N, N) array, a routed per-branch tuple
        # (dense / BandedSupports / ShardedBlockSparse), a single-device
        # block-CSR pytree, or CitySupports wrapping one of those per city
        each = supports.per_city if isinstance(supports, CitySupports) else (supports,)
        if any(_contains_blocksparse(s) for s in each) and hasattr(
            self.placement, "mesh"
        ):
            # guard at the seam the config-level check cannot see (explicit
            # placement / direct Trainer construction)
            raise ValueError(
                "single-device block-CSR supports cannot be mesh-sharded — "
                "route them as ShardedBlockSparse row strips "
                "(stmgcn_tpu.parallel.sparse.sharded_from_dense) or use a "
                "single-device placement"
            )
        if isinstance(supports, CitySupports):
            self.supports = supports.map(lambda s: self.placement.put(s, "supports"))
        else:
            self.supports = self.placement.put(supports, "supports")
        # Resident data placement: upload each split once and gather
        # batches on device by index — the per-batch host->device copy
        # leaves the epoch entirely (SURVEY.md §7 "device_put once" for
        # small configs; the reference's whole-split residency, quirk 7,
        # without its eager-in-the-dataset placement). Mesh placements
        # compose with residency only through the window-free gather: the
        # (T, N, C) series shards its node axis over 'region' and the
        # (S, B) index blocks shard over 'dp', so every window gather
        # stays device-local per shard — no per-shard index translation.
        # Materialized windows on a mesh still stream (their resident
        # form has no shardable layout); mesh "auto" also streams unless
        # window_free=True opts in, keeping default mesh runs unchanged.
        meshy = hasattr(self.placement, "mesh")
        self._meshy = meshy
        # Window-free residency needs the series/targets protocol — both
        # the homogeneous DemandDataset and the heterogeneous dataset
        # (per-city series delegation) speak it; custom datasets without
        # it fall back to materialized windows.
        wf_supported = hasattr(dataset, "series") and hasattr(
            dataset, "mode_targets"
        )
        if window_free and not wf_supported:
            raise ValueError(
                "window_free=True requires the series/mode_targets "
                "protocol (DemandDataset or HeteroCityDataset) — this "
                "dataset only materializes windows"
            )
        wf_candidate = wf_supported and window_free is not False
        if self.data_placement == "resident" and meshy and not wf_candidate:
            raise ValueError(
                "data_placement='resident' on a mesh placement composes "
                "only through the window-free gather (window_free must "
                "not be False and the dataset must speak the "
                "series/mode_targets protocol); materialized windows "
                "stream on meshes"
            )
        # "auto" sizes against what would actually sit in HBM: the raw
        # series (+ targets) on the window-free path — ~seq_len x smaller
        # — so long-window configs stop being capacity-bound here
        resident_bytes = (
            dataset.resident_nbytes if wf_candidate else dataset.nbytes
        )
        self._resident = self.data_placement == "resident" or (
            self.data_placement == "auto"
            and (not meshy or window_free is True)
            and resident_bytes <= self._resident_cap_bytes()
        )
        #: resident batches gather from the raw series on device instead of
        #: materialized window arrays (bit-identical; see module docstring)
        self._window_free = wf_candidate and self._resident
        if window_free and not self._window_free:
            raise ValueError(
                "window_free=True requires resident data placement "
                "(stream/mesh placements upload per batch)"
            )

        for mode in ("train", "validate"):
            if dataset.mode_size(mode) == 0:
                raise ValueError(
                    f"the {mode!r} split is empty — adjust split fractions/dates "
                    "or provide more data"
                )
        # schedule steps are optimizer steps: warmup/decay extents derive
        # from the dataset's actual per-epoch batch count, and the step
        # counter lives in opt_state so --resume continues the schedule
        # where the checkpoint left it
        spe = self._train_steps_per_epoch()

        def _optimizer_factory(scale: float = 1.0):
            return make_optimizer(
                lr * scale,
                weight_decay,
                schedule=lr_schedule,
                warmup_steps=int(warmup_epochs * spe),
                decay_steps=n_epochs * spe,
                min_lr_fraction=min_lr_fraction,
                grad_clip_norm=grad_clip_norm,
            )

        # a factory rather than a bound optimizer: the divergence guard's
        # lr_cut rebuilds the optimizer at a scaled base LR mid-run (the
        # optax state structure is invariant to the scale, so the live
        # opt_state stays valid); step-fn builders read self._optimizer at
        # call time so rebuilt fns pick up the cut
        self._optimizer_factory = _optimizer_factory
        self._optimizer = _optimizer_factory()

        def _fresh_fns(mdl, health: bool = False):
            return make_step_fns(
                mdl, self._optimizer, loss, checks=checks, health=health,
                precision=precision, sr_seed=sr_seed,
            )

        self._make_fns = _fresh_fns
        self.step_fns = _fresh_fns(model)
        # health-instrumented twins, built lazily on the first due health
        # step/block; separate compilations so health-off epochs never pay
        self._health_step_fns = None
        # built lazily on first superstep epoch — most trainers never need
        # it; the window-free variant gathers each scan step's microbatch
        # from the resident series instead of materialized window arrays
        self._make_superstep_fns = lambda health=False: (
            make_series_superstep_fns(
                model, self._optimizer, loss,
                horizon=self._horizon, checks=checks, health=health,
                precision=precision, sr_seed=sr_seed,
                placement=self.placement if self._meshy else None,
            )
            if self._window_free
            else make_superstep_fns(
                model, self._optimizer, loss, checks=checks, health=health,
                precision=precision, sr_seed=sr_seed,
            )
        )
        self._superstep_fns = None
        self._health_superstep_fns = None
        # Per-city gate pooling under per-city node padding: cities with
        # padded node rows need their own n_real_nodes (a static module
        # attribute), so their steps close over a clone of the model. jit
        # retraces per city shape anyway — this adds no compilations the
        # heterogeneous path wasn't already paying. Derived here (not a
        # parameter) so per-city pads can never silently pair with the
        # base model's pooling divisor. Homogeneous padding instead sets
        # n_real_nodes statically on the model itself (build_model).
        self._city_n_real = (
            tuple(
                n if p else None
                for n, p in zip(dataset.city_n_nodes, pads)
            )
            if hetero and any(pads)
            else None
        )
        self._city_fns: dict = {}
        # Fleet shape classes: heterogeneous cities grouped into a bounded
        # set of node-count rungs (data/fleet.py) so ONE compiled program
        # per class covers every member city — the fused window-free
        # superstep gathers each city's microbatch from the class's
        # concatenated resident series, selects its padded support stack
        # by slot, and feeds the traced real-node count to the gate
        # pooling. Engaged when requested (fleet=True) or automatically
        # when superstep fusion is asked for (S > 1) on a viable
        # heterogeneous dataset; fleet=False never engages.
        self._fleet_plan = None
        self._fleet_cities: dict = {}
        self._fleet_series_cache: dict = {}
        self._fleet_targets_cache: dict = {}
        self._fleet_supports_cache: dict = {}
        self._fleet_fns = None
        self._health_fleet_fns = None
        self._make_fleet_fns = lambda health=False: make_fleet_superstep_fns(
            model, self._optimizer, loss, horizon=self._horizon,
            checks=checks, health=health,
            precision=precision, sr_seed=sr_seed,
            placement=self.placement if self._meshy else None,
        )
        if fleet_max_classes < 1:
            raise ValueError(f"fleet_max_classes must be >= 1, got {fleet_max_classes}")
        if not 0.0 <= fleet_max_pad_waste < 1.0:
            raise ValueError(
                f"fleet_max_pad_waste must be in [0, 1), got {fleet_max_pad_waste}"
            )
        self.fleet = fleet
        self.fleet_max_classes = fleet_max_classes
        self.fleet_max_pad_waste = fleet_max_pad_waste
        want_fleet = fleet is True or (fleet is None and steps_per_superstep > 1)
        fleet_blocker = None
        fleet_tiled = False
        if not hetero:
            fleet_blocker = (
                "the dataset is homogeneous (one shared graph fuses already)"
            )
        elif not self._resident:
            fleet_blocker = (
                "data placement is not resident (stream/mesh upload per batch)"
            )
        else:
            from stmgcn_tpu.ops.tiling import TiledSupports

            per_city = (
                self.supports.per_city
                if isinstance(self.supports, CitySupports)
                else ()
            )
            fleet_tiled = bool(per_city) and all(
                isinstance(s, TiledSupports) for s in per_city
            )
            if not per_city or not (
                fleet_tiled
                or all(getattr(s, "ndim", None) == 4 for s in per_city)
            ):
                fleet_blocker = (
                    "per-city supports are neither dense (M, K, N, N) stacks "
                    "nor uniformly tiled (TiledSupports) plans"
                )
        if fleet is True and fleet_blocker is not None:
            raise ValueError(f"fleet=True cannot engage: {fleet_blocker}")
        if want_fleet and fleet_blocker is None:
            from stmgcn_tpu.data.fleet import plan_shape_classes

            # the planner sees the base-padded sizes (a mesh-divisibility
            # pad must survive inside the rung); the trainer's pads then
            # absorb the base pad: total pad = rung - real nodes
            self._fleet_plan = plan_shape_classes(
                [n + p for n, p in zip(dataset.city_n_nodes, pads)],
                max_classes=fleet_max_classes,
                max_pad_waste=fleet_max_pad_waste,
            )
            new_pads = list(self._node_pads)
            new_sup = list(self.supports.per_city)
            for ci, cls in enumerate(self._fleet_plan.classes):
                t_off = 0
                for slot, c in enumerate(cls.cities):
                    n = dataset.city_n_nodes[c]
                    new_pads[c] = cls.n_nodes - n
                    if fleet_tiled:
                        # identity-tail permutation + zero block rows up to
                        # the rung; block columns are unified per class below
                        # so the class's plans tree-stack into one operand
                        new_sup[c] = new_sup[c].pad_to(cls.n_nodes)
                    else:
                        grow = cls.n_nodes - new_sup[c].shape[-1]
                        if grow:  # zero node rows/cols up to the rung
                            new_sup[c] = jnp.pad(
                                new_sup[c], [(0, 0), (0, 0), (0, grow), (0, grow)]
                            )
                    self._fleet_cities[c] = _FleetCity(
                        cls=ci, slot=slot, rung=cls.n_nodes, n_real=n,
                        pad=cls.n_nodes - n, t_offset=t_off,
                    )
                    t_off += dataset.series(c).shape[0]
                if fleet_tiled and cls.cities:
                    c_common = max(new_sup[c].block_cols for c in cls.cities)
                    c_t_common = max(
                        new_sup[c].data_t.shape[3] for c in cls.cities
                    )
                    for c in cls.cities:
                        new_sup[c] = new_sup[c].with_block_cols(
                            c_common, c_t_common
                        )
            self._node_pads = tuple(new_pads)
            self.node_pad = (
                self._node_pads[0]
                if len(set(self._node_pads)) == 1
                else self._node_pads
            )
            self.supports = CitySupports(new_sup)
        # window-free: an index-only example batch keeps even init off the
        # materialized windows — no host window array is ever built
        example = next(dataset.batches(
            "train", batch_size, pad_last=True,
            with_arrays=not self._window_free,
        ))
        example_x, _, _ = self._place_batch(example, "train")  # node-padded when needed
        self.params, self.opt_state = self.step_fns.init(
            jax.random.key(seed), self._supports_for(example), example_x
        )
        self.params = self.placement.put(self.params, "state")
        self.opt_state = self.placement.put(self.opt_state, "state")

        self.epoch = 0
        self.best_val = float("inf")
        self.patience_left = patience
        if top_k < 1:
            raise ValueError("top_k must be >= 1")
        self.top_k = top_k
        self._kept: list = []  # (val_loss, path) of retained epoch checkpoints
        # In a multi-host job every process runs the same deterministic loop;
        # only the lead process touches shared storage and stdout.
        self.is_lead = jax.process_index() == 0
        if self.is_lead:
            os.makedirs(out_dir, exist_ok=True)

        # Surface the silent slow path: when superstep fusion was asked
        # for (S > 1) but training (fully or partly) runs the per-step
        # loop, say so once — one structured line naming the reason, and
        # the machine-readable `train_path` / `fallback_reason` for tests.
        #: which path training epochs take: "superstep" /
        #: "series_superstep" (homogeneous fused), "fleet_superstep"
        #: (per-class fused), or "per_step" (the materialized loop)
        self.train_path = "per_step"
        #: why (part of) training runs the per-step loop; None when the
        #: fused path fully covers the run or was never requested (S == 1)
        self.fallback_reason = None
        if steps_per_superstep > 1:
            if self._superstep_ready():
                self.train_path = (
                    "series_superstep" if self._window_free else "superstep"
                )
            elif self._fleet_superstep_ready():
                self.train_path = "fleet_superstep"
                if self._fleet_plan.unassigned:
                    self.fallback_reason = (
                        "no-class-fit: cities "
                        f"{sorted(self._fleet_plan.unassigned)} fit no shape "
                        f"class (fleet_max_classes={fleet_max_classes}, "
                        f"fleet_max_pad_waste={fleet_max_pad_waste}) and run "
                        "the per-step loop"
                    )
            elif not self._resident:
                self.fallback_reason = (
                    "stream: data placement is not resident, batches upload "
                    "per step"
                )
            elif hetero and fleet is False:
                self.fallback_reason = (
                    "hetero: heterogeneous cities with fleet=False take the "
                    "materialized per-city loop"
                )
            elif hetero and fleet_blocker is not None:
                self.fallback_reason = f"hetero: {fleet_blocker}"
            elif hetero and not self._window_free:
                self.fallback_reason = (
                    "hetero: window_free=False keeps the materialized "
                    "per-city loop (the fleet parity oracle)"
                )
            elif hetero:
                self.fallback_reason = "hetero: no city fits any shape class"
            elif isinstance(self.supports, CitySupports):
                self.fallback_reason = (
                    "per-city support stacks (CitySupports) on a homogeneous "
                    "dataset gather per step"
                )
            elif self._city_n_real is not None:
                self.fallback_reason = (
                    "per-city node padding clones the model per city"
                )
            else:
                self.fallback_reason = "superstep prerequisites not met"
        if self.fallback_reason is not None:
            self._event(
                "slow_path",
                f"[slow-path] {self.fallback_reason} "
                f"(steps_per_superstep={steps_per_superstep}, "
                f"train_path={self.train_path})",
                stream=sys.stderr,
                reason=self.fallback_reason,
                train_path=self.train_path,
            )

    # -- paths ----------------------------------------------------------
    @property
    def best_path(self) -> str:
        return os.path.join(self.out_dir, "best.ckpt")

    @property
    def latest_path(self) -> str:
        return os.path.join(self.out_dir, "latest.ckpt")

    @property
    def latest_prev_path(self) -> str:
        return os.path.join(self.out_dir, "latest.prev.ckpt")

    # -- internals ------------------------------------------------------
    def _log(self, msg: str) -> None:
        if self.verbose and self.is_lead:
            print(msg, flush=True)

    def _event(self, name: str, text: str, *, stream=None, **attrs) -> None:
        """Structured phase event: counted in the shared registry, stamped
        into the active trace (zero-duration span), and rendered as the
        SAME human-readable text the loop always printed — through
        :meth:`_log` by default, or lead-only to ``stream`` (the
        slow-path warning keeps its stderr contract)."""
        REGISTRY.counter("train.events", {"event": name}).inc()
        trc = obs_trace.active_tracer()
        if trc is not None:
            t = time.perf_counter()
            trc.record_span(f"event.{name}", t, t, attrs or None)
        if stream is not None:
            if self.is_lead:
                print(text, file=stream, flush=True)
        else:
            self._log(text)

    def _record(self, record: dict) -> None:
        if not self.is_lead:
            return
        with open(os.path.join(self.out_dir, "history.jsonl"), "a") as f:
            f.write(json.dumps(record) + "\n")

    def _save(self, path: str) -> Optional[bytes]:
        """Snapshot current state to ``path``; returns the serialized bytes
        (lead process only) so equal-content snapshots reuse them."""
        if not self.is_lead:
            return None
        trc = obs_trace.active_tracer()
        t0 = time.perf_counter() if trc is not None else 0.0
        data = serialize_checkpoint(self.params, self.opt_state, self._meta())
        if path == self.latest_path:
            # rotate before overwriting: if this write lands corrupt (disk
            # full, bit rot), latest.prev.ckpt is the previous verified
            # state and load_latest_verified falls back to it
            self._rotate(path, self.latest_prev_path)
        self._write(path, data)
        REGISTRY.counter("train.checkpoint_writes").inc()
        if trc is not None:
            # serialize + enqueue/write; the async worker's IO is off-thread
            t1 = time.perf_counter()
            trc.record_span("train.checkpoint", t0, t1,
                            {"path": os.path.basename(path),
                             "bytes": len(data)})
        return data

    def _rotate(self, src: str, dst: str) -> None:
        if self.async_checkpoint and self._write_queue is not None:
            # FIFO with the write that follows, so the rename can never
            # reorder past it and clobber the new file
            self._write_queue.put(("rotate", src, dst))
            return
        try:
            os.replace(src, dst)
        except OSError:  # first write: no previous latest to rotate
            pass

    def _write(self, path: str, data: bytes) -> None:
        data = self.fault_plan.mutate_write(path, data)
        if not self.async_checkpoint:
            write_checkpoint_bytes(path, data, self.fault_plan)
            return
        import queue

        if self._writer is None:
            # Bounded: each entry holds a full serialized state blob, so an
            # out_dir slower than the epoch cadence must apply backpressure
            # (enqueue blocks) instead of growing host memory without limit.
            self._write_queue = queue.Queue(maxsize=4)

            def worker():
                while True:
                    job = self._write_queue.get()
                    if job is None:
                        return
                    op, path, payload = job
                    try:
                        if op == "write":
                            write_checkpoint_bytes(path, payload, self.fault_plan)
                        elif op == "rotate":  # latest -> latest.prev
                            try:
                                os.replace(path, payload)
                            except OSError:
                                pass
                        else:  # "rm" — FIFO with writes, so a stale snapshot
                            try:  # cannot resurrect after its removal
                                os.remove(path)
                            except OSError:
                                pass
                    except BaseException as e:  # surfaced on the next flush
                        self._writer_error = e
                    finally:
                        self._write_queue.task_done()

            self._writer = threading.Thread(target=worker, daemon=True)
            self._writer.start()
        self._write_queue.put(("write", path, data))

    def _remove(self, path: str) -> None:
        if self.async_checkpoint and self._write_queue is not None:
            self._write_queue.put(("rm", path, None))
            return
        try:
            os.remove(path)
        except OSError:
            pass

    def flush_checkpoints(self) -> None:
        """Block until pending checkpoint writes land; re-raise failures."""
        if self._write_queue is not None:
            self._write_queue.join()
        if self._writer_error is not None:
            err, self._writer_error = self._writer_error, None
            raise RuntimeError("background checkpoint write failed") from err

    def _meta(self) -> dict:
        meta = {
            "epoch": self.epoch,
            "best_val": self.best_val,
            "patience_left": self.patience_left,
            "seed": self.seed,
            "kept": self._kept,  # top-k retention state survives resume
            "global_step": self.global_step,
            # mid-epoch resume cursor: consumed batches in the current
            # epoch; 0 means "epoch boundary — resume at epoch+1"
            "batch_in_epoch": self._batch_in_epoch,
            # data order is recomputable from (seed, shuffle, epoch) alone;
            # these pin it so resume refuses a mismatched data order
            "shuffle": self.shuffle,
            "steps_per_superstep": self.steps_per_superstep,
            # provenance only: payloads are f32 masters at any precision,
            # so bf16 runs restore into fp32 runs and vice versa
            "precision": self.precision,
        }
        if self.sr_seed is not None:
            meta["sr_seed"] = self.sr_seed
        if self._lr_scale != 1.0:
            meta["lr_scale"] = self._lr_scale
        if self._batch_in_epoch:
            # partial-epoch loss accumulators so the resumed run's epoch
            # reduction sees every batch; float() syncs each device scalar,
            # a cost only mid-epoch saves pay (epoch-boundary saves have
            # batch_in_epoch == 0 and skip this)
            meta["partial"] = {
                "losses": [
                    float(v)
                    for l in self._epoch_losses
                    for v in np.asarray(l, np.float32).reshape(-1)
                ],
                "counts": [int(c) for c in self._epoch_counts],
            }
            if self._deferred:
                # divergence-guard "defer" retries still pending at this
                # save: persist their batch ordinals so a resume replays
                # them at epoch end instead of silently dropping them
                meta["deferred"] = [ordinal for ordinal, _ in self._deferred]
        if getattr(self.dataset, "heterogeneous", False):
            meta["normalizers"] = [
                n.to_dict() if n is not None else None
                for n in self.dataset.normalizers
            ]
        elif self.dataset.normalizer is not None:
            meta["normalizer"] = self.dataset.normalizer.to_dict()
        if self.health and self._health_baseline_on:
            hb = self._health_baseline_blob()
            if hb is not None:
                meta["health_baseline"] = hb
        meta.update(self.extra_meta)
        return meta

    def _supports_for(self, batch):
        """The support stack that applies to a batch (per-city when graphs
        differ across cities; Batch.city is 0 otherwise)."""
        if isinstance(self.supports, CitySupports):
            return self.supports.for_city(batch.city)
        return self.supports

    def _pad_for(self, city: int) -> int:
        """Padded node rows appended to this city's arrays/supports."""
        return self._node_pads[city]

    def _city_nodes(self, city: int) -> int:
        """A city's real region count (per-city for heterogeneous data)."""
        ds = self.dataset
        if getattr(ds, "heterogeneous", False):
            return ds.city_n_nodes[city]
        return ds.n_nodes

    def _train_steps_per_epoch(self) -> int:
        """Optimizer steps per training epoch (sizes LR schedules).

        Batches never mix cities, so per-city tail batches each count
        (``pad_last`` fills them; the optimizer still steps once per
        batch).
        """
        b = self.batch_size
        ds = self.dataset
        if getattr(ds, "heterogeneous", False):
            return sum(-(-c.mode_size("train") // b) for c in ds.cities)
        if ds.shared_graphs:
            return -(-ds.mode_size("train") // b)
        per_city = ds.mode_size("train") // ds.n_cities
        return ds.n_cities * -(-per_city // b)

    def _fns(self, city: int):
        """The step functions for a city's batches.

        Fleet cities pass their real-node count as a *traced* argument
        (the same arithmetic the fused per-class program scans over, so
        per-step fallback/eval stay bit-identical to it — and one
        compiled step serves every city of a shape class). Non-fleet
        cities whose node axis carries padding get steps closed over a
        model clone with that city's static ``n_real_nodes`` (the gate
        pooling mean must divide by real nodes, not padded N).
        """
        info = self._fleet_cities.get(city)
        if info is not None:
            if city not in self._city_fns:
                base = self.step_fns
                nr = jnp.int32(info.n_real)
                self._city_fns[city] = StepFns(
                    init=base.init,
                    train_step=lambda p, o, s, x, y, m, _b=base, _nr=nr: (
                        _b.train_step(p, o, s, x, y, m, _nr)
                    ),
                    eval_step=lambda p, s, x, y, m, _b=base, _nr=nr: (
                        _b.eval_step(p, s, x, y, m, _nr)
                    ),
                )
            return self._city_fns[city]
        if self._city_n_real is None or self._city_n_real[city] is None:
            return self.step_fns
        if city not in self._city_fns:
            self._city_fns[city] = self._make_fns(
                self.model.clone(n_real_nodes=self._city_n_real[city])
            )
        return self._city_fns[city]

    def _health_fns(self, city: int):
        """Health-instrumented twin of :meth:`_fns` (same routing, same
        update arithmetic — the extra outputs are already-computed
        intermediates, so params stay bit-identical)."""
        key = ("health", city)
        info = self._fleet_cities.get(city)
        if info is not None:
            if key not in self._city_fns:
                if self._health_step_fns is None:
                    self._health_step_fns = self._make_fns(
                        self.model, health=True
                    )
                base = self._health_step_fns
                nr = jnp.int32(info.n_real)
                self._city_fns[key] = StepFns(
                    init=base.init,
                    train_step=lambda p, o, s, x, y, m, _b=base, _nr=nr: (
                        _b.train_step(p, o, s, x, y, m, _nr)
                    ),
                    eval_step=lambda p, s, x, y, m, _b=base, _nr=nr: (
                        _b.eval_step(p, s, x, y, m, _nr)
                    ),
                )
            return self._city_fns[key]
        if self._city_n_real is None or self._city_n_real[city] is None:
            if self._health_step_fns is None:
                self._health_step_fns = self._make_fns(self.model, health=True)
            return self._health_step_fns
        if key not in self._city_fns:
            self._city_fns[key] = self._make_fns(
                self.model.clone(n_real_nodes=self._city_n_real[city]),
                health=True,
            )
        return self._city_fns[key]

    def _health_due(self) -> bool:
        """Cadence gate, ticked once per dispatch unit (a step on the
        per-step path, a fused block on the superstep/fleet paths)."""
        if not self.health:
            return False
        due = self._health_counter % self.health_every_k == 0
        self._health_counter += 1
        return due

    def _health_out_path(self) -> str:
        return self._health_out or os.path.join(self.out_dir, "health.jsonl")

    def _health_emit(self, stats, losses, *, cities=None) -> None:
        """Download one health dispatch's device stats (a single
        ``device_get`` covering stats + losses) and fan out: registry
        gauges/counters on every host, ``health.jsonl`` on the lead."""
        stats_h, losses_h = jax.device_get((stats, losses))
        losses_h = np.atleast_1d(np.asarray(losses_h, np.float64))

        def _last(key):
            return float(np.atleast_1d(np.asarray(stats_h[key]))[-1])

        groups = health_group_names(self.params)
        gmat = np.atleast_2d(np.asarray(stats_h["group_norms"]))
        rec = {
            "kind": "train",
            "epoch": self.epoch,
            "step": self.global_step,
            "steps": int(losses_h.shape[0]),
            "loss": float(losses_h[-1]),
            "grad_norm": _last("grad_norm"),
            "update_ratio": _last("update_ratio"),
            "nonfinite_grads": int(np.sum(stats_h["nonfinite_grads"])),
            "nonfinite_loss": int(np.sum(stats_h["nonfinite_loss"])),
            "group_norms": {
                g: float(v) for g, v in zip(groups, gmat[-1])
            },
        }
        if cities is not None and "city_loss" in stats_h:
            csum = np.atleast_2d(
                np.asarray(stats_h["city_loss"])).sum(axis=0)
            rec["city_loss"] = {
                str(cities[slot]): float(v)
                for slot, v in enumerate(csum)
                if slot < len(cities)
            }
        publish_train_health(rec, REGISTRY)
        if self.is_lead:
            if self._health_writer is None:
                self._health_writer = HealthWriter(
                    self._health_out_path(),
                    {"every_k": self.health_every_k,
                     "groups": list(groups)},
                )
            self._health_writer.write(rec)

    def _health_baseline_blob(self) -> Optional[dict]:
        """Training-time drift baseline for checkpoint meta.

        Per city and phase: ``input`` summarizes the *normalized* series
        (what the model sees at the serving normalize boundary),
        ``prediction`` the denormalized values (the scale served
        predictions land on). Stride-subsampled to bound the two-pass
        cost; cached — the data never changes within a run.
        """
        if self._health_baseline_cache is not None:
            return self._health_baseline_cache
        from stmgcn_tpu.obs.drift import baseline_from_samples

        ds = self.dataset
        if not hasattr(ds, "series"):
            return None
        hetero = getattr(ds, "heterogeneous", False)
        n_cities = getattr(ds, "n_cities", 1)
        bins = self.health_sketch_size
        blob: dict = {"schema_version": 1, "bins": bins,
                      "input": {}, "prediction": {}}
        for c in range(n_cities):
            series = np.asarray(ds.series(c), dtype=np.float64)
            flat = series.reshape(-1, series.shape[-1])
            stride = max(1, flat.shape[0] // 65536)
            flat = flat[::stride]
            denorm = (
                ds.denormalize(flat, city=c) if hetero
                else ds.denormalize(flat)
            )
            blob["input"][str(c)] = baseline_from_samples(flat, bins=bins)
            blob["prediction"][str(c)] = baseline_from_samples(
                np.asarray(denorm, dtype=np.float64), bins=bins
            )
        self._health_baseline_cache = blob
        return blob

    def _placed_batches(
        self,
        mode: str,
        *,
        shuffle: bool = False,
        with_arrays: bool | None = None,
        skip: int = 0,
    ):
        """Iterate ``(batch, (x, y, mask))`` with placement run ahead.

        ``device_put`` issues the host->device copy asynchronously, so
        placing the *next* batch before the consumer dispatches the current
        step overlaps the copy with device compute — placement leaves the
        step's critical path (the reference instead uploads whole splits
        eagerly, ``Data_Container.py:88-89``). ``prefetch`` batches are kept
        in flight (host refs released as consumed).

        Resident placement iterates index-only batches (no host copies at
        all); callers that read ``batch.x``/``batch.y`` on the host (e.g.
        ``test()``'s metric accumulation) pass ``with_arrays=True``.
        """
        import collections

        if with_arrays is None:
            with_arrays = not self._resident
        queue: collections.deque = collections.deque()
        for batch in self.dataset.batches(
            mode,
            self.batch_size,
            shuffle=shuffle,
            seed=self.seed,
            epoch=self.epoch,
            pad_last=True,
            with_arrays=with_arrays,
        ):
            if skip:  # mid-epoch resume: already-consumed batches (the
                skip -= 1  # deterministic (seed, epoch) order re-yields
                continue  # them in the same positions) are not placed
            queue.append((batch, self._place_batch(batch, mode)))
            if len(queue) > self.prefetch:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    def _place_batch(self, batch, mode: str):
        sample_mask = (np.arange(len(batch)) < batch.n_real).astype(np.float32)
        pad = self._pad_for(batch.city)
        # fleet cities ALWAYS carry node-crossed masks, even at pad == 0:
        # the fused per-class program scans one mask shape for every
        # member, and per-step fallback/eval must feed the step body the
        # identical mask broadcast to stay bit-exact with it
        force = batch.city in self._fleet_cities
        if self._resident and batch.indices is not None:
            # a few hundred bytes, not the data; dp-sharded on a mesh so
            # the window gather (and its output) stays per-shard local
            idx = self.placement.put(np.asarray(batch.indices), "index")
            if self._window_free:
                # reconstruct (x, y) on device from the resident raw
                # series: index -> target timestep -> target + offsets
                x, y = gather_window_batch(
                    self._resident_series(batch.city),
                    self._resident_targets(mode, batch.city),
                    self._offsets_device(),
                    idx,
                    self._horizon,
                )
                mask = self._mask(
                    sample_mask, self._city_nodes(batch.city) + pad, pad,
                    force_nodes=force,
                )
                return x, y, mask
            x_all, y_all = self._resident_arrays(mode, batch.city)
            mask = self._mask(
                sample_mask, y_all.shape[y_all.ndim - 2], pad, force_nodes=force
            )
            return jnp.take(x_all, idx, axis=0), jnp.take(y_all, idx, axis=0), mask
        mask = self._mask(
            sample_mask, batch.y.shape[batch.y.ndim - 2] + pad, pad,
            force_nodes=force,
        )
        bx, by = batch.x, batch.y
        if pad:
            bx = self._pad_nodes(bx, 2, pad)  # (B,T,N,C)
            by = self._pad_nodes(by, by.ndim - 2, pad)  # (B,[H,]N,C)
        return self.placement.put(bx, "x"), self.placement.put(by, "y"), mask

    def _mask_np(
        self, sample_mask, n_padded_nodes: int, pad: int,
        force_nodes: bool = False,
    ) -> np.ndarray:
        """Loss mask: samples, crossed with real-node rows when node-padded.

        ``force_nodes`` emits the crossed ``(B, N)`` form even at
        ``pad == 0`` (fleet cities: one mask shape per shape class).
        Host-side numpy — the superstep path stacks S of these into one
        block before placing it; the per-step path places each via
        :meth:`_mask`.
        """
        if not pad and not force_nodes:
            return sample_mask
        node_mask = (
            np.arange(n_padded_nodes) < n_padded_nodes - pad
        ).astype(np.float32)
        return sample_mask[:, None] * node_mask[None, :]

    def _mask(
        self, sample_mask, n_padded_nodes: int, pad: int,
        force_nodes: bool = False,
    ):
        return self.placement.put(
            self._mask_np(sample_mask, n_padded_nodes, pad, force_nodes),
            "mask",
        )

    def _resident_arrays(self, mode: str, city: int):
        """Device copies of a mode's full (x, y), uploaded once per run
        (the materialized resident path; the window-free path keeps only
        :meth:`_resident_series` + :meth:`_resident_targets`)."""
        key = (mode, city)
        if key not in self._resident_cache:
            x, y = (
                self.dataset.arrays(mode)
                if self.dataset.shared_graphs
                else self.dataset.city_arrays(mode, city)
            )
            pad = self._pad_for(city)
            if pad:
                x = self._pad_nodes(x, 2, pad)
                y = self._pad_nodes(y, y.ndim - 2, pad)
            self._resident_cache[key] = (
                self.placement.put(x, "x"),
                self.placement.put(y, "y"),
            )
        return self._resident_cache[key]

    def _resident_series(self, city: int):
        """Device copy of the raw normalized series, uploaded once per run.

        ONE ``(T, N, C)`` tensor serves every mode's batches (the modes
        are target-index ranges over it) — this is where the window-free
        path's ~``seq_len``x memory saving lives. Node padding is applied
        to the series once; gathered windows come out pre-padded.
        """
        info = self._fleet_cities.get(city)
        if info is not None:
            # one resident copy per shape class: the city's rows live in
            # the class's time-concatenated series at its time offset
            return self._fleet_series(info.cls)
        if city not in self._resident_series_cache:
            s = (
                self.dataset.series_stack()
                if self.dataset.shared_graphs
                else self.dataset.series(city)
            )
            pad = self._pad_for(city)
            if pad:
                s = self._pad_nodes(s, 1, pad)
            self._resident_series_cache[city] = self.placement.put(s, "series")
        return self._resident_series_cache[city]

    def _resident_targets(self, mode: str, city: int):
        """Device int32 target-timestep vector for a mode's samples."""
        key = (mode, city)
        if key not in self._resident_targets_cache:
            info = self._fleet_cities.get(city)
            if info is not None:
                # offsets into the class's concatenated series — the
                # gathered rows are bitwise the per-city series rows
                t = (
                    np.asarray(self.dataset.mode_targets(mode, city))
                    + info.t_offset
                ).astype(np.int32)
            else:
                t = self.dataset.mode_targets(
                    mode, None if self.dataset.shared_graphs else city
                )
            self._resident_targets_cache[key] = self.placement.put(
                t, "replicated"
            )
        return self._resident_targets_cache[key]

    def _offsets_device(self):
        """Device copy of the window's gather-offset table."""
        if self._offsets_dev is None:
            self._offsets_dev = self.placement.put(
                np.asarray(self.dataset.window.offsets, np.int32),
                "replicated",
            )
        return self._offsets_dev

    def _pad_nodes(self, arr, axis: int, pad: int):
        widths = [(0, 0)] * arr.ndim
        widths[axis] = (0, pad)
        return np.pad(arr, widths)

    def _place_block(self, idx_np, mask_np):
        """Device placement of one packed ``(S, B)`` superstep block.

        On a mesh placement the index block shards its batch axis over
        ``dp`` and the mask block follows (``(S, B, N)`` masks shard the
        node axis over ``region`` too), so the fused program's in-scan
        gathers run shard-local; off-mesh this is the plain async upload
        the double buffer relies on.
        """
        if self._meshy:
            return (
                self.placement.put(idx_np, "index"),
                self.placement.put(mask_np, "mask_block"),
            )
        return jnp.asarray(idx_np), jnp.asarray(mask_np)

    def _superstep_ready(self) -> bool:
        """Whether training epochs can take the fused superstep path.

        The superstep gathers microbatches on device from one resident
        (x, y) pool against one support stack and one compiled model —
        streaming data, per-city graphs (``CitySupports``), and per-city
        model clones (heterogeneous node padding) all fall back to the
        per-step loop, which computes the identical result.
        """
        return (
            self.steps_per_superstep > 1
            and self._resident
            and self.dataset.shared_graphs
            and not isinstance(self.supports, CitySupports)
            and self._city_n_real is None
        )

    def _fleet_superstep_ready(self) -> bool:
        """Whether training epochs can take the per-class fleet superstep.

        Requires an engaged fleet plan (heterogeneous, resident, dense
        per-city supports — established in ``__init__``) plus the
        window-free gather the fused program is built on. Cities the plan
        left unassigned run per-step; ``window_free=False`` fleet
        trainers run the materialized per-city loop (the parity oracle).
        """
        return (
            self.steps_per_superstep > 1
            and bool(self._fleet_cities)
            and self._window_free
        )

    # -- fleet residency: one device copy per shape class ----------------
    def _fleet_series(self, cls_id: int):
        """The class's resident series: member cities node-padded to the
        rung and concatenated along time, uploaded once per run."""
        if cls_id not in self._fleet_series_cache:
            cls = self._fleet_plan.classes[cls_id]
            parts = []
            for c in cls.cities:
                s = self.dataset.series(c)
                pad = cls.n_nodes - s.shape[1]
                if pad:
                    s = self._pad_nodes(s, 1, pad)
                parts.append(s)
            self._fleet_series_cache[cls_id] = self.placement.put(
                np.concatenate(parts, axis=0), "series"
            )
        return self._fleet_series_cache[cls_id]

    def _fleet_targets(self, mode: str, cls_id: int):
        """``(targets, bases)``: the class's concatenated device target
        vector for a mode (per-city targets shifted by each city's time
        offset) and each member's base index into it."""
        key = (mode, cls_id)
        if key not in self._fleet_targets_cache:
            cls = self._fleet_plan.classes[cls_id]
            parts, bases, base = [], {}, 0
            for c in cls.cities:
                t = (
                    np.asarray(self.dataset.mode_targets(mode, c))
                    + self._fleet_cities[c].t_offset
                ).astype(np.int32)
                bases[c] = base
                base += t.shape[0]
                parts.append(t)
            self._fleet_targets_cache[key] = (
                self.placement.put(np.concatenate(parts), "replicated"),
                bases,
            )
        return self._fleet_targets_cache[key]

    def _fleet_supports(self, cls_id: int):
        """The class's member-stacked support operand: ``(n_members, M, K,
        rung, rung)`` for dense supports, or a leaf-wise member-stacked
        :class:`~stmgcn_tpu.ops.tiling.TiledSupports` (members share one
        rung-padded shape and block-column width, so the plans share a
        treedef; the scan body's per-slot ``jnp.take`` is leaf-wise either
        way). Member supports are already rung-padded in ``__init__``."""
        if cls_id not in self._fleet_supports_cache:
            cls = self._fleet_plan.classes[cls_id]
            members = [self.supports.for_city(c) for c in cls.cities]
            self._fleet_supports_cache[cls_id] = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *members
            )
        return self._fleet_supports_cache[cls_id]

    def composed_program(self, mode: str = "train"):
        """The engaged fused train program with one real packed block.

        Returns ``(name, fn, args)`` where ``fn`` is the jitted superstep
        the training epochs dispatch (``train_path`` names which) and
        ``args`` is a complete operand tuple built exactly the way
        :meth:`_run_train_epoch_superstep` / :meth:`_run_train_epoch_fleet`
        build it — resident operands placed by kind, the first packed
        ``(S, B)`` block placed through :meth:`_place_block`. This is the
        REAL composed program: ``analysis/spmd_check.py`` lowers it for
        the static SPMD audit and ``scripts/lint_gate.sh`` smokes it, so
        execution and certification share one program by construction.

        Raises ``ValueError`` when no fused path engaged (per-step
        trainers have no composed program to certify).

        The state operands are copies: the fused programs donate
        ``(params, opt_state)``, so executing ``fn(*args)`` must not
        invalidate the trainer's live buffers (one execution per returned
        ``args`` tuple — the copies are donated in turn).
        """
        params = jax.tree.map(jnp.copy, self.params)
        opt_state = jax.tree.map(jnp.copy, self.opt_state)
        S = self.steps_per_superstep
        batches = list(self.dataset.batches(
            mode, self.batch_size, shuffle=False, seed=self.seed,
            epoch=self.epoch, pad_last=True, with_arrays=False,
        ))
        if self._superstep_ready():
            if self._superstep_fns is None:
                self._superstep_fns = self._make_superstep_fns()
            blocks, _ = self._pack_blocks(batches, mode)
            if not blocks:
                raise ValueError(
                    f"fewer than steps_per_superstep={S} batches in "
                    f"{mode!r} — no full block to compose"
                )
            idx_np, mask_np, _ = blocks[0]
            idx_d, mask_d = self._place_block(idx_np, mask_np)
            if self._window_free:
                return (
                    "series_superstep",
                    self._superstep_fns.train_superstep,
                    (
                        params, opt_state, self.supports,
                        self._resident_series(0),
                        self._resident_targets(mode, 0),
                        self._offsets_device(), idx_d, mask_d,
                    ),
                )
            x_all, y_all = self._resident_arrays(mode, 0)
            return (
                "superstep",
                self._superstep_fns.train_superstep,
                (
                    params, opt_state, self.supports,
                    x_all, y_all, idx_d, mask_d,
                ),
            )
        if self._fleet_superstep_ready():
            if self._fleet_fns is None:
                self._fleet_fns = self._make_fleet_fns()
            for city, info in self._fleet_cities.items():
                run = [b for b in batches if b.city == city]
                targets, bases = self._fleet_targets(mode, info.cls)
                blocks, _ = self._pack_fleet_blocks(run, info, bases[city])
                if not blocks:
                    continue
                idx_np, mask_np, _ = blocks[0]
                idx_d, mask_d = self._place_block(idx_np, mask_np)
                slot_d = jnp.full((S,), info.slot, jnp.int32)
                nr_d = jnp.full((S,), info.n_real, jnp.int32)
                if self._meshy:
                    slot_d = self.placement.put(slot_d, "replicated")
                    nr_d = self.placement.put(nr_d, "replicated")
                return (
                    "fleet_superstep",
                    self._fleet_fns.train_superstep,
                    (
                        params, opt_state,
                        self._fleet_supports(info.cls),
                        self._fleet_series(info.cls), targets,
                        self._offsets_device(), idx_d, mask_d, slot_d, nr_d,
                    ),
                )
            raise ValueError(
                "fleet plan engaged but no city packed a full "
                f"steps_per_superstep={S} block in {mode!r}"
            )
        raise ValueError(
            "no fused program engaged (train_path="
            f"{self.train_path!r}, fallback_reason={self.fallback_reason!r})"
            " — the composed-program audit needs steps_per_superstep > 1 "
            "on a resident trainer"
        )

    def _run_epoch(self, mode: str, train: bool) -> float:
        """Sample-weighted mean loss over a mode (``Model_Trainer.py:43-44``).

        Losses stay on device until the epoch ends — a per-batch
        ``float(loss)`` would fence the pipeline every step and serialize
        host batch prep with device compute. (The opt-in divergence guard
        pays exactly that sync, which is why it is off by default.)

        Training epochs accumulate into ``self._epoch_losses`` /
        ``self._epoch_counts`` rather than locals: a mid-epoch checkpoint
        persists them (``_meta``) and a mid-epoch resume re-enters here
        with ``self._resume_skip`` batches already consumed, so the final
        reduction still covers every batch of the epoch bit-exactly.
        """
        if not train:
            losses, counts = [], []
            for batch, (x, y, mask) in self._placed_batches(mode):
                loss, _ = self._fns(batch.city).eval_step(
                    self.params, self._supports_for(batch), x, y, mask
                )
                losses.append(loss)
                counts.append(batch.n_real)
                self._check_preempt()
            if not counts:
                raise ValueError(f"no samples in mode {mode!r}")
            weights = np.asarray(counts, dtype=np.float32)
            weighted = jnp.stack(losses) @ jnp.asarray(weights)
            return float(weighted) / float(weights.sum())

        skip = self._resume_skip
        self._resume_skip = 0
        if skip == 0:
            self._epoch_losses, self._epoch_counts = [], []
        self._deferred = []
        resume_deferred, self._resume_deferred = self._resume_deferred, []
        if resume_deferred:
            # mid-epoch resume with guard-deferred batches pending: the
            # epoch's batch order is deterministic in (seed, shuffle,
            # epoch), so the persisted ordinals re-materialize the exact
            # batches the interrupted run was going to retry. They come
            # first in ordinal order; batches deferred after the resume
            # point have larger ordinals, so the combined retry order
            # matches the uninterrupted run's bit-exactly.
            want = set(resume_deferred)
            for ordinal, batch in enumerate(self.dataset.batches(
                mode,
                self.batch_size,
                shuffle=self.shuffle,
                seed=self.seed,
                epoch=self.epoch,
                pad_last=True,
                with_arrays=not self._resident,
            )):
                if ordinal in want:
                    self._deferred.append((ordinal, batch))
                    want.discard(ordinal)
                    if not want:
                        break
            if want:
                raise ValueError(
                    f"mid-epoch checkpoint defers batch ordinals "
                    f"{sorted(want)} that this epoch does not produce — "
                    "checkpoint from a different data configuration?"
                )
        # resume points landing mid-remainder (skip % S != 0) take the
        # per-step loop for the rest of the epoch — bit-identical to the
        # superstep by the PR 2 parity contract, just unfused
        if self._superstep_ready() and skip % self.steps_per_superstep == 0:
            self._run_train_epoch_superstep(mode, skip)
        elif (
            self._fleet_superstep_ready()
            and skip % self.steps_per_superstep == 0
        ):
            self._run_train_epoch_fleet(mode, skip)
        else:
            self._run_train_epoch_steps(mode, skip)
        deferred, self._deferred = self._deferred, []
        for _, batch in deferred:  # guard action="defer": one retry at epoch end
            x, y, mask = self._place_batch(batch, mode)
            self._train_one(batch, x, y, mask, retry=True)
            self._after_train_batch()
        if not self._epoch_counts:
            raise ValueError(f"no samples in mode {mode!r}")
        weights = np.asarray(self._epoch_counts, dtype=np.float32)
        # scalars and (S,) superstep vectors interleave in epoch order;
        # the flattened product is elementwise identical to the per-step
        # loop's stack @ weights
        vec = jnp.concatenate(
            [jnp.atleast_1d(jnp.asarray(l)) for l in self._epoch_losses]
        )
        return float(vec @ jnp.asarray(weights)) / float(weights.sum())

    def _run_train_epoch_steps(self, mode: str, skip: int) -> None:
        for batch, (x, y, mask) in self._placed_batches(
            mode, shuffle=self.shuffle, skip=skip
        ):
            self._train_one(batch, x, y, mask)
            self._after_train_batch()

    def _train_one(self, batch, x, y, mask, retry: bool = False) -> None:
        """One optimizer step with the resilience hooks threaded through.

        ``retry`` marks a deferred-batch re-run at epoch end: the fault
        plan is not consulted (its ordinals addressed the first pass) and
        the cursor does not advance (known limitation: deferred retries
        are not mid-epoch-resume addressable; a guard trip on a retry
        falls back to skip).
        """
        plan = self.fault_plan
        step = self._batch_in_epoch
        if not retry:
            plan.before_step(self.epoch, step)
            if plan.should_drop(self.epoch, step):
                self._batch_in_epoch += 1
                return
            poison = plan.poison_value(self.epoch, step)
            if poison is not None:
                mask = mask.at[(0,) * mask.ndim].set(poison)
        guard = self._guard
        if guard is not None:
            # donation invalidates the buffers we pass in — rollback needs
            # real copies taken before dispatch
            snapshot = (
                jax.tree.map(jnp.copy, self.params),
                jax.tree.map(jnp.copy, self.opt_state),
            )
        health_due = self._health_due()
        hstats = None
        if health_due:
            fns = self._health_fns(batch.city)
            self.params, self.opt_state, loss, hstats = fns.train_step(
                self.params, self.opt_state, self._supports_for(batch),
                x, y, mask,
            )
        else:
            fns = self._fns(batch.city)
            self.params, self.opt_state, loss = fns.train_step(
                self.params, self.opt_state, self._supports_for(batch),
                x, y, mask,
            )
        if not retry:
            self._batch_in_epoch += 1
        if guard is not None and not np.isfinite(float(loss)):
            self.params, self.opt_state = snapshot
            self._log(
                f"divergence guard: non-finite loss at epoch {self.epoch}, "
                f"step {step} — rolled back, {guard.action} batch"
            )
            if guard.lr_cut is not None:
                self._set_lr_scale(self._lr_scale * guard.lr_cut)
            guard.trip(float(loss), self.epoch, step)
            if guard.action == "defer" and not retry:
                self._deferred.append((step, batch))
            return  # no loss/count recorded; global_step does not advance
        if guard is not None:
            guard.ok()
        self.global_step += 1
        self._epoch_losses.append(loss)
        self._epoch_counts.append(batch.n_real)
        if hstats is not None:
            self._health_emit(hstats, loss)

    def _after_train_batch(self) -> None:
        """Step-cadence latest write + SIGTERM safe point, after every
        consumed batch / fused block."""
        K = self.checkpoint_every_steps
        if K and self.global_step - self._last_cadence_step >= K:
            self._save(self.latest_path)
            self._last_cadence_step = self.global_step
        self._check_preempt()

    def _check_preempt(self) -> None:
        """SIGTERM grace window: the in-flight step has finished, so write
        the emergency checkpoint here (a safe boundary — the meta cursor is
        consistent) and unwind with :class:`Preempted`."""
        if not self._preempted:
            return
        self._log(
            f"SIGTERM received — emergency checkpoint at epoch {self.epoch}, "
            f"step {self.global_step}"
        )
        self._save(self.latest_path)
        self.flush_checkpoints()
        raise Preempted(
            f"preempted at epoch {self.epoch}, step {self.global_step}; "
            "restart with --resume auto to continue bit-exactly"
        )

    def _set_lr_scale(self, scale: float) -> None:
        """Rebuild the optimizer at ``lr * scale`` (divergence lr_cut /
        resume of a cut run). opt_state structure is scale-invariant, so
        the live state carries over; step fns rebuild so their closures see
        the new optimizer."""
        if scale == self._lr_scale:
            return
        self._lr_scale = scale
        self._optimizer = self._optimizer_factory(scale)
        self.step_fns = self._make_fns(self.model)
        self._superstep_fns = None
        self._fleet_fns = None
        self._health_step_fns = None
        self._health_superstep_fns = None
        self._health_fleet_fns = None
        self._city_fns.clear()

    def _pack_blocks(self, batches, mode: str):
        """Stack index-only batches into (idx_block, mask_block, n_reals)
        triples of exactly S steps each; the tail short of a full S runs
        per-step (a zero-real padded scan step would divide 0/0 in the
        loss and poison the Adam moments — parity forbids it)."""
        S = self.steps_per_superstep
        pad = self._pad_for(0)
        n_nodes = self.dataset.n_nodes + pad
        blocks = []
        for i in range(len(batches) // S):
            chunk = batches[i * S:(i + 1) * S]
            idx_block = np.stack([b.indices for b in chunk]).astype(np.int32)
            mask_block = np.stack([
                self._mask_np(
                    (np.arange(len(b)) < b.n_real).astype(np.float32),
                    n_nodes, pad,
                )
                for b in chunk
            ])
            blocks.append((idx_block, mask_block, [b.n_real for b in chunk]))
        return blocks, batches[(len(batches) // S) * S:]

    def _run_train_epoch_superstep(self, mode: str, skip: int) -> None:
        """Training epoch as fused S-step dispatches (module docstring;
        train/step.py ``make_superstep_fns``).

        Packs the epoch's index-only batches into ``(S, B)`` blocks, keeps
        the *next* block's host->device copy in flight while the current
        superstep computes (double buffering — ``jnp.asarray`` issues the
        copy asynchronously), and runs the final ``n_batches % S`` batches
        through the ordinary per-step path. Per-step losses come back in
        batch order, so the epoch loss reduction is elementwise identical
        to the per-step loop's.

        Resilience hooks operate at block granularity: one-shot step
        faults and the SIGTERM safe point land at block boundaries; a
        block containing a drop fault, or one the divergence guard rolled
        back, re-runs through the per-step path (bit-identical by the
        parity contract), where poison faults re-fire per-microbatch and
        the guard skips exactly the offending one.
        """
        if self._superstep_fns is None:
            self._superstep_fns = self._make_superstep_fns()
        S = self.steps_per_superstep
        sup = self.supports
        if self._window_free:
            # the fused program gathers each microbatch from the resident
            # series (series superstep); resident operands here are the
            # series + this mode's targets + the offset table
            series = self._resident_series(0)
            targets = self._resident_targets(mode, 0)
            offsets = self._offsets_device()

            def dispatch(idx_d, mask_d, fns=None):
                fns = fns if fns is not None else self._superstep_fns
                return fns.train_superstep(
                    self.params, self.opt_state, sup, series, targets,
                    offsets, idx_d, mask_d,
                )
        else:
            x_all, y_all = self._resident_arrays(mode, 0)

            def dispatch(idx_d, mask_d, fns=None):
                fns = fns if fns is not None else self._superstep_fns
                return fns.train_superstep(
                    self.params, self.opt_state, sup, x_all, y_all,
                    idx_d, mask_d,
                )
        batches = list(self.dataset.batches(
            mode, self.batch_size, shuffle=self.shuffle, seed=self.seed,
            epoch=self.epoch, pad_last=True, with_arrays=False,
        ))
        if skip > len(batches):
            raise ValueError(
                f"resume cursor {skip} exceeds the epoch's {len(batches)} "
                "batches — checkpoint from a different data configuration?"
            )
        pending = batches[skip:]
        trc = obs_trace.active_tracer()
        if trc is None:
            blocks, remainder = self._pack_blocks(pending, mode)
        else:
            t_p0 = time.perf_counter()
            blocks, remainder = self._pack_blocks(pending, mode)
            t_p1 = time.perf_counter()
            trc.record_span("train.host_pack", t_p0, t_p1,
                            {"blocks": len(blocks)})
        plan, guard = self.fault_plan, self._guard

        def place(block):
            idx_np, mask_np, n_reals = block
            return (*self._place_block(idx_np, mask_np), n_reals)

        if trc is None:
            placer = place  # the hot loop binds the raw fn: zero obs cost
        else:
            def placer(block):
                t0 = time.perf_counter()
                out = place(block)
                t1 = time.perf_counter()
                nbytes = block[0].nbytes + block[1].nbytes
                jaxmon.record_upload(nbytes)
                trc.record_span("train.upload", t0, t1, {"bytes": nbytes})
                return out

        def per_step_block(i):
            for batch in pending[i * S:(i + 1) * S]:
                x, y, mask = self._place_batch(batch, mode)
                self._train_one(batch, x, y, mask)
                self._after_train_batch()

        placed = placer(blocks[0]) if blocks else None
        for i in range(len(blocks)):
            start = self._batch_in_epoch
            plan.before_step(self.epoch, start, start + S)
            if plan.active and plan.any_drop(self.epoch, start, start + S):
                # a dropped microbatch breaks the fused block's uniform
                # shape — run these S batches per-step instead
                placed = placer(blocks[i + 1]) if i + 1 < len(blocks) else None
                per_step_block(i)
                continue
            idx_d, mask_d, n_reals = placed
            if plan.active:
                for s in range(S):
                    poison = plan.poison_value(self.epoch, start + s)
                    if poison is not None:
                        mask_d = mask_d.at[
                            (s,) + (0,) * (mask_d.ndim - 1)
                        ].set(poison)
            if guard is not None:
                snapshot = (
                    jax.tree.map(jnp.copy, self.params),
                    jax.tree.map(jnp.copy, self.opt_state),
                )
            t_d0 = 0.0 if trc is None else time.perf_counter()
            hstats = None
            if self._health_due():
                if self._health_superstep_fns is None:
                    self._health_superstep_fns = self._make_superstep_fns(
                        health=True
                    )
                self.params, self.opt_state, loss_vec, hstats = dispatch(
                    idx_d, mask_d, self._health_superstep_fns
                )
            else:
                self.params, self.opt_state, loss_vec = dispatch(idx_d, mask_d)
            # superstep i is dispatched; upload block i+1 under its compute
            placed = placer(blocks[i + 1]) if i + 1 < len(blocks) else None
            if trc is not None:
                # close the span on the readback fence so it covers device
                # compute, not just dispatch enqueue; fencing AFTER the
                # next block's placement keeps the double buffer's
                # upload/compute overlap intact
                fence(loss_vec)
                t_d1 = time.perf_counter()
                trc.record_span("train.superstep", t_d0, t_d1,
                                {"step": start, "s": S})
            if guard is not None and not np.isfinite(np.asarray(loss_vec)).all():
                # a scanned step fed NaN forward into every later step of
                # the block: roll the whole block back and replay it
                # per-step, where the guard isolates the one bad microbatch
                self.params, self.opt_state = snapshot
                self._log(
                    f"divergence guard: non-finite loss in superstep block "
                    f"at epoch {self.epoch}, steps {start}..{start + S - 1} "
                    "— rolled back, replaying per-step"
                )
                per_step_block(i)
                continue
            if guard is not None:
                guard.ok()
            self._batch_in_epoch += S
            self.global_step += S
            self._epoch_losses.append(loss_vec)  # (S,) — stays on device
            self._epoch_counts.extend(n_reals)
            if hstats is not None:
                self._health_emit(hstats, loss_vec)
            self._after_train_batch()
        for batch in remainder:
            x, y, mask = self._place_batch(batch, mode)
            self._train_one(batch, x, y, mask)
            self._after_train_batch()

    def _pack_fleet_blocks(self, run, info, base: int):
        """Stack one fleet city's index-only batches into ``(idx_block,
        mask_block, n_reals)`` triples of exactly S steps; the tail short
        of a full S runs per-step (same rule as :meth:`_pack_blocks`).

        Indices shift by the city's ``base`` into the class's concatenated
        target vector; masks are node-crossed at the rung width always
        (``force_nodes`` — the scanned program's one mask shape).
        """
        S = self.steps_per_superstep
        blocks = []
        for i in range(len(run) // S):
            chunk = run[i * S:(i + 1) * S]
            idx_block = np.stack(
                [np.asarray(b.indices, np.int64) + base for b in chunk]
            ).astype(np.int32)
            mask_block = np.stack([
                self._mask_np(
                    (np.arange(len(b)) < b.n_real).astype(np.float32),
                    info.rung, info.pad, force_nodes=True,
                )
                for b in chunk
            ])
            blocks.append((idx_block, mask_block, [b.n_real for b in chunk]))
        return blocks, run[(len(run) // S) * S:]

    def _run_train_epoch_fleet(self, mode: str, skip: int) -> None:
        """Training epoch as per-class fused S-step dispatches.

        The heterogeneous epoch arrives city-sequential; consecutive
        batches of one fleet city pack into ``(S, B)`` blocks dispatched
        through the class's ONE compiled program (``train/step.py``
        ``make_fleet_superstep_fns``): each scanned step selects the
        city's padded support stack by slot, gathers its microbatch from
        the class's concatenated resident series, and divides the gate
        pooling by the traced real-node count. Cities the plan left
        unassigned — and every run's tail short of a full S — take the
        per-step loop, bit-identical by the parity contract. Double
        buffering, fault handling, and the divergence guard mirror
        :meth:`_run_train_epoch_superstep` at block granularity.
        """
        if self._fleet_fns is None:
            self._fleet_fns = self._make_fleet_fns()
        S = self.steps_per_superstep
        batches = list(self.dataset.batches(
            mode, self.batch_size, shuffle=self.shuffle, seed=self.seed,
            epoch=self.epoch, pad_last=True, with_arrays=False,
        ))
        if skip > len(batches):
            raise ValueError(
                f"resume cursor {skip} exceeds the epoch's {len(batches)} "
                "batches — checkpoint from a different data configuration?"
            )
        runs: list = []  # consecutive same-city runs, epoch order kept
        for b in batches[skip:]:
            if runs and runs[-1][0] == b.city:
                runs[-1][1].append(b)
            else:
                runs.append((b.city, [b]))
        plan, guard = self.fault_plan, self._guard

        def per_step(batch):
            x, y, mask = self._place_batch(batch, mode)
            self._train_one(batch, x, y, mask)
            self._after_train_batch()

        def place(block):
            idx_np, mask_np, n_reals = block
            return (*self._place_block(idx_np, mask_np), n_reals)

        trc = obs_trace.active_tracer()
        if trc is None:
            placer = place  # the hot loop binds the raw fn: zero obs cost
        else:
            def placer(block):
                t0 = time.perf_counter()
                out = place(block)
                t1 = time.perf_counter()
                nbytes = block[0].nbytes + block[1].nbytes
                jaxmon.record_upload(nbytes)
                trc.record_span("train.upload", t0, t1, {"bytes": nbytes})
                return out

        for city, run in runs:
            info = self._fleet_cities.get(city)
            if info is None:  # no shape class fits: the per-step loop
                for batch in run:
                    per_step(batch)
                continue
            series = self._fleet_series(info.cls)
            targets, bases = self._fleet_targets(mode, info.cls)
            offsets = self._offsets_device()
            sup_stack = self._fleet_supports(info.cls)
            blocks, remainder = self._pack_fleet_blocks(
                run, info, bases[city]
            )
            slot_d = jnp.full((S,), info.slot, jnp.int32)
            nr_d = jnp.full((S,), info.n_real, jnp.int32)
            if self._meshy:  # every shard selects the same slot / divisor
                slot_d = self.placement.put(slot_d, "replicated")
                nr_d = self.placement.put(nr_d, "replicated")

            def per_step_block(i, run=run):
                for batch in run[i * S:(i + 1) * S]:
                    per_step(batch)

            placed = placer(blocks[0]) if blocks else None
            for i in range(len(blocks)):
                start = self._batch_in_epoch
                plan.before_step(self.epoch, start, start + S)
                if plan.active and plan.any_drop(self.epoch, start, start + S):
                    placed = placer(blocks[i + 1]) if i + 1 < len(blocks) else None
                    per_step_block(i)
                    continue
                idx_d, mask_d, n_reals = placed
                if plan.active:
                    for s in range(S):
                        poison = plan.poison_value(self.epoch, start + s)
                        if poison is not None:
                            mask_d = mask_d.at[
                                (s,) + (0,) * (mask_d.ndim - 1)
                            ].set(poison)
                if guard is not None:
                    snapshot = (
                        jax.tree.map(jnp.copy, self.params),
                        jax.tree.map(jnp.copy, self.opt_state),
                    )
                t_d0 = 0.0 if trc is None else time.perf_counter()
                hstats = None
                if self._health_due():
                    if self._health_fleet_fns is None:
                        self._health_fleet_fns = self._make_fleet_fns(
                            health=True
                        )
                    self.params, self.opt_state, loss_vec, hstats = (
                        self._health_fleet_fns.train_superstep(
                            self.params, self.opt_state, sup_stack, series,
                            targets, offsets, idx_d, mask_d, slot_d, nr_d,
                        )
                    )
                else:
                    self.params, self.opt_state, loss_vec = (
                        self._fleet_fns.train_superstep(
                            self.params, self.opt_state, sup_stack, series,
                            targets, offsets, idx_d, mask_d, slot_d, nr_d,
                        )
                    )
                # block i is dispatched; upload i+1 under its compute
                placed = placer(blocks[i + 1]) if i + 1 < len(blocks) else None
                if trc is not None:
                    # fence AFTER the next placement: overlap preserved
                    fence(loss_vec)
                    t_d1 = time.perf_counter()
                    trc.record_span("train.superstep", t_d0, t_d1,
                                    {"step": start, "s": S, "city": city})
                if guard is not None and not np.isfinite(
                    np.asarray(loss_vec)
                ).all():
                    self.params, self.opt_state = snapshot
                    self._log(
                        f"divergence guard: non-finite loss in fleet "
                        f"superstep block at epoch {self.epoch}, steps "
                        f"{start}..{start + S - 1} — rolled back, "
                        "replaying per-step"
                    )
                    per_step_block(i)
                    continue
                if guard is not None:
                    guard.ok()
                self._batch_in_epoch += S
                self.global_step += S
                self._epoch_losses.append(loss_vec)  # (S,) — stays on device
                self._epoch_counts.extend(n_reals)
                if hstats is not None:
                    self._health_emit(
                        hstats, loss_vec,
                        cities=self._fleet_plan.classes[info.cls].cities,
                    )
                self._after_train_batch()
            for batch in remainder:
                per_step(batch)

    # -- public API -----------------------------------------------------
    def train(self) -> dict:
        """Run the epoch loop; returns the history dict.

        While training runs (main thread only — ``signal.signal`` is
        unavailable elsewhere), SIGTERM is caught and deferred to the next
        safe step boundary, where :meth:`_check_preempt` writes an
        emergency checkpoint and raises :class:`Preempted`; the previous
        handler is restored on the way out.
        """
        history = {"train": [], "validate": []}
        self._event("train_start", f"Training starts at: {time.ctime()}")
        in_main = threading.current_thread() is threading.main_thread()
        prev_handler = None
        if in_main:

            def _on_sigterm(signum, frame):
                self._preempted = True

            prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        # a mid-epoch resume cursor re-enters the checkpointed epoch to
        # finish it; an epoch-boundary checkpoint starts the next one
        start_epoch = self.epoch + (1 if self._resume_skip == 0 else 0)
        try:
            self._epoch_loop(history, start_epoch)
        except BaseException:
            # Queued async checkpoint writes must land even when the loop
            # dies (preemption, OOM, Ctrl-C) — the writer is a daemon
            # thread, killed at interpreter exit with whatever it still
            # holds; without this, latest.ckpt can silently be epochs
            # stale. But the in-flight exception stays the primary one: a
            # flush failure here is logged, not raised over it.
            try:
                self.flush_checkpoints()
            except Exception as flush_exc:
                self._log(f"checkpoint flush failed during teardown: {flush_exc}")
            raise
        finally:
            if in_main:
                signal.signal(signal.SIGTERM, prev_handler)
            if self._health_writer is not None:
                self._health_writer.flush()
        self.flush_checkpoints()
        self._event("train_end", f"Training ends at: {time.ctime()}")
        return history

    def _epoch_loop(self, history: dict, start_epoch: int) -> None:
        for epoch in range(start_epoch, self.n_epochs + 1):
            self.epoch = epoch
            t0 = time.time()
            trc = obs_trace.active_tracer()
            sp_epoch = None if trc is None else trc.span("train.epoch", epoch=epoch)
            sp = None if trc is None else trc.span("train.train_epoch")
            train_loss = self._run_epoch("train", train=True)
            if sp is not None:
                sp.end()
            self._check_preempt()
            sp = None if trc is None else trc.span("train.eval_epoch")
            val_loss = self._run_epoch("validate", train=False)
            if sp is not None:
                sp.end()
            self._check_preempt()
            if epoch == start_epoch and jaxmon.installed():
                # every train/eval program has traced once (pad_last keeps
                # batch shapes constant) — any later compile is a runtime
                # recompile, surfaced by the recompiles_after_warmup gauge
                jaxmon.mark_warmup_complete()
            # the epoch's batches are all consumed: zero the resume cursor
            # *before* the bookkeeping saves below, so their meta points a
            # resume at epoch+1. A preemption before this line instead
            # saved cursor == steps-per-epoch: the resume re-enters this
            # epoch with nothing left to train, recomputes the loss from
            # the persisted partials, and redoes val + bookkeeping (which
            # had not happened yet) exactly once.
            self._batch_in_epoch = 0
            self._epoch_losses, self._epoch_counts = [], []
            history["train"].append(train_loss)
            history["validate"].append(val_loss)

            improved = val_loss <= self.best_val  # <= : reference Model_Trainer.py:48
            if improved:
                self._log(
                    f"Epoch {epoch}, val_loss drops from {self.best_val:.5} to "
                    f"{val_loss:.5}. Updating best checkpoint.."
                )
                self.best_val = val_loss
                self.patience_left = self.patience
                data = self._save(self.best_path)
                if self.top_k > 1 and self.is_lead:
                    # best-k retention (SURVEY.md §5.d): keep the k best
                    # improvement snapshots alongside best/latest; reuse the
                    # bytes just serialized for best.ckpt (identical content,
                    # and best.ckpt may still be in the async write queue)
                    path = os.path.join(self.out_dir, f"best_e{epoch}.ckpt")
                    self._write(path, data)
                    # rank by (loss, newest-wins-on-ties) to match the
                    # `val <= best` improvement rule
                    self._kept.append((val_loss, -epoch, path))
                    self._kept.sort()
                    while len(self._kept) > self.top_k:
                        _, _, stale = self._kept.pop()
                        self._remove(stale)
            else:
                self.patience_left -= 1
                self._log(
                    f"Epoch {epoch}, val_loss {val_loss:.5} does not improve "
                    f"from {self.best_val:.5} (patience {self.patience_left})"
                )
            self._save(self.latest_path)
            self._record(
                {
                    "epoch": epoch,
                    "train_loss": train_loss,
                    "val_loss": val_loss,
                    "best_val": self.best_val,
                    "improved": improved,
                    "seconds": round(time.time() - t0, 3),
                }
            )
            if sp_epoch is not None:
                sp_epoch.end()
            if self.patience_left == 0:
                self._log(f"Early stopping at epoch {epoch}..")
                break
            self._check_preempt()  # SIGTERM during bookkeeping

    def _load_state(self, path: str):
        """Read a checkpoint — on the lead process only in multi-host jobs,
        broadcasting the state to everyone else (module docstring)."""
        if jax.process_count() == 1:
            self.flush_checkpoints()  # a pending async write may own this path
            return load_checkpoint(path, self.params, self.opt_state)
        import json as _json

        from jax.experimental import multihost_utils

        # Lead-side failures (flush or read) are encoded into the broadcast
        # payload so every process raises together — a lead that raised
        # *before* the collective would leave the others blocked in it.
        params, opt_state = self.params, self.opt_state
        blob = np.zeros(0, np.uint8)
        if self.is_lead:
            try:
                self.flush_checkpoints()
                meta, params, opt_state = load_checkpoint(
                    path, self.params, self.opt_state
                )
            except Exception as e:
                meta = {"__load_error__": f"{type(e).__name__}: {e}"}
                params, opt_state = self.params, self.opt_state
            blob = np.frombuffer(_json.dumps(meta).encode(), dtype=np.uint8)
        n = int(multihost_utils.broadcast_one_to_all(np.int64(blob.size)))
        buf = np.zeros(n, np.uint8)
        if self.is_lead:
            buf[:] = blob
        meta = _json.loads(bytes(np.asarray(
            multihost_utils.broadcast_one_to_all(buf)
        )).decode())
        if "__load_error__" in meta:
            raise RuntimeError(
                f"lead process failed to load {path}: {meta['__load_error__']}"
            )
        params = multihost_utils.broadcast_one_to_all(params)
        opt_state = multihost_utils.broadcast_one_to_all(opt_state)
        return meta, params, opt_state

    def _apply_meta(self, meta: dict) -> None:
        """Install a checkpoint's meta into the live loop state, including
        the mid-epoch resume cursor when the save was mid-epoch."""
        self.epoch = meta["epoch"]
        self.best_val = meta["best_val"]
        self.patience_left = meta["patience_left"]
        self._kept = [tuple(entry) for entry in meta.get("kept", [])]
        self.global_step = int(meta.get("global_step", 0))
        self._last_cadence_step = self.global_step
        self._resume_skip = int(meta.get("batch_in_epoch", 0))
        scale = float(meta.get("lr_scale", 1.0))
        if scale != self._lr_scale:
            self._set_lr_scale(scale)
        if self._resume_skip:
            # exact resume replays the interrupted epoch's remaining
            # batches — only sound if the data order is reproduced, which
            # (seed, shuffle, epoch) fully determines
            if int(meta.get("seed", self.seed)) != self.seed:
                raise ValueError(
                    f"mid-epoch checkpoint was written with seed "
                    f"{meta['seed']}, trainer has seed {self.seed} — the "
                    "data order would differ; resume with the same seed"
                )
            if bool(meta.get("shuffle", self.shuffle)) != self.shuffle:
                raise ValueError(
                    f"mid-epoch checkpoint was written with "
                    f"shuffle={meta['shuffle']}, trainer has "
                    f"shuffle={self.shuffle} — the data order would differ"
                )
            if self._resume_skip > self._train_steps_per_epoch():
                raise ValueError(
                    f"mid-epoch resume cursor {self._resume_skip} exceeds "
                    f"{self._train_steps_per_epoch()} steps per epoch — "
                    "checkpoint from a different data configuration?"
                )
            partial = meta.get("partial") or {"losses": [], "counts": []}
            # np.float32 roundtrips the f32 device scalar bit-exactly
            # through JSON's float
            self._epoch_losses = [np.float32(v) for v in partial["losses"]]
            self._epoch_counts = [int(c) for c in partial["counts"]]
            self._batch_in_epoch = self._resume_skip
            self._resume_deferred = [
                int(o) for o in meta.get("deferred", [])
            ]
        else:
            self._epoch_losses, self._epoch_counts = [], []
            self._batch_in_epoch = 0
            self._resume_deferred = []

    def restore(self, path: Optional[str] = None) -> dict:
        """Load a checkpoint into the live trainer state.

        With an explicit ``path``, that file is loaded (and must verify).
        Without one, this is the strict resume: the newest *verified*
        checkpoint in ``out_dir`` (via :meth:`restore_auto`), raising
        ``FileNotFoundError`` when nothing resumable exists — use
        :meth:`restore_auto` directly for resume-if-possible semantics.

        Multi-host jobs read on the lead and broadcast (see the module
        docstring), so ``out_dir`` may be host-local.
        """
        if path is None:
            meta = self.restore_auto()
            if meta is None:
                raise FileNotFoundError(
                    errno.ENOENT,
                    "no verified checkpoint to resume from",
                    self.latest_path,
                )
            return meta
        meta, params, opt_state = self._load_state(path)
        self.params = self.placement.put(params, "state")
        self.opt_state = self.placement.put(opt_state, "state")
        self._apply_meta(meta)
        REGISTRY.counter("train.checkpoint_recoveries").inc()
        return meta

    def restore_auto(self) -> Optional[dict]:
        """Resume from the newest verified checkpoint, if any.

        Walks ``load_latest_verified``'s recovery chain (latest -> rotated
        previous latest -> best-k -> best), quarantining corrupt files, and
        installs the first verified state. Returns its meta, or ``None``
        when ``out_dir`` holds nothing loadable — the ``--resume auto``
        fresh-start case. Multi-host jobs verify/read on the lead process
        and broadcast the outcome so every process takes the same branch.
        """
        if jax.process_count() == 1:
            self.flush_checkpoints()  # pending writes may own these paths
            found = load_latest_verified(
                self.out_dir, self.params, self.opt_state, log=self._log
            )
            if found is None:
                return None
            path, meta, params, opt_state = found
            self.params = self.placement.put(params, "state")
            self.opt_state = self.placement.put(opt_state, "state")
            self._apply_meta(meta)
            REGISTRY.counter("train.checkpoint_recoveries").inc()
            self._log(
                f"resumed from {path} (epoch {self.epoch}, "
                f"step {self.global_step})"
            )
            return meta
        import json as _json

        from jax.experimental import multihost_utils

        # Same protocol as _load_state: lead-side outcomes (found / not
        # found / failed) ride the meta broadcast so no process raises or
        # returns before the collectives complete.
        params, opt_state = self.params, self.opt_state
        blob = np.zeros(0, np.uint8)
        if self.is_lead:
            try:
                self.flush_checkpoints()
                found = load_latest_verified(
                    self.out_dir, self.params, self.opt_state, log=self._log
                )
                if found is None:
                    meta = {"__none__": True}
                else:
                    _, meta, params, opt_state = found
            except Exception as e:
                meta = {"__load_error__": f"{type(e).__name__}: {e}"}
            blob = np.frombuffer(_json.dumps(meta).encode(), dtype=np.uint8)
        n = int(multihost_utils.broadcast_one_to_all(np.int64(blob.size)))
        buf = np.zeros(n, np.uint8)
        if self.is_lead:
            buf[:] = blob
        meta = _json.loads(bytes(np.asarray(
            multihost_utils.broadcast_one_to_all(buf)
        )).decode())
        if "__load_error__" in meta:
            raise RuntimeError(
                f"lead process failed to resume from {self.out_dir}: "
                f"{meta['__load_error__']}"
            )
        if meta.pop("__none__", False):
            return None
        params = multihost_utils.broadcast_one_to_all(params)
        opt_state = multihost_utils.broadcast_one_to_all(opt_state)
        self.params = self.placement.put(params, "state")
        self.opt_state = self.placement.put(opt_state, "state")
        self._apply_meta(meta)
        return meta

    def test(self, modes=("train", "test"), checkpoint: Optional[str] = "best") -> dict:
        """Evaluate denormalized metrics per mode using the best params.

        Mirrors ``ModelTrainer.test`` (``Model_Trainer.py:68-98``) including
        its re-scoring of the train split; pass ``checkpoint=None`` to
        evaluate the live parameters instead of reloading.
        """
        params = self.params
        if checkpoint is not None:
            path = self.best_path if checkpoint == "best" else checkpoint
            _, params, _ = self._load_state(path)
            params = self.placement.put(params, "state")
        self._event("test_start", f"Testing starts at: {time.ctime()}")
        if jaxmon.installed():
            # the warmed training loop is over: pin recompiles_after_warmup
            # so evaluation's first-touch programs (test-split gathers were
            # never traced during training) don't read as loop recompiles
            jaxmon.freeze_recompiles()
        sp_test = obs_trace.span("train.test")  # no-op when tracing is off
        hetero = getattr(self.dataset, "heterogeneous", False)
        results = {}
        for mode in modes:
            preds, trues = {}, {}  # per-city accumulation (one key unless hetero)
            # metric accumulation reads batch.y on the host — keep arrays
            for batch, (x, y, mask) in self._placed_batches(mode, with_arrays=True):
                _, pred = self._fns(batch.city).eval_step(
                    params, self._supports_for(batch), x, y, mask
                )
                pred = np.asarray(pred)[: batch.n_real]
                pad = self._pad_for(batch.city)
                if pad:  # drop padded node rows ((B,[H,]N,C))
                    pred = pred[..., :-pad, :]
                preds.setdefault(batch.city, []).append(pred)
                trues.setdefault(batch.city, []).append(batch.y[: batch.n_real])
            if hetero:
                # per-city denormalization (each city has its own scale) +
                # per-city reports; the overall report pools the flattened
                # raw-unit values so cities with more regions weigh more,
                # exactly as their demand points do
                per_city, flat_p, flat_t = {}, [], []
                for c in sorted(preds):
                    p = self.dataset.denormalize(
                        np.concatenate(preds[c], axis=0), city=c
                    )
                    t = self.dataset.denormalize(
                        np.concatenate(trues[c], axis=0), city=c
                    )
                    per_city[f"city{c}"] = regression_report(p, t)
                    flat_p.append(p.ravel())
                    flat_t.append(t.ravel())
                results[mode] = regression_report(
                    np.concatenate(flat_p), np.concatenate(flat_t)
                )
                results[mode]["per_city"] = per_city
            else:
                # homogeneous cities share one normalizer and one shape:
                # pool every city's batches as before
                pred = self.dataset.denormalize(
                    np.concatenate([a for c in sorted(preds) for a in preds[c]])
                )
                true = self.dataset.denormalize(
                    np.concatenate([a for c in sorted(trues) for a in trues[c]])
                )
                results[mode] = regression_report(pred, true)
            self._log(
                f"{mode} true MSE: {results[mode]['mse']:.6g}  "
                f"RMSE: {results[mode]['rmse']:.6g}  "
                f"MAE: {results[mode]['mae']:.6g}  "
                f"MAPE: {results[mode]['mape'] * 100:.4g}%  "
                f"PCC: {results[mode]['pcc']:.4g}"
            )
            if hetero:
                for name, rep in results[mode]["per_city"].items():
                    self._log(
                        f"  {mode}/{name} RMSE: {rep['rmse']:.6g}  "
                        f"MAE: {rep['mae']:.6g}  PCC: {rep['pcc']:.4g}"
                    )
        sp_test.end()
        self._event("test_end", f"Testing ends at: {time.ctime()}")
        return results
