"""Continual fine-tuning behind the serving path: the loop's train side.

The closed loop is: live rows land in a device-resident
:class:`~stmgcn_tpu.data.SeriesRing`; PR 11's drift gauges (or a
wall-clock cadence) trip a retrain; :class:`ContinualTrainer` fine-tunes
on the freshest ring contents through the existing fused series
superstep and writes a CRC-verified candidate checkpoint;
:class:`~stmgcn_tpu.serving.PromotionGate` either promotes it through
the atomic hot-swap path or quarantines it with a typed reason.

The supervision contract — the part that makes the loop safe to leave
unattended — is isolation by construction:

- the trainer keeps its committed state as **host** numpy pytrees
  (the superstep donates its device operands, so device state cannot be
  the source of truth across a crashed step); a fine-tune produces
  *pending* state that becomes committed only after the gate accepts
  its checkpoint, and is discarded wholesale on rejection or crash;
- :class:`ContinualDaemon` supervises ``finetune()`` with exponential
  backoff + deterministic jitter under a bounded restart budget; when
  the budget is spent the daemon marks itself ``down`` and stops —
  serving continues on the last promoted generation either way;
- daemon fault drills ride the training-side
  :class:`~stmgcn_tpu.resilience.FaultPlan`: ``raise``/``hang`` fire at
  the fine-tune's step boundary, ``poison`` lands NaN in one step's
  loss mask (the gate then rejects the candidate as ``nonfinite``), and
  the write kinds (``corrupt-write``/``torn-write``) corrupt or tear
  the candidate checkpoint itself.

``closed_loop_smoke`` packs the whole loop — ingest, drift/cadence
trigger, fine-tune, one clean promotion, one poisoned rejection, live
serving throughout — into a CPU-sized drill for ``scripts/lint_gate.sh``
and the soak bench.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.train.step import (
    gather_window_batch,
    make_series_superstep_fns,
    make_step_fns,
)

__all__ = [
    "ContinualDaemon",
    "ContinualTrainer",
    "closed_loop_smoke",
    "make_holdout_eval",
]


class ContinualTrainer:
    """Fine-tune on the freshest ring contents; emit candidate checkpoints.

    Never mutates its committed state on its own: ``finetune()`` stages
    the post-step params/opt-state as *pending* and the caller promotes
    them with :meth:`commit` only after the gate accepts the candidate
    (or drops them with :meth:`discard`). Committed state lives as host
    numpy — the fused superstep donates its device params/opt-state
    buffers, so a fresh device copy is staged per fine-tune and a crash
    mid-step can never leave half-updated truth behind.
    """

    def __init__(self, model, optimizer, supports, ring, spec, config,
                 out_dir: str, *, params, opt_state=None, loss: str = "mse",
                 holdout: int = 4, fault_plan=None, health_baseline=None,
                 meta: Optional[dict] = None, registry=None, log=None):
        self.ring = ring
        self.spec = spec
        self.config = config
        self.out_dir = out_dir
        self.candidate_dir = os.path.join(out_dir, "candidates")
        os.makedirs(self.candidate_dir, exist_ok=True)
        self.holdout = int(holdout)
        self.fault_plan = fault_plan
        self.health_baseline = health_baseline
        self.meta = dict(meta) if meta else {}
        self._supports = jnp.asarray(supports) if not isinstance(
            supports, (list, tuple, dict)) else supports
        self._offsets = jnp.asarray(spec.offsets, jnp.int32)
        self._fns = make_series_superstep_fns(
            model, optimizer, loss=loss, horizon=spec.horizon, health=True,
        )
        # committed truth is HOST numpy (donation-safe); opt_state defaults
        # to a fresh optimizer state over the serving params
        self._params = jax.tree.map(np.asarray, params)
        self._opt_state = jax.tree.map(
            np.asarray,
            optimizer.init(params) if opt_state is None else opt_state,
        )
        self._pending: Optional[Tuple] = None
        self.ordinal = 0
        self._reg = REGISTRY if registry is None else registry
        self._log = log if log is not None else (lambda msg: None)

    @property
    def params(self):
        """The committed (last accepted) host params pytree."""
        return self._params

    def _train_idx_block(self) -> Tuple[np.ndarray, np.ndarray]:
        """(targets, idx_block): the freshest S*B training samples.

        ``targets`` are ring-local target timesteps with the last
        ``holdout`` excluded (the gate's held-out eval scores those);
        ``idx_block`` is ``(S, B)`` int32 into ``targets``, taking the
        freshest samples and wrapping when the ring holds fewer than a
        full block.
        """
        cfg = self.config
        last = cfg.finetune_window if cfg.finetune_window else None
        targets = self.ring.target_indices(self.spec, last=last)
        if self.holdout and len(targets) > self.holdout:
            targets = targets[: -self.holdout]
        n = len(targets)
        s, b = cfg.finetune_steps, cfg.finetune_batch
        flat = (np.arange(s * b) + max(0, n - s * b)) % n
        return targets, flat.reshape(s, b).astype(np.int32)

    def finetune(self) -> Tuple[str, dict]:
        """One supervised fine-tune: S fused steps on the freshest ring
        rows, candidate checkpoint written, health summary returned.

        Returns ``(candidate_path, health)`` where ``health`` is the
        aggregate the promotion gate consumes: ``nonfinite`` (total
        nonfinite grad/loss observations), ``grad_norm_max``,
        ``update_ratio_max``, ``loss_last``. Raises whatever the fault
        plan or the step raises — supervision (backoff, restart budget)
        is the daemon's job, not this method's.
        """
        ordinal = self.ordinal
        self.ordinal += 1
        cfg = self.config
        s, b = cfg.finetune_steps, cfg.finetune_batch
        targets, idx_block = self._train_idx_block()
        mask_block = np.ones((s, b), np.float32)
        plan = self.fault_plan
        if plan is not None:
            plan.before_step(ordinal, 0, s)  # raise/sigterm/hang drills
            for step in range(s):
                payload = plan.poison_value(ordinal, step)
                if payload is not None:
                    mask_block[step, 0] = payload
        series = self.ring.series()
        params, opt_state, losses, stats = self._fns.train_superstep(
            jax.tree.map(jnp.asarray, self._params),
            jax.tree.map(jnp.asarray, self._opt_state),
            self._supports,
            series,
            jnp.asarray(targets, jnp.int32),
            self._offsets,
            jnp.asarray(idx_block),
            jnp.asarray(mask_block),
        )
        health = {
            "nonfinite": int(
                np.asarray(stats["nonfinite_grads"]).sum()
                + np.asarray(stats["nonfinite_loss"]).sum()
            ),
            "grad_norm_max": float(np.max(np.asarray(stats["grad_norm"]))),
            "update_ratio_max": float(
                np.max(np.asarray(stats["update_ratio"]))
            ),
            "loss_last": float(np.asarray(losses)[-1]),
        }
        self._pending = (
            jax.tree.map(np.asarray, params),
            jax.tree.map(np.asarray, opt_state),
        )
        path = os.path.join(
            self.candidate_dir, f"candidate-{ordinal:04d}.ckpt"
        )
        meta = dict(self.meta)
        meta.update({
            "kind": "continual",
            "ordinal": ordinal,
            "steps": s,
            "batch": b,
            "next_ts": int(self.ring.next_ts),
            "health": {k: v for k, v in health.items()
                       if v == v},  # keep the meta JSON NaN-free
        })
        if self.health_baseline is not None:
            meta["health_baseline"] = self.health_baseline
        from stmgcn_tpu.train.checkpoint import save_checkpoint

        save_checkpoint(path, self._pending[0], self._pending[1], meta,
                        fault_plan=plan)
        self._reg.counter("continual.retrains").inc()
        self._log(f"fine-tune {ordinal}: loss {health['loss_last']:.5f}, "
                  f"candidate {path}")
        return path, health

    def commit(self) -> None:
        """Adopt the pending fine-tune as committed truth (gate accepted)."""
        if self._pending is not None:
            self._params, self._opt_state = self._pending
            self._pending = None

    def discard(self) -> None:
        """Drop the pending fine-tune (gate rejected, or the step crashed).
        The next fine-tune restarts from the committed state."""
        self._pending = None


def make_holdout_eval(model, supports, ring, spec, *, holdout: int = 4,
                      loss: str = "mse") -> Callable:
    """``callable(params) -> float``: loss on the ring's freshest targets.

    The gate calls this twice per candidate (candidate params vs the
    live baseline) against the SAME held-out rows — the freshest
    ``holdout`` targets, which :class:`ContinualTrainer` excludes from
    its training block. Re-reads the ring per call, so the comparison
    always scores current traffic; shapes are constant (``holdout``
    fixed), so the underlying jitted eval compiles once.
    """
    import optax

    fns = make_step_fns(model, optax.sgd(0.0), loss=loss)
    supports = jnp.asarray(supports)
    mask = jnp.ones((holdout,), jnp.float32)

    def evaluate(params) -> float:
        targets = ring.target_indices(spec)[-holdout:]
        series = ring.series()
        x, y = gather_window_batch(
            series,
            jnp.asarray(targets, jnp.int32),
            jnp.asarray(spec.offsets, jnp.int32),
            jnp.arange(holdout, dtype=jnp.int32),
            spec.horizon,
        )
        loss_val, _ = fns.eval_step(
            jax.tree.map(jnp.asarray, params), supports, x, y, mask
        )
        return float(loss_val)

    return evaluate


class ContinualDaemon:
    """Supervise the fine-tune → gate loop; never endanger serving.

    Synchronous core (``should_retrain``/``poll``/``retrain`` — what the
    tests drive deterministically) plus an optional background thread
    (``start``/``stop``) that mirrors the checkpoint watcher's
    discipline: stop event, daemon thread, bounded join.

    A fine-tune that raises is retried with exponential backoff and
    deterministic jitter up to ``config.max_restarts`` times; exhausting
    the budget marks the daemon ``down`` (gauge ``continual.daemon_up``
    drops to 0) and retires it. In every failure mode the serving engine
    keeps answering from its last promoted generation.
    """

    JOIN_TIMEOUT_S = 5.0

    def __init__(self, trainer: ContinualTrainer, gate, *, config,
                 time_fn=time.monotonic, sleep_fn=time.sleep,
                 rng_seed: int = 0, registry=None, log=None,
                 replica: Optional[str] = None):
        self.trainer = trainer
        self.gate = gate
        self.config = config
        self._time = time_fn
        self._sleep = sleep_fn
        self._rng = random.Random(rng_seed)
        self._reg = REGISTRY if registry is None else registry
        self._log = log if log is not None else (lambda msg: None)
        # federation shards run one daemon each: a replica label keeps
        # their up/down gauges distinguishable in one registry
        self._labels = None if replica is None else {"replica": str(replica)}
        self._last_retrain = time_fn()
        self.down = False
        self.restarts = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reg.gauge("continual.daemon_up", self._labels).set(1)

    # -- trigger ---------------------------------------------------------

    def should_retrain(self) -> Optional[str]:
        """``"drift"`` | ``"cadence"`` | None — why to retrain now.

        Drift wins: any city/phase gauge in the engine's live drift
        snapshot over ``drift_z_max``/``drift_psi`` fires regardless of
        cadence. Cadence fires when ``cadence_s > 0`` and that much wall
        clock has passed since the last completed retrain.
        """
        if self.down:
            return None
        snap = self.gate._engine.drift_snapshot()
        if snap is not None:
            cfg = self.config
            for phases in snap.get("cities", {}).values():
                for gauges in phases.values():
                    z = float(gauges.get("z_max", 0.0))
                    psi = float(gauges.get("psi", 0.0))
                    if z > cfg.drift_z_max or psi > cfg.drift_psi:
                        return "drift"
        if self.config.cadence_s > 0:
            if self._time() - self._last_retrain >= self.config.cadence_s:
                return "cadence"
        return None

    def poll(self):
        """Check the trigger; run one retrain cycle if it fires.
        Returns the gate's decision, or None when idle/down/exhausted."""
        reason = self.should_retrain()
        if reason is None:
            return None
        return self.retrain(reason)

    def retrain(self, reason: str):
        """One supervised fine-tune → gate cycle.

        Crashes inside ``finetune()`` are retried under the restart
        budget with backoff ``min(backoff_s * 2**k, backoff_max_s)``
        plus up to 10% deterministic jitter; the budget spent, the
        daemon goes ``down`` and returns None. A completed fine-tune
        always reaches the gate, and the gate's verdict decides whether
        the trainer commits or discards the pending state.
        """
        cfg = self.config
        attempts = 0
        while True:
            try:
                path, health = self.trainer.finetune()
                break
            except Exception as e:  # Preempted is BaseException: passes
                self.trainer.discard()
                attempts += 1
                self.restarts += 1
                if attempts > cfg.max_restarts:
                    self.down = True
                    self._reg.gauge("continual.daemon_up", self._labels).set(0)
                    self._log(f"retrain ({reason}) abandoned after "
                              f"{attempts} attempts: {e!r} — daemon down, "
                              "serving continues on the live generation")
                    return None
                delay = min(cfg.backoff_s * (2.0 ** (attempts - 1)),
                            cfg.backoff_max_s)
                delay *= 1.0 + 0.1 * self._rng.random()
                self._log(f"retrain ({reason}) attempt {attempts} failed: "
                          f"{e!r}; backing off {delay * 1e3:.0f} ms")
                self._sleep(delay)
        decision = self.gate.consider(path, health)
        if decision.accepted:
            self.trainer.commit()
        else:
            self.trainer.discard()
        self._last_retrain = self._time()
        self._log(f"retrain ({reason}) -> {decision.reason} "
                  f"(generation {decision.generation})")
        return decision

    # -- background supervision ------------------------------------------

    def start(self, poll_s: float = 1.0) -> "ContinualDaemon":
        """Poll the trigger on a daemon thread until :meth:`stop`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(poll_s):
                try:
                    self.poll()
                except Exception as e:  # the daemon never kills serving
                    self._log(f"continual daemon poll error: {e!r}")
                if self.down:
                    return

        self._thread = threading.Thread(
            target=loop, name="continual-daemon", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: Optional[float] = None) -> bool:
        """Signal the loop and join it, bounded (thread is daemon — a
        straggler cannot hold the process open). True when it exited."""
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(self.JOIN_TIMEOUT_S if timeout_s is None else timeout_s)
        if t.is_alive():
            return False
        self._thread = None
        return True


def closed_loop_smoke(out_dir: str, *, poison: bool = True,
                      seed: int = 0) -> dict:
    """The whole closed loop, CPU-sized: the lint-gate/soak drill.

    Builds a tiny serial-only model + ring, serves live throughout, and
    runs two retrain cycles: one clean (promoted through the gate into
    the engine) and — with ``poison=True`` — one with a NaN poisoned
    into the fine-tune's loss mask (rejected as ``nonfinite``; serving
    stays on the promoted generation). Returns the verdict counts the
    gate script asserts on: ``promotions``, ``rejections``,
    ``nonfinite`` (of the *clean* fine-tune), ``rejection_reason``,
    ``generation``, plus ingest/serving evidence.
    """
    import optax

    from stmgcn_tpu.config import ContinualConfig, ServingConfig, preset
    from stmgcn_tpu.data import (
        DemandDataset,
        MinMaxNormalizer,
        SeriesRing,
        WindowSpec,
        synthetic_dataset,
    )
    from stmgcn_tpu.experiment import build_model
    from stmgcn_tpu.inference import Forecaster
    from stmgcn_tpu.ops import SupportConfig
    from stmgcn_tpu.resilience import FaultPlan, FaultSpec
    from stmgcn_tpu.serving import PromotionGate

    cfg = preset("smoke")
    cfg.data.override(rows=2, n_timesteps=64,
                      serial_len=3, daily_len=0, weekly_len=0)
    spec = WindowSpec(3, 0, 0, 24 // cfg.data.dt, cfg.data.horizon)
    data = synthetic_dataset(rows=2, n_timesteps=64, seed=seed)
    ds = DemandDataset(data, spec)
    supports = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
            ds.adjs.values()
        ),
        np.float32,
    )[: cfg.model.m_graphs]
    model = build_model(cfg, ds.n_feats)
    x0 = jnp.zeros((1, spec.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
    params = model.init(jax.random.key(seed), jnp.asarray(supports), x0)
    norm = MinMaxNormalizer.fit(np.asarray(data.demand))
    normalized = np.asarray(norm.transform(np.asarray(data.demand)),
                            np.float32)

    warm = 48  # pre-filled history; the rest arrives live below
    ring = SeriesRing.from_series(normalized[:warm], capacity=64,
                                  reorder_window=2)
    fc = Forecaster(model, params, norm, cfg,
                    {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes})
    engine = fc.serving_engine(
        supports, config=ServingConfig(buckets=(1, 2), max_batch=2,
                                       max_delay_ms=2.0),
    )
    ccfg = ContinualConfig(
        enabled=True, ring_capacity=64, reorder_window=2,
        finetune_steps=2, finetune_batch=2, max_restarts=1,
        backoff_s=0.01, backoff_max_s=0.02,
        promote_grad_norm_max=1e6, promote_update_ratio_max=100.0,
        promote_eval_margin=10.0,
    )
    # the second fine-tune (ordinal 1) gets NaN in step 0's loss mask
    plan = FaultPlan(FaultSpec(kind="poison", epoch=1, step=0)) \
        if poison else FaultPlan()
    trainer = ContinualTrainer(
        model, optax.adam(1e-3), supports, ring, spec, ccfg, out_dir,
        params=params, holdout=2, fault_plan=plan,
    )
    gate = PromotionGate.from_config(
        engine, out_dir, ccfg,
        holdout_eval=make_holdout_eval(model, supports, ring, spec,
                                       holdout=2),
        live_params=params,
    )
    daemon = ContinualDaemon(trainer, gate, config=ccfg)

    rng = np.random.default_rng(seed)

    def serve() -> np.ndarray:
        hist = rng.uniform(
            0, 50, (1, spec.seq_len, ds.n_nodes, ds.n_feats)
        ).astype(np.float32)
        return np.asarray(engine.predict(hist))

    try:
        predictions = 1
        serve()  # generation 0 answers before any retrain
        for ts in range(warm, 56):  # live rows land mid-loop
            ring.ingest(ts, normalized[ts])
        clean = daemon.retrain("drift")
        predictions += 1
        serve()  # the promoted generation answers
        for ts in range(56, 64):
            ring.ingest(ts, normalized[ts])
        second = daemon.retrain("cadence")
        predictions += 1
        serve()  # rejection left serving untouched
        return {
            "schema_version": 1,
            "promotions": gate.promotions,
            "rejections": gate.rejections,
            "nonfinite": int(clean.checks.get("nonfinite", -1))
            if clean is not None else -1,
            "rejection_reason": None if second is None else second.reason,
            "generation": engine.generation,
            "rows_ingested": int(ring.rows),
            "ring_len": len(ring),
            "predictions": predictions,
            "daemon_down": daemon.down,
        }
    finally:
        engine.close()
