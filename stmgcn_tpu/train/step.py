"""Jitted train/eval steps and the optimizer.

The reference's per-batch body — forward, loss, ``zero_grad``/``backward``/
``step`` (``Model_Trainer.py:32-44``) — becomes two jitted functions over
explicit state. Notes:

- **Optimizer parity**: torch ``optim.Adam(lr, weight_decay=wd)``
  (``Main.py:13,76``) applies *L2 regularization* (decay added to the
  gradient before the Adam moments), not AdamW. The optax equivalent is
  ``add_decayed_weights`` chained *before* ``scale_by_adam``; hyperparams
  match torch defaults (b1=0.9, b2=0.999, eps=1e-8).
- **Loss parity**: MSE / MAE (L1) / Huber with mean reduction
  (``Main.py:68-75``); Huber uses delta=1 like ``nn.SmoothL1Loss``.
- **Masking**: batches padded to static shape carry ``n_real``; the loss
  weights padding rows to zero so jit sees one shape while results match
  ragged batches exactly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "FleetSuperstepFns",
    "PRECISIONS",
    "PRECISION_ROLES",
    "SeriesSuperstepFns",
    "StepFns",
    "SuperstepFns",
    "gather_window_batch",
    "health_group_names",
    "make_checked_raw_train_step",
    "make_fleet_superstep_fns",
    "make_optimizer",
    "make_series_superstep_fns",
    "make_step_fns",
    "make_superstep_fns",
]

LOSSES = ("mse", "mae", "huber")

#: training compute dtypes the step factories can build programs for.
#: "fp32" is the default and traces to EXACTLY the pre-mixed-precision
#: program (byte-identical jaxprs — the pinned primitive budgets enforce
#: this); "bf16" casts params/activations to bfloat16 at program entry
#: while the optimizer state, loss, reductions and scan carries stay f32
#: (the f32 accumulation islands annotated throughout ops/ and models/).
PRECISIONS = ("fp32", "bf16")

#: Precision-role annotations for every registered contract program:
#: ``program -> (input argument roles, output roles)`` in positional
#: order, declared HERE (next to the functions whose signatures they
#: mirror) because the dtype-flow pass cannot infer them — a flattened
#: jaxpr does not say which invars are master params vs data. The
#: contract tracer (stmgcn_tpu/analysis/jaxpr_check.py) expands them to
#: per-leaf labels: ``param``/``opt_state`` expand to their pytree leaf
#: counts, a trailing-``*`` role absorbs the remaining leaves (checkify
#: error payloads, health stats), everything else is one leaf. The
#: labels seed dtype provenance chains (``input:param[3]``) and drive
#: the master-param / loss boundary checks of the precision pass.
PRECISION_ROLES = {
    "serve_bucket": (
        ("param", "supports", "history"),
        ("prediction*",),
    ),
    "train_step": (
        ("param", "opt_state", "supports", "window", "target", "mask"),
        ("param", "opt_state", "loss"),
    ),
    "eval_step": (
        ("param", "supports", "window", "target", "mask"),
        ("loss", "prediction*"),
    ),
    "train_superstep": (
        ("param", "opt_state", "supports", "window", "target", "index",
         "mask"),
        ("param", "opt_state", "loss"),
    ),
    "train_series_superstep": (
        ("param", "opt_state", "supports", "series", "index", "index",
         "index", "mask"),
        ("param", "opt_state", "loss"),
    ),
    "train_series_superstep_health": (
        ("param", "opt_state", "supports", "series", "index", "index",
         "index", "mask"),
        ("param", "opt_state", "loss", "stats*"),
    ),
    "train_fleet_superstep": (
        ("param", "opt_state", "supports", "series", "index", "index",
         "index", "mask", "index", "index"),
        ("param", "opt_state", "loss"),
    ),
    "serve_fleet_bucket": (
        ("param", "supports", "index", "index", "history"),
        ("prediction*",),
    ),
    "train_step_checked": (
        ("param", "opt_state", "supports", "window", "target", "mask"),
        ("error*", "param", "opt_state", "loss"),
    ),
    # bf16 twins: same signatures as their fp32 counterparts — the
    # master params / optimizer state / loss boundary stays f32 (the
    # whole point of the master/compute split), only the in-program
    # compute dtype differs, which the dtype-flow pass reads off the
    # jaxpr itself.
    "train_step_bf16": (
        ("param", "opt_state", "supports", "window", "target", "mask"),
        ("param", "opt_state", "loss"),
    ),
    "train_superstep_bf16": (
        ("param", "opt_state", "supports", "window", "target", "index",
         "mask"),
        ("param", "opt_state", "loss"),
    ),
    "train_series_superstep_bf16": (
        ("param", "opt_state", "supports", "series", "index", "index",
         "index", "mask"),
        ("param", "opt_state", "loss"),
    ),
    "train_fleet_superstep_bf16": (
        ("param", "opt_state", "supports", "series", "index", "index",
         "index", "mask", "index", "index"),
        ("param", "opt_state", "loss"),
    ),
}


def make_optimizer(
    lr: float,
    weight_decay: float = 0.0,
    schedule: str = "none",
    warmup_steps: int = 0,
    decay_steps: int = 0,
    min_lr_fraction: float = 0.0,
    grad_clip_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    """Adam with L2 regularization, matching torch ``optim.Adam`` semantics.

    ``grad_clip_norm`` prepends global-norm gradient clipping (the
    ``torch.nn.utils.clip_grad_norm_`` idiom LSTM training commonly adds;
    the reference has none) — clipping the raw gradient BEFORE the L2
    term and Adam moments, matching where torch users call it.

    ``schedule`` extends the reference's fixed learning rate (``Main.py:13``
    has no scheduler):

    - ``"none"`` (default): constant ``lr`` — reference parity.
    - ``"cosine"``: linear warmup over ``warmup_steps`` optimizer steps,
      then cosine decay over ``decay_steps`` down to
      ``lr * min_lr_fraction``. ``decay_steps`` must be set (the trainer
      derives it from epochs x steps-per-epoch).

    The L2 term stays *inside* the scheduled scaling (decay added to the
    gradient before the Adam moments, then the whole update is scaled by
    the current LR) — the same coupling torch's Adam(weight_decay=..)
    has under external LR schedulers.
    """
    if not 0.0 <= min_lr_fraction <= 1.0:
        # a negative floor would cross zero late in training and ascend
        # the loss — silently corrupting the converged params
        raise ValueError(
            f"min_lr_fraction must be in [0, 1], got {min_lr_fraction}"
        )
    parts = []
    if grad_clip_norm is not None:
        if grad_clip_norm <= 0:
            raise ValueError(f"grad_clip_norm must be > 0, got {grad_clip_norm}")
        parts.append(optax.clip_by_global_norm(grad_clip_norm))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_adam())
    if schedule == "none":
        if warmup_steps or min_lr_fraction:
            # silently ignoring these would run constant-LR training while
            # the user believes warmup/decay is active
            raise ValueError(
                "warmup_steps/min_lr_fraction only apply to "
                "schedule='cosine' (got schedule='none' with "
                f"warmup_steps={warmup_steps}, "
                f"min_lr_fraction={min_lr_fraction})"
            )
        parts.append(optax.scale(-lr))
    elif schedule == "cosine":
        if decay_steps <= 0:
            raise ValueError("schedule='cosine' needs decay_steps > 0")
        if warmup_steps >= decay_steps:
            raise ValueError(
                f"warmup_steps ({warmup_steps}) must be shorter than the "
                f"run (decay_steps={decay_steps}) — the schedule would "
                "never leave warmup, let alone decay"
            )
        sched = optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else lr,
            peak_value=lr,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
            end_value=lr * min_lr_fraction,
        )
        parts.append(optax.scale_by_schedule(lambda step: -sched(step)))
    else:
        raise ValueError(f"schedule must be none|cosine, got {schedule!r}")
    return optax.chain(*parts)


def _elementwise_loss(kind: str, pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    if kind == "mse":
        return jnp.square(pred - target)
    if kind == "mae":
        return jnp.abs(pred - target)
    if kind == "huber":
        return optax.losses.huber_loss(pred, target, delta=1.0)
    raise ValueError(f"loss must be one of {LOSSES}, got {kind!r}")


@dataclasses.dataclass(frozen=True)
class StepFns:
    """Jitted callables closed over the model and optimizer."""

    init: Callable  # (rng, supports, x) -> (params, opt_state)
    train_step: Callable  # (params, opt_state, supports, x, y, mask) -> (params, opt_state, loss)
    eval_step: Callable  # (params, supports, x, y, mask) -> (loss, pred)


@dataclasses.dataclass(frozen=True)
class SuperstepFns:
    """A jitted S-step training superstep (see :func:`make_superstep_fns`)."""

    #: (params, opt_state, supports, x_all, y_all, idx_block, mask_block)
    #: -> (params, opt_state, losses); idx_block is (S, B) int32 into the
    #: leading axis of the resident x_all/y_all, mask_block stacks the
    #: per-step loss masks ((S, B) or (S, B, N)), losses comes back (S,)
    train_superstep: Callable


@dataclasses.dataclass(frozen=True)
class SeriesSuperstepFns:
    """A jitted S-step superstep over the window-free resident series
    (see :func:`make_series_superstep_fns`)."""

    #: (params, opt_state, supports, series, targets, offsets, idx_block,
    #: mask_block) -> (params, opt_state, losses); series is the resident
    #: (T, N, C) normalized series, targets the mode's int32 target
    #: timesteps, offsets the window's int32 gather offsets, idx_block
    #: (S, B) int32 into targets — each scan step reconstructs its
    #: microbatch with :func:`gather_window_batch` before the shared
    #: train-step body
    train_superstep: Callable


@dataclasses.dataclass(frozen=True)
class FleetSuperstepFns:
    """A jitted S-step superstep over one fleet shape class
    (see :func:`make_fleet_superstep_fns`)."""

    #: (params, opt_state, supports_stack, series, targets, offsets,
    #: idx_block, mask_block, slot_block, n_real_block) -> (params,
    #: opt_state, losses); supports_stack is the class's stacked
    #: (n_members, M, K, N_c, N_c) padded supports, series the class's
    #: time-concatenated (sum_T, N_c, C) resident series, targets the
    #: mode's class-absolute int32 target timesteps, slot_block (S,) int32
    #: member slots (one support gather per step), n_real_block (S,) int32
    #: real node counts feeding the traced gate pooling
    train_superstep: Callable


def gather_window_batch(series, targets, offsets, idx, horizon: int = 1):
    """Reconstruct a microbatch ``(x, y)`` from the resident raw series.

    ``x[b] = series[targets[idx[b]] + offsets]`` and
    ``y[b] = series[targets[idx[b]] (+ arange(horizon))]`` — the same
    gather ``sliding_windows`` runs on the host, expressed as ``jnp.take``
    so it executes on device from a resident ``(T, N, C)`` series. Pure
    index copies, no arithmetic, so the result is bit-identical to the
    materialized windows. This is the ONE definition site both the
    per-step placement and the fused superstep body use; ``horizon`` is
    static (it shapes ``y``).
    """
    tgt = jnp.take(targets, idx)
    x = jnp.take(series, tgt[:, None] + offsets[None, :], axis=0)
    if horizon == 1:
        y = jnp.take(series, tgt, axis=0)
    else:
        y = jnp.take(
            series, tgt[:, None] + jnp.arange(horizon)[None, :], axis=0
        )
    return x, y


#: checkify error-set names accepted by ``make_step_fns(checks=...)``
CHECK_SETS = ("nan", "index", "float", "all")


def _error_set(checks: str):
    """Resolve a :data:`CHECK_SETS` name to its checkify error set."""
    from jax.experimental import checkify

    return {
        "nan": checkify.nan_checks,
        "index": checkify.index_checks,
        "float": checkify.float_checks,  # nan + div (no index checks)
        "all": checkify.all_checks,
    }[checks]


def health_group_names(tree) -> tuple:
    """Static layer-group names of a params/grads pytree: the sorted
    top-level module names under flax's ``"params"`` collection (or the
    top-level keys of a bare dict). This is the host-side key for the
    ``(G,)`` per-group norm vector the health scan ys carry."""
    try:
        inner = tree["params"] if "params" in tree else tree
    except TypeError:
        return ()
    try:
        return tuple(sorted(inner.keys()))
    except AttributeError:
        return ()


def _health_stats(params, grads, updates, loss_val):
    """On-device numeric health of one optimizer step.

    Pure readout of values the step already computed (grads/updates/
    pre-update params) — no extra dispatches; the superstep carries
    these as extra scan ys downloaded with the losses. ``update_ratio``
    is ‖Δparam‖/‖param‖, the classic learning-dynamics gauge (~1e-3
    healthy; ~1 means the optimizer is overwriting the model).
    """
    # Norm math runs in f32 regardless of the leaves' dtype: a bf16
    # sum-of-squares overflows at ~2e19 (max bf16 ~3.4e38, but the
    # squares sum across millions of elements) and quantizes the band
    # checks the promotion gate reads. Same-dtype astype is a no-op
    # jaxpr-wise, so the fp32 health program is byte-identical.
    f32 = lambda t: jax.tree.map(lambda leaf: leaf.astype(jnp.float32), t)
    names = health_group_names(grads)
    grads32 = f32(grads)
    inner = grads32["params"] if names and "params" in grads32 else grads32
    group = (
        jnp.stack([optax.global_norm(inner[k]) for k in names])
        if names else jnp.zeros((0,), jnp.float32)
    )
    # nonfinite counting stays on the RAW grads: casting first could
    # overflow a finite bf16 value's square, not the value itself
    nonfinite = sum(
        jnp.sum(~jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads)
    )
    return {
        "grad_norm": optax.global_norm(grads32),
        "update_ratio": optax.global_norm(f32(updates))
        / jnp.maximum(optax.global_norm(f32(params)), 1e-12),
        "nonfinite_grads": jnp.asarray(nonfinite, jnp.int32),
        "nonfinite_loss": (~jnp.isfinite(loss_val)).astype(jnp.int32),
        "group_norms": group,
    }


def _raw_step_bodies(model, optimizer, loss: str, precision: str = "fp32"):
    """The unjitted init/train/eval bodies shared by :func:`make_step_fns`
    and :func:`make_superstep_fns`.

    One definition site is what makes the superstep's bit-exactness claim
    structural rather than coincidental: the scan body runs the *same*
    Python function the per-step path jits, so the two paths can only
    diverge if XLA itself breaks determinism.

    ``train_step_full`` is the same body returning the grads/updates it
    already computed — the health variants read their statistics off
    those, and ``train_step`` dropping them adds no primitives
    (``jax.make_jaxpr`` performs no DCE, so the plain program's jaxpr is
    unchanged — the ``train_series_superstep`` budget pins this).

    ``precision="bf16"`` builds the mixed-precision twin of the same
    body: the ``params`` argument stays the f32 *master* copy the
    optimizer owns, and the model is cloned to ``dtype=bfloat16`` so
    every matmul/conv casts its operands (master-dtype weights AND
    activations) to bf16 at the *use site* and contracts with
    ``preferred_element_type=f32`` — the f32 accumulation islands
    annotated in ops/ and models/. Use-site casting (rather than one
    whole-tree cast at entry) is what keeps the BACKWARD pass clean
    too: each cast's VJP converts cotangents to f32 right where they
    are produced, so bias-grad reductions, fan-out ``add_any``
    accumulations, and the LSTM backward scan's weight-grad carries are
    all f32 — the precision lint certifies this per program. Grads,
    Adam moments, updates and the loss are therefore f32 end to end;
    ``precision`` selects at *trace* time (a Python branch), so the
    fp32 program is byte-identical to the pre-mixed-precision one.

    The trailing ``sr_rng`` of the train bodies is an optional PRNG key
    enabling stochastically-rounded master->shadow casts: when set, the
    whole param tree is cast to bf16 at program entry via
    ``models/params.py:compute_cast`` (SR noise must be drawn once per
    leaf per step, which has no use-site analogue). The tradeoff is
    explicit: under SR the LSTM's recurrent weight-grad accumulation
    rides the backward scan carry in bf16 — SR programs are a training
    knob, not registered contract programs. ``None`` (and fp32) adds
    nothing to the jaxpr.
    """
    if loss not in LOSSES:
        raise ValueError(f"loss must be one of {LOSSES}, got {loss!r}")
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {PRECISIONS}, got {precision!r}"
        )
    bf16 = precision == "bf16"
    if bf16:
        from stmgcn_tpu.models.params import compute_cast

        model = model.clone(dtype=jnp.bfloat16)

    def loss_fn(params, supports, x, y, mask, n_real=None, sr_rng=None):
        if bf16 and sr_rng is not None:
            params = compute_cast(params, jnp.bfloat16, sr_rng)
        pred = model.apply(params, supports, x, n_real)
        err = _elementwise_loss(loss, pred.astype(jnp.float32), y.astype(jnp.float32))
        # y is (B, N, C) single-step or (B, H, N, C) seq2seq
        if mask.ndim == 1:  # (B,): per-sample weights
            w = mask.reshape(mask.shape + (1,) * (y.ndim - 1))
            denom = mask.sum() * math.prod(y.shape[1:])
        else:  # (B, N): sample x node weights (padded node axis on a mesh)
            w = mask[:, None, :, None] if y.ndim == 4 else mask[:, :, None]
            per_node_elems = y.shape[-1] * (y.shape[1] if y.ndim == 4 else 1)
            denom = mask.sum() * per_node_elems
        return (err * w).sum() / denom, pred

    def init(rng, supports, x):
        params = model.init(rng, supports, x)
        return params, optimizer.init(params)

    def train_step_full(
        params, opt_state, supports, x, y, mask, n_real=None, sr_rng=None
    ):
        (loss_val, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, supports, x, y, mask, n_real, sr_rng
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, opt_state, loss_val, grads, updates, params

    def train_step(
        params, opt_state, supports, x, y, mask, n_real=None, sr_rng=None
    ):
        params, opt_state, loss_val, _, _, _ = train_step_full(
            params, opt_state, supports, x, y, mask, n_real, sr_rng
        )
        return params, opt_state, loss_val

    def eval_step(params, supports, x, y, mask, n_real=None):
        loss_val, pred = loss_fn(params, supports, x, y, mask, n_real)
        return loss_val, pred

    return init, train_step, eval_step, train_step_full


def make_step_fns(
    model,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    checks: str | None = None,
    health: bool = False,
    precision: str = "fp32",
    sr_seed: Optional[int] = None,
) -> StepFns:
    """Build jitted init/train/eval steps for a flax model.

    ``mask`` is a ``(B,)`` 0/1 vector (1 = real sample) or, when the node
    axis carries mesh-divisibility padding, a ``(B, N)`` 0/1 matrix
    (sample x real-node); the loss is the mean over real elements only, so
    padded tail batches and padded nodes yield exactly the loss of the
    unpadded equivalent.

    ``checks`` enables functional sanitizing via ``jax.experimental
    .checkify`` — the in-jit analogue of the sanitizers the reference
    has no counterpart for (SURVEY.md §5.b): ``"nan"`` traps NaN
    production, ``"index"`` out-of-bounds gathers/scatters, ``"float"``
    is nan + division-by-zero (NOT index — jax's ``float_checks`` does
    not include it), ``"all"`` is everything plus user ``checkify.check``
    calls.
    The checked step raises ``JaxRuntimeError`` at the failing step with
    the op's location. Debug tool: error flags are fetched per step, so
    it costs a device sync per call — unlike ``jax_debug_nans`` it works
    under jit *with* donation and on TPU without recompiling per op.

    ``health=True`` builds the numeric-health variant: ``train_step``
    returns ``(params, opt_state, loss, stats)`` where ``stats`` is the
    :func:`_health_stats` dict read off the grads/updates the step
    already computed. The params/opt-state/loss math is the *same*
    shared body, so results are bit-identical to the plain step.

    ``precision="bf16"`` builds the mixed-precision twin (see
    :func:`_raw_step_bodies`): f32 master params in/out, bf16 compute
    shadow per step. ``sr_seed`` (bf16 only) stochastically rounds the
    master->shadow cast; on this per-step path the noise stream is a
    fixed function of the seed — every call reuses the same draws
    (unbiased per cast, but not independent across steps; the superstep
    factories fold the step index in, use those for real SR training).
    """
    if checks is not None and checks not in CHECK_SETS:
        raise ValueError(f"checks must be one of {CHECK_SETS}, got {checks!r}")

    init, train_step, eval_step, train_step_full = _raw_step_bodies(
        model, optimizer, loss, precision
    )
    sr_rng = (
        jax.random.PRNGKey(sr_seed)
        if precision == "bf16" and sr_seed is not None
        else None
    )
    if sr_rng is not None and not health:
        _plain_step = train_step

        def train_step(params, opt_state, supports, x, y, mask, n_real=None):
            return _plain_step(
                params, opt_state, supports, x, y, mask, n_real, sr_rng
            )
    if health:
        def train_step(params, opt_state, supports, x, y, mask, n_real=None):
            params, opt_state, loss_val, grads, updates, prev = train_step_full(
                params, opt_state, supports, x, y, mask, n_real, sr_rng
            )
            return params, opt_state, loss_val, _health_stats(
                prev, grads, updates, loss_val
            )

    # init is jitted too: eager flax init dispatches hundreds of tiny ops,
    # which is pathologically slow on remote-tunneled TPU backends.
    # donate_argnums on every train-step jit is a lint contract
    # (missing-donate, stmgcn_tpu/analysis): params/opt-state buffers are
    # reused in place instead of copied each step.
    if checks is None:
        return StepFns(
            init=jax.jit(init),
            train_step=jax.jit(train_step, donate_argnums=(0, 1)),
            eval_step=jax.jit(eval_step),
        )

    from jax.experimental import checkify

    errset = _error_set(checks)
    ck_train = jax.jit(checkify.checkify(train_step, errors=errset), donate_argnums=(0, 1))
    ck_eval = jax.jit(checkify.checkify(eval_step, errors=errset))

    def checked_train(params, opt_state, supports, x, y, mask, n_real=None):
        err, out = ck_train(params, opt_state, supports, x, y, mask, n_real)
        checkify.check_error(err)  # device sync; raises at the failing step
        return out

    def checked_eval(params, supports, x, y, mask, n_real=None):
        err, out = ck_eval(params, supports, x, y, mask, n_real)
        checkify.check_error(err)
        return out

    return StepFns(init=jax.jit(init), train_step=checked_train, eval_step=checked_eval)


def make_checked_raw_train_step(
    model,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    checks: str = "nan",
):
    """The *unjitted* checkify-wrapped train step, for abstract tracing.

    This is exactly the program :func:`make_step_fns` jits when ``checks``
    is set — ``checkify.checkify(train_step, errors=...)`` over the shared
    raw body — exposed so the static-analysis contract pass can
    ``jax.make_jaxpr`` it and budget its primitive count like the unchecked
    programs (stmgcn_tpu/analysis/jaxpr_check.py). Returns a callable
    ``(params, opt_state, supports, x, y, mask) -> (err, (params,
    opt_state, loss))``.
    """
    if checks not in CHECK_SETS:
        raise ValueError(f"checks must be one of {CHECK_SETS}, got {checks!r}")
    from jax.experimental import checkify

    _, train_step, _, _ = _raw_step_bodies(model, optimizer, loss)
    return checkify.checkify(train_step, errors=_error_set(checks))


def make_superstep_fns(
    model,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    checks: str | None = None,
    health: bool = False,
    precision: str = "fp32",
    sr_seed: Optional[int] = None,
) -> SuperstepFns:
    """Fuse S train steps into one jitted ``lax.scan`` over microbatches.

    The per-step epoch loop pays host dispatch latency once per batch; on
    remote-tunneled TPU backends that dominates small-step wall time. The
    superstep instead runs S optimizer steps inside a single device
    program: ``(params, opt_state)`` ride the scan carry (donated, so the
    buffers update in place), each step gathers its microbatch **on
    device** from the mode's resident arrays via a row of the ``(S, B)``
    ``idx_block``, and the S per-step losses come back as one stacked
    ``(S,)`` array — one dispatch and one host readback per S steps.

    The scan body is the *same* raw train step :func:`make_step_fns` jits
    (shared via ``_raw_step_bodies``), and the losses are returned in step
    order as scan ys rather than accumulated in the carry, so a
    superstep's results — params, opt state, and every per-step loss — are
    bit-identical to S iterations of the per-step loop over the same
    index/mask rows.

    S is not fixed here: it is the leading axis of ``idx_block`` /
    ``mask_block``, so jit specializes per block shape (the trainer packs
    fixed-S blocks; the remainder batches run per-step).

    ``checks`` wraps the whole superstep in ``jax.experimental.checkify``
    (same sets as :func:`make_step_fns`); the error surfaces after the
    S-step program, not at the individual failing step.

    ``health=True`` builds the health-instrumented program variant:
    each scan step additionally carries its :func:`_health_stats` dict
    as extra scan ys, so ``train_superstep`` returns ``(params,
    opt_state, losses, stats)`` with ``(S,)``/``(S, G)`` stat arrays —
    downloaded with the losses in the same host readback, no extra
    dispatches. The params/loss math is the same shared body, so the
    health program is bit-identical to the plain one; health *off*
    builds exactly today's program (the jaxpr budget pins this).

    ``precision="bf16"`` scans the mixed-precision body (f32 master
    params ride the carry, bf16 shadows regenerate per step — see
    :func:`_raw_step_bodies`); with ``sr_seed`` set, each scan step
    folds its step index into the seed so the stochastic master->shadow
    rounding draws fresh noise per step, deterministically per
    ``(sr_seed, step index within the block)``.
    """
    if checks is not None and checks not in CHECK_SETS:
        raise ValueError(f"checks must be one of {CHECK_SETS}, got {checks!r}")

    _, train_step, _, train_step_full = _raw_step_bodies(
        model, optimizer, loss, precision
    )
    sr_on = precision == "bf16" and sr_seed is not None

    def train_superstep(params, opt_state, supports, x_all, y_all, idx_block, mask_block):
        def body(carry, step_inputs):
            params, opt_state = carry
            if sr_on:
                idx, mask, step_i = step_inputs
                sr_rng = jax.random.fold_in(jax.random.PRNGKey(sr_seed), step_i)
            else:
                idx, mask = step_inputs
                sr_rng = None
            x = jnp.take(x_all, idx, axis=0)
            y = jnp.take(y_all, idx, axis=0)
            if health:
                params, opt_state, loss_val, grads, updates, prev = (
                    train_step_full(
                        params, opt_state, supports, x, y, mask, None, sr_rng
                    )
                )
                stats = _health_stats(prev, grads, updates, loss_val)
                return (params, opt_state), (loss_val, stats)
            params, opt_state, loss_val = train_step(
                params, opt_state, supports, x, y, mask, None, sr_rng
            )
            return (params, opt_state), loss_val

        xs = (idx_block, mask_block)
        if sr_on:
            xs = xs + (jnp.arange(idx_block.shape[0]),)
        (params, opt_state), ys = jax.lax.scan(body, (params, opt_state), xs)
        if health:
            losses, stats = ys
            return params, opt_state, losses, stats
        return params, opt_state, ys

    if checks is None:
        return SuperstepFns(
            train_superstep=jax.jit(train_superstep, donate_argnums=(0, 1))
        )

    from jax.experimental import checkify

    ck = jax.jit(
        checkify.checkify(train_superstep, errors=_error_set(checks)),
        donate_argnums=(0, 1),
    )

    def checked_superstep(params, opt_state, supports, x_all, y_all, idx_block, mask_block):
        err, out = ck(params, opt_state, supports, x_all, y_all, idx_block, mask_block)
        checkify.check_error(err)  # device sync; raises after the failing block
        return out

    return SuperstepFns(train_superstep=checked_superstep)


def make_series_superstep_fns(
    model,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    horizon: int = 1,
    checks: str | None = None,
    health: bool = False,
    precision: str = "fp32",
    sr_seed: Optional[int] = None,
    placement=None,
) -> SeriesSuperstepFns:
    """The superstep of :func:`make_superstep_fns` over window-free data.

    Instead of gathering microbatches from materialized ``(S_mode, seq,
    N, C)`` window arrays, each scan step reconstructs its batch from the
    resident raw ``(T, N, C)`` series via :func:`gather_window_batch`
    (index block -> target timesteps -> target + offset-table gather) —
    the resident footprint drops from ~``seq_len`` copies of every
    timestep to one. The gather is a pure copy, the scan body is the same
    shared raw train step, and the losses come back as ordered scan ys,
    so results stay bit-identical to the materialized superstep and to
    the per-step loop. ``horizon`` is static (it shapes ``y``); ``checks``
    wraps the whole program in checkify as in :func:`make_superstep_fns`;
    ``health=True`` adds the per-step :func:`_health_stats` scan ys
    (same semantics and bit-identity guarantees as there).
    ``precision``/``sr_seed`` behave as in :func:`make_superstep_fns`.

    ``placement`` (a :class:`~stmgcn_tpu.parallel.MeshPlacement`, or
    ``None`` off-mesh) is the composed multi-chip fast path: the gathered
    per-step ``x``/``y`` get an in-scan ``with_sharding_constraint`` to
    the mesh's batch-sharded specs, so GSPMD keeps every window gather
    device-local per dp shard and places the gradient ``psum`` *inside*
    the S-step scan body — one while-loop program whose per-iteration
    wire is exactly the per-step program's. ``placement=None`` traces the
    byte-identical single-device program (the constraint is a trace-time
    Python branch, so jaxpr/primitive budgets are unchanged).
    """
    if checks is not None and checks not in CHECK_SETS:
        raise ValueError(f"checks must be one of {CHECK_SETS}, got {checks!r}")

    _, train_step, _, train_step_full = _raw_step_bodies(
        model, optimizer, loss, precision
    )
    sr_on = precision == "bf16" and sr_seed is not None

    def train_superstep(
        params, opt_state, supports, series, targets, offsets, idx_block, mask_block
    ):
        def body(carry, step_inputs):
            params, opt_state = carry
            if sr_on:
                idx, mask, step_i = step_inputs
                sr_rng = jax.random.fold_in(jax.random.PRNGKey(sr_seed), step_i)
            else:
                idx, mask = step_inputs
                sr_rng = None
            x, y = gather_window_batch(series, targets, offsets, idx, horizon)
            if placement is not None:
                x = jax.lax.with_sharding_constraint(
                    x, placement.sharding("x", x.ndim)
                )
                y = jax.lax.with_sharding_constraint(
                    y, placement.sharding("y", y.ndim)
                )
            if health:
                params, opt_state, loss_val, grads, updates, prev = (
                    train_step_full(
                        params, opt_state, supports, x, y, mask, None, sr_rng
                    )
                )
                stats = _health_stats(prev, grads, updates, loss_val)
                return (params, opt_state), (loss_val, stats)
            params, opt_state, loss_val = train_step(
                params, opt_state, supports, x, y, mask, None, sr_rng
            )
            return (params, opt_state), loss_val

        xs = (idx_block, mask_block)
        if sr_on:
            xs = xs + (jnp.arange(idx_block.shape[0]),)
        (params, opt_state), ys = jax.lax.scan(body, (params, opt_state), xs)
        if health:
            losses, stats = ys
            return params, opt_state, losses, stats
        return params, opt_state, ys

    if checks is None:
        return SeriesSuperstepFns(
            train_superstep=jax.jit(train_superstep, donate_argnums=(0, 1))
        )

    from jax.experimental import checkify

    ck = jax.jit(
        checkify.checkify(train_superstep, errors=_error_set(checks)),
        donate_argnums=(0, 1),
    )

    def checked_superstep(
        params, opt_state, supports, series, targets, offsets, idx_block, mask_block
    ):
        err, out = ck(
            params, opt_state, supports, series, targets, offsets, idx_block,
            mask_block,
        )
        checkify.check_error(err)
        return out

    return SeriesSuperstepFns(train_superstep=checked_superstep)


def make_fleet_superstep_fns(
    model,
    optimizer: optax.GradientTransformation,
    loss: str = "mse",
    horizon: int = 1,
    checks: str | None = None,
    health: bool = False,
    precision: str = "fp32",
    sr_seed: Optional[int] = None,
    placement=None,
) -> FleetSuperstepFns:
    """The window-free superstep of :func:`make_series_superstep_fns`
    generalized to one fleet *shape class* of cities.

    One compiled program serves every member city of the class: the
    class's padded per-city supports ride stacked on a leading member
    axis and each scan step selects its city's stack with a ``jnp.take``
    over ``slot_block``; the per-city resident series are concatenated
    along time (targets pre-shifted to class-absolute timesteps, so the
    window gather never crosses a city boundary); and the traced
    ``n_real_block`` feeds the gate pooling so cities with fewer real
    nodes than the class rung pool over real rows only. The support
    gather and window gather are pure index copies and the scan body is
    the same shared raw train step, so a class block's results are
    bit-identical to per-step iteration at the class shapes — which is
    exactly what the materialized per-city oracle computes
    (``tests/test_fleet.py``). Padded nodes carry zero supports, a
    traced-masked gate pool, and zero ``(B, N_c)`` loss-mask columns.

    ``health=True`` adds the per-step :func:`_health_stats` scan ys
    plus fleet-only per-city loss attribution: the scan body already
    knows each step's member slot, so ``stats["city_loss"]`` is the
    ``(S, n_members)`` one-hot scatter of each step's loss into its
    slot — summing it over both axes reproduces the summed fleet loss
    exactly, and per-slot columns attribute it city by city.

    ``precision``/``sr_seed`` behave as in :func:`make_superstep_fns`;
    ``placement`` is the in-scan sharding constraint of
    :func:`make_series_superstep_fns` (dp-sharded gathered batches, grad
    psum inside the scan body; ``None`` traces the byte-identical
    single-device program).
    """
    if checks is not None and checks not in CHECK_SETS:
        raise ValueError(f"checks must be one of {CHECK_SETS}, got {checks!r}")

    _, train_step, _, train_step_full = _raw_step_bodies(
        model, optimizer, loss, precision
    )
    sr_on = precision == "bf16" and sr_seed is not None

    def train_superstep(
        params, opt_state, supports_stack, series, targets, offsets,
        idx_block, mask_block, slot_block, n_real_block,
    ):
        def body(carry, step_inputs):
            params, opt_state = carry
            if sr_on:
                idx, mask, slot, n_real, step_i = step_inputs
                sr_rng = jax.random.fold_in(jax.random.PRNGKey(sr_seed), step_i)
            else:
                idx, mask, slot, n_real = step_inputs
                sr_rng = None
            # leaf-wise slot select: for the dense (n_members, M, K, N, N)
            # stack this is exactly the old jnp.take; a pytree support
            # representation (e.g. a tiled-supports class stack) rides the
            # same scan body — the supports are a per-slot *representation*,
            # not a scheduler concern
            supports = jax.tree.map(
                lambda a: jnp.take(a, slot, axis=0), supports_stack
            )
            x, y = gather_window_batch(series, targets, offsets, idx, horizon)
            if placement is not None:
                x = jax.lax.with_sharding_constraint(
                    x, placement.sharding("x", x.ndim)
                )
                y = jax.lax.with_sharding_constraint(
                    y, placement.sharding("y", y.ndim)
                )
            if health:
                params, opt_state, loss_val, grads, updates, prev = (
                    train_step_full(
                        params, opt_state, supports, x, y, mask, n_real, sr_rng
                    )
                )
                stats = _health_stats(prev, grads, updates, loss_val)
                n_members = jax.tree.leaves(supports_stack)[0].shape[0]
                stats["city_loss"] = (
                    jax.nn.one_hot(slot, n_members, dtype=jnp.float32)
                    * loss_val
                )
                return (params, opt_state), (loss_val, stats)
            params, opt_state, loss_val = train_step(
                params, opt_state, supports, x, y, mask, n_real, sr_rng
            )
            return (params, opt_state), loss_val

        xs = (idx_block, mask_block, slot_block, n_real_block)
        if sr_on:
            xs = xs + (jnp.arange(idx_block.shape[0]),)
        (params, opt_state), ys = jax.lax.scan(body, (params, opt_state), xs)
        if health:
            losses, stats = ys
            return params, opt_state, losses, stats
        return params, opt_state, ys

    if checks is None:
        return FleetSuperstepFns(
            train_superstep=jax.jit(train_superstep, donate_argnums=(0, 1))
        )

    from jax.experimental import checkify

    ck = jax.jit(
        checkify.checkify(train_superstep, errors=_error_set(checks)),
        donate_argnums=(0, 1),
    )

    def checked_superstep(
        params, opt_state, supports_stack, series, targets, offsets,
        idx_block, mask_block, slot_block, n_real_block,
    ):
        err, out = ck(
            params, opt_state, supports_stack, series, targets, offsets,
            idx_block, mask_block, slot_block, n_real_block,
        )
        checkify.check_error(err)
        return out

    return FleetSuperstepFns(train_superstep=checked_superstep)
