"""Self-sufficient single-file checkpoints with atomic, verified writes.

The reference saves only ``{'epoch', 'state_dict'}`` on validation
improvement (``Model_Trainer.py:18,52-53``): optimizer state is lost (no
true resume) and the normalizer statistics live only on the in-memory
loader object, so its checkpoints cannot even denormalize predictions
(SURVEY.md §5.d). Here one file carries everything a preempted TPU job
needs: model params, optimizer state, and a JSON meta block (step/epoch,
best validation loss, early-stop counter, normalizer statistics, config).

Format v2 (``STMG2\\n``): three blobs — JSON meta, flax-serialized params,
flax-serialized optimizer state — each preceded by a ``<QI`` header
(length, CRC32). Files are written to a temp file and ``os.replace``d so a
preemption mid-write never corrupts the previous checkpoint; the CRCs
catch what atomic rename cannot — disk-level truncation or bit rot of a
file that *did* land. v1 files (``STMG1\\n``, length-prefixed blobs, no
CRC) remain readable.

Every read path verifies structure: a header or blob that comes back
short of its declared length raises :class:`CorruptCheckpointError`
naming the path and the blob, never a garbage pytree or a confusing
msgpack error. :func:`load_latest_verified` turns that into a recovery
chain for ``--resume auto``: latest -> rotated previous latest -> best-k
snapshots (newest first) -> best, quarantining each corrupt candidate as
``<name>.corrupt`` with a logged reason.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import struct
import zlib
from typing import Any, Callable, Optional

from flax import serialization

__all__ = [
    "CorruptCheckpointError",
    "FORMAT_VERSION",
    "load_checkpoint",
    "load_latest_verified",
    "save_checkpoint",
    "serialize_checkpoint",
    "verify_checkpoint",
    "write_checkpoint_bytes",
]

_MAGIC_V1 = b"STMG1\n"
_MAGIC_V2 = b"STMG2\n"
#: current on-disk format: v2 = per-blob CRC32 (v1 files stay readable)
FORMAT_VERSION = 2
_BLOB_NAMES = ("meta", "params", "opt_state")
#: v2 per-blob header: little-endian (length: u64, crc32: u32)
_HEADER_V2 = struct.Struct("<QI")
_LEN_V1 = struct.Struct("<Q")


class CorruptCheckpointError(ValueError):
    """A checkpoint file failed structural or CRC verification.

    Raised instead of handing back garbage blobs: short reads (truncated
    file), CRC mismatches (bit rot), unknown magic on a file that claims
    to be a checkpoint. The message names the path and the failing blob.
    """


def serialize_checkpoint(params: Any, opt_state: Any, meta: dict) -> bytes:
    """Snapshot state into one self-contained byte string (format v2).

    This is the device→host boundary: ``to_bytes`` materializes every leaf
    to host numpy, so the returned blob is immune to later in-place updates
    / donation of the live training state — safe to hand to a background
    writer thread.
    """
    blobs = [
        json.dumps(meta).encode("utf-8"),
        serialization.to_bytes(params),
        serialization.to_bytes(opt_state),
    ]
    out = [_MAGIC_V2]
    for blob in blobs:
        out.append(_HEADER_V2.pack(len(blob), zlib.crc32(blob)))
        out.append(blob)
    return b"".join(out)


def write_checkpoint_bytes(path: str, data: bytes, fault_plan=None) -> None:
    """Atomically write a serialized checkpoint (temp file + ``os.replace``
    so a preemption mid-write never corrupts the previous checkpoint).

    ``fault_plan`` threads a :class:`~stmgcn_tpu.resilience.FaultPlan`
    through for the torn-write drill — a crash *between* the tmp write
    and the rename, the one case the atomic dance cannot cover from
    inside the process (the plan leaves a partial ``*.tmp.<pid>`` orphan
    and raises without ever touching ``path``). Empty/absent plan is the
    production no-op.
    """
    tmp = f"{path}.tmp.{os.getpid()}"
    if fault_plan is not None:
        fault_plan.torn_write(path, data, tmp)
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def save_checkpoint(path: str, params: Any, opt_state: Any, meta: dict, *,
                    fault_plan=None) -> None:
    """Atomically write ``params``/``opt_state``/``meta`` to ``path``
    (``fault_plan`` reaches both the byte-mutation and torn-write write
    faults — the continual daemon's candidate writes go through here)."""
    data = serialize_checkpoint(params, opt_state, meta)
    if fault_plan is not None:
        data = fault_plan.mutate_write(path, data)
    write_checkpoint_bytes(path, data, fault_plan)


def _read_exact(f, n: int, path: str, what: str) -> bytes:
    """``f.read(n)`` that refuses to come back short.

    A truncated file yields fewer bytes than the header promised; without
    this check the garbage propagates into flax's msgpack decoder (or
    silently into the params) — the short-read bug this PR's issue names.
    """
    data = f.read(n)
    if len(data) != n:
        raise CorruptCheckpointError(
            f"{path}: short read in {what} — wanted {n} bytes, file had "
            f"{len(data)} (truncated checkpoint?)"
        )
    return data


def _read_blobs(path: str, *, skip_opt_state: bool = False, verify_crc: bool = True):
    """Read (version, [meta_bytes, params_bytes, opt_bytes|None]).

    Structural verification happens here for both formats: every length is
    checked against what the file actually holds, and (v2) every blob's
    CRC32 against its header. ``skip_opt_state`` avoids *decoding* cost
    upstream but still verifies the final blob's extent (and, for v2 with
    ``verify_crc``, its checksum — the inference cold-start path keeps the
    cheap variant by passing ``verify_crc=False``).
    """
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        magic = f.read(len(_MAGIC_V2))
        if magic == _MAGIC_V2:
            version = 2
        elif magic == _MAGIC_V1:
            version = 1
        else:
            raise ValueError(f"{path} is not a stmgcn-tpu checkpoint")
        header = _HEADER_V2 if version == 2 else _LEN_V1
        blobs = []
        for name in _BLOB_NAMES:
            raw = _read_exact(f, header.size, path, f"{name} header")
            if version == 2:
                length, crc = header.unpack(raw)
            else:
                (length,) = header.unpack(raw)
                crc = None
            if f.tell() + length > size:
                raise CorruptCheckpointError(
                    f"{path}: {name} blob declares {length} bytes but only "
                    f"{size - f.tell()} remain (truncated checkpoint?)"
                )
            if name == "opt_state" and skip_opt_state and not (
                version == 2 and verify_crc
            ):
                blobs.append(None)
                f.seek(length, os.SEEK_CUR)
            else:
                blob = _read_exact(f, length, path, f"{name} blob")
                if crc is not None and verify_crc and zlib.crc32(blob) != crc:
                    raise CorruptCheckpointError(
                        f"{path}: CRC32 mismatch in {name} blob — expected "
                        f"{crc:#010x}, got {zlib.crc32(blob):#010x} "
                        "(bit rot or partial overwrite)"
                    )
                blobs.append(None if name == "opt_state" and skip_opt_state else blob)
        if version == 2 and f.tell() != size:
            raise CorruptCheckpointError(
                f"{path}: {size - f.tell()} trailing bytes after the "
                "opt_state blob (corrupt or mixed-up file)"
            )
    return version, blobs


def load_checkpoint(
    path: str,
    params_template: Optional[Any] = None,
    opt_state_template: Optional[Any] = None,
    *,
    load_opt_state: bool = True,
) -> tuple[dict, Any, Any]:
    """Read ``(meta, params, opt_state)`` back, verifying as it goes.

    With templates (the freshly-initialized structures), the exact pytree
    types are restored; without, params/opt_state come back as plain nested
    dicts — sufficient for ``model.apply`` at inference.
    ``load_opt_state=False`` skips deserializing the optimizer blob
    (~2x the parameter bytes) and returns ``None`` for it — the inference
    cold-start path (its extent is still verified; its CRC is not, to keep
    the cheap variant cheap).

    Truncated files, short reads, and (v2) CRC mismatches raise
    :class:`CorruptCheckpointError` naming the failing blob.
    """
    _, blobs = _read_blobs(
        path, skip_opt_state=not load_opt_state, verify_crc=load_opt_state
    )
    meta = json.loads(blobs[0].decode("utf-8"))
    if params_template is not None:
        params = serialization.from_bytes(params_template, blobs[1])
    else:
        params = serialization.msgpack_restore(blobs[1])
    if blobs[2] is None:
        opt_state = None
    elif opt_state_template is not None:
        opt_state = serialization.from_bytes(opt_state_template, blobs[2])
    else:
        opt_state = serialization.msgpack_restore(blobs[2])
    return meta, params, opt_state


def verify_checkpoint(path: str) -> dict:
    """Structurally verify a checkpoint and return its (parsed) meta.

    Checks magic, every blob extent against the file size, and (v2) every
    blob's CRC32 — without paying flax deserialization. Raises
    :class:`CorruptCheckpointError` (or ``ValueError`` for a non-checkpoint
    file) on any violation; a return means the file's bytes are intact.
    """
    _, blobs = _read_blobs(path)
    try:
        return json.loads(blobs[0].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CorruptCheckpointError(f"{path}: meta blob is not JSON: {e}") from e


def _resume_candidates(out_dir: str) -> list[str]:
    """Recovery order: latest -> rotated previous latest -> best-k
    (newest epoch first) -> best."""
    paths = []
    for name in ("latest.ckpt", "latest.prev.ckpt"):
        p = os.path.join(out_dir, name)
        if os.path.exists(p):
            paths.append(p)
    bests = []
    for p in _glob.glob(os.path.join(out_dir, "best_e*.ckpt")):
        m = re.fullmatch(r"best_e(\d+)\.ckpt", os.path.basename(p))
        if m:
            bests.append((int(m.group(1)), p))
    paths.extend(p for _, p in sorted(bests, reverse=True))
    best = os.path.join(out_dir, "best.ckpt")
    if os.path.exists(best):
        paths.append(best)
    return paths


def load_latest_verified(
    out_dir: str,
    params_template: Optional[Any] = None,
    opt_state_template: Optional[Any] = None,
    *,
    load_opt_state: bool = True,
    quarantine: bool = True,
    log: Optional[Callable[[str], None]] = None,
) -> Optional[tuple[str, dict, Any, Any]]:
    """The newest checkpoint in ``out_dir`` that passes verification.

    Walks the recovery chain latest.ckpt -> latest.prev.ckpt (rotated by
    the trainer before each latest write) -> best_e*.ckpt (newest epoch
    first) -> best.ckpt. Every candidate is CRC/structure-verified before
    it is loaded; corrupt ones are never silently loaded — they are
    renamed to ``<name>.corrupt`` (``quarantine=True``, so the next resume
    does not trip over them again) with the reason sent to ``log``.

    Returns ``(path, meta, params, opt_state)`` for the first verified
    candidate, or ``None`` when the directory holds no loadable checkpoint
    at all (the ``--resume auto`` fresh-start case). Template-mismatch
    errors from flax (a *valid* file for a different model) propagate —
    quarantining those would destroy good checkpoints.
    """
    for path in _resume_candidates(out_dir):
        try:
            verify_checkpoint(path)
        except (ValueError, OSError) as e:  # CorruptCheckpointError is a ValueError
            if quarantine:
                quarantined = path + ".corrupt"
                try:
                    os.replace(path, quarantined)
                except OSError:
                    quarantined = "(rename failed; left in place)"
                if log:
                    log(
                        f"checkpoint {path} failed verification "
                        f"({e}) — quarantined as {quarantined}"
                    )
            elif log:
                log(f"checkpoint {path} failed verification ({e}) — skipped")
            continue
        meta, params, opt_state = load_checkpoint(
            path, params_template, opt_state_template, load_opt_state=load_opt_state
        )
        return path, meta, params, opt_state
    return None
