"""Self-sufficient single-file checkpoints with atomic writes.

The reference saves only ``{'epoch', 'state_dict'}`` on validation
improvement (``Model_Trainer.py:18,52-53``): optimizer state is lost (no
true resume) and the normalizer statistics live only on the in-memory
loader object, so its checkpoints cannot even denormalize predictions
(SURVEY.md §5.d). Here one file carries everything a preempted TPU job
needs: model params, optimizer state, and a JSON meta block (step/epoch,
best validation loss, early-stop counter, normalizer statistics, config).

Format: three length-prefixed blobs — JSON meta, flax-serialized params,
flax-serialized optimizer state — written to a temp file and ``os.replace``d
so a preemption mid-write never corrupts the previous checkpoint.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Optional

from flax import serialization

__all__ = [
    "load_checkpoint",
    "save_checkpoint",
    "serialize_checkpoint",
    "write_checkpoint_bytes",
]

_MAGIC = b"STMG1\n"


def serialize_checkpoint(params: Any, opt_state: Any, meta: dict) -> bytes:
    """Snapshot state into one self-contained byte string.

    This is the device→host boundary: ``to_bytes`` materializes every leaf
    to host numpy, so the returned blob is immune to later in-place updates
    / donation of the live training state — safe to hand to a background
    writer thread.
    """
    blobs = [
        json.dumps(meta).encode("utf-8"),
        serialization.to_bytes(params),
        serialization.to_bytes(opt_state),
    ]
    out = [_MAGIC]
    for blob in blobs:
        out.append(struct.pack("<Q", len(blob)))
        out.append(blob)
    return b"".join(out)


def write_checkpoint_bytes(path: str, data: bytes) -> None:
    """Atomically write a serialized checkpoint (temp file + ``os.replace``
    so a preemption mid-write never corrupts the previous checkpoint)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def save_checkpoint(path: str, params: Any, opt_state: Any, meta: dict) -> None:
    """Atomically write ``params``/``opt_state``/``meta`` to ``path``."""
    write_checkpoint_bytes(path, serialize_checkpoint(params, opt_state, meta))


def load_checkpoint(
    path: str,
    params_template: Optional[Any] = None,
    opt_state_template: Optional[Any] = None,
    *,
    load_opt_state: bool = True,
) -> tuple[dict, Any, Any]:
    """Read ``(meta, params, opt_state)`` back.

    With templates (the freshly-initialized structures), the exact pytree
    types are restored; without, params/opt_state come back as plain nested
    dicts — sufficient for ``model.apply`` at inference.
    ``load_opt_state=False`` skips deserializing the optimizer blob
    (~2x the parameter bytes) and returns ``None`` for it — the inference
    cold-start path.
    """
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path} is not a stmgcn-tpu checkpoint")
        blobs = []
        for i in range(3):
            (length,) = struct.unpack("<Q", f.read(8))
            if i == 2 and not load_opt_state:
                blobs.append(None)
                break
            blobs.append(f.read(length))
    meta = json.loads(blobs[0].decode("utf-8"))
    if params_template is not None:
        params = serialization.from_bytes(params_template, blobs[1])
    else:
        params = serialization.msgpack_restore(blobs[1])
    if blobs[2] is None:
        opt_state = None
    elif opt_state_template is not None:
        opt_state = serialization.from_bytes(opt_state_template, blobs[2])
    else:
        opt_state = serialization.msgpack_restore(blobs[2])
    return meta, params, opt_state
