"""Regression metrics.

Reference: the five staticmethods at ``Model_Trainer.py:100-114`` — MSE,
RMSE, MAE, MAPE with an ``epsilon=1.0`` zero-division guard (``:110``), and
PCC (defined there, never called; wired into the report here). Metrics are
computed host-side on denormalized arrays, matching the reference's
evaluation flow (``Model_Trainer.py:89-95``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["MSE", "RMSE", "MAE", "MAPE", "PCC", "regression_report"]


def MSE(y_pred, y_true) -> float:
    return float(np.mean(np.square(np.asarray(y_pred) - np.asarray(y_true))))


def RMSE(y_pred, y_true) -> float:
    return float(np.sqrt(MSE(y_pred, y_true)))


def MAE(y_pred, y_true) -> float:
    return float(np.mean(np.abs(np.asarray(y_pred) - np.asarray(y_true))))


def MAPE(y_pred, y_true, epsilon: float = 1.0) -> float:
    """Mean absolute percentage error with the reference's additive guard.

    Note the guard is ``y_true + epsilon`` in the denominator
    (``Model_Trainer.py:110-111``), not ``max(|y|, eps)``.
    """
    y_pred, y_true = np.asarray(y_pred), np.asarray(y_true)
    return float(np.mean(np.abs(y_pred - y_true) / (y_true + epsilon)))


def PCC(y_pred, y_true) -> float:
    """Pearson correlation of the flattened arrays (``Model_Trainer.py:112-114``).

    Returns NaN (without the numpy warning) when either side is constant.
    """
    a = np.asarray(y_pred).ravel()
    b = np.asarray(y_true).ravel()
    if a.std() == 0.0 or b.std() == 0.0:
        return float("nan")
    return float(np.corrcoef(a, b)[0, 1])


def regression_report(y_pred, y_true) -> dict:
    """All metrics at once; the reference prints MSE/RMSE/MAE/MAPE
    (``Model_Trainer.py:92-95``) — PCC included as a bonus."""
    return {
        "mse": MSE(y_pred, y_true),
        "rmse": RMSE(y_pred, y_true),
        "mae": MAE(y_pred, y_true),
        "mape": MAPE(y_pred, y_true),
        "pcc": PCC(y_pred, y_true),
    }
