"""Host-load provenance and the inter-process bench lock.

This container is a single-core host, so *any* concurrent Python process
(a TPU-tunnel probe child, a test run, a second bench) depresses a
measurement by 4-20% (BASELINE.md, round 4: the same tuned schedule
measured 0.97x contended vs 1.02x idle). Round 4's records could not say
which regime they were taken in — ``vs_baseline`` silently lied whenever
anything shared the host. Two fixes live here:

- :func:`host_load_snapshot` captures machine-verifiable load provenance
  (loadavg, core count, competing Python PIDs with command briefs) that
  every bench record embeds before and after its measurement, so a
  contended ratio is flagged in-band instead of explained in prose.
- :class:`BenchLock` is an advisory ``flock`` both sides of the
  measurement machinery respect: ``bench.py`` (driver-invoked or not)
  holds it while measuring, and the background tunnel-recovery loop
  (``benchmarks/tpu_probe_loop.py``) holds it around its probes and its
  non-bench runbook legs (bench legs take the lock themselves in the
  child) — so the loop can never again run concurrently with the
  driver's record. ``flock`` releases with the holder's death, so a
  crashed holder never leaves a stale lock behind.
"""

from __future__ import annotations

import os
import time
from typing import Optional

#: one lock per host: the resource being serialized is the host's single
#: core (and the single TPU chip behind the tunnel), not the repo
LOCK_PATH = "/tmp/stmgcn_bench.lock"

#: the ONE backend-probe snippet, shared by bench.py's watchdog and the
#: tunnel-recovery loop so the two can never probe differently. Cheap
#: enough to run under the lock; prints the *resolved* backend because a
#: plugin-less host "succeeds" on CPU and callers must be able to tell.
PROBE_SRC = (
    "import jax, jax.numpy as jnp; "
    "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
    "print(jax.default_backend())"
)

#: how a probe child is recognized in a /proc cmdline brief (derived, so
#: an edit to PROBE_SRC can never strand the drain on a stale pattern)
PROBE_MARKER = PROBE_SRC[:40]


def _competing_python(max_procs: int = 16) -> list[dict]:
    """Python processes on the host other than this one and its ancestors.

    Reads ``/proc`` directly (no psutil in this image). Ancestors are
    excluded because the driver's shell chain (``claude`` -> ``bash`` ->
    ``python bench.py``) is not *competing* load — it is how the
    measurement itself was launched. Children are NOT excluded: a probe
    child this process forked still burns the core.
    """
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(32):
        try:
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split(")")[-1].split()[1])
        except (OSError, ValueError, IndexError):
            break
        ancestors.add(pid)
        if ppid <= 1:
            break
        pid = ppid
    out = []
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return out
    for pid in pids:
        if pid in ancestors:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if not argv or b"python" not in os.path.basename(argv[0]):
            continue
        brief = b" ".join(argv[:4]).decode(errors="replace").strip()
        out.append({"pid": pid, "cmd": brief[:120]})
        if len(out) >= max_procs:
            break
    return out


def host_load_snapshot() -> dict:
    """One machine-verifiable snapshot of the host's load regime."""
    try:
        load1, load5, _ = os.getloadavg()
    except OSError:  # pragma: no cover - /proc-less host
        load1 = load5 = None
    return {
        "loadavg_1m": round(load1, 2) if load1 is not None else None,
        "loadavg_5m": round(load5, 2) if load5 is not None else None,
        "nproc": os.cpu_count(),
        "competing_python": _competing_python(),
    }


def is_contended(host_load: dict) -> bool:
    """Whether a record's host-load provenance shows a contended regime.

    ``host_load`` is the ``{"before": snapshot, "after": snapshot, ...}``
    dict bench records embed; any competing Python process on either side
    of the measurement counts (on this 1-core host it depresses
    throughput 4-20%). The persist policy keys off this: a contended
    record is still printed, but it is excluded from baseline comparison
    and never overwrites last-good evidence.
    """
    return bool(
        (host_load.get("before") or {}).get("competing_python")
        or (host_load.get("after") or {}).get("competing_python")
    )


def probe_backend_child(timeout_s: int = 120) -> Optional[str]:
    """Resolve the backend in a killable child; ``None`` when it never
    answers. The ONE probe implementation the measurement scripts share
    (a wedged axon tunnel blocks backend init inside native code where
    signal handlers never run — probing in-process is a 10-minute hang).
    Safe against a zero-returncode child with empty stdout."""
    import subprocess
    import sys

    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            timeout=timeout_s,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    lines = out.stdout.decode().strip().splitlines()
    return lines[-1] if lines else None


def wait_for_probe_children(max_wait_s: float = 150.0, poll_s: float = 5.0) -> bool:
    """Wait (bounded) for lingering backend-probe children to die.

    A probe child blocked dialing the wedged tunnel can stick in
    uninterruptible sleep past its parent's SIGKILL and depress a
    concurrent measurement ~10% on this 1-core host (seen in the wild:
    round-5 driver-sim record flagged exactly this in ``host_load``).
    The probe snippet is recognizable by its ``jnp.ones((8, 8))``
    matmul. Returns True when no probe child remains."""
    deadline = time.monotonic() + max_wait_s
    while True:
        lingering = [
            p for p in _competing_python() if PROBE_MARKER in p["cmd"]
        ]
        if not lingering or time.monotonic() >= deadline:
            return not lingering
        time.sleep(poll_s)


def measurement_preamble(wait_env: str = "STMGCN_BENCH_LOCK_WAIT"):
    """Standard start of every measurement script: acquire the host-wide
    bench lock (honoring ``STMGCN_BENCH_LOCK_PATH``), let lingering
    probe children drain, and snapshot the load regime. Returns
    ``(lock, load_before)``."""
    lock_path = os.environ.get("STMGCN_BENCH_LOCK_PATH")
    lock = BenchLock(lock_path) if lock_path else BenchLock()
    lock.acquire(wait_s=float(os.environ.get(wait_env, 300)))
    wait_for_probe_children()
    return lock, host_load_snapshot()


def persist_measurement(out_path: str, record: dict, on_tpu: bool, label: str) -> bool:
    """The ONE evidence-file overwrite policy: an on-chip record persists;
    a cpu-fallback record persists only when the existing file is absent,
    unreadable, or itself cpu-fallback — never over on-chip evidence; and
    a *contended* record (:func:`is_contended` over its ``host_load``)
    never overwrites a clean on-chip record, whatever platform it ran on.
    Stamps ``record["contended"]`` and ``record["persisted"]`` so the
    printed record says which happened, and returns the latter."""
    import json
    import sys

    contended = is_contended(record.get("host_load") or {})
    record["contended"] = contended
    existing = None
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = None
    persist, why = True, ""
    if existing is not None and existing.get("platform") == "tpu":
        if not on_tpu:
            persist, why = False, "a cpu-fallback run"
        elif contended and not existing.get("contended"):
            persist, why = False, "a host-contended run"
    record["persisted"] = persist
    if persist:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1)
    else:
        print(
            f"{label}: NOT overwriting on-chip record {out_path} with {why}",
            file=sys.stderr,
        )
    return persist


class BenchLock:
    """Advisory host-wide measurement lock (``flock`` on :data:`LOCK_PATH`).

    ``acquire(wait_s)`` polls non-blocking so the caller can bound its
    wait and *proceed anyway* on timeout — a measurement record with
    ``lock.acquired: false`` is still better than no record, and the
    ``host_load`` snapshot will show who was competing. The holder's PID
    is written into the file purely as a diagnostic; correctness rests on
    the flock, which the kernel releases when the holder exits.
    """

    def __init__(self, path: str = LOCK_PATH):
        self.path = path
        self._fd: Optional[int] = None
        self.acquired = False
        self.waited_s = 0.0

    def acquire(self, wait_s: float = 300.0, poll_s: float = 2.0) -> bool:
        import fcntl

        if self._fd is not None:  # re-acquire after timeout: reuse, don't leak
            os.close(self._fd)
            self._fd = None
        t0 = time.monotonic()
        try:
            self._fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o666)
        except OSError:
            # advisory contract: an unopenable lock file (e.g. another
            # user's 0644 /tmp file) must degrade to acquired=false, not
            # abort the measurement the lock exists to protect
            self.acquired = False
            self.waited_s = 0.0
            return False
        deadline = time.monotonic() + wait_s
        while True:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                self.acquired = True
                os.ftruncate(self._fd, 0)
                os.write(self._fd, str(os.getpid()).encode())
                break
            except OSError:
                if time.monotonic() >= deadline:
                    break
                time.sleep(poll_s)
        self.waited_s = round(time.monotonic() - t0, 1)
        return self.acquired

    def holder_pid(self) -> Optional[int]:
        """Best-effort PID of the current holder (diagnostic only)."""
        try:
            with open(self.path) as f:
                return int(f.read().strip() or 0) or None
        except (OSError, ValueError):
            return None

    def release(self) -> None:
        if self._fd is not None:
            import fcntl

            try:
                if self.acquired:
                    fcntl.flock(self._fd, fcntl.LOCK_UN)
            finally:
                os.close(self._fd)
                self._fd = None
                self.acquired = False

    def __enter__(self) -> "BenchLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def record(self) -> dict:
        """The in-record provenance of this acquisition attempt."""
        rec = {"acquired": self.acquired, "waited_s": self.waited_s}
        if not self.acquired:
            rec["holder_pid"] = self.holder_pid()
        return rec
