"""Tracing and throughput measurement.

The reference's only observability is ``time.ctime()`` prints at phase
boundaries (``Model_Trainer.py:21,62,74,96``; SURVEY.md §5.a). Here:

- :func:`fence` — force device completion via a value readback.
  ``jax.block_until_ready`` is NOT a reliable fence on every backend: on
  this image's tunneled ``axon`` TPU plugin it returns while the
  computation is still in flight, which silently turns "fenced" timings
  into dispatch timings (measured: a train step "timed" at 1 ms that a
  readback proves takes 82 ms). Fetching a computed scalar to the host
  cannot lie — the executable must have finished to produce it.
- :func:`time_chained` — the honest steady-state methodology on a
  remote-tunneled device: time N *chained* steps (each consuming the
  previous step's outputs) and fence once at the end, so the per-sync
  round-trip (~68 ms over the tunnel) is amortized instead of billed to
  every step.
- :class:`StepTimer` — per-step timing with a readback fence per step.
  Correct everywhere, but on a tunneled backend each fence pays a full
  round-trip, so prefer :func:`time_chained` for throughput numbers.
- :func:`trace` — context manager around ``jax.profiler`` trace capture
  for TensorBoard/XProf (per-op device timelines, fusion inspection).
- :func:`region_timesteps_per_sec` — the framework's north-star
  throughput metric (BASELINE.json): demand points advanced per second.
"""

from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "StepTimer",
    "fence",
    "region_timesteps_per_sec",
    "time_chained",
    "trace",
]


def fence(tree) -> None:
    """Block until ``tree``'s computation has finished, via value readback.

    First waits with ``jax.block_until_ready`` (correct and cheap on
    well-behaved backends, covers every leaf including outputs of
    independent dispatches), then reads one scalar element of one leaf
    back to the host — outputs of a jitted call come from one executable,
    so a materialized value implies the call completed, and for a chain of
    calls fencing the last forces every predecessor. The readback is what
    makes this hold on the tunneled ``axon`` backend, where
    ``block_until_ready`` returns while work is still in flight (module
    docstring). Callers timing trees that mix *independent* dispatches on
    such a backend should fence the legs separately.
    """
    jax.block_until_ready(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in reversed(leaves):  # prefer the last (e.g. a loss scalar)
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            jax.device_get(jnp.ravel(leaf)[0])
            return
    raise ValueError(
        "fence: no non-empty array leaf to read back — on backends where "
        "block_until_ready does not actually fence (module docstring), a "
        "silent pass here would turn timings into dispatch-only numbers; "
        "return (or pass) at least one computed array"
    )


def time_chained(step, iters: int, warmup: int = 3) -> float:
    """Mean seconds/step of ``step`` over ``iters`` chained calls.

    ``step()`` must perform one iteration whose inputs depend on the
    previous iteration's outputs (e.g. by closing over and rebinding
    ``params``/``opt_state``) and return something :func:`fence` can read.
    The fence happens once after the timed loop, so the measurement is
    dispatch-pipelined steady state — the honest number on a backend where
    every individual sync costs a network round-trip.
    """
    out = None
    for _ in range(warmup):
        out = step()
    if out is not None:
        fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step()
    fence(out)
    return (time.perf_counter() - t0) / iters


class StepTimer:
    """Measure per-step wall time with a readback fence per step.

    Usage::

        timer = StepTimer(warmup=3)
        for batch in batches:
            result = timer.measure(train_step, params, opt_state, *batch)
        print(timer.summary())

    On a remote-tunneled backend each per-step fence costs a full round
    trip that is billed to the step; use :func:`time_chained` when the
    quantity of interest is steady-state throughput.
    """

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self._times: list = []
        self._seen = 0

    def measure(self, fn, *args, **kwargs):
        """Run ``fn``, fence its result on device completion, record the time."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        fence(out)
        self.record(time.perf_counter() - t0)
        return out

    def record(self, seconds: float) -> None:
        """Record an externally-measured step (already fenced)."""
        self._seen += 1
        if self._seen > self.warmup:
            self._times.append(seconds)

    @property
    def times(self) -> np.ndarray:
        return np.asarray(self._times)

    @property
    def mean(self) -> float:
        return float(self.times.mean()) if self._times else float("nan")

    def summary(self) -> dict:
        if not self._times:
            return {"steps": 0}
        t = self.times
        return {
            "steps": len(t),
            "mean_s": float(t.mean()),
            "p50_s": float(np.percentile(t, 50)),
            "p95_s": float(np.percentile(t, 95)),
            "min_s": float(t.min()),
        }


def region_timesteps_per_sec(
    batch_size: int, seq_len: int, n_nodes: int, step_seconds: float
) -> float:
    """Demand points advanced per second — the BASELINE.json north-star."""
    return batch_size * seq_len * n_nodes / step_seconds


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a ``jax.profiler`` trace viewable in TensorBoard/XProf."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
