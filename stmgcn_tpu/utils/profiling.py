"""Tracing and throughput measurement.

The reference's only observability is ``time.ctime()`` prints at phase
boundaries (``Model_Trainer.py:21,62,74,96``; SURVEY.md §5.a). Here:

- :class:`StepTimer` — steady-state step timing with device-completion
  fences (``block_until_ready``), warmup exclusion, and percentile
  summaries; wall-clock-only timing of async dispatch is the classic JAX
  benchmarking mistake.
- :func:`trace` — context manager around ``jax.profiler`` trace capture
  for TensorBoard/XProf (per-op device timelines, fusion inspection).
- :func:`region_timesteps_per_sec` — the framework's north-star
  throughput metric (BASELINE.json): demand points advanced per second.
"""

from __future__ import annotations

import contextlib
import time

import jax
import numpy as np

__all__ = ["StepTimer", "region_timesteps_per_sec", "trace"]


class StepTimer:
    """Measure per-step wall time with proper device fencing.

    Usage::

        timer = StepTimer(warmup=3)
        for batch in batches:
            result = timer.measure(train_step, params, opt_state, *batch)
        print(timer.summary())
    """

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self._times: list = []
        self._seen = 0

    def measure(self, fn, *args, **kwargs):
        """Run ``fn``, fence its result on device completion, record the time."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.record(time.perf_counter() - t0)
        return out

    def record(self, seconds: float) -> None:
        """Record an externally-measured step (already fenced)."""
        self._seen += 1
        if self._seen > self.warmup:
            self._times.append(seconds)

    @property
    def times(self) -> np.ndarray:
        return np.asarray(self._times)

    @property
    def mean(self) -> float:
        return float(self.times.mean()) if self._times else float("nan")

    def summary(self) -> dict:
        if not self._times:
            return {"steps": 0}
        t = self.times
        return {
            "steps": len(t),
            "mean_s": float(t.mean()),
            "p50_s": float(np.percentile(t, 50)),
            "p95_s": float(np.percentile(t, 95)),
            "min_s": float(t.min()),
        }


def region_timesteps_per_sec(
    batch_size: int, seq_len: int, n_nodes: int, step_seconds: float
) -> float:
    """Demand points advanced per second — the BASELINE.json north-star."""
    return batch_size * seq_len * n_nodes / step_seconds


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a ``jax.profiler`` trace viewable in TensorBoard/XProf."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
