"""Pin the JAX host platform before backend initialization.

The axon TPU plugin in this image **ignores the ``JAX_PLATFORMS`` env
var** — only the ``jax_platforms`` config flag sticks — and its backend
init can hang indefinitely on a wedged tunnel. Every caller that needs a
guaranteed-CPU (or guaranteed-virtual-multi-device) JAX therefore routes
through this one helper instead of hand-copying the workaround.

Must run **before** the JAX backend initializes (any ``jax.devices()`` /
first op): both ``XLA_FLAGS`` and the platform choice are read once at
backend init and silently ignored afterwards.
"""

from __future__ import annotations

import os
import re
from typing import Optional

__all__ = ["force_host_platform"]


def force_host_platform(platform: str = "cpu", n_devices: Optional[int] = None) -> None:
    """Pin the platform; optionally set the virtual host-device count.

    ``n_devices`` overrides any existing
    ``--xla_force_host_platform_device_count`` in ``XLA_FLAGS`` (a smaller
    preexisting value would otherwise win and starve multi-device runs).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
        else:
            flags = (flags + " " + opt).strip()
        os.environ["XLA_FLAGS"] = flags
    # The env var is honored by stock JAX (harmless under axon, which
    # ignores it); the config flag is what actually sticks here.
    os.environ["JAX_PLATFORMS"] = platform

    import jax

    jax.config.update("jax_platforms", platform)
