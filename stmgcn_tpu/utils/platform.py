"""Pin the JAX host platform before backend initialization + JAX compat shims.

The axon TPU plugin in this image **ignores the ``JAX_PLATFORMS`` env
var** — only the ``jax_platforms`` config flag sticks — and its backend
init can hang indefinitely on a wedged tunnel. Every caller that needs a
guaranteed-CPU (or guaranteed-virtual-multi-device) JAX therefore routes
through this one helper instead of hand-copying the workaround.

Must run **before** the JAX backend initializes (any ``jax.devices()`` /
first op): both ``XLA_FLAGS`` and the platform choice are read once at
backend init and silently ignored afterwards.

This module is also the single home for symbols that moved between the
JAX versions the project supports (``jax>=0.4.30,<0.6``, pinned in
pyproject.toml):

- :func:`shard_map` — ``jax.shard_map`` only exists from 0.5.x; on 0.4.x
  the public spelling is ``jax.experimental.shard_map.shard_map``, whose
  replication-check kwarg is ``check_rep`` rather than ``check_vma``.
- :func:`axis_size` — ``jax.lax.axis_size`` only exists from 0.5.x; on
  0.4.x the portable spelling is ``lax.psum(1, axis_name)``, which XLA
  constant-folds to the mesh extent.

Importing these symbols from jax directly anywhere else is a lint error
(rule ``jax-compat-import`` in :mod:`stmgcn_tpu.analysis`).
"""

from __future__ import annotations

import os
import re
from typing import Optional

__all__ = ["axis_size", "force_host_platform", "shard_map"]


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True, **kwargs):
    """Version-portable ``shard_map`` (new-API spelling, old-API fallback).

    Accepts the modern ``check_vma`` kwarg on every supported JAX; on
    0.4.x it is forwarded as ``check_rep`` (same meaning, renamed when
    the varying-mesh-axes checker replaced the replication checker).
    """
    import jax

    new = getattr(jax, "shard_map", None)
    if new is not None:
        return new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma, **kwargs)
    from jax.experimental.shard_map import shard_map as old  # stmgcn: ignore[jax-compat-import]

    return old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,  # stmgcn: ignore[jax-compat-import]
               check_rep=check_vma, **kwargs)


def axis_size(axis_name) -> "int | jax.Array":
    """Version-portable ``jax.lax.axis_size`` (mesh extent of a named axis).

    Must be called under a binding of ``axis_name`` (inside ``shard_map``
    / ``pmap``). On 0.4.x jax, falls back to ``lax.psum(1, axis_name)`` —
    semantically identical and constant-folded by XLA.
    """
    import jax

    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def force_host_platform(platform: str = "cpu", n_devices: Optional[int] = None) -> None:
    """Pin the platform; optionally set the virtual host-device count.

    ``n_devices`` overrides any existing
    ``--xla_force_host_platform_device_count`` in ``XLA_FLAGS`` (a smaller
    preexisting value would otherwise win and starve multi-device runs).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
        else:
            flags = (flags + " " + opt).strip()
        os.environ["XLA_FLAGS"] = flags
    # The env var is honored by stock JAX (harmless under axon, which
    # ignores it); the config flag is what actually sticks here.
    os.environ["JAX_PLATFORMS"] = platform

    import jax

    jax.config.update("jax_platforms", platform)
