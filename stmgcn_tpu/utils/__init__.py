"""Utilities: profiling/timing, FLOPs/MFU accounting, numeric debugging."""

from stmgcn_tpu.utils.comm import collective_stats, step_comm_report
from stmgcn_tpu.utils.flops import device_peak_flops, mfu, stmgcn_step_flops
from stmgcn_tpu.utils.hostload import BenchLock, host_load_snapshot
from stmgcn_tpu.utils.platform import force_host_platform, shard_map
from stmgcn_tpu.utils.profiling import (
    StepTimer,
    fence,
    region_timesteps_per_sec,
    time_chained,
    trace,
)

__all__ = [
    "BenchLock",
    "StepTimer",
    "collective_stats",
    "host_load_snapshot",
    "device_peak_flops",
    "fence",
    "force_host_platform",
    "mfu",
    "region_timesteps_per_sec",
    "shard_map",
    "step_comm_report",
    "stmgcn_step_flops",
    "time_chained",
    "trace",
]
