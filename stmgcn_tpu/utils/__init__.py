"""Utilities: profiling/timing and numeric-debugging helpers."""

from stmgcn_tpu.utils.profiling import StepTimer, region_timesteps_per_sec, trace

__all__ = ["StepTimer", "region_timesteps_per_sec", "trace"]
