"""Analytic FLOPs model for the ST-MGCN training step + TPU peak lookup.

MFU (model FLOPs utilization) = analytic-model FLOPs / step time / chip
peak — the honest single-chip evidence that the chip is busy, as opposed to
a throughput number whose anchor ran on different hardware.

The FLOPs model counts the multiply-accumulate work of the reference's hot
path (each term cites the reference op it models; SURVEY.md §3.2):

- per-branch temporal graph conv in the gate: K support matmuls over the
  length-T history-as-features (``/root/reference/GCN.py:34-36`` inside
  ``STMGCN.py:40``) plus the ``(K*T, T)`` weight contraction
  (``GCN.py:39``);
- the two gate FC applications (``STMGCN.py:43``, eq. 8);
- the globally-shared L-layer LSTM over ``B*N`` folded rows
  (``STMGCN.py:47-48``): 4 gates, input + recurrent matmuls per step;
- the per-branch output graph conv on the LSTM state (``STMGCN.py:114``);
- the fusion head (``STMGCN.py:118``).

Elementwise work (activations, gating, residuals, Adam update) is excluded
— it is HBM-bound, not MXU-bound, and inflating the numerator would
overstate MFU. The backward pass is modeled as 2x the forward (the standard
dense-layer accounting: one matmul each for input and weight gradients per
forward matmul), giving the usual 3x total.

Peak lookup: per-JAX-device bf16 MXU peaks. On TPU, XLA's *default* f32
``dot_general`` precision multiplies in bf16 (with f32 accumulation), so
the bf16 peak is the correct denominator for both dtypes measured by
``bench.py``; a documented conservative choice.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["stmgcn_step_flops", "device_peak_flops", "mfu"]


def stmgcn_step_flops(
    batch: int,
    seq_len: int,
    n_nodes: int,
    n_feats: int,
    m_graphs: int,
    n_supports: int,
    lstm_hidden_dim: int,
    lstm_num_layers: int,
    gcn_hidden_dim: int,
    horizon: int = 1,
    backward: bool = True,
) -> float:
    """Matmul FLOPs (2 * MACs) of one training (or forward) step."""
    B, T, N, C = batch, seq_len, n_nodes, n_feats
    K, H, G, L, M = n_supports, lstm_hidden_dim, gcn_hidden_dim, lstm_num_layers, m_graphs

    # Gate: K supports x (N,N)@(N,T) per sample, then (B,N,K*T)@(K*T,T),
    # then the FC pair (B,T)@(T,T) twice (shared or not, same FLOPs).
    gate_gconv = 2.0 * K * B * N * N * T + 2.0 * B * N * (K * T) * T
    gate_fc = 2 * (2.0 * B * T * T)
    # LSTM: per folded row (B*N) per step, 4 gates of input+recurrent matmul.
    lstm = (
        B * N * T * (8.0 * (C + H) * H + (L - 1) * 8.0 * (H + H) * H)
    )
    # Output graph conv on the (B, N, H) LSTM state.
    out_gconv = 2.0 * K * B * N * N * H + 2.0 * B * N * (K * H) * G
    branch = gate_gconv + gate_fc + lstm + out_gconv
    head = 2.0 * B * N * G * (horizon * C)
    fwd = M * branch + head
    return 3.0 * fwd if backward else fwd


#: Per-JAX-device bf16 peak FLOP/s by `device_kind` substring (first match
#: wins; ordered most-specific first). Sources: published TPU specs.
_TPU_PEAK_BF16 = (
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports device_kind "TPU v5 lite"
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 61.5e12),  # per core (a v3 JAX device is one of 2 chip cores)
    ("v2", 22.5e12),  # per core
)


def device_peak_flops(device=None) -> Optional[float]:
    """bf16 peak FLOP/s of a JAX device; None when unknown (e.g. CPU)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and device.platform != "tpu":
        return None
    for needle, peak in _TPU_PEAK_BF16:
        if needle in kind:
            return peak
    return None


def mfu(model_flops: float, step_seconds: float, peak_flops: Optional[float]) -> Optional[float]:
    """Model FLOPs utilization in [0, 1]; None when the peak is unknown."""
    if peak_flops is None or step_seconds <= 0:
        return None
    return model_flops / step_seconds / peak_flops
