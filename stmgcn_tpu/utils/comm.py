"""Communication accounting from compiled HLO.

``collective_stats`` parses an XLA-compiled executable's HLO text and
tallies the collective ops (all-gather, all-reduce, collective-permute,
reduce-scatter, all-to-all) with their output bytes — the direct way to
*measure* what a sharding plan communicates per step instead of guessing.
Used to compare the explicit banded halo-exchange plan against GSPMD's
automatic plan (``stmgcn_tpu/parallel/banded.py``) and available to users
via :func:`step_comm_report`.

Byte counts are per-op *output* shapes summed over the program — a proxy
for wire volume (an all-gather's output is exactly the gathered tensor;
a collective-permute's output is the permuted block), not a hardware
counter. Loops/calls may repeat an op at runtime; counts are static.
"""

from __future__ import annotations

import re
from typing import Callable

__all__ = ["collective_stats", "step_comm_report"]

COLLECTIVES = (
    "all-gather",
    "all-reduce",
    "collective-permute",
    "reduce-scatter",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# e.g.:  %all-gather.3 = f32[8,256,3]{2,1,0} all-gather(%param.1), ...
# TPU HLO often splits collectives into async pairs ('all-gather-start' /
# 'all-gather-done'); the op name must be followed by '(' or '-start(' so a
# pair counts once ('-done' never matches), and a start op's tuple shape is
# (operands..., result) — only the result element is wire volume.
_OP_RE = re.compile(
    r"=\s*(?:\(([^)]*)\)|(\w+)\[([\d,]*)\][^ ]*)\s+("
    + "|".join(COLLECTIVES)
    + r")(-start)?\("
)
_TUPLE_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        return 0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * size


# result shape may be a tuple — '%while.0 = (f32[4,4]{1,0}, f32[2]{0}) while('
# — whose spaces a bare \S+ cannot span; any multi-array carry (every real
# scan/fori_loop) prints that way
_WHILE_RE = re.compile(r"=\s*(\([^)]*\)|\S+)\s+while\(")


def collective_stats(hlo_text: str) -> dict:
    """``{op: {"count": int, "bytes": int}}`` over all collectives found.

    ``while_count`` reports HLO ``while`` loops in the program: static
    counts do not multiply through loop trip counts, so any loop means the
    tallies may under-report runtime wire volume (see module docstring).
    """
    stats = {op: {"count": 0, "bytes": 0} for op in COLLECTIVES}
    while_count = 0
    for line in hlo_text.splitlines():
        if _WHILE_RE.search(line):
            while_count += 1
        m = _OP_RE.search(line)
        if not m:
            continue
        tuple_shape, dtype, dims, op, is_start = m.groups()
        stats[op]["count"] += 1
        if dtype is not None:
            stats[op]["bytes"] += _shape_bytes(dtype, dims)
        else:
            elems = _TUPLE_SHAPE_RE.findall(tuple_shape)
            if is_start:
                # TPU async-start tuples are (operands..., result) possibly
                # followed by scalar u32[] context elements: drop scalars,
                # then the result is the last remaining element.
                nonscalar = [e for e in elems if e[1]]
                elems = (nonscalar or elems)[-1:]
            for dt, dm in elems:
                stats[op]["bytes"] += _shape_bytes(dt, dm)
    stats["total_bytes"] = sum(v["bytes"] for v in stats.values() if isinstance(v, dict))
    stats["while_count"] = while_count
    return stats


def step_comm_report(fn: Callable, *args, allow_loops: bool = False, **kwargs) -> dict:
    """Compile ``fn(*args)`` (jit-wrapped if needed) and report its
    collective stats. Shardings are taken from the argument placements.

    Raises when the compiled program contains ``while`` loops (static
    per-op counts would silently under-report a loop's repeated
    collectives) unless ``allow_loops=True`` is passed explicitly.
    """
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    stats = collective_stats(compiled.as_text())
    if stats["while_count"] and not allow_loops:
        raise ValueError(
            f"compiled program has {stats['while_count']} while-loop(s); "
            "static collective counts would under-report them — pass "
            "allow_loops=True to accept lower-bound numbers"
        )
    return stats
