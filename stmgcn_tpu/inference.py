"""Checkpoint-to-forecast inference, no training stack required.

The reference cannot do this: its checkpoints hold only a ``state_dict``
and the normalization statistics live on the in-memory loader object
(SURVEY.md §5.d), so a saved model cannot even denormalize its outputs.
Here a checkpoint is self-sufficient — config, derived model facts, and
normalizer statistics travel inside it — so serving is::

    fc = Forecaster.from_checkpoint("output/best.ckpt")
    demand_forecast = fc.predict(supports, history)   # raw demand units

``supports`` are rebuilt from the city's adjacency matrices (offline,
:class:`~stmgcn_tpu.ops.graph.SupportConfig`), which are data, not model
state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from stmgcn_tpu.config import ExperimentConfig
from stmgcn_tpu.data.normalize import normalizer_from_dict
from stmgcn_tpu.serving import serve_predict
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.train.checkpoint import load_checkpoint

__all__ = ["Forecaster"]


class Forecaster:
    """A trained ST-MGCN ready to forecast from raw demand history."""

    def __init__(
        self,
        model,
        params,
        normalizer,
        config: ExperimentConfig,
        derived: dict,
        normalizers=None,
        health_baseline: Optional[dict] = None,
    ):
        self.model = model
        self.params = params
        self.normalizer = normalizer
        #: heterogeneous multi-city checkpoints: one normalizer per city
        #: (``derived["n_nodes"]`` is then a per-city list); ``predict``
        #: selects with ``city=``
        self.normalizers = normalizers
        self.config = config
        self.derived = derived  # {"input_dim": C, "n_nodes": N | [N_city...]}
        #: training-time drift baseline from checkpoint meta (None when
        #: the run trained without health baseline capture) — what the
        #: serving engines' DriftMonitor compares live traffic against
        self.health_baseline = health_baseline
        self._apply = jax.jit(model.apply)

    @classmethod
    def from_checkpoint(cls, path: str) -> "Forecaster":
        meta, params, _ = load_checkpoint(path, load_opt_state=False)
        if "config" not in meta or "derived" not in meta:
            raise ValueError(
                f"{path} lacks the config/derived metadata needed to rebuild "
                "the model (was it written by stmgcn_tpu.train.Trainer?)"
            )
        cfg = ExperimentConfig.from_dict(meta["config"])
        normalizer = (
            normalizer_from_dict(meta["normalizer"]) if "normalizer" in meta else None
        )
        normalizers = None
        if "normalizers" in meta:  # heterogeneous multi-city checkpoint
            normalizers = [
                normalizer_from_dict(n) if n is not None else None
                for n in meta["normalizers"]
            ]
        model = build_model(cfg, meta["derived"]["input_dim"])
        params = jax.tree.map(jnp.asarray, params)
        return cls(model, params, normalizer, cfg, meta["derived"], normalizers,
                   health_baseline=meta.get("health_baseline"))

    @property
    def seq_len(self) -> int:
        return self.config.data.seq_len

    @property
    def horizon(self) -> int:
        return self.config.data.horizon

    def predict(
        self,
        supports,
        history,
        *,
        normalized: bool = False,
        city: Optional[int] = None,
    ) -> np.ndarray:
        """Forecast demand from raw-scale history.

        ``history``: ``(B, seq_len, N, C)`` windowed observations in raw
        demand units (set ``normalized=True`` if already model-scaled);
        ``supports``: the stacked ``(M, K, N, N)`` array (or sparse pytree)
        built from the city's graphs. With a heterogeneous multi-city
        checkpoint, ``city`` is REQUIRED and selects that city's normalizer
        and expected region count — cities may share shapes (hetero twins),
        so no shape check could catch a wrong default. Returns raw-unit
        forecasts of shape ``(B, N, C)`` or ``(B, H, N, C)``.
        """
        n_nodes, normalizer = self.derived["n_nodes"], self.normalizer
        if self.normalizers is not None:
            if city is None:
                if len(self.normalizers) > 1:
                    # hetero cities can share N (twins with distinct
                    # normalizers), so an implicit city 0 would silently
                    # denormalize another city's data with nothing
                    # downstream to catch it. Unlike export_forecaster
                    # (which always demands city= because the artifact
                    # bakes one city in), a single-normalizer checkpoint
                    # has nothing to choose — default to it.
                    raise ValueError(
                        "this checkpoint holds "
                        f"{len(self.normalizers)} per-city normalizers; "
                        "pass city= to select one"
                    )
                city = 0
            if not 0 <= city < len(self.normalizers):
                raise ValueError(
                    f"city must be in [0, {len(self.normalizers)}), got {city}"
                )
            normalizer = self.normalizers[city]
            n_nodes = n_nodes[city]
        elif city not in (None, 0):
            # mirror export_forecaster: silently applying the shared
            # normalizer to a city-selecting caller would mask their bug
            raise ValueError(
                "city= only applies to heterogeneous multi-city checkpoints"
            )
        expected = (self.seq_len, n_nodes, self.derived["input_dim"])
        return serve_predict(
            lambda h: self._apply(self.params, supports, jnp.asarray(h)),
            normalizer,
            expected,
            history,
            normalized,
        )

    def serving_engine(self, supports, *, config=None, city=None,
                       fault_plan=None):
        """A :class:`stmgcn_tpu.serving.ServingEngine` over this checkpoint:
        per-bucket AOT programs (no per-call jit dispatch), ``supports``
        pinned device-resident, params hot-swappable, concurrent
        ``predict`` calls micro-batched behind SLO admission control.
        Results are bit-identical to :meth:`predict`. ``fault_plan``
        threads a :class:`stmgcn_tpu.resilience.ServeFaultPlan` through
        (deterministic overload/fault tests; empty plan is a no-op)."""
        from stmgcn_tpu.serving import ServingEngine

        return ServingEngine.from_forecaster(
            self, supports, config=config, city=city, fault_plan=fault_plan
        )

    def fleet_engine(self, city_supports, *, config=None,
                     max_classes: int = 8, max_pad_waste: float = 0.5,
                     fault_plan=None):
        """A :class:`stmgcn_tpu.serving.FleetServingEngine` over this
        heterogeneous checkpoint: every city served from one engine,
        requests for different cities of a shape class coalescing into
        one dispatch, params hot-swappable fleet-wide. Results are
        bit-identical to per-city :meth:`predict`."""
        from stmgcn_tpu.serving import FleetServingEngine

        return FleetServingEngine.from_forecaster(
            self, city_supports, config=config,
            max_classes=max_classes, max_pad_waste=max_pad_waste,
            fault_plan=fault_plan,
        )
