"""SLO admission control: typed load shedding in front of the batcher.

Under overload an unbounded micro-batch queue converts excess arrival
rate into unbounded p99 — every request is eventually served, all of
them late. The operable behavior is the opposite: decide *at arrival*
whether a request can plausibly meet its deadline, and shed it with a
typed error if not, so admitted requests keep their latency and callers
get an actionable signal (retry elsewhere / back off) instead of a
timeout.

:class:`AdmissionController` fronts :class:`~stmgcn_tpu.serving
.microbatch.MicroBatcher` with two tests, both O(1) under the queue
lock:

- **bounded queue** — more than ``queue_bound_rows`` pending rows
  rejects with :class:`Overloaded` (the queue-depth circuit breaker);
- **estimated wait** — pending dispatches ahead x the measured per-rung
  device time (:meth:`~stmgcn_tpu.serving.metrics.EngineStats
  .device_ms_estimate`) already past ``deadline_ms`` rejects with
  :class:`DeadlineExceeded` — the request would miss its SLO even if
  everything goes right, so device time is not spent on it.

Admitted requests carry their deadline into the queue; the batcher sheds
any whose deadline expires *before dispatch* (same typed error), so a
stalled device never burns a dispatch on rows nobody is waiting for.

Both knobs live on :class:`~stmgcn_tpu.config.ServingConfig`
(``deadline_ms`` / ``queue_bound_rows`` / ``shed_policy`` /
``degrade_rung``) and are validated by ``violations()`` + the
``serving-slo`` lint rule. The no-SLO config (all defaults) builds no
controller at all — the engine behaves exactly as before this layer
existed.
"""

from __future__ import annotations

from typing import Optional

__all__ = [
    "AdmissionController",
    "BatcherWedged",
    "DeadlineExceeded",
    "DispatchError",
    "Overloaded",
    "ShedError",
]


class ShedError(RuntimeError):
    """Base of the typed admission rejections — a request the engine
    chose not to serve (never a half-served one). Catch this to treat
    both shed kinds uniformly (e.g. retry against another replica)."""


class Overloaded(ShedError):
    """Rejected at arrival: the pending queue is over its row bound."""


class DeadlineExceeded(ShedError):
    """Rejected because the deadline cannot (estimated wait at arrival)
    or did not (expiry while queued) leave room to serve the request."""


class DispatchError(RuntimeError):
    """A coalesced dispatch died; every waiter of that batch receives its
    own instance carrying the batch context (``bucket``, ``rows``,
    ``requests``) with the device error as ``__cause__``."""

    def __init__(self, message: str, *, bucket: Optional[int] = None,
                 rows: Optional[int] = None, requests: Optional[int] = None):
        super().__init__(message)
        self.bucket = bucket
        self.rows = rows
        self.requests = requests


class BatcherWedged(RuntimeError):
    """The micro-batch worker thread is dead (injected fault, interpreter
    shutdown, or a BaseException escaping a dispatch). Queued and future
    ``submit`` calls fail fast with this instead of blocking forever; the
    engine degrades to the inline ``predict_direct`` path on seeing it."""


class AdmissionController:
    """Arrival-time admission decisions for one micro-batch queue.

    Stateless beyond its config + a telemetry handle: the queue depth is
    passed in by the batcher (which owns the lock), and the per-dispatch
    device-time estimate comes from the live :class:`EngineStats` the
    same engine records into — the wait model tracks the actual host.
    """

    def __init__(self, config, stats, buckets):
        self.deadline_ms: Optional[float] = config.deadline_ms
        self.queue_bound_rows: int = int(config.queue_bound_rows)
        self._stats = stats
        self._top = max(buckets)
        #: conservative floor used until the first dispatch is measured:
        #: the coalescing delay itself (a dispatch can never be estimated
        #: faster than the wait the batcher deliberately adds)
        self._floor_ms = float(config.max_delay_ms)

    @property
    def deadline_s(self) -> Optional[float]:
        return None if self.deadline_ms is None else self.deadline_ms / 1e3

    def estimated_wait_ms(self, pending_rows: int) -> float:
        """Expected queue wait for an arrival behind ``pending_rows``:
        full dispatches ahead of it x the measured per-rung device time
        (top rung — saturated dispatches are what a backlog drains as).
        """
        dispatches_ahead = pending_rows // self._top
        per_dispatch = self._stats.device_ms_estimate(
            self._top, default=self._floor_ms
        )
        return dispatches_ahead * per_dispatch

    def admit(self, n_rows: int, pending_rows: int) -> None:
        """Raise the typed rejection for an arrival of ``n_rows`` behind
        ``pending_rows`` queued rows; return silently when admitted.
        Called by the batcher under its queue lock."""
        if (
            self.queue_bound_rows
            and pending_rows + n_rows > self.queue_bound_rows
        ):
            self._stats.record_shed("overloaded")
            raise Overloaded(
                f"queue holds {pending_rows} rows, bound is "
                f"{self.queue_bound_rows} — request of {n_rows} rows shed"
            )
        if self.deadline_ms is not None:
            est = self.estimated_wait_ms(pending_rows)
            if est > self.deadline_ms:
                self._stats.record_shed("deadline")
                raise DeadlineExceeded(
                    f"estimated queue wait {est:.1f} ms exceeds the "
                    f"{self.deadline_ms} ms deadline at arrival — shed "
                    "instead of serving late"
                )
