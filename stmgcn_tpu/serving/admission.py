"""SLO admission control: typed load shedding in front of the batcher.

Under overload an unbounded micro-batch queue converts excess arrival
rate into unbounded p99 — every request is eventually served, all of
them late. The operable behavior is the opposite: decide *at arrival*
whether a request can plausibly meet its deadline, and shed it with a
typed error if not, so admitted requests keep their latency and callers
get an actionable signal (retry elsewhere / back off) instead of a
timeout.

:class:`AdmissionController` fronts :class:`~stmgcn_tpu.serving
.microbatch.MicroBatcher` with two tests, both O(1) under the queue
lock:

- **bounded queue** — more than ``queue_bound_rows`` pending rows
  rejects with :class:`Overloaded` (the queue-depth circuit breaker);
- **estimated wait** — pending dispatches ahead x the measured per-rung
  device time (:meth:`~stmgcn_tpu.serving.metrics.EngineStats
  .device_ms_estimate`) already past ``deadline_ms`` rejects with
  :class:`DeadlineExceeded` — the request would miss its SLO even if
  everything goes right, so device time is not spent on it.

Admitted requests carry their deadline into the queue; the batcher sheds
any whose deadline expires *before dispatch* (same typed error), so a
stalled device never burns a dispatch on rows nobody is waiting for.

Both knobs live on :class:`~stmgcn_tpu.config.ServingConfig`
(``deadline_ms`` / ``queue_bound_rows`` / ``shed_policy`` /
``degrade_rung``) and are validated by ``violations()`` + the
``serving-slo`` lint rule. The no-SLO config (all defaults) builds no
controller at all — the engine behaves exactly as before this layer
existed.

**Tier-wide budget** (the federation layer): per-replica bounds cannot
see each other, so M replicas each under their local bound can still
jointly hold M x ``queue_bound_rows`` rows — a tier-sized backlog no
single controller would admit. :class:`GlobalBudget` is one shared
pending-row account every replica's controller draws down at admission
and the replica's batcher pays back as rows leave its queue (dispatch,
expiry shed, or wedge-drain). Lock discipline: the budget has its own
lock, always acquired *inside* a batcher's queue lock and never the
reverse — queue-lock → budget-lock is the only order, so M batchers
sharing one budget cannot deadlock.
"""

from __future__ import annotations

import threading
from typing import Optional

__all__ = [
    "AdmissionController",
    "BatcherWedged",
    "DeadlineExceeded",
    "DispatchError",
    "GlobalBudget",
    "Overloaded",
    "ShedError",
]


class ShedError(RuntimeError):
    """Base of the typed admission rejections — a request the engine
    chose not to serve (never a half-served one). Catch this to treat
    both shed kinds uniformly (e.g. retry against another replica)."""


class Overloaded(ShedError):
    """Rejected at arrival: the pending queue is over its row bound."""


class DeadlineExceeded(ShedError):
    """Rejected because the deadline cannot (estimated wait at arrival)
    or did not (expiry while queued) leave room to serve the request."""


class DispatchError(RuntimeError):
    """A coalesced dispatch died; every waiter of that batch receives its
    own instance carrying the batch context (``bucket``, ``rows``,
    ``requests``) with the device error as ``__cause__``."""

    def __init__(self, message: str, *, bucket: Optional[int] = None,
                 rows: Optional[int] = None, requests: Optional[int] = None):
        super().__init__(message)
        self.bucket = bucket
        self.rows = rows
        self.requests = requests


class BatcherWedged(RuntimeError):
    """The micro-batch worker thread is dead (injected fault, interpreter
    shutdown, or a BaseException escaping a dispatch). Queued and future
    ``submit`` calls fail fast with this instead of blocking forever; the
    engine degrades to the inline ``predict_direct`` path on seeing it."""


class GlobalBudget:
    """One tier-wide pending-row account shared by every replica's
    :class:`AdmissionController`.

    ``try_draw`` either reserves ``n`` rows atomically or refuses (the
    caller sheds ``Overloaded``); ``release`` pays rows back when they
    leave a replica's queue. All state lives behind the budget's own
    lock; callers hold at most one batcher queue lock while calling in,
    and the budget never calls out — the queue-lock → budget-lock order
    is acyclic by construction.
    """

    def __init__(self, total_rows: int):
        if total_rows < 1:
            raise ValueError(
                f"GlobalBudget needs total_rows >= 1, got {total_rows}"
            )
        self.total_rows = int(total_rows)
        self._lock = threading.Lock()
        self._outstanding = 0
        self._peak = 0
        self._refused = 0

    def try_draw(self, n: int) -> bool:
        """Reserve ``n`` rows of the tier budget; False = over budget."""
        with self._lock:
            if self._outstanding + n > self.total_rows:
                self._refused += 1
                return False
            self._outstanding += n
            if self._outstanding > self._peak:
                self._peak = self._outstanding
            return True

    def release(self, n: int) -> None:
        """Pay back ``n`` rows that left a replica's queue. Clamped at
        zero so a double-release (e.g. a wedge-drain racing an expiry
        shed) can never manufacture budget."""
        with self._lock:
            self._outstanding = max(0, self._outstanding - n)

    def snapshot(self) -> dict:
        """JSON-ready accounting view (the soak record source)."""
        with self._lock:
            return {
                "total_rows": self.total_rows,
                "outstanding": self._outstanding,
                "peak": self._peak,
                "refused": self._refused,
            }


class AdmissionController:
    """Arrival-time admission decisions for one micro-batch queue.

    Stateless beyond its config + a telemetry handle: the queue depth is
    passed in by the batcher (which owns the lock), and the per-dispatch
    device-time estimate comes from the live :class:`EngineStats` the
    same engine records into — the wait model tracks the actual host.
    With a :class:`GlobalBudget` attached, an arrival must clear the
    local checks *and* draw its rows from the tier account — and the
    batcher pays the account back through :meth:`release_rows` as rows
    leave its queue.
    """

    def __init__(self, config, stats, buckets, *, global_budget=None):
        self.deadline_ms: Optional[float] = config.deadline_ms
        self.queue_bound_rows: int = int(config.queue_bound_rows)
        self._stats = stats
        self._global: Optional[GlobalBudget] = global_budget
        self._top = max(buckets)
        #: conservative floor used until the first dispatch is measured:
        #: the coalescing delay itself (a dispatch can never be estimated
        #: faster than the wait the batcher deliberately adds)
        self._floor_ms = float(config.max_delay_ms)

    @property
    def deadline_s(self) -> Optional[float]:
        return None if self.deadline_ms is None else self.deadline_ms / 1e3

    def estimated_wait_ms(self, pending_rows: int) -> float:
        """Expected queue wait for an arrival behind ``pending_rows``:
        full dispatches ahead of it x the measured per-rung device time
        (top rung — saturated dispatches are what a backlog drains as).
        """
        dispatches_ahead = pending_rows // self._top
        per_dispatch = self._stats.device_ms_estimate(
            self._top, default=self._floor_ms
        )
        return dispatches_ahead * per_dispatch

    def admit(self, n_rows: int, pending_rows: int) -> None:
        """Raise the typed rejection for an arrival of ``n_rows`` behind
        ``pending_rows`` queued rows; return silently when admitted.
        Called by the batcher under its queue lock."""
        if (
            self.queue_bound_rows
            and pending_rows + n_rows > self.queue_bound_rows
        ):
            self._stats.record_shed("overloaded")
            raise Overloaded(
                f"queue holds {pending_rows} rows, bound is "
                f"{self.queue_bound_rows} — request of {n_rows} rows shed"
            )
        if self.deadline_ms is not None:
            est = self.estimated_wait_ms(pending_rows)
            if est > self.deadline_ms:
                self._stats.record_shed("deadline")
                raise DeadlineExceeded(
                    f"estimated queue wait {est:.1f} ms exceeds the "
                    f"{self.deadline_ms} ms deadline at arrival — shed "
                    "instead of serving late"
                )
        # tier budget last: a locally-shed request must never draw it down
        if self._global is not None and not self._global.try_draw(n_rows):
            self._stats.record_shed("tier-overloaded")
            raise Overloaded(
                f"tier-wide budget of {self._global.total_rows} pending "
                f"rows is exhausted — request of {n_rows} rows shed"
            )

    def release_rows(self, n_rows: int) -> None:
        """Pay ``n_rows`` back to the tier budget (no-op without one).
        The batcher calls this wherever admitted rows leave its queue:
        dispatch take, in-queue expiry, and the wedge drain."""
        if self._global is not None and n_rows:
            self._global.release(n_rows)
