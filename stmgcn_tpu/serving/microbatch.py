"""Dynamic micro-batching: coalesce pending requests into one dispatch.

A single worker thread owns the queue. The dispatch policy:

- **saturation** — the worker dispatches immediately when the pending
  prefix can no longer grow: it fills the top ladder rung exactly, or
  the next queued request would overflow it. Under sustained load the
  queue refills while the worker is inside a dispatch, so consecutive
  dispatches run back-to-back at full rungs with *zero* added delay
  (continuous batching) — which is why deployments size the top rung to
  their peak concurrency.
- **deadline** — an unsaturated queue waits for more arrivals until the
  oldest pending request has aged ``max_delay_ms``, then dispatches the
  longest queue prefix that fits the top rung, padded up to the smallest
  covering rung. A lone caller therefore pays at most ``max_delay_ms``;
  latency-critical single callers use ``ServingEngine.predict_direct``,
  which bypasses the queue entirely.
- requests are never split and never reordered.

Failure contract (the part overload turns from nicety into necessity):

- an exception from a coalesced dispatch reaches **every** waiter of
  that batch as its own typed :class:`~stmgcn_tpu.serving.admission
  .DispatchError` carrying the batch context, and the worker survives;
- a ``BaseException`` escaping a dispatch — or anything killing the
  worker loop itself — marks the batcher **wedged**: every queued
  waiter is released with :class:`~stmgcn_tpu.serving.admission
  .BatcherWedged` and every later ``submit`` raises it immediately (the
  engine then degrades to its inline path). No caller ever blocks on a
  dead worker;
- ``submit`` after ``close()`` raises immediately;
- with an :class:`~stmgcn_tpu.serving.admission.AdmissionController`
  attached, arrivals are admission-checked under the queue lock (typed
  ``Overloaded``/``DeadlineExceeded`` sheds) and admitted requests
  carry their deadline: ones that expire *before dispatch* are shed at
  the dispatch boundary instead of burning device time;
- a :class:`~stmgcn_tpu.resilience.ServeFaultPlan` is consulted at
  dispatch entry (by 0-based dispatch ordinal) so all of the above is
  reproducible in tests; the empty plan is a production no-op.

Throughput discipline for one-core hosts: the submit side only wakes the
worker when it can act (first arrival starts the deadline clock,
saturation triggers a dispatch — intermediate arrivals just enqueue),
and results scatter back to callers as numpy *views* of the batched
output — zero-copy. A single request whose rows exactly fill a rung is
passed through to the dispatch without a pad copy at all.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from stmgcn_tpu.obs import jaxmon
from stmgcn_tpu.obs import trace as obs_trace
from stmgcn_tpu.serving.admission import (
    BatcherWedged,
    DeadlineExceeded,
    DispatchError,
)
from stmgcn_tpu.serving.bucketing import smallest_covering_bucket
from stmgcn_tpu.serving.metrics import EngineStats

__all__ = ["MicroBatcher"]


class _Request:
    __slots__ = ("rows", "n", "tag", "done", "result", "error", "t_enqueue",
                 "t_deadline", "info")

    def __init__(self, rows: np.ndarray, tag, deadline_s: Optional[float]):
        self.rows = rows
        self.n = rows.shape[0]
        self.tag = tag
        self.done = False
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()
        #: absolute expiry (perf_counter seconds); None = no deadline
        self.t_deadline = (
            None if deadline_s is None else self.t_enqueue + deadline_s
        )
        #: dispatch-scoped metadata the dispatch callable may attach
        #: (the engine stamps its param generation here)
        self.info = None


class MicroBatcher:
    """The request queue + worker behind :class:`ServingEngine.predict`.

    ``dispatch(payload, bucket, segments)`` runs the bucket's compiled
    program over the coalesced ``(bucket, ...)`` payload and returns the
    prediction array (host-side numpy) — or a ``(array, info)`` pair,
    in which case ``info`` is stamped on every coalesced request of the
    dispatch (the engine returns its param generation this way, making
    the stamp atomic with the params the dispatch actually used).
    ``segments`` is a tuple of ``(offset, n_rows, tag)`` triples — one
    per coalesced request, in payload order — so the dispatch can apply
    per-request handling (the engine uses ``tag`` for pre-normalized
    inputs) while still running every expensive transform once per
    *batch*, not once per request.
    """

    def __init__(self, dispatch: Callable[[np.ndarray, int, tuple], np.ndarray],
                 buckets, max_delay_ms: float, stats: EngineStats,
                 admission=None, fault_plan=None):
        self._dispatch = dispatch
        self._buckets = tuple(sorted(buckets))
        self._cap = self._buckets[-1]
        self._max_delay_s = max_delay_ms / 1e3
        self._stats = stats
        self._admission = admission
        self._fault_plan = fault_plan
        self._dispatch_seq = 0  # ordinal for the fault plan
        # two condvars on ONE lock: submitters signal the worker on
        # _cond; the worker signals completions on _done (a per-request
        # Event would cost an allocation + an extra lock round-trip per
        # request — measurable at micro-batched request rates)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._done = threading.Condition(self._lock)
        self._pending: collections.deque = collections.deque()
        self._pending_rows = 0
        self._closed = False
        self._dead: Optional[BaseException] = None  # worker-death cause
        self._worker = threading.Thread(
            target=self._run, name="stmgcn-microbatch", daemon=True
        )
        self._worker.start()

    @property
    def wedged(self) -> bool:
        """Whether the worker thread has died (submits now fail fast)."""
        with self._lock:
            return self._dead is not None

    def submit(self, rows: np.ndarray, tag=None, *, with_info: bool = False):
        """Enqueue one request and block until its predictions are ready.

        Raises immediately (never blocks) when the batcher is closed or
        wedged, and with the typed shed error when admission rejects the
        arrival. ``with_info=True`` returns ``(result, info)`` with the
        dispatch's stamped metadata (None for array-only dispatches).
        """
        if rows.shape[0] > self._cap:
            raise ValueError(
                f"request of {rows.shape[0]} rows exceeds the largest bucket "
                f"{self._cap} — the engine splits oversized batches before "
                "submitting"
            )
        adm = self._admission
        req = _Request(rows, tag, adm.deadline_s if adm is not None else None)
        with self._lock:
            if self._closed:
                raise RuntimeError("ServingEngine is closed")
            if self._dead is not None:
                raise self._wedged_error()
            if adm is not None:
                adm.admit(req.n, self._pending_rows)  # raises the typed shed
            trc = obs_trace.active_tracer()
            if trc is not None:
                # submit-entry -> admitted (lock wait + admission check)
                trc.record_span("serve.admit", req.t_enqueue,
                                time.perf_counter())
            self._pending.append(req)
            self._pending_rows += req.n
            # wake the worker only when it can act: the first arrival
            # starts the deadline clock; saturation triggers a dispatch;
            # anything in between would be a wasted GIL hand-off
            if len(self._pending) == 1 or self._pending_rows >= self._cap:
                self._cond.notify_all()
            while not req.done:
                self._done.wait()
        if req.error is not None:
            raise req.error
        return (req.result, req.info) if with_info else req.result

    def close(self) -> None:
        """Stop accepting requests, drain the queue, join the worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join()

    # -- worker side ----------------------------------------------------

    def _wedged_error(self) -> BatcherWedged:
        err = BatcherWedged(
            "micro-batch worker is dead — serve via predict_direct or "
            "rebuild the engine"
        )
        err.__cause__ = self._dead
        return err

    def _shed_expired(self) -> None:
        """Drop queue-front requests whose deadline already passed (FIFO +
        uniform deadlines keep expiry monotonic in queue order). Runs
        under the lock at the dispatch boundary: device time is never
        spent on rows nobody is waiting for."""
        now = time.perf_counter()
        shed = 0
        while (
            self._pending
            and self._pending[0].t_deadline is not None
            and now > self._pending[0].t_deadline
        ):
            req = self._pending.popleft()
            self._pending_rows -= req.n
            if self._admission is not None:
                self._admission.release_rows(req.n)
            req.error = DeadlineExceeded(
                f"request expired in queue after "
                f"{(now - req.t_enqueue) * 1e3:.1f} ms — shed at the "
                "dispatch boundary instead of served late"
            )
            req.done = True
            shed += 1
            self._stats.record_shed("deadline")
        if shed:
            self._done.notify_all()

    def _take_prefix(self) -> List[_Request]:
        batch: List[_Request] = []
        total = 0
        while self._pending and total + self._pending[0].n <= self._cap:
            req = self._pending.popleft()
            batch.append(req)
            total += req.n
        self._pending_rows -= total
        if self._admission is not None:
            self._admission.release_rows(total)
        return batch

    def _run(self) -> None:
        try:
            self._run_loop()
        except BaseException as e:  # noqa: BLE001 — a dying worker must
            # never strand its waiters: release everyone, fail new submits
            with self._lock:
                self._dead = e
                while self._pending:
                    req = self._pending.popleft()
                    req.error = self._wedged_error()
                    req.done = True
                if self._admission is not None:
                    self._admission.release_rows(self._pending_rows)
                self._pending_rows = 0
                self._done.notify_all()

    def _run_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    return  # closed and drained
                deadline = self._pending[0].t_enqueue + self._max_delay_s
                while not self._closed:
                    # saturated: the FIFO prefix cannot grow any further
                    # (>= cap means it either fills the top rung exactly
                    # or a queued request is too big to join) — waiting
                    # longer cannot improve this dispatch
                    if self._pending_rows >= self._cap:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                self._shed_expired()
                batch = self._take_prefix()
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[_Request]) -> None:
        total = sum(req.n for req in batch)
        t0 = time.perf_counter()
        bucket = None
        info = None
        payload = None
        try:
            bucket = smallest_covering_bucket(total, self._buckets)
            if self._fault_plan is not None:
                ordinal, self._dispatch_seq = (
                    self._dispatch_seq, self._dispatch_seq + 1
                )
                self._fault_plan.before_dispatch(ordinal)
            segments, ofs = [], 0
            if len(batch) == 1:
                # single request: hand the caller's array straight to the
                # dispatch (exact fit never copies; the dispatch pads)
                payload = batch[0].rows
                segments.append((0, total, batch[0].tag))
            else:
                payload = np.empty(
                    (bucket,) + batch[0].rows.shape[1:], dtype=np.float32
                )
                for req in batch:
                    payload[ofs:ofs + req.n] = req.rows
                    segments.append((ofs, req.n, req.tag))
                    ofs += req.n
                payload[total:] = 0.0
            out = self._dispatch(payload, bucket, tuple(segments))
            if isinstance(out, tuple):
                out, info = out
            t1 = time.perf_counter()
            ofs = 0
            for req in batch:
                req.result = out[ofs:ofs + req.n]  # view — zero-copy scatter
                req.info = info
                ofs += req.n
        except Exception as e:  # a dying dispatch releases every coalesced
            # caller — each gets its OWN typed error with the batch context
            t1 = time.perf_counter()
            for req in batch:
                err = DispatchError(
                    f"coalesced dispatch failed (bucket {bucket}, {total} "
                    f"rows, {len(batch)} requests): "
                    f"{type(e).__name__}: {e}",
                    bucket=bucket, rows=total, requests=len(batch),
                )
                err.__cause__ = e
                req.error = err
        except BaseException as e:  # worker-killing fault (BatcherKilled,
            # interpreter teardown): release THIS batch, then let _run's
            # protector wedge the batcher and release the queued rest
            for req in batch:
                err = BatcherWedged(
                    "micro-batch worker died mid-dispatch"
                )
                err.__cause__ = e
                req.error = err
            with self._lock:
                for req in batch:
                    req.done = True
                self._done.notify_all()
            raise
        finally:
            if all(not req.done for req in batch):
                with self._lock:
                    for req in batch:
                        req.done = True
                    self._done.notify_all()
        device_ms = (t1 - t0) * 1e3
        queue_ms = [(t0 - req.t_enqueue) * 1e3 for req in batch]
        self._stats.record_dispatch(bucket, total, queue_ms, device_ms)
        if payload is not None and jaxmon.installed():
            # the dispatch just moved the coalesced payload host->device
            jaxmon.record_upload(payload.nbytes)
        trc = obs_trace.active_tracer()
        if trc is not None:
            # retroactive per-dispatch spans (generation-stamped): the
            # device window is honest — the dispatch materializes host
            # numpy (np.array readback) before t1 — and each coalesced
            # request contributes its own queue-wait span
            t_end = time.perf_counter()
            attrs = {"bucket": bucket, "rows": total,
                     "requests": len(batch), "gen": info}
            for req in batch:
                trc.record_span("serve.queue", req.t_enqueue, t0)
            trc.record_span("serve.device", t0, t1, attrs)
            trc.record_span("serve.scatter", t1, t_end, attrs)
