"""Dynamic micro-batching: coalesce pending requests into one dispatch.

A single worker thread owns the queue. The dispatch policy:

- **saturation** — the worker dispatches immediately when the pending
  prefix can no longer grow: it fills the top ladder rung exactly, or
  the next queued request would overflow it. Under sustained load the
  queue refills while the worker is inside a dispatch, so consecutive
  dispatches run back-to-back at full rungs with *zero* added delay
  (continuous batching) — which is why deployments size the top rung to
  their peak concurrency.
- **deadline** — an unsaturated queue waits for more arrivals until the
  oldest pending request has aged ``max_delay_ms``, then dispatches the
  longest queue prefix that fits the top rung, padded up to the smallest
  covering rung. A lone caller therefore pays at most ``max_delay_ms``;
  latency-critical single callers use ``ServingEngine.predict_direct``,
  which bypasses the queue entirely.
- requests are never split and never reordered.

Throughput discipline for one-core hosts: the submit side only wakes the
worker when it can act (first arrival starts the deadline clock,
saturation triggers a dispatch — intermediate arrivals just enqueue),
and results scatter back to callers as numpy *views* of the batched
output — zero-copy. A single request whose rows exactly fill a rung is
passed through to the dispatch without a pad copy at all.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from stmgcn_tpu.serving.bucketing import smallest_covering_bucket
from stmgcn_tpu.serving.metrics import EngineStats

__all__ = ["MicroBatcher"]


class _Request:
    __slots__ = ("rows", "n", "tag", "done", "result", "error", "t_enqueue")

    def __init__(self, rows: np.ndarray, tag):
        self.rows = rows
        self.n = rows.shape[0]
        self.tag = tag
        self.done = False
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_enqueue = time.perf_counter()


class MicroBatcher:
    """The request queue + worker behind :class:`ServingEngine.predict`.

    ``dispatch(payload, bucket, segments)`` runs the bucket's compiled
    program over the coalesced ``(bucket, ...)`` payload and returns the
    prediction array (host-side numpy). ``segments`` is a tuple of
    ``(offset, n_rows, tag)`` triples — one per coalesced request, in
    payload order — so the dispatch can apply per-request handling (the
    engine uses ``tag`` for pre-normalized inputs) while still running
    every expensive transform once per *batch*, not once per request.
    """

    def __init__(self, dispatch: Callable[[np.ndarray, int, tuple], np.ndarray],
                 buckets, max_delay_ms: float, stats: EngineStats):
        self._dispatch = dispatch
        self._buckets = tuple(sorted(buckets))
        self._cap = self._buckets[-1]
        self._max_delay_s = max_delay_ms / 1e3
        self._stats = stats
        # two condvars on ONE lock: submitters signal the worker on
        # _cond; the worker signals completions on _done (a per-request
        # Event would cost an allocation + an extra lock round-trip per
        # request — measurable at micro-batched request rates)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._done = threading.Condition(self._lock)
        self._pending: collections.deque = collections.deque()
        self._pending_rows = 0
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="stmgcn-microbatch", daemon=True
        )
        self._worker.start()

    def submit(self, rows: np.ndarray, tag=None) -> np.ndarray:
        """Enqueue one request and block until its predictions are ready."""
        if rows.shape[0] > self._cap:
            raise ValueError(
                f"request of {rows.shape[0]} rows exceeds the largest bucket "
                f"{self._cap} — the engine splits oversized batches before "
                "submitting"
            )
        req = _Request(rows, tag)
        with self._lock:
            if self._closed:
                raise RuntimeError("ServingEngine is closed")
            self._pending.append(req)
            self._pending_rows += req.n
            # wake the worker only when it can act: the first arrival
            # starts the deadline clock; saturation triggers a dispatch;
            # anything in between would be a wasted GIL hand-off
            if len(self._pending) == 1 or self._pending_rows >= self._cap:
                self._cond.notify_all()
            while not req.done:
                self._done.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def close(self) -> None:
        """Stop accepting requests, drain the queue, join the worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._worker.join()

    # -- worker side ----------------------------------------------------

    def _take_prefix(self) -> List[_Request]:
        batch: List[_Request] = []
        total = 0
        while self._pending and total + self._pending[0].n <= self._cap:
            req = self._pending.popleft()
            batch.append(req)
            total += req.n
        self._pending_rows -= total
        return batch

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    return  # closed and drained
                deadline = self._pending[0].t_enqueue + self._max_delay_s
                while not self._closed:
                    # saturated: the FIFO prefix cannot grow any further
                    # (>= cap means it either fills the top rung exactly
                    # or a queued request is too big to join) — waiting
                    # longer cannot improve this dispatch
                    if self._pending_rows >= self._cap:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = self._take_prefix()
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[_Request]) -> None:
        total = sum(req.n for req in batch)
        bucket = smallest_covering_bucket(total, self._buckets)
        t0 = time.perf_counter()
        try:
            segments, ofs = [], 0
            if len(batch) == 1:
                # single request: hand the caller's array straight to the
                # dispatch (exact fit never copies; the dispatch pads)
                payload = batch[0].rows
                segments.append((0, total, batch[0].tag))
            else:
                payload = np.empty(
                    (bucket,) + batch[0].rows.shape[1:], dtype=np.float32
                )
                for req in batch:
                    payload[ofs:ofs + req.n] = req.rows
                    segments.append((ofs, req.n, req.tag))
                    ofs += req.n
                payload[total:] = 0.0
            out = self._dispatch(payload, bucket, tuple(segments))
            t1 = time.perf_counter()
            ofs = 0
            for req in batch:
                req.result = out[ofs:ofs + req.n]  # view — zero-copy scatter
                ofs += req.n
        except BaseException as e:  # noqa: BLE001 — a dying dispatch must
            # release every coalesced caller, not leave them blocked
            t1 = time.perf_counter()
            for req in batch:
                req.error = e
        finally:
            with self._lock:
                for req in batch:
                    req.done = True
                self._done.notify_all()
        device_ms = (t1 - t0) * 1e3
        queue_ms = [(t0 - req.t_enqueue) * 1e3 for req in batch]
        self._stats.record_dispatch(bucket, total, queue_ms, device_ms)
