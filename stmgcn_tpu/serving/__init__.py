"""High-throughput serving: shared predict flow + the bucketed engine.

Layout:

- :mod:`.predict` — ``serve_predict``, the numpy-only normalize → call →
  denormalize flow shared by ``Forecaster``, ``ExportedForecaster`` and
  the engine (one implementation, so raw-units contracts cannot drift);
- :mod:`.bucketing` — shape-bucket arithmetic (covering rung, padding);
- :mod:`.admission` — :class:`AdmissionController` and the typed
  overload errors (``Overloaded``/``DeadlineExceeded`` sheds,
  ``DispatchError``, ``BatcherWedged``): SLO admission in front of the
  queue, so overload degrades operably instead of into unbounded p99;
- :mod:`.engine` — :class:`ServingEngine`: per-rung AOT programs with
  device-resident supports, hot-swappable params behind one atomic
  ``(generation, params)`` reference (``swap_params`` /
  ``watch_checkpoints``), built from a live forecaster or an export
  artifact;
- :mod:`.fleet` — :class:`FleetServingEngine`: a ``(city -> shape
  class)`` routing layer over per-class programs + micro-batchers, so
  one engine serves a whole heterogeneous fleet from one checkpoint and
  requests for different cities of a class coalesce;
- :mod:`.federation` — :class:`FederationRouter`: city→replica
  consistent hashing over M engine replicas with scatter/gather
  (per-city typed outcomes, never a hung caller), tier generation
  consistency, global admission via
  :class:`~stmgcn_tpu.serving.admission.GlobalBudget`, and the
  drain/re-shard/warm-spare lifecycle the ``serve-bench --federation``
  drills exercise;
- :mod:`.microbatch` — the request queue coalescing concurrent callers
  into one dispatch (exact-fit fast path, ``max_delay_ms`` deadline);
- :mod:`.metrics` — per-bucket p50/p95/p99 latency, queue-wait vs
  device-time split, pad-waste, throughput;
- :mod:`.bench` — ``stmgcn serve-bench`` and the bench.py serving leg
  (NOT imported here: it pulls the training stack for its throwaway
  checkpoint, and this package must stay lean enough for
  ``stmgcn_tpu.export`` — no flax, no models at import time).
"""

from stmgcn_tpu.serving.admission import (
    AdmissionController,
    BatcherWedged,
    DeadlineExceeded,
    DispatchError,
    GlobalBudget,
    Overloaded,
    ShedError,
)
from stmgcn_tpu.serving.bucketing import pad_to_bucket, smallest_covering_bucket
from stmgcn_tpu.serving.engine import (
    CheckpointWatcher,
    ServingEngine,
    serve_bucket_fn,
)
from stmgcn_tpu.serving.federation import (
    CityOutcome,
    FederationRouter,
    HashRing,
    ReplicaHandle,
    ReplicaUnavailable,
    ring_hash,
)
from stmgcn_tpu.serving.fleet import FleetServingEngine, fleet_bucket_fn
from stmgcn_tpu.serving.metrics import EngineStats
from stmgcn_tpu.serving.promotion import (
    GateDecision,
    PromotionGate,
    TierPromotionGate,
)
from stmgcn_tpu.serving.microbatch import MicroBatcher
from stmgcn_tpu.serving.predict import serve_predict

__all__ = [
    "AdmissionController",
    "BatcherWedged",
    "CheckpointWatcher",
    "CityOutcome",
    "DeadlineExceeded",
    "DispatchError",
    "EngineStats",
    "FederationRouter",
    "FleetServingEngine",
    "GateDecision",
    "GlobalBudget",
    "HashRing",
    "MicroBatcher",
    "Overloaded",
    "PromotionGate",
    "ReplicaHandle",
    "ReplicaUnavailable",
    "ServingEngine",
    "ShedError",
    "TierPromotionGate",
    "fleet_bucket_fn",
    "pad_to_bucket",
    "ring_hash",
    "serve_bucket_fn",
    "serve_predict",
    "smallest_covering_bucket",
]
