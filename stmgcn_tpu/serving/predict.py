"""Framework-free serving glue shared by live and exported predictors.

Deliberately imports nothing but numpy: :class:`stmgcn_tpu.export
.ExportedForecaster` promises to serve without the model stack (no flax,
no config machinery), and :class:`stmgcn_tpu.inference.Forecaster` pulls
the full framework — this module is the piece both can share so their
raw-units contracts cannot drift. :class:`stmgcn_tpu.serving.engine
.ServingEngine` implements the same validate → normalize → call →
denormalize contract with the normalization vectorized per coalesced
dispatch; bit-identity between the two flows is pinned in
tests/test_serving.py.
"""

from __future__ import annotations

import numpy as np

__all__ = ["serve_predict"]


def serve_predict(call, normalizer, expected, history, normalized: bool,
                  *, monitor=None, city: int = 0) -> np.ndarray:
    """Shared raw-units serving flow: validate → normalize → call →
    denormalize. ``expected`` is ``(seq_len, n_nodes, input_dim)``;
    ``call`` maps a normalized ``(B, T, N, C)`` array to predictions.

    ``monitor`` (a :class:`stmgcn_tpu.obs.drift.DriftMonitor`) observes
    at the two distribution boundaries: the normalized inputs the model
    actually sees, and the denormalized predictions it serves — the
    values never change, only their moments are recorded.
    """
    history = np.asarray(history, dtype=np.float32)
    if history.ndim != 4 or history.shape[1:] != tuple(expected):
        raise ValueError(
            f"history must be (B, seq_len={expected[0]}, n_nodes={expected[1]}, "
            f"n_feats={expected[2]}) for this model, got {history.shape}"
        )
    if not normalized and normalizer is not None:
        history = normalizer.transform(history)
    if monitor is not None:
        monitor.observe_input(city, history)
    pred = np.asarray(call(history))
    if normalizer is not None:
        pred = normalizer.inverse(pred)
    if monitor is not None:
        monitor.observe_prediction(city, pred)
    return pred
