"""Per-bucket serving telemetry: latency percentiles, queue/device split.

Every dispatch the engine makes — micro-batched or direct — lands here,
so a long-lived engine can answer the capacity-planning questions the
bucket ladder raises: which rungs actually fire, how much padding they
waste, and where a request's wall time goes (queue wait vs device time).
``snapshot()`` is what ``stmgcn serve-bench`` and the bench.py serving
leg publish.

Two changes from the original accumulator, shape-compatible with every
pinned ``snapshot()`` consumer:

- sample lists are bounded :class:`~stmgcn_tpu.obs.registry.Reservoir`
  rings (the old unbounded ``queue_ms``/``device_ms``/``latency_ms``
  lists grew forever in a long-lived engine) — percentiles come from the
  most recent ``reservoir`` samples per rung;
- scalar totals (dispatches / requests / rows, shed reasons) are
  registered in the process-wide :data:`~stmgcn_tpu.obs.registry
  .REGISTRY` under ``serving.*`` with an ``engine=<n>`` label, so soak
  records, the Prometheus exporter, and ``snapshot()`` all read the same
  counters instead of a private dict per engine.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List

import numpy as np

from stmgcn_tpu.obs.registry import REGISTRY, Reservoir

__all__ = ["EngineStats", "percentiles"]

#: bounded-window sample capacity per rung (see config.ObsConfig.reservoir)
DEFAULT_RESERVOIR = 1024

_ENGINE_IDS = itertools.count()


def percentiles(samples: List[float]) -> dict:
    """p50/p95/p99/mean of a millisecond sample list (None when empty)."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None, "mean": None}
    arr = np.asarray(samples, dtype=np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p95": round(float(np.percentile(arr, 95)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "mean": round(float(arr.mean()), 3),
    }


class _BucketStats:
    __slots__ = ("dispatches", "requests", "rows", "queue_ms", "device_ms",
                 "latency_ms")

    def __init__(self, reservoir: int):
        self.dispatches = 0
        self.requests = 0
        self.rows = 0
        self.queue_ms = Reservoir(capacity=reservoir)   # one sample/request
        self.device_ms = Reservoir(capacity=reservoir)  # one sample/dispatch
        self.latency_ms = Reservoir(capacity=reservoir)  # queue + device

    def reset(self) -> None:
        self.dispatches = self.requests = self.rows = 0
        self.queue_ms.reset()
        self.device_ms.reset()
        self.latency_ms.reset()


class EngineStats:
    """Thread-safe accumulator; the micro-batch worker and any number of
    direct-path callers record concurrently. ``reservoir`` bounds the
    per-rung sample windows (memory is O(buckets x reservoir) forever)."""

    def __init__(self, reservoir: int = DEFAULT_RESERVOIR):
        self._lock = threading.Lock()
        self._reservoir = reservoir
        self._buckets: Dict[int, _BucketStats] = {}
        self._t_first = None  # wall window over all dispatches, for
        self._t_last = None   # end-to-end throughput
        # scalar totals live in the shared registry, one label-set per
        # engine instance; shed counters are created per reason on first
        # use and remembered here for snapshot()/reset()
        self._labels = {"engine": str(next(_ENGINE_IDS))}
        self._c_dispatches = REGISTRY.counter("serving.dispatches",
                                              self._labels)
        self._c_requests = REGISTRY.counter("serving.requests", self._labels)
        self._c_rows = REGISTRY.counter("serving.rows", self._labels)
        self._shed: Dict[str, object] = {}

    def record_dispatch(self, bucket: int, rows: int, queue_ms: List[float],
                        device_ms: float) -> None:
        """One program dispatch: ``rows`` real rows in a ``bucket``-sized
        batch, ``queue_ms`` holding each coalesced request's queue wait."""
        now = time.perf_counter()
        with self._lock:
            bs = self._buckets.get(bucket)
            if bs is None:
                bs = self._buckets[bucket] = _BucketStats(self._reservoir)
            bs.dispatches += 1
            bs.requests += len(queue_ms)
            bs.rows += rows
            bs.device_ms.add(device_ms)
            bs.queue_ms.extend(queue_ms)
            bs.latency_ms.extend(q + device_ms for q in queue_ms)
            start = now - device_ms / 1e3
            if self._t_first is None or start < self._t_first:
                self._t_first = start
            if self._t_last is None or now > self._t_last:
                self._t_last = now
        self._c_dispatches.inc()
        self._c_requests.inc(len(queue_ms))
        self._c_rows.inc(rows)

    def record_shed(self, reason: str) -> None:
        """One admission-control rejection (``"overloaded"`` at the queue
        bound, ``"deadline"`` at the wait estimate or in-queue expiry)."""
        with self._lock:
            c = self._shed.get(reason)
            if c is None:
                c = self._shed[reason] = REGISTRY.counter(
                    "serving.shed", {**self._labels, "reason": reason}
                )
        c.inc()

    def device_ms_estimate(self, bucket: int, default: float = 0.0) -> float:
        """Measured mean device time per dispatch for ``bucket`` — the
        admission controller's wait model. Falls back to the mean over
        every rung, then to ``default``, while the rung is still cold."""
        with self._lock:
            bs = self._buckets.get(bucket)
            if bs is not None:
                samples = bs.device_ms.samples()
                if samples:
                    return float(np.mean(samples))
            samples = [
                v for b in self._buckets.values()
                for v in b.device_ms.samples()
            ]
        return float(np.mean(samples)) if samples else default

    def shed_counts(self) -> Dict[str, int]:
        """Registry-backed shed totals by reason (the soak record source)."""
        with self._lock:
            return {reason: int(c.value) for reason, c in self._shed.items()}

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._t_first = self._t_last = None
            for c in self._shed.values():
                c.reset()
            self._shed.clear()
        self._c_dispatches.reset()
        self._c_requests.reset()
        self._c_rows.reset()

    def snapshot(self) -> dict:
        """A JSON-ready view: per-bucket percentiles + engine totals.

        Totals are read from the shared registry counters (see
        MIGRATION.md); per-bucket sample stats come from the bounded
        reservoirs, i.e. the most recent ``reservoir`` samples per rung.
        """
        with self._lock:
            buckets = {
                b: (bs.dispatches, bs.requests, bs.rows,
                    bs.queue_ms.samples(), bs.device_ms.samples(),
                    bs.latency_ms.samples())
                for b, bs in self._buckets.items()
            }
            window = (
                self._t_last - self._t_first
                if self._t_first is not None and self._t_last > self._t_first
                else None
            )
            shed = {reason: int(c.value) for reason, c in self._shed.items()}
        out: dict = {"buckets": {}, "totals": {}}
        tot_capacity = 0
        all_queue: List[float] = []
        all_device: List[float] = []
        for b in sorted(buckets):
            dispatches, requests, rows, queue_ms, device_ms, latency_ms = buckets[b]
            capacity = dispatches * b
            out["buckets"][str(b)] = {
                "dispatches": dispatches,
                "requests": requests,
                "rows": rows,
                "pad_waste": round(1.0 - rows / capacity, 4) if capacity else 0.0,
                "latency_ms": percentiles(latency_ms),
                "queue_wait_ms": percentiles(queue_ms),
                "device_ms": percentiles(device_ms),
            }
            tot_capacity += capacity
            all_queue.extend(queue_ms)
            all_device.extend(device_ms)
        tot_rows = int(self._c_rows.value)
        out["totals"] = {
            "dispatches": int(self._c_dispatches.value),
            "requests": int(self._c_requests.value),
            "rows": tot_rows,
            "pad_waste": round(1.0 - tot_rows / tot_capacity, 4)
            if tot_capacity else 0.0,
            "queue_wait_ms_mean": percentiles(all_queue)["mean"],
            "device_ms_mean": percentiles(all_device)["mean"],
            "rows_per_sec": round(tot_rows / window, 1) if window else None,
            "shed": shed,
        }
        return out
