"""Per-bucket serving telemetry: latency percentiles, queue/device split.

Every dispatch the engine makes — micro-batched or direct — lands here,
so a long-lived engine can answer the capacity-planning questions the
bucket ladder raises: which rungs actually fire, how much padding they
waste, and where a request's wall time goes (queue wait vs device time).
``snapshot()`` is what ``stmgcn serve-bench`` and the bench.py serving
leg publish.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

import numpy as np

__all__ = ["EngineStats", "percentiles"]


def percentiles(samples: List[float]) -> dict:
    """p50/p95/p99/mean of a millisecond sample list (None when empty)."""
    if not samples:
        return {"p50": None, "p95": None, "p99": None, "mean": None}
    arr = np.asarray(samples, dtype=np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p95": round(float(np.percentile(arr, 95)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "mean": round(float(arr.mean()), 3),
    }


class _BucketStats:
    __slots__ = ("dispatches", "requests", "rows", "queue_ms", "device_ms",
                 "latency_ms")

    def __init__(self):
        self.dispatches = 0
        self.requests = 0
        self.rows = 0
        self.queue_ms: List[float] = []   # one sample per request
        self.device_ms: List[float] = []  # one sample per dispatch
        self.latency_ms: List[float] = []  # queue + device, per request


class EngineStats:
    """Thread-safe accumulator; the micro-batch worker and any number of
    direct-path callers record concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[int, _BucketStats] = {}
        self._t_first = None  # wall window over all dispatches, for
        self._t_last = None   # end-to-end throughput
        #: admission-control rejections by reason ("overloaded" /
        #: "deadline"); admitted = totals.requests
        self._shed: Dict[str, int] = {}

    def record_dispatch(self, bucket: int, rows: int, queue_ms: List[float],
                        device_ms: float) -> None:
        """One program dispatch: ``rows`` real rows in a ``bucket``-sized
        batch, ``queue_ms`` holding each coalesced request's queue wait."""
        now = time.perf_counter()
        with self._lock:
            bs = self._buckets.setdefault(bucket, _BucketStats())
            bs.dispatches += 1
            bs.requests += len(queue_ms)
            bs.rows += rows
            bs.device_ms.append(device_ms)
            bs.queue_ms.extend(queue_ms)
            bs.latency_ms.extend(q + device_ms for q in queue_ms)
            start = now - device_ms / 1e3
            if self._t_first is None or start < self._t_first:
                self._t_first = start
            if self._t_last is None or now > self._t_last:
                self._t_last = now

    def record_shed(self, reason: str) -> None:
        """One admission-control rejection (``"overloaded"`` at the queue
        bound, ``"deadline"`` at the wait estimate or in-queue expiry)."""
        with self._lock:
            self._shed[reason] = self._shed.get(reason, 0) + 1

    def device_ms_estimate(self, bucket: int, default: float = 0.0) -> float:
        """Measured mean device time per dispatch for ``bucket`` — the
        admission controller's wait model. Falls back to the mean over
        every rung, then to ``default``, while the rung is still cold."""
        with self._lock:
            bs = self._buckets.get(bucket)
            if bs is not None and bs.device_ms:
                return float(np.mean(bs.device_ms))
            samples = [v for b in self._buckets.values() for v in b.device_ms]
        return float(np.mean(samples)) if samples else default

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._t_first = self._t_last = None
            self._shed.clear()

    def snapshot(self) -> dict:
        """A JSON-ready view: per-bucket percentiles + engine totals."""
        with self._lock:
            buckets = {
                b: (bs.dispatches, bs.requests, bs.rows, list(bs.queue_ms),
                    list(bs.device_ms), list(bs.latency_ms))
                for b, bs in self._buckets.items()
            }
            window = (
                self._t_last - self._t_first
                if self._t_first is not None and self._t_last > self._t_first
                else None
            )
            shed = dict(self._shed)
        out: dict = {"buckets": {}, "totals": {}}
        tot_rows = tot_reqs = tot_disp = tot_capacity = 0
        all_queue: List[float] = []
        all_device: List[float] = []
        for b in sorted(buckets):
            dispatches, requests, rows, queue_ms, device_ms, latency_ms = buckets[b]
            capacity = dispatches * b
            out["buckets"][str(b)] = {
                "dispatches": dispatches,
                "requests": requests,
                "rows": rows,
                "pad_waste": round(1.0 - rows / capacity, 4) if capacity else 0.0,
                "latency_ms": percentiles(latency_ms),
                "queue_wait_ms": percentiles(queue_ms),
                "device_ms": percentiles(device_ms),
            }
            tot_rows += rows
            tot_reqs += requests
            tot_disp += dispatches
            tot_capacity += capacity
            all_queue.extend(queue_ms)
            all_device.extend(device_ms)
        out["totals"] = {
            "dispatches": tot_disp,
            "requests": tot_reqs,
            "rows": tot_rows,
            "pad_waste": round(1.0 - tot_rows / tot_capacity, 4)
            if tot_capacity else 0.0,
            "queue_wait_ms_mean": percentiles(all_queue)["mean"],
            "device_ms_mean": percentiles(all_device)["mean"],
            "rows_per_sec": round(tot_rows / window, 1) if window else None,
            "shed": shed,
        }
        return out
