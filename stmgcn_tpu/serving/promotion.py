"""Guarded checkpoint promotion: the serving end of the continual loop.

A continual learner that can promote a NaN checkpoint into the live
engine is worse than no learner at all — the failure mode of an
unattended loop is not a crashed daemon (bounded restarts cover that)
but a *successfully written* bad candidate. :class:`PromotionGate` is
the one door between the fine-tune daemon and the serving path: a
candidate checkpoint reaches ``ServingEngine.swap_params`` only after
passing, in order,

1. **integrity** — the file CRC/structure-verifies (a corrupt candidate
   write, torn or bit-flipped, is caught here, not by the watcher);
2. **nonfinite** — zero nonfinite grad/loss observations in the
   fine-tune health stream;
3. **grad-norm band** — the fine-tune's peak gradient norm within the
   configured bound;
4. **update-ratio band** — the peak ‖Δparam‖/‖param‖ within bound (an
   optimizer overwriting the model is drift, not learning);
5. **held-out eval** — candidate loss on the freshest held-out targets
   no worse than the live generation's by more than the configured
   relative margin.

Rejected candidates are quarantined in place as
``<name>.rejected-<reason>`` with a typed :class:`GateDecision`, and
the engine keeps serving the last good generation indefinitely —
degradation, not failure. Accepted candidates are rotated into the
watch directory (``latest.ckpt``) and applied through the existing
``CheckpointWatcher.poll()`` → atomic ``swap_params(...,
health_baseline=...)`` path, so promotion exercises exactly the
hot-swap machinery production uses.

Promotion-stage fault drills: the engine's
:class:`~stmgcn_tpu.resilience.ServeFaultPlan` gets its
``promotion-raise`` shot at the top of each gate evaluation; an
injected gate crash quarantines the candidate with reason
``"gate-error"`` rather than touching the serving path.

:class:`TierPromotionGate` lifts the same door to a federation of M
replicas: the candidate is evaluated **once** (one integrity read, one
held-out eval — not M), a rejection quarantines it **once** (the
rename happens before any replica's watcher could see the file), and
an acceptance is one rotation followed by a cutover poll on *every*
replica's watcher over the shared watch directory. A replica whose
poll fails is detached from the serving ring rather than left serving
the old generation — the tier never holds a mixed-generation active
set, and the router's gather-retry covers callers that race the
cutover window between polls.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Optional

import jax
import numpy as np

from stmgcn_tpu.obs import trace as obs_trace
from stmgcn_tpu.obs.registry import REGISTRY

__all__ = ["GateDecision", "PromotionGate", "TierPromotionGate"]


@dataclasses.dataclass(frozen=True)
class GateDecision:
    """Outcome of one gate evaluation. ``reason`` is ``"promoted"`` on
    acceptance, else the typed rejection: ``"corrupt"``,
    ``"nonfinite"``, ``"grad-norm"``, ``"update-ratio"``,
    ``"eval-regression"``, ``"swap-failed"``, or ``"gate-error"``
    (injected/unexpected gate crash). ``path`` is where the candidate
    ended up — the live ``latest.ckpt`` or its quarantine name."""

    accepted: bool
    reason: str
    ordinal: int
    path: str
    generation: int
    checks: dict


class PromotionGate:
    """Evaluate candidate checkpoints and promote survivors atomically.

    ``holdout_eval`` is ``callable(params) -> float`` scoring a raw
    params pytree on the freshest held-out targets (see
    ``stmgcn_tpu.train.continual.make_holdout_eval``); with it,
    ``live_params`` must carry the currently-serving raw params so the
    candidate has a baseline to beat. Without either, the eval check is
    skipped (the numeric checks still gate).
    """

    def __init__(self, engine, out_dir: str, *,
                 grad_norm_max: float = 1e3,
                 update_ratio_max: float = 0.5,
                 eval_margin: float = 0.05,
                 holdout_eval: Optional[Callable] = None,
                 live_params=None,
                 log=None, registry=None):
        self._engine = engine
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.grad_norm_max = float(grad_norm_max)
        self.update_ratio_max = float(update_ratio_max)
        self.eval_margin = float(eval_margin)
        self.holdout_eval = holdout_eval
        self._live_params = (
            None if live_params is None
            else jax.tree.map(np.asarray, live_params)
        )
        self._log = log if log is not None else (lambda msg: None)
        self._reg = REGISTRY if registry is None else registry
        # promotion rides the production hot-swap path: a passive
        # watcher the gate polls after rotating a survivor in
        self.watcher = engine.watch_checkpoints(out_dir)
        self.ordinal = 0
        self.promotions = 0
        self.rejections = 0
        self.decisions: list[GateDecision] = []

    @classmethod
    def from_config(cls, engine, out_dir: str, config, **kwargs) -> "PromotionGate":
        """Build with the bands of a :class:`~stmgcn_tpu.config
        .ContinualConfig`."""
        return cls(
            engine, out_dir,
            grad_norm_max=config.promote_grad_norm_max,
            update_ratio_max=config.promote_update_ratio_max,
            eval_margin=config.promote_eval_margin,
            **kwargs,
        )

    # ------------------------------------------------------------------

    def consider(self, candidate_path: str, health: dict) -> GateDecision:
        """Run the full gate on one candidate; promote or quarantine.

        ``health`` is the fine-tune's aggregated health summary
        (``nonfinite``, ``grad_norm_max``, ``update_ratio_max`` — what
        ``ContinualTrainer.finetune`` returns). Never raises on a bad
        candidate: every failure becomes a typed rejection and the
        engine keeps its current generation.
        """
        from stmgcn_tpu.resilience.faults import InjectedFault

        t0 = time.perf_counter()
        ordinal = self.ordinal
        self.ordinal += 1
        checks: dict = {}
        try:
            reason = self._evaluate(candidate_path, health, ordinal, checks)
        except InjectedFault as e:
            reason = "gate-error"
            checks["error"] = str(e)
        if reason is None:
            decision = self._promote(candidate_path, ordinal, checks)
        else:
            decision = self._reject(candidate_path, ordinal, reason, checks)
        t1 = time.perf_counter()
        self._reg.histogram("promotion.gate_ms").add((t1 - t0) * 1e3)
        trc = obs_trace.active_tracer()
        if trc is not None:
            trc.record_span("promotion.gate", t0, t1, {
                "ordinal": ordinal, "accepted": decision.accepted,
                "reason": decision.reason,
            })
        self.decisions.append(decision)
        return decision

    def _evaluate(self, path: str, health: dict, ordinal: int,
                  checks: dict) -> Optional[str]:
        """The check chain; returns the rejection reason or None."""
        from stmgcn_tpu.train.checkpoint import load_checkpoint, verify_checkpoint

        plan = getattr(self._engine, "_fault_plan", None)
        if plan is not None:
            plan.before_promotion(ordinal)
        try:
            verify_checkpoint(path)
        except (ValueError, OSError) as e:
            checks["corrupt"] = str(e)
            return "corrupt"
        nonfinite = int(health.get("nonfinite", 0))
        checks["nonfinite"] = nonfinite
        if nonfinite:
            return "nonfinite"
        grad_norm = float(health.get("grad_norm_max", 0.0))
        checks["grad_norm"] = (grad_norm, self.grad_norm_max)
        # NaN-safe: "within band" must hold, not "not above band"
        if not grad_norm <= self.grad_norm_max:
            return "grad-norm"
        ratio = float(health.get("update_ratio_max", 0.0))
        checks["update_ratio"] = (ratio, self.update_ratio_max)
        if not ratio <= self.update_ratio_max:
            return "update-ratio"
        if self.holdout_eval is not None and self._live_params is not None:
            _, params, _ = load_checkpoint(
                path, self._engine._params_template, None,
                load_opt_state=False,
            )
            cand = float(self.holdout_eval(params))
            live = float(self.holdout_eval(self._live_params))
            bound = live * (1.0 + self.eval_margin)
            checks["eval"] = (cand, live, bound)
            if not cand <= bound:
                return "eval-regression"
            checks["_params"] = params  # reuse for live baseline update
        return None

    def _promote(self, path: str, ordinal: int, checks: dict) -> GateDecision:
        latest = os.path.join(self.out_dir, "latest.ckpt")
        prev = os.path.join(self.out_dir, "latest.prev.ckpt")
        try:
            os.replace(latest, prev)
        except OSError:  # first promotion: nothing to rotate
            pass
        os.replace(path, latest)
        params = checks.pop("_params", None)
        if not self.watcher.poll():
            # the rotated-in file did not swap (e.g. raced quarantine) —
            # the engine is untouched, so report it as a rejection
            self._count_reject("swap-failed")
            self._log(f"promotion {ordinal}: rotated {latest} but the "
                      "watcher applied no swap")
            return GateDecision(False, "swap-failed", ordinal, latest,
                                self._engine.generation, checks)
        if params is not None:
            self._live_params = jax.tree.map(np.asarray, params)
        self.promotions += 1
        self._reg.counter("continual.promotions").inc()
        self._log(f"promotion {ordinal}: {latest} -> generation "
                  f"{self._engine.generation}")
        return GateDecision(True, "promoted", ordinal, latest,
                            self._engine.generation, checks)

    def _reject(self, path: str, ordinal: int, reason: str,
                checks: dict) -> GateDecision:
        checks.pop("_params", None)
        quarantined = f"{path}.rejected-{reason}"
        try:
            os.replace(path, quarantined)
        except OSError:
            quarantined = path  # nothing to move (already gone/torn)
        self._count_reject(reason)
        self._log(f"promotion {ordinal}: rejected ({reason}) — quarantined "
                  f"as {quarantined}")
        return GateDecision(False, reason, ordinal, quarantined,
                            self._engine.generation, checks)

    def _count_reject(self, reason: str) -> None:
        self.rejections += 1
        self._reg.counter("continual.rejections", {"reason": reason}).inc()


class TierPromotionGate(PromotionGate):
    """One promotion door for a whole replica tier.

    Built over a :class:`~stmgcn_tpu.serving.federation
    .FederationRouter`: every replica (active *and* warm spare — a
    spare promoted later must not time-travel) gets a checkpoint
    watcher over the same ``out_dir``, and the base gate's evaluation
    chain runs against one designated primary replica. The tier
    contract on top of the single-engine gate:

    - **evaluate once** — integrity/health/eval checks run once for
      the tier, not once per replica;
    - **quarantine once** — a rejected candidate is renamed away
      before any watcher could observe it, so a poisoned candidate
      costs one quarantine, not M;
    - **generation-consistent cutover** — acceptance rotates
      ``latest.ckpt`` once, then polls every live replica's watcher;
      a replica whose poll fails (torn read, wedged loop) is detached
      from the ring via :meth:`FederationRouter.detach` instead of
      serving the previous generation.

    A :class:`~stmgcn_tpu.resilience.FederationFaultPlan` attached to
    the router gets its ``poisoned-candidate`` shot (an at-rest byte
    flip) before evaluation — the drilled path *is* the integrity
    check.
    """

    def __init__(self, router, out_dir: str, **kwargs):
        engines = router.engines()
        if not engines:
            raise ValueError("TierPromotionGate needs at least one live replica")
        self.router = router
        self._primary_rid = next(iter(engines))
        super().__init__(engines[self._primary_rid], out_dir, **kwargs)
        # base __init__ already pointed the primary's watcher here
        self.watchers = {self._primary_rid: self.watcher}
        for rid, eng in engines.items():
            if rid != self._primary_rid:
                self.watchers[rid] = eng.watch_checkpoints(out_dir)
        self.detached: list[int] = []

    @classmethod
    def from_config(cls, router, out_dir: str, config, **kwargs) -> "TierPromotionGate":
        """Build with the bands of a :class:`~stmgcn_tpu.config
        .ContinualConfig` (mirrors :meth:`PromotionGate.from_config`)."""
        return cls(
            router, out_dir,
            grad_norm_max=config.promote_grad_norm_max,
            update_ratio_max=config.promote_update_ratio_max,
            eval_margin=config.promote_eval_margin,
            **kwargs,
        )

    def consider(self, candidate_path: str, health: dict) -> GateDecision:
        plan = getattr(self.router, "_fault_plan", None)
        if plan is not None:
            # at-rest poisoning lands *before* the integrity check — the
            # drill asserts the tier rejects it exactly once
            plan.poison_candidate(candidate_path)
        return super().consider(candidate_path, health)

    def _promote(self, path: str, ordinal: int, checks: dict) -> GateDecision:
        latest = os.path.join(self.out_dir, "latest.ckpt")
        prev = os.path.join(self.out_dir, "latest.prev.ckpt")
        try:
            os.replace(latest, prev)
        except OSError:  # first promotion: nothing to rotate
            pass
        os.replace(path, latest)
        params = checks.pop("_params", None)
        live = self.router.engines()  # killed/detached replicas skip cutover
        swapped, failed = [], []
        for rid in sorted(self.watchers):
            if rid not in live:
                continue
            if self.watchers[rid].poll():
                swapped.append(rid)
            else:
                failed.append(rid)
        if not swapped:
            # nothing cut over: every engine is untouched, report as the
            # base gate does for a single failed swap
            self._count_reject("swap-failed")
            self._log(f"tier promotion {ordinal}: rotated {latest} but no "
                      "replica applied a swap")
            return GateDecision(False, "swap-failed", ordinal, latest,
                                self._engine.generation, checks)
        for rid in failed:
            moved = self.router.detach(rid)
            self.detached.append(rid)
            self._log(f"tier promotion {ordinal}: replica {rid} missed the "
                      f"cutover — detached from the ring ({moved} cities "
                      "moved)")
        gens = {rid: live[rid].generation for rid in swapped}
        checks["tier"] = {"swapped": swapped, "failed": failed,
                          "generations": gens}
        if params is not None:
            self._live_params = jax.tree.map(np.asarray, params)
        self.promotions += 1
        self._reg.counter("continual.promotions").inc()
        generation = max(gens.values())
        self._log(f"tier promotion {ordinal}: {latest} -> generation "
                  f"{generation} on replicas {swapped}")
        return GateDecision(True, "promoted", ordinal, latest, generation,
                            checks)
