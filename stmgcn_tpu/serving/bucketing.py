"""Shape-bucket arithmetic: which AOT program serves a request batch.

The engine pre-compiles one program per ladder rung; every request batch
is padded up to the smallest rung that covers it. Padded rows are zeros
and provably inert — XLA's row-wise forward cannot mix batch rows, so
the real rows are bit-identical to an unpadded call (pinned by
``tests/test_serving.py``). The ladder itself is validated by
:meth:`stmgcn_tpu.config.ServingConfig.violations` (and statically by
the ``serving-bucket-shape`` analysis rule).
"""

from __future__ import annotations

import numpy as np

__all__ = ["pad_to_bucket", "smallest_covering_bucket"]


def smallest_covering_bucket(n: int, buckets) -> int:
    """The smallest ladder rung holding ``n`` rows (ladder is sorted)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(
        f"batch of {n} rows exceeds the largest bucket {buckets[-1]} — "
        "the caller must split oversized batches before bucketing"
    )


def pad_to_bucket(rows: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``rows`` along axis 0 up to ``bucket``.

    An exact fit returns ``rows`` itself — the zero-copy fast path the
    micro-batcher relies on for bucket-sized batches.
    """
    n = rows.shape[0]
    if n == bucket:
        return rows
    if n > bucket:
        raise ValueError(f"{n} rows cannot fit bucket {bucket}")
    padded = np.zeros((bucket,) + rows.shape[1:], dtype=rows.dtype)
    padded[:n] = rows
    return padded
