"""``stmgcn serve-bench``: before/after proof for the serving engine.

Measures the three generations of the inference path on one host:

- **naive** — ``Forecaster.predict`` / ``ExportedForecaster.predict``
  called per request (the r05 serving legs; jit dispatch + support
  re-upload per call — the path whose batch-16 throughput sat *below*
  batch-1);
- **engine (direct)** — :class:`~stmgcn_tpu.serving.engine.ServingEngine`
  bucket programs, no queue: pure AOT dispatch with resident operands;
- **engine (micro-batched)** — N concurrent batch-1 clients coalesced by
  the micro-batcher into bucket-sized dispatches.

Each timed leg reports mean/p50/p95/p99 latency and predictions/sec with
warmup excluded; the record carries the engine's per-bucket telemetry
(queue-wait vs device-time split, pad waste) and the two acceptance
ratios as ``speedup``. A fourth generation rides in ``record["fleet"]``:
one :class:`~stmgcn_tpu.serving.fleet.FleetServingEngine` serving a
two-city heterogeneous view of the same checkpoint
(:func:`fleet_forecaster`), with mixed-city concurrent clients whose
requests coalesce into shared dispatches (``cross_city_dispatches``)
and a per-city bit-parity spot check. ``--soak`` adds the overload leg
(:func:`run_soak_leg`, ``record["soak"]``): open-loop arrivals above the
host's calibrated capacity against an SLO-configured engine — typed shed
counts, admitted-request percentiles vs the derived SLO target, a
mid-soak atomic param hot-swap with per-generation bit parity, a
distribution-drift rider (shifted soak stream vs a calibration-fitted
baseline, generation-labeled gauges reset by the swap —
``record["soak"]["drift"]``), and a ``contended`` marker from
:mod:`stmgcn_tpu.utils.hostload`. Soak records also carry
``record["soak"]["continual"]``: the closed-loop continual drill
(:func:`stmgcn_tpu.train.continual.closed_loop_smoke` — live ring
ingest, a triggered fine-tune, one guarded promotion, one poisoned
candidate rejected as ``nonfinite`` while serving continues).
``--federation M`` adds the replica-tier soak (:func:`run_federation_soak`,
``record["federation"]``): M fleet replicas plus one warm spare behind a
:class:`~stmgcn_tpu.serving.federation.FederationRouter` under open-loop
multi-city scatter/gather load, drilled through four deterministic fault
legs — replica-kill mid-traffic (hash-ring heal, typed per-city errors,
zero hung callers), thundering-herd city spike against the shared
:class:`~stmgcn_tpu.serving.admission.GlobalBudget`, tier-wide poisoned
candidate rejection (quarantined once, not M times) followed by a
mid-soak tier-wide promotion with zero cross-generation responses, and
hang-on-drain + warm-spare re-shard under load with bounded handover.
Capacity is *measured* against the single-engine calibration
(``capacity_x``) with core count and host-load provenance in the record
— on a 1-core host the tier cannot multiply wall-clock compute, and the
record says so instead of pretending. NOT imported by
``stmgcn_tpu.serving.__init__`` — the throwaway-checkpoint trainer
pulls the full stack, and the serving package must stay lean for
``stmgcn_tpu.export``.

Default operating point is a 4x4 grid (N=16) with slim hidden dims and
the bucket ladder topped at the client count: the dispatch-dominated
regime where serving engines earn their keep (see
:func:`train_throwaway`), with the top rung sized to peak concurrency so
saturated dispatches run back-to-back. The shapes ride in the record,
so apples stay with apples across rounds.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from typing import List, Optional

import numpy as np

from stmgcn_tpu.serving.metrics import percentiles

__all__ = [
    "federation_forecaster",
    "fleet_forecaster",
    "main",
    "run_federation_soak",
    "run_fleet_serve_bench",
    "run_serve_bench",
    "run_soak_leg",
    "train_throwaway",
]


def _leg(samples_s: List[float], batch: int) -> dict:
    """One timed leg: per-call seconds -> latency stats + throughput."""
    mean_s = float(np.mean(samples_s))
    ms = [s * 1e3 for s in samples_s]
    pct = percentiles(ms)
    return {
        "ms": round(mean_s * 1e3, 3),
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(batch / mean_s, 1),
    }


def _timed(fn, warmup: int, iters: int) -> List[float]:
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return samples


def train_throwaway(rows: int = 4, epochs: int = 2, batch_size: int = 16,
                    out_dir: Optional[str] = None, slim: bool = True):
    """A 2-epoch throwaway checkpoint at the serve-bench operating point.

    Accuracy is irrelevant — only the compiled prediction path's
    wall-clock matters. ``slim`` keeps the full 3-branch ST-MGCN but
    shrinks the hidden dims so the forward is *dispatch*-dominated, the
    regime the engine exists for: on an accelerator per-row compute is
    microseconds and per-call overhead (trace, dispatch, host↔device
    churn) is what serving throughput dies on; a 1-core CPU host only
    reaches that regime with a small forward. ``slim=False`` measures
    the full-size model instead (compute-bound on CPU — every path
    flattens to memory bandwidth). Returns ``(forecaster, supports)``.
    """
    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_trainer
    from stmgcn_tpu.inference import Forecaster

    cfg = preset("default")
    cfg.data.rows = rows
    cfg.data.n_timesteps = 24 * 7 * 2 + 64
    cfg.train.epochs = epochs
    cfg.train.batch_size = batch_size
    tmp_ckpt_dir = None
    if out_dir is None:
        # throwaway means throwaway: the checkpoint dir exists only long
        # enough to round-trip the forecaster through from_checkpoint
        tmp_ckpt_dir = tempfile.mkdtemp(prefix="stmgcn_serve_")
        out_dir = tmp_ckpt_dir
    cfg.train.out_dir = out_dir
    if slim:
        cfg.model.lstm_hidden_dim = 8
        cfg.model.lstm_num_layers = 1
        cfg.model.gcn_hidden_dim = 8
    try:
        trainer = build_trainer(cfg, verbose=False)
        trainer.train()
        fc = Forecaster.from_checkpoint(os.path.join(out_dir, "best.ckpt"))
    finally:
        if tmp_ckpt_dir is not None:
            shutil.rmtree(tmp_ckpt_dir, ignore_errors=True)
    supports = np.asarray(
        cfg.model.support_config.build_all(trainer.dataset.adjs.values()),
        np.float32,
    )
    return fc, supports


def fleet_forecaster(fc, supports):
    """Lift the throwaway checkpoint into a two-city heterogeneous
    forecaster for the fleet leg: the trained 4x4 grid serves as city 0
    (N=16) and a fresh 2x7 grid (N=14) joins as city 1 — inside the
    default waste budget, so both land in ONE shape class and their
    requests can coalesce. The model's params are node-count agnostic
    (GCN weights contract feature dims, supports carry N), so one
    checkpoint legitimately serves both. Returns
    ``(hetero_fc, per_city_supports, n_nodes)``.
    """
    from stmgcn_tpu.data import MinMaxNormalizer, synthetic_dataset
    from stmgcn_tpu.inference import Forecaster
    from stmgcn_tpu.ops import SupportConfig

    cfg = fc.config
    m = cfg.model.m_graphs
    small = synthetic_dataset(rows=2, cols=7, n_timesteps=24 * 7 * 2 + 40,
                              seed=2)
    small_sup = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
            small.adjs.values()
        ),
        np.float32,
    )[:m]
    sups = [np.asarray(supports, np.float32)[:m], small_sup]
    n_nodes = [sups[0].shape[-1], sups[1].shape[-1]]
    normalizers = [
        fc.normalizer if fc.normalizer is not None
        else MinMaxNormalizer.fit(
            np.asarray(
                synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40,
                                  seed=1).demand
            )
        ),
        MinMaxNormalizer.fit(np.asarray(small.demand)),
    ]
    hetero = Forecaster(
        fc.model, fc.params, None, cfg,
        {"input_dim": fc.derived["input_dim"], "n_nodes": n_nodes},
        normalizers,
    )
    return hetero, sups, n_nodes


def _microbatch_leg(engine, history_row: np.ndarray, clients: int,
                    per_client: int) -> dict:
    """N concurrent batch-1 clients hammering ``engine.predict``."""
    # warmup outside the measured window (threads + first coalesced
    # dispatches), then reset telemetry so the snapshot is measurement-only
    for _ in range(2):
        engine.predict(history_row)
    engine.stats.reset()

    latencies_ms: List[float] = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client():
        mine = []
        barrier.wait()
        for _ in range(per_client):
            t0 = time.perf_counter()
            engine.predict(history_row)
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            latencies_ms.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(clients)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    total = clients * per_client
    pct = percentiles(latencies_ms)
    return {
        "clients": clients,
        "requests": total,
        "ms": pct["mean"],
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(total / elapsed, 1),
    }


def _fleet_microbatch_leg(engine, hists, clients: int,
                          per_client: int) -> dict:
    """N concurrent batch-1 clients split round-robin across the fleet's
    cities (``hists`` is ``[(history, city), ...]``), all hammering ONE
    engine — the coalescing a per-city engine cannot do. Reports the
    usual latency/throughput stats plus how many dispatches actually
    mixed cities in one device batch."""
    for h, c in hists:
        engine.predict(h, city=c)
    for st in engine.class_stats.values():
        st.reset()
    cross_before = engine.cross_city_dispatches

    latencies_ms: List[float] = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client(i: int):
        h, c = hists[i % len(hists)]
        mine = []
        barrier.wait()
        for _ in range(per_client):
            t0 = time.perf_counter()
            engine.predict(h, city=c)
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            latencies_ms.extend(mine)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(clients)
    ]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    total = clients * per_client
    pct = percentiles(latencies_ms)
    return {
        "clients": clients,
        "requests": total,
        "ms": pct["mean"],
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(total / elapsed, 1),
        "cross_city_dispatches": engine.cross_city_dispatches - cross_before,
    }


def run_fleet_serve_bench(fc, supports, *, buckets=(1, 4, 16),
                          max_delay_ms: float = 2.0, clients: int = 16,
                          per_client: int = 40, warmup: int = 3,
                          iters: int = 30) -> dict:
    """The fleet serving record: one :class:`FleetServingEngine` over a
    two-city heterogeneous view of the throwaway checkpoint
    (:func:`fleet_forecaster`), measured three ways — per-city naive
    ``Forecaster.predict`` alternating cities (the no-engine floor),
    direct per-city engine dispatch, and mixed-city concurrent clients
    whose requests coalesce across cities within the shape class. A
    per-city parity spot-check rides in the record so the throughput
    claim is pinned to bit-identical outputs."""
    from stmgcn_tpu.config import ServingConfig

    hetero, sups, n_nodes = fleet_forecaster(fc, supports)
    ladder = tuple(sorted(set(buckets)))
    cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=ladder[-1],
    )
    rng = np.random.default_rng(0)
    hists = [
        (
            (rng.random((1, hetero.seq_len, n, fc.derived["input_dim"]))
             * 50).astype(np.float32),
            city,
        )
        for city, n in enumerate(n_nodes)
    ]

    with hetero.fleet_engine(sups, config=cfg) as engine:
        parity = all(
            bool(
                np.array_equal(
                    hetero.predict(sups[c], h, city=c),
                    engine.predict_direct(h, city=c),
                )
            )
            for h, c in hists
        )

        legs = {}
        calls = {"i": 0}

        def naive_alternating():
            h, c = hists[calls["i"] % len(hists)]
            calls["i"] += 1
            hetero.predict(sups[c], h, city=c)

        legs["naive/b1-alternating"] = _leg(
            _timed(naive_alternating, warmup, iters), 1
        )

        def direct_alternating():
            h, c = hists[calls["i"] % len(hists)]
            calls["i"] += 1
            engine.predict_direct(h, city=c)

        legs["engine/b1-alternating"] = _leg(
            _timed(direct_alternating, warmup, iters), 1
        )
        legs["engine/microbatch-mixed-city"] = _fleet_microbatch_leg(
            engine, hists, clients, per_client
        )

        stats = {
            str(ci): st.snapshot()
            for ci, st in engine.class_stats.items()
        }
        plan = engine.plan
        record = {
            "cities": {
                "n_nodes": n_nodes,
                "class_of": [engine.class_of(c) for c in range(len(n_nodes))],
                "shape_classes": [
                    {
                        "n_nodes": cls.n_nodes,
                        "cities": list(cls.cities),
                        "node_waste": round(cls.node_waste, 4),
                    }
                    for cls in plan.classes
                ],
            },
            "buckets": list(ladder),
            "max_delay_ms": max_delay_ms,
            "parity": parity,
            "legs": legs,
            "engine_stats": stats,
            "speedup": {
                "microbatch_vs_naive_b1": round(
                    legs["engine/microbatch-mixed-city"][
                        "predictions_per_sec"
                    ]
                    / legs["naive/b1-alternating"]["predictions_per_sec"],
                    2,
                ),
            },
        }
    return record


def run_serve_bench(fc, supports, *, batch: int = 16, buckets=(1, 4, 16),
                    max_delay_ms: float = 2.0, clients: int = 16,
                    per_client: int = 40, warmup: int = 3, iters: int = 30,
                    artifact_path: Optional[str] = None) -> dict:
    """Measure every serving path over one forecaster. Returns the record
    body (``legs``/``engine_stats``/``speedup``/shape provenance)."""
    from stmgcn_tpu.config import ServingConfig
    from stmgcn_tpu.export import ExportedForecaster, export_forecaster
    from stmgcn_tpu.serving.engine import ServingEngine

    seq_len, n_nodes, input_dim = (
        fc.seq_len,
        fc.derived["n_nodes"],
        fc.derived["input_dim"],
    )
    rng = np.random.default_rng(0)
    hist = {
        b: (rng.random((b, seq_len, n_nodes, input_dim)) * 50).astype(np.float32)
        for b in (1, batch)
    }

    # an internal artifact dir lives exactly as long as the measurement:
    # the exported model must stay loadable through every timed leg, and
    # the dir must not outlive this call (it used to leak one mkdtemp per
    # bench run)
    tmp_artifact_dir = None
    if artifact_path is None:
        tmp_artifact_dir = tempfile.mkdtemp(prefix="stmgcn_serve_")
        artifact_path = os.path.join(tmp_artifact_dir, "model.stmgx")
    try:
        export_forecaster(fc, artifact_path)
        ex = ExportedForecaster.load(artifact_path)

        ladder = tuple(sorted(set(buckets)))
        cfg = ServingConfig(
            buckets=ladder, max_delay_ms=max_delay_ms, max_batch=ladder[-1],
        )
        engine = ServingEngine.from_forecaster(fc, supports, config=cfg)

        legs = {}
        for b in (1, batch):
            h = hist[b]
            legs[f"forecaster/b{b}"] = _leg(
                _timed(lambda h=h: fc.predict(supports, h), warmup, iters), b
            )
            legs[f"exported/b{b}"] = _leg(
                _timed(lambda h=h: ex.predict(supports, h), warmup, iters), b
            )
            legs[f"engine/b{b}"] = _leg(
                _timed(lambda h=h: engine.predict_direct(h), warmup, iters), b
            )
        legs[f"engine/microbatch{batch}"] = _microbatch_leg(
            engine, hist[1], clients, per_client
        )

        stats = engine.stats.snapshot()
        engine.close()
    finally:
        if tmp_artifact_dir is not None:
            shutil.rmtree(tmp_artifact_dir, ignore_errors=True)
    speedup = {
        # the r05 inversion check: engine batch-N rows/sec over batch-1
        "b16_vs_b1": round(
            legs[f"engine/b{batch}"]["predictions_per_sec"]
            / legs["engine/b1"]["predictions_per_sec"],
            2,
        ),
        # micro-batched concurrent throughput over the naive sequential path
        "microbatch_vs_sequential_b1": round(
            legs[f"engine/microbatch{batch}"]["predictions_per_sec"]
            / legs["forecaster/b1"]["predictions_per_sec"],
            2,
        ),
    }
    return {
        "shapes": {
            "n_nodes": n_nodes,
            "seq_len": seq_len,
            "input_dim": input_dim,
            "batch": batch,
            "buckets": list(cfg.buckets),
            "max_delay_ms": max_delay_ms,
        },
        "legs": legs,
        "engine_stats": stats,
        "speedup": speedup,
    }


def run_soak_leg(fc, supports, *, buckets=(1, 4, 16),
                 max_delay_ms: float = 2.0, soak_seconds: float = 2.0,
                 overload: float = 2.0, seed: int = 0) -> dict:
    """Overload soak: open-loop load above capacity against an SLO engine.

    The operability proof behind ``record["soak"]``:

    1. **calibrate** — measure the host's top-rung dispatch time on a
       throwaway engine; that sets capacity (rows/sec the device can
       actually drain) and derives the SLO from the host instead of a
       wall-clock constant (so the leg is meaningful on any machine).
    2. **soak** — an open-loop arrival schedule at ``overload``x capacity
       for ``soak_seconds``: arrivals fire on the clock whether or not
       earlier requests finished (what a real ingress does; a closed
       loop would politely self-throttle and never overload). Admitted
       requests record latency; sheds are counted by typed reason. No
       caller may hang — that's the zero-hung-callers claim.
    3. **hot-swap mid-soak** — halfway in, ``swap_params`` publishes a
       perturbed checkpoint under full load; responses carry their
       generation, and a bit-parity spot-check pins each generation's
       outputs to ``Forecaster.predict`` with the matching params.
    4. **distribution drift** — a :class:`~stmgcn_tpu.obs.drift
       .DriftMonitor` rides on the engine with a baseline fitted to the
       calibration traffic, while the soak stream is deliberately
       shifted (``x1.6 + 10``): the generation-labeled drift gauges must
       move under the shifted load (``record["drift"]["pre_swap"]``) and
       the mid-soak swap must reset them atomically (``post_swap`` shows
       the bumped generation and a fresh, smaller sample count).

    The record marks ``contended`` via :func:`stmgcn_tpu.utils.hostload
    .is_contended` — on a noisy host, judge ``slo_met`` accordingly.
    """
    import jax

    from stmgcn_tpu.config import ServingConfig
    from stmgcn_tpu.inference import Forecaster
    from stmgcn_tpu.obs import jaxmon
    from stmgcn_tpu.obs.drift import baseline_from_samples
    from stmgcn_tpu.obs.registry import REGISTRY
    from stmgcn_tpu.serving.admission import DeadlineExceeded, Overloaded
    from stmgcn_tpu.serving.engine import ServingEngine
    from stmgcn_tpu.utils.hostload import host_load_snapshot, is_contended

    ladder = tuple(sorted(set(buckets)))
    top = ladder[-1]
    seq_len, n_nodes, input_dim = (
        fc.seq_len, fc.derived["n_nodes"], fc.derived["input_dim"],
    )
    rng = np.random.default_rng(seed)
    h_req = (rng.random((top, seq_len, n_nodes, input_dim)) * 50).astype(
        np.float32
    )

    # -- 1. calibrate: top-rung dispatch time on THIS host --------------
    probe_cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=top,
    )
    with ServingEngine.from_forecaster(fc, supports, config=probe_cfg) as pr:
        for _ in range(3):
            pr.predict_direct(h_req)
        out_cal = pr.predict_direct(h_req)  # in-dist predictions for the
        t0 = time.perf_counter()            # drift baseline below
        n_probe = 10
        for _ in range(n_probe):
            pr.predict_direct(h_req)
        per_dispatch_ms = (time.perf_counter() - t0) * 1e3 / n_probe
    capacity_rps = top / (per_dispatch_ms / 1e3)

    # drift baseline fitted to the calibration-distribution traffic; the
    # soak stream below is shifted so the monitor has something to catch
    drift_bins = 32
    drift_baseline = {
        "schema_version": 1,
        "bins": drift_bins,
        "input": {"0": baseline_from_samples(
            h_req.reshape(-1, input_dim), bins=drift_bins
        )},
        "prediction": {"0": baseline_from_samples(
            np.asarray(out_cal, np.float32).reshape(-1, input_dim),
            bins=drift_bins,
        )},
    }
    h_soak = (h_req * 1.6 + 10.0).astype(np.float32)

    # SLO derived from the measured floor: tolerate a queue ~5 dispatches
    # deep (the queue bound sheds Overloaded first at 4), then shed on
    # estimated wait / in-queue expiry. End-to-end target = the deadline
    # an admitted request may burn in queue + its own dispatch, with
    # host-jitter headroom.
    deadline_ms = 6.0 * per_dispatch_ms + 4.0 * max_delay_ms
    queue_bound_rows = 4 * top
    slo_target_ms = deadline_ms + 3.0 * per_dispatch_ms
    cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=top,
        deadline_ms=deadline_ms, queue_bound_rows=queue_bound_rows,
    )

    # open-loop schedule: batch-`top` requests (one dispatch each) at
    # overload x the calibrated dispatch rate, for the wall budget
    interval_s = (per_dispatch_ms / 1e3) / overload
    n_arrivals = min(int(soak_seconds / interval_s), 2000)
    # enough clients that the schedule stays open-loop even when every
    # request rides out the full deadline before returning
    worst_s = (deadline_ms + 2.0 * per_dispatch_ms) / 1e3
    clients = min(64, max(8, int(worst_s / interval_s) + 4))

    load_before = host_load_snapshot()
    admitted_ms: List[float] = []
    gen_counts: dict = {}
    shed_local = {"overloaded": 0, "deadline": 0}
    behind_schedule = [0]
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)
    t_start = [0.0]

    swaps_before = REGISTRY.counter("serving.swaps").value
    engine = ServingEngine.from_forecaster(fc, supports, config=cfg)
    try:
        base = fc.predict(supports, h_req)
        parity_gen0 = bool(np.array_equal(base, engine.predict_direct(h_req)))
        # arm drift AFTER the parity probe so the sketches hold only the
        # (shifted) soak stream; the swap below must reset them
        engine.enable_drift(drift_baseline, city=0)
        drift_pre: List[dict] = []

        new_params = jax.tree.map(lambda a: a * 1.001, fc.params)
        fc_new = Forecaster(
            fc.model, new_params, fc.normalizer, fc.config, fc.derived,
            getattr(fc, "normalizers", None),
        )
        if jaxmon.installed():
            # engine bucket programs are AOT-built and probed, and the
            # swap payload is materialized: any compile DURING the soak
            # (including across the hot-swap) is a serving incident the
            # gauge must surface
            jaxmon.mark_warmup_complete()

        def client(i: int):
            my_admitted, my_gens = [], {}
            my_shed = {"overloaded": 0, "deadline": 0}
            my_behind = 0
            barrier.wait()
            for k in range(i, n_arrivals, clients):
                delay = t_start[0] + k * interval_s - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                else:
                    my_behind += 1  # fired late but still fired: open loop
                t0 = time.perf_counter()
                try:
                    _, gen = engine.predict(h_soak, with_generation=True)
                    my_admitted.append((time.perf_counter() - t0) * 1e3)
                    my_gens[gen] = my_gens.get(gen, 0) + 1
                except Overloaded:
                    my_shed["overloaded"] += 1
                except DeadlineExceeded:
                    my_shed["deadline"] += 1
            with lock:
                admitted_ms.extend(my_admitted)
                for g, c in my_gens.items():
                    gen_counts[g] = gen_counts.get(g, 0) + c
                for r in my_shed:
                    shed_local[r] += my_shed[r]
                behind_schedule[0] += my_behind

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(clients)
        ]
        for th in threads:
            th.start()
        swap_done = threading.Event()
        swap_error: List[str] = []

        def mid_soak_swap():
            try:
                # the drift sketches as the shifted stream left them,
                # captured the instant before the swap resets them
                drift_pre.append(engine.drift_snapshot())
                engine.swap_params(new_params)
                swap_done.set()
            except Exception as e:  # a failed swap must land in the record,
                # not vanish with the timer thread
                swap_error.append(f"{type(e).__name__}: {e}")

        swapper = threading.Timer(
            max(0.05, n_arrivals * interval_s / 2.0), mid_soak_swap
        )
        barrier.wait()
        t_start[0] = time.perf_counter()
        swapper.start()
        deadline_join = time.monotonic() + 60.0
        for th in threads:
            th.join(timeout=max(0.0, deadline_join - time.monotonic()))
        hung = sum(th.is_alive() for th in threads)
        swapper.join()
        recompiles_soak = (
            int(jaxmon.freeze_recompiles()) if jaxmon.installed() else None
        )
        # post-swap drift state BEFORE the parity probe below feeds the
        # gen-1 sketches in-dist rows: must show the bumped generation
        # and only post-swap soak traffic
        drift_post = engine.drift_snapshot()
        # generation-1 parity after the dust settles: the engine now
        # serves the swapped params and must match a Forecaster built
        # from them bit-exactly
        parity_gen1 = bool(
            np.array_equal(fc_new.predict(supports, h_req),
                           engine.predict_direct(h_req))
        )
        stats = engine.stats.snapshot()
        generation_after = engine.generation
        # shed/degrade/swap counts read back from the process-wide
        # metrics registry (stmgcn_tpu.obs.registry) — the same counters
        # a metrics endpoint would scrape, cross-checkable against the
        # client-side tallies above
        registry_counts = {
            "shed": engine.stats.shed_counts(),
            "swaps": int(
                REGISTRY.counter("serving.swaps").value - swaps_before
            ),
            "generation": int(REGISTRY.gauge("serving.generation").value),
        }
        if recompiles_soak is not None:
            registry_counts["recompiles_during_soak"] = recompiles_soak
    finally:
        engine.close()
    load_after = host_load_snapshot()

    pct = percentiles(admitted_ms)
    host_load = {"before": load_before, "after": load_after}
    return {
        "calibration": {
            "per_dispatch_ms": round(per_dispatch_ms, 3),
            "capacity_rows_per_sec": round(capacity_rps, 1),
        },
        "config": {
            "buckets": list(ladder),
            "max_delay_ms": max_delay_ms,
            "deadline_ms": round(deadline_ms, 3),
            "queue_bound_rows": queue_bound_rows,
            "overload": overload,
            "soak_seconds": soak_seconds,
            "clients": clients,
            "request_rows": top,
            "offered_requests": n_arrivals,
            "offered_rows_per_sec": round(overload * capacity_rps, 1),
        },
        "admitted": len(admitted_ms),
        "shed": shed_local,
        "shed_recorded": stats["totals"]["shed"],
        "registry": registry_counts,
        "behind_schedule": behind_schedule[0],
        "admitted_latency_ms": pct,
        "slo_target_ms": round(slo_target_ms, 3),
        "slo_met": (
            pct["p99"] is not None and pct["p99"] <= slo_target_ms
        ),
        "hung_clients": hung,
        "hot_swap": {
            "swap_applied": swap_done.is_set(),
            "swap_error": swap_error[0] if swap_error else None,
            "generation_after": generation_after,
            "responses_by_generation": {
                str(g): c for g, c in sorted(gen_counts.items())
            },
            "parity_gen0": parity_gen0,
            "parity_gen1": parity_gen1,
        },
        "drift": {
            "bins": drift_bins,
            "stream_shift": "x1.6 + 10",
            "pre_swap": drift_pre[0] if drift_pre else None,
            "post_swap": drift_post,
        },
        "host_load": host_load,
        "contended": is_contended(host_load),
    }


def federation_forecaster(fc, supports, n_cities: int = 8):
    """Lift the throwaway checkpoint into a C-city *homogeneous* fleet
    view for the federation tier: every city is the trained 4x4 grid, so
    all land in one shape class, any replica can serve any city (ring
    ownership is routing policy, not capability — a re-shard never
    rebuilds an engine), and same-class requests coalesce. Returns
    ``(hetero_fc, per_city_supports, n_nodes)``."""
    from stmgcn_tpu.data import MinMaxNormalizer, synthetic_dataset
    from stmgcn_tpu.inference import Forecaster

    cfg = fc.config
    m = cfg.model.m_graphs
    sup = np.asarray(supports, np.float32)[:m]
    norm = (
        fc.normalizer if fc.normalizer is not None
        else MinMaxNormalizer.fit(
            np.asarray(
                synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40,
                                  seed=1).demand
            )
        )
    )
    hetero = Forecaster(
        fc.model, fc.params, None, cfg,
        {"input_dim": fc.derived["input_dim"],
         "n_nodes": [sup.shape[-1]] * n_cities},
        [norm] * n_cities,
    )
    return hetero, [sup] * n_cities, [sup.shape[-1]] * n_cities


def run_federation_soak(fc, supports, *, replicas: int = 4,
                        n_cities: int = 0, buckets=(1, 4, 16),
                        max_delay_ms: float = 2.0,
                        soak_seconds: float = 2.0, overload: float = 2.0,
                        seed: int = 0) -> dict:
    """The federation tier under open-loop load + four fault drills.

    Builds ``replicas`` fleet engines plus one warm spare over a C-city
    homogeneous view (:func:`federation_forecaster`; C defaults to
    ``max(2 * replicas, 4)`` so the ``federation-config`` topology rule
    holds), shares one :class:`GlobalBudget` across every replica's
    admission controller, and routes multi-city scatter/gather requests
    through a :class:`FederationRouter`. The drills, all driven by one
    deterministic :class:`~stmgcn_tpu.resilience.FederationFaultPlan`:

    1. **tier-wide rejection** (pre-soak) — a candidate checkpoint is
       byte-poisoned at rest; the :class:`TierPromotionGate` must
       quarantine it exactly once (one rename, one rejection count),
       with every replica untouched.
    2. **replica-kill mid-traffic** — at a scheduled scatter ordinal a
       replica is hard-killed; its cities re-shard away on the hash
       ring, affected in-flight cities come back as *typed* errors,
       and no caller hangs.
    3. **thundering-herd** — a scheduled burst hammers one city; local
       queue bounds and the tier-wide budget shed typed ``Overloaded``
       (reason ``tier-overloaded`` for global sheds), p99 of admitted
       work stays bounded by the derived SLO.
    4. **drain + re-shard under load** (post-soak, traffic still
       offered) — a replica with a hang-on-drain fault drains within
       its timeout (the hang is *bounded*, not waited out), and the
       warm spare is promoted into the ring mid-burst with a bounded
       handover and zero cross-generation responses.

    Mid-soak, a *good* candidate goes through the tier gate: every live
    replica cuts to the new generation and the router's gather contract
    keeps every multi-city response single-generation
    (``cross_generation`` must be 0). Capacity is reported as measured
    tier throughput over the calibrated single-engine rate
    (``capacity_x``) with ``n_cores`` and host-load provenance — wall
    -clock honesty on shared hosts.
    """
    import jax

    from stmgcn_tpu.config import FederationConfig, ServingConfig
    from stmgcn_tpu.resilience.faults import (
        FederationFaultPlan,
        FederationFaultSpec,
    )
    from stmgcn_tpu.serving.admission import GlobalBudget, ShedError
    from stmgcn_tpu.serving.federation import (
        FederationRouter,
        ReplicaUnavailable,
    )
    from stmgcn_tpu.serving.fleet import FleetServingEngine
    from stmgcn_tpu.serving.promotion import TierPromotionGate
    from stmgcn_tpu.train.checkpoint import save_checkpoint
    from stmgcn_tpu.utils.hostload import host_load_snapshot, is_contended

    if n_cities <= 0:
        n_cities = max(2 * replicas, 4)
    hetero, sups, n_nodes = federation_forecaster(fc, supports, n_cities)
    ladder = tuple(sorted(set(buckets)))
    top = ladder[-1]
    seq_len = hetero.seq_len
    input_dim = fc.derived["input_dim"]
    rng = np.random.default_rng(seed)
    hists = {
        c: (rng.random((1, seq_len, n_nodes[c], input_dim)) * 50).astype(
            np.float32
        )
        for c in range(n_cities)
    }

    # -- calibrate: single-engine batch-1 rate on THIS host -------------
    probe_cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=top,
    )
    with FleetServingEngine.from_forecaster(
        hetero, sups, config=probe_cfg
    ) as probe:
        for _ in range(3):
            probe.predict_direct(hists[0], city=0)
        t0 = time.perf_counter()
        n_probe = 10
        for _ in range(n_probe):
            probe.predict_direct(hists[0], city=0)
        per_dispatch_ms = (time.perf_counter() - t0) * 1e3 / n_probe
    single_rps = 1e3 / per_dispatch_ms  # batch-1 predictions/sec

    # SLO + budgets derived from the measured floor (same discipline as
    # run_soak_leg); the tier budget sits above any single replica's
    # local bound so the federation-config ordering contract holds
    deadline_ms = 6.0 * per_dispatch_ms + 4.0 * max_delay_ms
    queue_bound_rows = 4 * top
    global_bound_rows = 2 * queue_bound_rows
    cities_per_request = min(3, n_cities)
    slo_target_ms = cities_per_request * (deadline_ms + 3.0 * per_dispatch_ms)
    slo_cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=top,
        deadline_ms=deadline_ms, queue_bound_rows=queue_bound_rows,
    )
    fed_cfg = FederationConfig(
        enabled=True, replicas=replicas, spares=1,
        global_queue_bound_rows=global_bound_rows,
    )
    config_findings = fed_cfg.violations(serving=slo_cfg, n_cities=n_cities)

    # open-loop schedule: multi-city requests at overload x the rate one
    # engine could serve them sequentially
    interval_s = cities_per_request * (per_dispatch_ms / 1e3) / overload
    n_arrivals = max(12, min(int(soak_seconds / interval_s), 600))
    clients = min(32, max(6, int(
        (cities_per_request * (deadline_ms + 2.0 * per_dispatch_ms) / 1e3)
        / interval_s
    ) + 4))

    # the drill schedule, all in one deterministic plan
    kill_rid = min(2, replicas - 1)
    drain_rid = 1 if replicas > 1 else 0
    spare_rid = replicas  # the warm spare's id in the router
    kill_ordinal = max(2, n_arrivals // 3)
    herd_city = 0
    herd_burst_n = 4 * clients
    herd_ordinal = max(kill_ordinal + 2, (2 * n_arrivals) // 3)
    plan = FederationFaultPlan(
        FederationFaultSpec(kind="poisoned-candidate",
                            path_glob="candidate-0.ckpt"),
        FederationFaultSpec(kind="replica-kill", replica=kill_rid,
                            dispatch=kill_ordinal),
        FederationFaultSpec(kind="herd-spike", city=herd_city,
                            dispatch=herd_ordinal, burst=herd_burst_n),
        FederationFaultSpec(kind="hang-on-drain", replica=drain_rid,
                            hang_ms=80.0),
    )

    load_before = host_load_snapshot()
    budget = GlobalBudget(global_bound_rows)
    engines = [
        FleetServingEngine.from_forecaster(
            hetero, sups, config=slo_cfg, global_budget=budget
        )
        for _ in range(replicas)
    ]
    spare = FleetServingEngine.from_forecaster(
        hetero, sups, config=slo_cfg, global_budget=budget
    )
    router = FederationRouter(
        engines, range(n_cities), config=fed_cfg, spare_engines=[spare],
        global_budget=budget, fault_plan=plan,
    )
    record: dict = {}
    with tempfile.TemporaryDirectory(prefix="stmgcn_fed_") as tmp:
        watch_dir = os.path.join(tmp, "watch")
        stage_dir = os.path.join(tmp, "stage")
        os.makedirs(stage_dir)
        gate = TierPromotionGate(router, watch_dir)
        clean_health = {
            "nonfinite": 0, "grad_norm_max": 1.0, "update_ratio_max": 0.01,
        }
        try:
            # -- drill 1: tier-wide rejection of a poisoned candidate --
            poisoned = os.path.join(stage_dir, "candidate-0.ckpt")
            save_checkpoint(poisoned, fc.params, {}, {"drill": "poison"})
            decision_bad = gate.consider(poisoned, clean_health)
            tier_rejection = {
                "reason": decision_bad.reason,
                "accepted": decision_bad.accepted,
                "quarantined_path": os.path.basename(decision_bad.path),
                # the gate ran once for the whole tier: one rejection,
                # one quarantine rename — not one per replica
                "rejections_counted": gate.rejections,
                "generations_untouched": all(
                    e.generation == 0 for e in router.engines().values()
                ),
            }

            # -- soak: open-loop multi-city scatter/gather -------------
            good = os.path.join(stage_dir, "candidate-1.ckpt")
            new_params = jax.tree.map(lambda a: a * 1.001, fc.params)
            save_checkpoint(good, new_params, {}, {"drill": "promote"})

            req_ms: List[float] = []
            outcome_counts = {"ok": 0}
            cross_generation = [0]
            herd_stats = {"extra_ok": 0, "extra_shed": 0}
            behind = [0]
            ok_predictions = [0]
            lock = threading.Lock()
            barrier = threading.Barrier(clients + 1)
            t_start = [0.0]
            promote_result: List[object] = []

            def one_request(k: int):
                cities_k = [
                    (k * cities_per_request + j) % n_cities
                    for j in range(cities_per_request)
                ]
                t0 = time.perf_counter()
                outcomes = router.predict_many(
                    {c: hists[c] for c in cities_k}
                )
                dt_ms = (time.perf_counter() - t0) * 1e3
                gens = set()
                counts: dict = {}
                n_ok = 0
                for o in outcomes.values():
                    if o.ok:
                        n_ok += 1
                        gens.add(o.generation)
                    else:
                        key = type(o.error).__name__
                        counts[key] = counts.get(key, 0) + 1
                mixed = len(gens) > 1
                return dt_ms, n_ok, counts, mixed

            def client(i: int):
                mine_ms, mine_counts = [], {}
                mine_ok = mine_mixed = mine_behind = 0
                herd_ok = herd_shed = 0
                barrier.wait()
                for k in range(i, n_arrivals, clients):
                    delay = t_start[0] + k * interval_s - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    else:
                        mine_behind += 1  # late but fired: open loop
                    for city, burst in plan.herd_burst(k):
                        # the herd drill: a synchronized spike of extra
                        # single-city arrivals on top of the schedule
                        for _ in range(burst // clients + 1):
                            try:
                                router.predict(hists[city], city=city)
                                herd_ok += 1
                            except ShedError:
                                herd_shed += 1
                    dt_ms, n_ok, counts, mixed = one_request(k)
                    mine_ms.append(dt_ms)
                    mine_ok += n_ok
                    mine_mixed += int(mixed)
                    for key, n in counts.items():
                        mine_counts[key] = mine_counts.get(key, 0) + n
                with lock:
                    req_ms.extend(mine_ms)
                    ok_predictions[0] += mine_ok
                    cross_generation[0] += mine_mixed
                    behind[0] += mine_behind
                    herd_stats["extra_ok"] += herd_ok
                    herd_stats["extra_shed"] += herd_shed
                    for key, n in mine_counts.items():
                        outcome_counts[key] = outcome_counts.get(key, 0) + n

            def mid_soak_promotion():
                try:
                    promote_result.append(gate.consider(good, clean_health))
                except Exception as e:  # must land in the record, not die
                    # silently with the timer thread
                    promote_result.append(f"{type(e).__name__}: {e}")

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(clients)
            ]
            for th in threads:
                th.start()
            promoter = threading.Timer(
                max(0.05, n_arrivals * interval_s / 2.0), mid_soak_promotion
            )
            barrier.wait()
            t_start[0] = time.perf_counter()
            promoter.start()
            t_soak0 = time.perf_counter()
            deadline_join = time.monotonic() + 60.0
            for th in threads:
                th.join(timeout=max(0.0, deadline_join - time.monotonic()))
            hung = sum(th.is_alive() for th in threads)
            promoter.join()
            soak_elapsed = time.perf_counter() - t_soak0
            outcome_counts["ok"] = ok_predictions[0]
            tier_rps = ok_predictions[0] / soak_elapsed

            # -- drill 4: hang-on-drain, then warm spare under load ----
            drain_report = router.drain(drain_rid)
            burst_errors = {"ok": 0}
            burst_mixed = [0]

            def reshard_burst(i: int):
                for k in range(6):
                    dt_ms, n_ok, counts, mixed = one_request(
                        n_arrivals + i * 6 + k
                    )
                    with lock:
                        burst_errors["ok"] += n_ok
                        burst_mixed[0] += int(mixed)
                        for key, n in counts.items():
                            burst_errors[key] = burst_errors.get(key, 0) + n

            burst_threads = [
                threading.Thread(target=reshard_burst, args=(i,))
                for i in range(4)
            ]
            for th in burst_threads:
                th.start()
            promote_report = router.promote_spare(spare_rid)
            for th in burst_threads:
                th.join(30.0)
            hung += sum(th.is_alive() for th in burst_threads)

            # recovery: after kill + drain + re-shard, every city must
            # still be served by some live replica
            recovered = 0
            for c in range(n_cities):
                try:
                    router.predict(hists[c], city=c)
                    recovered += 1
                except ReplicaUnavailable:
                    pass  # no live owner: the drill failed to heal
                except ShedError:
                    recovered += 1  # shed on load is still a live owner
            gens_after = {
                str(rid): eng.generation
                for rid, eng in router.engines().items()
            }

            pct = percentiles(req_ms)
            record = {
                "config": {
                    "replicas": replicas,
                    "spares": 1,
                    "cities": n_cities,
                    "vnodes": fed_cfg.vnodes,
                    "buckets": list(ladder),
                    "max_delay_ms": max_delay_ms,
                    "deadline_ms": round(deadline_ms, 3),
                    "queue_bound_rows": queue_bound_rows,
                    "global_queue_bound_rows": global_bound_rows,
                    "overload": overload,
                    "soak_seconds": soak_seconds,
                    "clients": clients,
                    "cities_per_request": cities_per_request,
                    "offered_requests": n_arrivals,
                },
                "config_findings": config_findings,
                "calibration": {
                    "per_dispatch_ms": round(per_dispatch_ms, 3),
                    "single_engine_rps": round(single_rps, 1),
                },
                "capacity": {
                    "tier_rps": round(tier_rps, 1),
                    "capacity_x": round(tier_rps / single_rps, 2),
                    "n_cores": os.cpu_count(),
                },
                "soak": {
                    "offered": n_arrivals,
                    "outcomes": outcome_counts,
                    "cross_generation": cross_generation[0],
                    "hung_clients": hung,
                    "behind_schedule": behind[0],
                    "request_latency_ms": pct,
                    "slo_target_ms": round(slo_target_ms, 3),
                    "slo_met": (
                        pct["p99"] is not None and pct["p99"] <= slo_target_ms
                    ),
                },
                "drills": {
                    "tier_rejection": tier_rejection,
                    "replica_kill": {
                        "replica": kill_rid,
                        "ordinal": kill_ordinal,
                        "kills": router.kills,
                        "cities_moved": router.cities_moved,
                    },
                    "herd": {
                        "city": herd_city,
                        "burst": herd_burst_n,
                        **herd_stats,
                        "tier_shed": budget.snapshot()["refused"],
                    },
                    "drain": drain_report,
                    "reshard_promote": {
                        **promote_report,
                        "burst_outcomes": burst_errors,
                        "burst_cross_generation": burst_mixed[0],
                    },
                },
                "promotion": {
                    "mid_soak": (
                        {
                            "accepted": promote_result[0].accepted,
                            "reason": promote_result[0].reason,
                            "generation": promote_result[0].generation,
                        }
                        if promote_result and not isinstance(
                            promote_result[0], str
                        )
                        else (promote_result[0] if promote_result else None)
                    ),
                    "generations_after": gens_after,
                    "detached_on_cutover": list(gate.detached),
                },
                "recovery": {
                    "cities_serveable": recovered,
                    "cities_total": n_cities,
                },
                "budget": budget.snapshot(),
                "router": router.health(),
            }
        finally:
            router.close()
    load_after = host_load_snapshot()
    record["host_load"] = {"before": load_before, "after": load_after}
    record["contended"] = is_contended(record["host_load"])
    return record


def build_serve_bench_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn serve-bench",
        description="serving-engine benchmark: naive vs AOT-bucketed vs "
        "micro-batched prediction throughput",
    )
    p.add_argument("--rows", type=int, default=4,
                   help="synthetic grid rows for the throwaway checkpoint "
                        "(N = rows^2; default 4)")
    p.add_argument("--batch", type=int, default=16,
                   help="the large-batch point to measure (default 16)")
    p.add_argument("--buckets", type=str, default="1,4,16",
                   help="comma-separated bucket ladder (default 1,4,16 — "
                        "size the top rung to peak concurrency)")
    p.add_argument("--full-model", action="store_true",
                   help="bench the full-size default model instead of the "
                        "slim dispatch-dominated operating point")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="micro-batcher coalescing deadline (default 2.0)")
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent batch-1 clients for the micro-batch leg")
    p.add_argument("--per-client", type=int, default=40,
                   help="requests each client issues (default 40)")
    p.add_argument("--iters", type=int, default=30,
                   help="timed iterations per direct leg (default 30)")
    p.add_argument("--warmup", type=int, default=3,
                   help="warmup calls per leg, excluded from stats")
    p.add_argument("--no-fleet", action="store_true",
                   help="skip the two-city fleet-engine leg "
                        "(record['fleet'])")
    p.add_argument("--soak", action="store_true",
                   help="run the overload soak leg (record['soak']): "
                        "open-loop load above calibrated capacity against "
                        "an SLO-configured engine, typed shed counts, "
                        "admitted p50/p95/p99 vs the derived SLO target, "
                        "and a mid-soak param hot-swap with per-generation "
                        "parity")
    p.add_argument("--soak-seconds", type=float, default=2.0,
                   help="soak wall budget in seconds (default 2.0)")
    p.add_argument("--soak-overload", type=float, default=2.0,
                   help="offered load as a multiple of calibrated capacity "
                        "(default 2.0)")
    p.add_argument("--federation", type=int, default=0, metavar="M",
                   help="run the M-replica federation soak "
                        "(record['federation']): a warm spare, a shared "
                        "tier-wide admission budget, open-loop multi-city "
                        "scatter/gather, and the four fault drills — "
                        "replica-kill mid-traffic, thundering-herd, "
                        "tier-wide poisoned-candidate rejection + "
                        "generation-consistent promotion, hang-on-drain + "
                        "warm-spare re-shard under load (default 0: off)")
    p.add_argument("--federation-cities", type=int, default=0,
                   help="cities the federation shards across the hash ring "
                        "(default 0: max(2*M, 4) — at least as many cities "
                        "as replicas, per the federation-config rule)")
    p.add_argument("--trace-out", type=str, default=None, metavar="PATH",
                   help="record per-request spans (admit -> queue -> "
                        "device -> scatter, generation-stamped) plus JAX "
                        "compile telemetry; writes the JSONL timeline to "
                        "PATH and adds record['obs']")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry. Prints EXACTLY one JSON line on stdout (the record);
    everything else — training chatter, compile logs — goes to stderr."""
    args = build_serve_bench_parser().parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    if args.trace_out:
        from stmgcn_tpu.obs import jaxmon
        from stmgcn_tpu.obs import trace as obs_trace

        obs_trace.configure()
        jaxmon.install()

    record_stream = sys.stdout
    sys.stdout = sys.stderr  # anything a dependency prints stays off-record
    try:
        # one temp dir holds the throwaway checkpoint AND the export
        # artifact for exactly the measurement's lifetime (both leaked
        # before: mkdtemp'd dirs nothing ever removed)
        with tempfile.TemporaryDirectory(prefix="stmgcn_serve_") as tmp:
            def _phase(name):
                # top-level bench phases bound the trace timeline, so the
                # report's wall-coverage is honest even for legs whose
                # inner spans live on worker/client threads; no-ops (and
                # costs nothing) without --trace-out
                from stmgcn_tpu.obs import trace as _tr

                return _tr.span(name)

            sp = _phase("bench.train_throwaway")
            fc, supports = train_throwaway(
                rows=args.rows, slim=not args.full_model,
                out_dir=os.path.join(tmp, "ckpt"),
            )
            sp.end()
            if args.trace_out:
                # pin the train-loop recompile reading: every engine the
                # legs below build compiles fresh programs (first-touch,
                # not recompiles); the soak leg re-marks once its own
                # warmup is done
                jaxmon.freeze_recompiles()
            sp = _phase("bench.serve")
            record = run_serve_bench(
                fc, supports, batch=args.batch, buckets=buckets,
                max_delay_ms=args.max_delay_ms, clients=args.clients,
                per_client=args.per_client, warmup=args.warmup,
                iters=args.iters,
                artifact_path=os.path.join(tmp, "model.stmgx"),
            )
            sp.end()
            if not args.no_fleet:
                sp = _phase("bench.fleet")
                record["fleet"] = run_fleet_serve_bench(
                    fc, supports, buckets=buckets,
                    max_delay_ms=args.max_delay_ms, clients=args.clients,
                    per_client=args.per_client, warmup=args.warmup,
                    iters=args.iters,
                )
                sp.end()
            if args.soak:
                sp = _phase("bench.soak")
                record["soak"] = run_soak_leg(
                    fc, supports, buckets=buckets,
                    max_delay_ms=args.max_delay_ms,
                    soak_seconds=args.soak_seconds,
                    overload=args.soak_overload,
                )
                sp.end()
                # the continual-loop drill rides every soak: live ingest
                # into the device ring, a drift-triggered fine-tune, one
                # guarded promotion, and one poisoned candidate rejected
                # at the gate — all while the engine keeps answering
                sp = _phase("bench.continual")
                from stmgcn_tpu.train.continual import closed_loop_smoke

                record["soak"]["continual"] = closed_loop_smoke(
                    os.path.join(tmp, "continual")
                )
                sp.end()
            if args.federation > 0:
                sp = _phase("bench.federation")
                record["federation"] = run_federation_soak(
                    fc, supports, replicas=args.federation,
                    n_cities=args.federation_cities, buckets=buckets,
                    max_delay_ms=args.max_delay_ms,
                    soak_seconds=args.soak_seconds,
                    overload=args.soak_overload,
                )
                sp.end()
        record["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
        if args.trace_out:
            trc = obs_trace.active_tracer()
            n_spans = trc.export_jsonl(args.trace_out) if trc else 0
            record["obs"] = {
                **jaxmon.snapshot(),
                "trace_path": args.trace_out,
                "trace_spans": n_spans,
            }
            print(
                f"trace written to {args.trace_out} ({n_spans} spans) — "
                f"inspect with `stmgcn obs {args.trace_out}`",
                file=sys.stderr,
            )
    finally:
        sys.stdout = record_stream
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
