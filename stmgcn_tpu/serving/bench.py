"""``stmgcn serve-bench``: before/after proof for the serving engine.

Measures the three generations of the inference path on one host:

- **naive** — ``Forecaster.predict`` / ``ExportedForecaster.predict``
  called per request (the r05 serving legs; jit dispatch + support
  re-upload per call — the path whose batch-16 throughput sat *below*
  batch-1);
- **engine (direct)** — :class:`~stmgcn_tpu.serving.engine.ServingEngine`
  bucket programs, no queue: pure AOT dispatch with resident operands;
- **engine (micro-batched)** — N concurrent batch-1 clients coalesced by
  the micro-batcher into bucket-sized dispatches.

Each timed leg reports mean/p50/p95/p99 latency and predictions/sec with
warmup excluded; the record carries the engine's per-bucket telemetry
(queue-wait vs device-time split, pad waste) and the two acceptance
ratios as ``speedup``. A fourth generation rides in ``record["fleet"]``:
one :class:`~stmgcn_tpu.serving.fleet.FleetServingEngine` serving a
two-city heterogeneous view of the same checkpoint
(:func:`fleet_forecaster`), with mixed-city concurrent clients whose
requests coalesce into shared dispatches (``cross_city_dispatches``)
and a per-city bit-parity spot check. NOT imported by ``stmgcn_tpu.serving.__init__``
— the throwaway-checkpoint trainer pulls the full stack, and the
serving package must stay lean for ``stmgcn_tpu.export``.

Default operating point is a 4x4 grid (N=16) with slim hidden dims and
the bucket ladder topped at the client count: the dispatch-dominated
regime where serving engines earn their keep (see
:func:`train_throwaway`), with the top rung sized to peak concurrency so
saturated dispatches run back-to-back. The shapes ride in the record,
so apples stay with apples across rounds.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from typing import List, Optional

import numpy as np

from stmgcn_tpu.serving.metrics import percentiles

__all__ = [
    "fleet_forecaster",
    "main",
    "run_fleet_serve_bench",
    "run_serve_bench",
    "train_throwaway",
]


def _leg(samples_s: List[float], batch: int) -> dict:
    """One timed leg: per-call seconds -> latency stats + throughput."""
    mean_s = float(np.mean(samples_s))
    ms = [s * 1e3 for s in samples_s]
    pct = percentiles(ms)
    return {
        "ms": round(mean_s * 1e3, 3),
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(batch / mean_s, 1),
    }


def _timed(fn, warmup: int, iters: int) -> List[float]:
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return samples


def train_throwaway(rows: int = 4, epochs: int = 2, batch_size: int = 16,
                    out_dir: Optional[str] = None, slim: bool = True):
    """A 2-epoch throwaway checkpoint at the serve-bench operating point.

    Accuracy is irrelevant — only the compiled prediction path's
    wall-clock matters. ``slim`` keeps the full 3-branch ST-MGCN but
    shrinks the hidden dims so the forward is *dispatch*-dominated, the
    regime the engine exists for: on an accelerator per-row compute is
    microseconds and per-call overhead (trace, dispatch, host↔device
    churn) is what serving throughput dies on; a 1-core CPU host only
    reaches that regime with a small forward. ``slim=False`` measures
    the full-size model instead (compute-bound on CPU — every path
    flattens to memory bandwidth). Returns ``(forecaster, supports)``.
    """
    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_trainer
    from stmgcn_tpu.inference import Forecaster

    cfg = preset("default")
    cfg.data.rows = rows
    cfg.data.n_timesteps = 24 * 7 * 2 + 64
    cfg.train.epochs = epochs
    cfg.train.batch_size = batch_size
    tmp_ckpt_dir = None
    if out_dir is None:
        # throwaway means throwaway: the checkpoint dir exists only long
        # enough to round-trip the forecaster through from_checkpoint
        tmp_ckpt_dir = tempfile.mkdtemp(prefix="stmgcn_serve_")
        out_dir = tmp_ckpt_dir
    cfg.train.out_dir = out_dir
    if slim:
        cfg.model.lstm_hidden_dim = 8
        cfg.model.lstm_num_layers = 1
        cfg.model.gcn_hidden_dim = 8
    try:
        trainer = build_trainer(cfg, verbose=False)
        trainer.train()
        fc = Forecaster.from_checkpoint(os.path.join(out_dir, "best.ckpt"))
    finally:
        if tmp_ckpt_dir is not None:
            shutil.rmtree(tmp_ckpt_dir, ignore_errors=True)
    supports = np.asarray(
        cfg.model.support_config.build_all(trainer.dataset.adjs.values()),
        np.float32,
    )
    return fc, supports


def fleet_forecaster(fc, supports):
    """Lift the throwaway checkpoint into a two-city heterogeneous
    forecaster for the fleet leg: the trained 4x4 grid serves as city 0
    (N=16) and a fresh 2x7 grid (N=14) joins as city 1 — inside the
    default waste budget, so both land in ONE shape class and their
    requests can coalesce. The model's params are node-count agnostic
    (GCN weights contract feature dims, supports carry N), so one
    checkpoint legitimately serves both. Returns
    ``(hetero_fc, per_city_supports, n_nodes)``.
    """
    from stmgcn_tpu.data import MinMaxNormalizer, synthetic_dataset
    from stmgcn_tpu.inference import Forecaster
    from stmgcn_tpu.ops import SupportConfig

    cfg = fc.config
    m = cfg.model.m_graphs
    small = synthetic_dataset(rows=2, cols=7, n_timesteps=24 * 7 * 2 + 40,
                              seed=2)
    small_sup = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
            small.adjs.values()
        ),
        np.float32,
    )[:m]
    sups = [np.asarray(supports, np.float32)[:m], small_sup]
    n_nodes = [sups[0].shape[-1], sups[1].shape[-1]]
    normalizers = [
        fc.normalizer if fc.normalizer is not None
        else MinMaxNormalizer.fit(
            np.asarray(
                synthetic_dataset(rows=4, n_timesteps=24 * 7 * 2 + 40,
                                  seed=1).demand
            )
        ),
        MinMaxNormalizer.fit(np.asarray(small.demand)),
    ]
    hetero = Forecaster(
        fc.model, fc.params, None, cfg,
        {"input_dim": fc.derived["input_dim"], "n_nodes": n_nodes},
        normalizers,
    )
    return hetero, sups, n_nodes


def _microbatch_leg(engine, history_row: np.ndarray, clients: int,
                    per_client: int) -> dict:
    """N concurrent batch-1 clients hammering ``engine.predict``."""
    # warmup outside the measured window (threads + first coalesced
    # dispatches), then reset telemetry so the snapshot is measurement-only
    for _ in range(2):
        engine.predict(history_row)
    engine.stats.reset()

    latencies_ms: List[float] = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client():
        mine = []
        barrier.wait()
        for _ in range(per_client):
            t0 = time.perf_counter()
            engine.predict(history_row)
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            latencies_ms.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(clients)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    total = clients * per_client
    pct = percentiles(latencies_ms)
    return {
        "clients": clients,
        "requests": total,
        "ms": pct["mean"],
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(total / elapsed, 1),
    }


def _fleet_microbatch_leg(engine, hists, clients: int,
                          per_client: int) -> dict:
    """N concurrent batch-1 clients split round-robin across the fleet's
    cities (``hists`` is ``[(history, city), ...]``), all hammering ONE
    engine — the coalescing a per-city engine cannot do. Reports the
    usual latency/throughput stats plus how many dispatches actually
    mixed cities in one device batch."""
    for h, c in hists:
        engine.predict(h, city=c)
    for st in engine.class_stats.values():
        st.reset()
    cross_before = engine.cross_city_dispatches

    latencies_ms: List[float] = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)

    def client(i: int):
        h, c = hists[i % len(hists)]
        mine = []
        barrier.wait()
        for _ in range(per_client):
            t0 = time.perf_counter()
            engine.predict(h, city=c)
            mine.append((time.perf_counter() - t0) * 1e3)
        with lock:
            latencies_ms.extend(mine)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(clients)
    ]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    total = clients * per_client
    pct = percentiles(latencies_ms)
    return {
        "clients": clients,
        "requests": total,
        "ms": pct["mean"],
        "p50_ms": pct["p50"],
        "p95_ms": pct["p95"],
        "p99_ms": pct["p99"],
        "predictions_per_sec": round(total / elapsed, 1),
        "cross_city_dispatches": engine.cross_city_dispatches - cross_before,
    }


def run_fleet_serve_bench(fc, supports, *, buckets=(1, 4, 16),
                          max_delay_ms: float = 2.0, clients: int = 16,
                          per_client: int = 40, warmup: int = 3,
                          iters: int = 30) -> dict:
    """The fleet serving record: one :class:`FleetServingEngine` over a
    two-city heterogeneous view of the throwaway checkpoint
    (:func:`fleet_forecaster`), measured three ways — per-city naive
    ``Forecaster.predict`` alternating cities (the no-engine floor),
    direct per-city engine dispatch, and mixed-city concurrent clients
    whose requests coalesce across cities within the shape class. A
    per-city parity spot-check rides in the record so the throughput
    claim is pinned to bit-identical outputs."""
    from stmgcn_tpu.config import ServingConfig

    hetero, sups, n_nodes = fleet_forecaster(fc, supports)
    ladder = tuple(sorted(set(buckets)))
    cfg = ServingConfig(
        buckets=ladder, max_delay_ms=max_delay_ms, max_batch=ladder[-1],
    )
    rng = np.random.default_rng(0)
    hists = [
        (
            (rng.random((1, hetero.seq_len, n, fc.derived["input_dim"]))
             * 50).astype(np.float32),
            city,
        )
        for city, n in enumerate(n_nodes)
    ]

    with hetero.fleet_engine(sups, config=cfg) as engine:
        parity = all(
            bool(
                np.array_equal(
                    hetero.predict(sups[c], h, city=c),
                    engine.predict_direct(h, city=c),
                )
            )
            for h, c in hists
        )

        legs = {}
        calls = {"i": 0}

        def naive_alternating():
            h, c = hists[calls["i"] % len(hists)]
            calls["i"] += 1
            hetero.predict(sups[c], h, city=c)

        legs["naive/b1-alternating"] = _leg(
            _timed(naive_alternating, warmup, iters), 1
        )

        def direct_alternating():
            h, c = hists[calls["i"] % len(hists)]
            calls["i"] += 1
            engine.predict_direct(h, city=c)

        legs["engine/b1-alternating"] = _leg(
            _timed(direct_alternating, warmup, iters), 1
        )
        legs["engine/microbatch-mixed-city"] = _fleet_microbatch_leg(
            engine, hists, clients, per_client
        )

        stats = {
            str(ci): st.snapshot()
            for ci, st in engine.class_stats.items()
        }
        plan = engine.plan
        record = {
            "cities": {
                "n_nodes": n_nodes,
                "class_of": [engine.class_of(c) for c in range(len(n_nodes))],
                "shape_classes": [
                    {
                        "n_nodes": cls.n_nodes,
                        "cities": list(cls.cities),
                        "node_waste": round(cls.node_waste, 4),
                    }
                    for cls in plan.classes
                ],
            },
            "buckets": list(ladder),
            "max_delay_ms": max_delay_ms,
            "parity": parity,
            "legs": legs,
            "engine_stats": stats,
            "speedup": {
                "microbatch_vs_naive_b1": round(
                    legs["engine/microbatch-mixed-city"][
                        "predictions_per_sec"
                    ]
                    / legs["naive/b1-alternating"]["predictions_per_sec"],
                    2,
                ),
            },
        }
    return record


def run_serve_bench(fc, supports, *, batch: int = 16, buckets=(1, 4, 16),
                    max_delay_ms: float = 2.0, clients: int = 16,
                    per_client: int = 40, warmup: int = 3, iters: int = 30,
                    artifact_path: Optional[str] = None) -> dict:
    """Measure every serving path over one forecaster. Returns the record
    body (``legs``/``engine_stats``/``speedup``/shape provenance)."""
    from stmgcn_tpu.config import ServingConfig
    from stmgcn_tpu.export import ExportedForecaster, export_forecaster
    from stmgcn_tpu.serving.engine import ServingEngine

    seq_len, n_nodes, input_dim = (
        fc.seq_len,
        fc.derived["n_nodes"],
        fc.derived["input_dim"],
    )
    rng = np.random.default_rng(0)
    hist = {
        b: (rng.random((b, seq_len, n_nodes, input_dim)) * 50).astype(np.float32)
        for b in (1, batch)
    }

    # an internal artifact dir lives exactly as long as the measurement:
    # the exported model must stay loadable through every timed leg, and
    # the dir must not outlive this call (it used to leak one mkdtemp per
    # bench run)
    tmp_artifact_dir = None
    if artifact_path is None:
        tmp_artifact_dir = tempfile.mkdtemp(prefix="stmgcn_serve_")
        artifact_path = os.path.join(tmp_artifact_dir, "model.stmgx")
    try:
        export_forecaster(fc, artifact_path)
        ex = ExportedForecaster.load(artifact_path)

        ladder = tuple(sorted(set(buckets)))
        cfg = ServingConfig(
            buckets=ladder, max_delay_ms=max_delay_ms, max_batch=ladder[-1],
        )
        engine = ServingEngine.from_forecaster(fc, supports, config=cfg)

        legs = {}
        for b in (1, batch):
            h = hist[b]
            legs[f"forecaster/b{b}"] = _leg(
                _timed(lambda h=h: fc.predict(supports, h), warmup, iters), b
            )
            legs[f"exported/b{b}"] = _leg(
                _timed(lambda h=h: ex.predict(supports, h), warmup, iters), b
            )
            legs[f"engine/b{b}"] = _leg(
                _timed(lambda h=h: engine.predict_direct(h), warmup, iters), b
            )
        legs[f"engine/microbatch{batch}"] = _microbatch_leg(
            engine, hist[1], clients, per_client
        )

        stats = engine.stats.snapshot()
        engine.close()
    finally:
        if tmp_artifact_dir is not None:
            shutil.rmtree(tmp_artifact_dir, ignore_errors=True)
    speedup = {
        # the r05 inversion check: engine batch-N rows/sec over batch-1
        "b16_vs_b1": round(
            legs[f"engine/b{batch}"]["predictions_per_sec"]
            / legs["engine/b1"]["predictions_per_sec"],
            2,
        ),
        # micro-batched concurrent throughput over the naive sequential path
        "microbatch_vs_sequential_b1": round(
            legs[f"engine/microbatch{batch}"]["predictions_per_sec"]
            / legs["forecaster/b1"]["predictions_per_sec"],
            2,
        ),
    }
    return {
        "shapes": {
            "n_nodes": n_nodes,
            "seq_len": seq_len,
            "input_dim": input_dim,
            "batch": batch,
            "buckets": list(cfg.buckets),
            "max_delay_ms": max_delay_ms,
        },
        "legs": legs,
        "engine_stats": stats,
        "speedup": speedup,
    }


def build_serve_bench_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn serve-bench",
        description="serving-engine benchmark: naive vs AOT-bucketed vs "
        "micro-batched prediction throughput",
    )
    p.add_argument("--rows", type=int, default=4,
                   help="synthetic grid rows for the throwaway checkpoint "
                        "(N = rows^2; default 4)")
    p.add_argument("--batch", type=int, default=16,
                   help="the large-batch point to measure (default 16)")
    p.add_argument("--buckets", type=str, default="1,4,16",
                   help="comma-separated bucket ladder (default 1,4,16 — "
                        "size the top rung to peak concurrency)")
    p.add_argument("--full-model", action="store_true",
                   help="bench the full-size default model instead of the "
                        "slim dispatch-dominated operating point")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="micro-batcher coalescing deadline (default 2.0)")
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent batch-1 clients for the micro-batch leg")
    p.add_argument("--per-client", type=int, default=40,
                   help="requests each client issues (default 40)")
    p.add_argument("--iters", type=int, default=30,
                   help="timed iterations per direct leg (default 30)")
    p.add_argument("--warmup", type=int, default=3,
                   help="warmup calls per leg, excluded from stats")
    p.add_argument("--no-fleet", action="store_true",
                   help="skip the two-city fleet-engine leg "
                        "(record['fleet'])")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry. Prints EXACTLY one JSON line on stdout (the record);
    everything else — training chatter, compile logs — goes to stderr."""
    args = build_serve_bench_parser().parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    record_stream = sys.stdout
    sys.stdout = sys.stderr  # anything a dependency prints stays off-record
    try:
        # one temp dir holds the throwaway checkpoint AND the export
        # artifact for exactly the measurement's lifetime (both leaked
        # before: mkdtemp'd dirs nothing ever removed)
        with tempfile.TemporaryDirectory(prefix="stmgcn_serve_") as tmp:
            fc, supports = train_throwaway(
                rows=args.rows, slim=not args.full_model,
                out_dir=os.path.join(tmp, "ckpt"),
            )
            record = run_serve_bench(
                fc, supports, batch=args.batch, buckets=buckets,
                max_delay_ms=args.max_delay_ms, clients=args.clients,
                per_client=args.per_client, warmup=args.warmup,
                iters=args.iters,
                artifact_path=os.path.join(tmp, "model.stmgx"),
            )
            if not args.no_fleet:
                record["fleet"] = run_fleet_serve_bench(
                    fc, supports, buckets=buckets,
                    max_delay_ms=args.max_delay_ms, clients=args.clients,
                    per_client=args.per_client, warmup=args.warmup,
                    iters=args.iters,
                )
        record["captured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        )
    finally:
        sys.stdout = record_stream
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
