"""Serving federation: city-sharded engine replicas behind one router.

One engine process closed the single-host story (admission control,
atomic hot-swap, guarded promotion, the continual loop); "millions of
users" is a *tier* of them. :class:`FederationRouter` shards cities
across M engine replicas via consistent city→replica hashing — one
level above the fleet engine's city→class routing, the Morphling
multi-graph batching pattern lifted to processes — and owns the pieces
a replica tier is only real with:

- **scatter/gather** — a multi-city request fans out per owning
  replica and gathers under a bounded join; every city comes back as a
  :class:`CityOutcome` carrying either its prediction or its *own*
  typed error (shed, dispatch failure, dead replica, gather timeout).
  A caller is never hung and never handed a half-answer it cannot
  attribute.
- **tier generation consistency** — the per-engine atomic
  ``(generation, params)`` contract lifted to M engines: a gathered
  multi-city response is re-dispatched (bounded, like the engine's own
  ``_SWAP_RETRIES``) until every city answers from one generation, so
  a tier-wide cutover never leaks a mixed-generation response.
- **global admission** — every replica's
  :class:`~stmgcn_tpu.serving.admission.AdmissionController` draws one
  shared :class:`~stmgcn_tpu.serving.admission.GlobalBudget` down, so
  tier-wide pending work is bounded even when each local bound alone
  would admit.
- **lifecycle** — drain (stop admitting, flush in-flight bounded by
  ``drain_timeout_s``, detach — a wedged checkpoint watcher is
  *reported*, not waited on), re-shard (consistent-hash ring move:
  only the removed/added replica's cities move, handover bounded by
  ``handover_timeout_s``), and warm-spare promotion (a spare already
  built and checkpoint-watching joins the ring in one assignment
  swap).
- **fleet drift rollup** — per-replica drift snapshots published as
  replica-labeled gauges (``federation.drift_*{replica=...}``) plus a
  fleet-wide worst-case, the signal one
  :class:`~stmgcn_tpu.train.continual.ContinualDaemon` per shard
  retrains on.

Fault drills, not mocks: a
:class:`~stmgcn_tpu.resilience.FederationFaultPlan` gets its shot at
scatter entry (replica-kill by scatter ordinal), drain entry
(hang-on-drain), and the open-loop schedule (herd-spike); the empty
plan short-circuits every hook — production routes exactly the drilled
code.

Lock discipline (the concurrency lint rules hold here too): the
router's ring/assignment state lives behind ``self._lock``; engine
calls NEVER run under it (group snapshots are copied out first);
per-replica state lives behind each :class:`ReplicaHandle`'s own lock;
and the only cross-object order is router-lock → handle-lock →
budget-lock, acyclic by construction.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.serving.admission import ShedError

__all__ = [
    "CityOutcome",
    "FederationRouter",
    "HashRing",
    "ReplicaHandle",
    "ReplicaUnavailable",
    "ring_hash",
]

#: absolute never-hang backstop for one scatter/gather (normal requests
#: are bounded far tighter by each replica's admission deadline)
GATHER_TIMEOUT_S = 30.0

#: bounded re-dispatch budget for single-generation gather assembly —
#: mirrors the engine's ``_SWAP_RETRIES`` (a swap can land mid-gather
#: at most once per generation; 20 covers pathological stacking)
_TIER_RETRIES = 20

#: pause between generation-consistency retry rounds: long enough for a
#: cutover poll on a sibling replica to land, short enough to stay
#: inside any sane deadline
_RETRY_PAUSE_S = 0.002


def ring_hash(key: str) -> int:
    """Deterministic 64-bit ring position (process-salt-free, unlike
    builtin ``hash`` — ring layouts must agree across runs and hosts)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class ReplicaUnavailable(ShedError):
    """The owning replica is dead, draining, or detached — a typed
    routing rejection (retryable: the ring heals on the next scatter)."""


@dataclasses.dataclass
class CityOutcome:
    """One city's slice of a gathered multi-city response: exactly one
    of ``prediction`` (with its ``generation``) or ``error`` is set."""

    city: int
    prediction: Optional[np.ndarray] = None
    generation: Optional[int] = None
    error: Optional[BaseException] = None
    replica: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class HashRing:
    """Consistent city→replica hash ring with virtual nodes.

    Each replica contributes ``vnodes`` points; a city is owned by the
    first point clockwise of its own hash. Removing a replica moves
    only *its* cities (the minimal-movement property re-sharding relies
    on); adding one steals only the cities its new points cover.
    Immutable once built — the router swaps whole rings atomically.
    """

    def __init__(self, replica_ids, vnodes: int = 64):
        self.replica_ids = tuple(sorted(replica_ids))
        if not self.replica_ids:
            raise ValueError("HashRing needs at least one replica")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, int]] = sorted(
            (ring_hash(f"replica:{rid}#{v}"), rid)
            for rid in self.replica_ids
            for v in range(self.vnodes)
        )
        self._keys = [p[0] for p in self._points]

    def owner(self, city: int) -> int:
        """The replica owning ``city`` (deterministic across runs)."""
        h = ring_hash(f"city:{city}")
        i = bisect.bisect_right(self._keys, h)
        if i == len(self._points):
            i = 0  # wrap: the ring is a circle
        return self._points[i][1]

    def assignment(self, cities) -> Dict[int, int]:
        """city → owning replica for every city."""
        return {c: self.owner(c) for c in cities}

    def imbalance(self, cities) -> float:
        """Max relative per-replica overload vs the uniform share
        (0.0 = perfectly even). The ``federation-config`` rule bounds
        what a config may *demand*; this measures what a ring *does*."""
        cities = list(cities)
        if not cities:
            return 0.0
        counts = {rid: 0 for rid in self.replica_ids}
        for c in cities:
            counts[self.owner(c)] += 1
        uniform = len(cities) / len(self.replica_ids)
        return max(n / uniform - 1.0 for n in counts.values())


class ReplicaHandle:
    """One replica's identity + lifecycle state + in-flight account.

    States: ``active`` (in the ring), ``spare`` (built and watching,
    outside the ring), ``draining`` (no new admissions, flushing),
    ``detached`` (out of the ring, engine alive), ``dead`` (killed).
    All state is guarded by the handle's own lock; the engine reference
    itself is immutable.
    """

    def __init__(self, replica_id: int, engine, state: str = "active"):
        self.replica_id = replica_id
        self.engine = engine
        self._lock = threading.Lock()
        self._state = state
        self._in_flight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def mark(self, state: str) -> None:
        with self._lock:
            self._state = state

    def routable(self) -> bool:
        """Whether the router may send new work here."""
        with self._lock:
            return self._state == "active"

    def begin(self) -> bool:
        """Account one in-flight request; False = not admitting."""
        with self._lock:
            if self._state != "active":
                return False
            self._in_flight += 1
            return True

    def end(self) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)

    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight


class FederationRouter:
    """City-sharded scatter/gather over M engine replicas.

    ``engines`` are fully built fleet/serving engines able to serve any
    city (the ring decides *ownership*, so a re-shard is an assignment
    move, not a rebuild); ``spare_engines`` join as warm spares.
    ``global_budget`` is the :class:`GlobalBudget` the engines'
    admission controllers were built with (the router only reports it).
    """

    def __init__(self, engines, cities, *, config=None, spare_engines=(),
                 global_budget=None, fault_plan=None, log=None):
        if config is None:
            from stmgcn_tpu.config import FederationConfig

            config = FederationConfig(enabled=True, replicas=len(engines))
        bad = config.violations(n_cities=len(tuple(cities)))
        if bad:
            raise ValueError("invalid federation config: " + "; ".join(bad))
        self.config = config
        self.cities = tuple(int(c) for c in cities)
        self.budget = global_budget
        self._log = log if log is not None else (lambda msg: None)
        self._fault_plan = (
            fault_plan if fault_plan is not None and fault_plan.active else None
        )
        self._handles: Dict[int, ReplicaHandle] = {}
        for rid, eng in enumerate(engines):
            self._handles[rid] = ReplicaHandle(rid, eng, "active")
        for off, eng in enumerate(spare_engines):
            rid = len(engines) + off
            self._handles[rid] = ReplicaHandle(rid, eng, "spare")
        if not any(h.routable() for h in self._handles.values()):
            raise ValueError("FederationRouter needs at least one active replica")
        #: per-shard continual daemons (attach_continual)
        self.daemons: Dict[int, object] = {}
        # ring + assignment swap atomically under one lock; scatter and
        # drill counters share it (single-writer hot path, cheap)
        self._lock = threading.Lock()
        self._ring = HashRing(
            [rid for rid, h in self._handles.items() if h.routable()],
            vnodes=config.vnodes,
        )
        self._assignment = self._ring.assignment(self.cities)
        # the city *set* is immutable after construction (re-shards move
        # ownership, never membership) — validation reads this, not the
        # mutable assignment
        self._city_set = frozenset(self.cities)
        self._scatter_seq = 0
        self.generation_retries = 0
        self.cities_moved = 0
        self.kills = 0

    # -- routing ---------------------------------------------------------

    def replica_for(self, city: int) -> int:
        """Current owner of ``city`` (ring + any re-shard moves)."""
        self._check_city(city)
        with self._lock:
            return self._assignment[city]

    def _check_city(self, city: int) -> None:
        if city not in self._city_set:
            raise ValueError(
                f"city must be one of {sorted(self._city_set)}, got {city}"
            )

    def assignment(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._assignment)

    def predict(self, history, *, city: int, with_generation: bool = False):
        """Single-city predict through the owning replica.

        Typed errors propagate exactly like the engine API (sheds,
        dispatch failures); a dead/draining owner raises
        :class:`ReplicaUnavailable` after one transparent re-shard
        attempt finds no live owner.
        """
        self._check_city(city)
        for _ in range(2):  # original owner, then post-heal owner
            with self._lock:
                rid = self._assignment[city]
            handle = self._handles[rid]
            if handle.begin():
                try:
                    return handle.engine.predict(
                        history, city=city, with_generation=with_generation
                    )
                finally:
                    handle.end()
            self._heal(rid)
        raise ReplicaUnavailable(
            f"no live replica owns city {city} — replica {rid} is "
            f"{handle.state} and the ring could not re-shard around it"
        )

    # -- scatter/gather --------------------------------------------------

    def predict_many(self, requests: Mapping[int, np.ndarray], *,
                     timeout_s: Optional[float] = None
                     ) -> Dict[int, CityOutcome]:
        """Scatter a multi-city batch request, gather per-city outcomes.

        Never hangs (bounded join per round, ``timeout_s`` overall,
        default :data:`GATHER_TIMEOUT_S`) and never mixes generations:
        successful cities are re-dispatched until they agree on the
        newest generation seen, so a tier-wide cutover mid-gather costs
        retries, not consistency. Cities that cannot be served come
        back with their own typed error.
        """
        deadline = time.perf_counter() + (
            GATHER_TIMEOUT_S if timeout_s is None else timeout_s
        )
        with self._lock:
            ordinal = self._scatter_seq
            self._scatter_seq += 1
        REGISTRY.counter("federation.scatters").inc()
        plan = self._fault_plan
        if plan is not None:
            victim = plan.kill_at_scatter(ordinal)
            if victim is not None:
                self.kill(victim)
        outcomes: Dict[int, CityOutcome] = {}
        todo = [int(c) for c in requests]
        for round_no in range(_TIER_RETRIES):
            if not todo:
                break
            self._gather_round(requests, todo, outcomes, deadline)
            # generation consistency: retry successes behind the newest
            # generation any city answered from (errors keep their type)
            gens = {o.generation for o in outcomes.values() if o.ok}
            if len(gens) <= 1:
                break
            target = max(gens)
            todo = [c for c, o in outcomes.items()
                    if o.ok and o.generation < target]
            self.generation_retries += len(todo)
            REGISTRY.counter("federation.generation_retries").inc(len(todo))
            if time.perf_counter() >= deadline:
                break
            time.sleep(_RETRY_PAUSE_S)
        # retries exhausted with generations still split: demote the
        # stale minority to a typed error — a mixed success response
        # must never leave the router
        gens = {o.generation for o in outcomes.values() if o.ok}
        if len(gens) > 1:
            target = max(gens)
            for c, o in outcomes.items():
                if o.ok and o.generation < target:
                    outcomes[c] = CityOutcome(
                        city=c, replica=o.replica,
                        error=ReplicaUnavailable(
                            f"city {c} could not be re-served on the tier "
                            f"generation {target} within {_TIER_RETRIES} "
                            "retries"
                        ),
                    )
        return outcomes

    def _gather_round(self, requests, todo, outcomes, deadline) -> None:
        """One scatter round over ``todo`` cities (mutates ``outcomes``)."""
        groups: Dict[int, List[int]] = {}
        unroutable: List[int] = []
        with self._lock:
            owners = {c: self._assignment[c] for c in todo}
        for c, rid in owners.items():
            if self._handles[rid].routable():
                groups.setdefault(rid, []).append(c)
            else:
                unroutable.append((c, rid))
        healed = set()
        for c, rid in unroutable:
            # dead/draining owner: heal the ring once per replica, then
            # re-resolve — the city either finds a live owner now or
            # reports a typed error this round
            if rid not in healed:
                healed.add(rid)
                self._heal(rid)
            with self._lock:
                new_rid = self._assignment[c]
            if new_rid != rid and self._handles[new_rid].routable():
                groups.setdefault(new_rid, []).append(c)
            else:
                outcomes[c] = CityOutcome(
                    city=c, replica=rid,
                    error=ReplicaUnavailable(
                        f"replica {rid} owning city {c} is "
                        f"{self._handles[rid].state} and no live replica "
                        "could take it over"
                    ),
                )
        if not groups:
            return
        if len(groups) == 1:
            # single-replica scatter: dispatch inline, no thread overhead
            ((rid, cities),) = groups.items()
            self._dispatch_group(rid, cities, requests, outcomes)
            return
        threads = []
        for rid, cities in groups.items():
            t = threading.Thread(
                target=self._dispatch_group,
                args=(rid, cities, requests, outcomes),
                name=f"stmgcn-scatter-{rid}", daemon=True,
            )
            t.start()
            threads.append((t, rid, cities))
        for t, rid, cities in threads:
            t.join(max(0.0, deadline - time.perf_counter()))
            if t.is_alive():
                # bounded-join miss: the caller gets typed timeouts NOW;
                # the daemon thread writes into a dict nobody re-reads
                # for these cities (outcomes are overwritten here)
                REGISTRY.counter("federation.hung_gathers").inc()
                for c in cities:
                    outcomes[c] = CityOutcome(
                        city=c, replica=rid,
                        error=ReplicaUnavailable(
                            f"gather from replica {rid} timed out for "
                            f"city {c} — caller released, replica marked "
                            "for drain"
                        ),
                    )

    def _dispatch_group(self, rid: int, cities, requests, outcomes) -> None:
        """Serve one replica's cities; every exception becomes that
        city's typed outcome (the worker must never die loudly)."""
        handle = self._handles[rid]
        for c in cities:
            if not handle.begin():
                outcomes[c] = CityOutcome(
                    city=c, replica=rid,
                    error=ReplicaUnavailable(
                        f"replica {rid} stopped admitting mid-gather "
                        f"({handle.state})"
                    ),
                )
                continue
            try:
                pred, gen = handle.engine.predict(
                    np.asarray(requests[c], dtype=np.float32),
                    city=c, with_generation=True,
                )
                outcomes[c] = CityOutcome(
                    city=c, prediction=pred, generation=gen, replica=rid
                )
            except Exception as e:  # typed per-city error, never a hang
                outcomes[c] = CityOutcome(city=c, replica=rid, error=e)
            finally:
                handle.end()

    # -- lifecycle -------------------------------------------------------

    def _heal(self, rid: int) -> int:
        """Re-shard around a non-routable replica; returns cities moved.
        Idempotent: a replica already outside the ring moves nothing."""
        handle = self._handles.get(rid)
        if handle is None or handle.routable():
            return 0
        return self._rebuild_ring()

    def _rebuild_ring(self) -> int:
        """Swap in a ring over the currently-routable replicas; returns
        how many cities changed owner (the minimal-movement property
        keeps this at ~1/M of cities per single-replica change)."""
        live = [r for r, h in self._handles.items() if h.routable()]
        if not live:
            return 0
        ring = HashRing(live, vnodes=self.config.vnodes)
        assignment = ring.assignment(self.cities)
        with self._lock:
            moved = sum(
                1 for c in self.cities if assignment[c] != self._assignment[c]
            )
            self._ring = ring
            self._assignment = assignment
            self.cities_moved += moved
        if moved:
            REGISTRY.counter("federation.resharded_cities").inc(moved)
        return moved

    def kill(self, rid: int) -> None:
        """Hard-kill a replica (the replica-kill drill's production
        path): mark dead, heal the ring, close the engine off-path —
        the scatter path never blocks behind a dying engine's drain."""
        handle = self._handles[rid]
        handle.mark("dead")
        self.kills += 1
        REGISTRY.counter("federation.replica_killed").inc()
        self._log(f"_event=replica_killed replica={rid}")
        self._heal(rid)
        closer = threading.Thread(
            target=self._close_engine, args=(rid,),
            name=f"stmgcn-reaper-{rid}", daemon=True,
        )
        closer.start()

    def _close_engine(self, rid: int) -> None:
        try:
            self._handles[rid].engine.close()
        except Exception as e:  # a dying engine must not kill the reaper
            self._log(f"_event=replica_close_error replica={rid} err={e!r}")

    def drain(self, rid: int, timeout_s: Optional[float] = None) -> dict:
        """Graceful replica removal: stop admitting, re-shard its
        cities away, flush in-flight within ``drain_timeout_s``, then
        detach. Always returns within the timeout (+ watcher join
        bound): a hang-on-drain fault or wedged watcher is *reported*
        in the result, never waited out.
        """
        timeout_s = (
            float(self.config.drain_timeout_s) if timeout_s is None
            else float(timeout_s)
        )
        t0 = time.perf_counter()
        handle = self._handles[rid]
        handle.mark("draining")
        moved = self._heal(rid)
        plan = self._fault_plan
        if plan is not None:
            plan.on_drain(rid)  # a hang here burns the drain budget
        deadline = t0 + timeout_s
        while handle.in_flight() > 0 and time.perf_counter() < deadline:
            time.sleep(0.005)
        flushed = handle.in_flight() == 0
        watcher = getattr(handle.engine, "_watcher", None)
        watcher_wedged = False
        if watcher is not None:
            # a False stop() already counted serving.watcher_wedged and
            # emitted the structured event naming this watch dir
            watcher_wedged = not watcher.stop()
        handle.mark("detached")
        report = {
            "replica": rid,
            "flushed": flushed,
            "moved_cities": moved,
            "watcher_wedged": watcher_wedged,
            "drain_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        self._log(f"_event=replica_drained {report}")
        return report

    def reshard(self, *, remove=(), add=()) -> dict:
        """Explicit ring membership change with a bounded handover.

        ``remove`` replicas stop admitting first; ``add`` replicas
        (spares or previously detached) become active; then one
        assignment swap moves only the affected cities. The handover
        window waits — bounded by ``handover_timeout_s`` — for the
        removed replicas' in-flight work, and reports whether it
        flushed.
        """
        for rid in remove:
            self._handles[rid].mark("draining")
        for rid in add:
            self._handles[rid].mark("active")
        moved = self._rebuild_ring()
        t0 = time.perf_counter()
        deadline = t0 + float(self.config.handover_timeout_s)
        flushed = True
        for rid in remove:
            handle = self._handles[rid]
            while handle.in_flight() > 0 and time.perf_counter() < deadline:
                time.sleep(0.005)
            flushed = flushed and handle.in_flight() == 0
            handle.mark("detached")
        return {
            "moved_cities": moved,
            "handover_flushed": flushed,
            "handover_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "active": sorted(
                r for r, h in self._handles.items() if h.routable()
            ),
        }

    def detach(self, rid: int) -> int:
        """Administrative detach: take a replica out of the ring without
        closing its engine (the tier promotion gate uses this for a
        replica whose cutover poll failed — it must leave the ring
        rather than serve a stale generation). Returns cities moved."""
        self._handles[rid].mark("detached")
        REGISTRY.counter("federation.replica_detached").inc()
        self._log(f"_event=replica_detached replica={rid}")
        return self._heal(rid)

    def promote_spare(self, spare_rid: int, *, replacing: Optional[int] = None
                      ) -> dict:
        """Warm-spare promotion: a built, checkpoint-watching spare
        joins the ring (optionally draining the replica it replaces).
        The spare's watcher/swap machinery already tracked the live
        generation, so the cutover is one assignment swap."""
        handle = self._handles[spare_rid]
        if handle.state != "spare":
            raise ValueError(
                f"replica {spare_rid} is {handle.state}, not a spare"
            )
        report = self.reshard(
            remove=(() if replacing is None else (replacing,)),
            add=(spare_rid,),
        )
        report["promoted"] = spare_rid
        report["replacing"] = replacing
        REGISTRY.counter("federation.spare_promoted").inc()
        return report

    # -- tier health / continual ----------------------------------------

    def engines(self) -> Dict[int, object]:
        """Engines that must track the live generation: active replicas
        AND warm spares (a spare promoted later must not time-travel)."""
        return {
            rid: h.engine for rid, h in sorted(self._handles.items())
            if h.state in ("active", "spare")
        }

    def health(self) -> dict:
        """Per-replica state + the tier invariant surface."""
        replicas = {}
        for rid, h in sorted(self._handles.items()):
            replicas[str(rid)] = {
                "state": h.state,
                "in_flight": h.in_flight(),
                "generation": h.engine.generation,
            }
        with self._lock:
            out = {
                "replicas": replicas,
                "scatters": self._scatter_seq,
                "generation_retries": self.generation_retries,
                "cities_moved": self.cities_moved,
                "kills": self.kills,
            }
        if self.budget is not None:
            out["budget"] = self.budget.snapshot()
        return out

    def drift_rollup(self) -> dict:
        """Fleet-wide drift view: replica-labeled gauges + the worst
        city/phase anywhere in the tier (what shard daemons and the
        fleet retrain trigger read)."""
        per: Dict[str, dict] = {}
        fleet = {"z_max": 0.0, "psi": 0.0}
        for rid, handle in sorted(self._handles.items()):
            if handle.state not in ("active", "draining"):
                continue
            snap = handle.engine.drift_snapshot()
            if snap is None:
                continue
            worst = {"z_max": 0.0, "psi": 0.0}
            for phases in snap.get("cities", {}).values():
                for gauges in phases.values():
                    worst["z_max"] = max(
                        worst["z_max"], float(gauges.get("z_max", 0.0))
                    )
                    worst["psi"] = max(
                        worst["psi"], float(gauges.get("psi", 0.0))
                    )
            labels = {"replica": str(rid)}
            REGISTRY.gauge("federation.drift_z_max", labels).set(worst["z_max"])
            REGISTRY.gauge("federation.drift_psi", labels).set(worst["psi"])
            per[str(rid)] = worst
            fleet["z_max"] = max(fleet["z_max"], worst["z_max"])
            fleet["psi"] = max(fleet["psi"], worst["psi"])
        REGISTRY.gauge("federation.drift_z_max", {"replica": "fleet"}).set(
            fleet["z_max"]
        )
        REGISTRY.gauge("federation.drift_psi", {"replica": "fleet"}).set(
            fleet["psi"]
        )
        return {"replicas": per, "fleet": fleet}

    def attach_continual(self, make_daemon) -> Dict[int, object]:
        """One continual daemon per shard: ``make_daemon(rid, engine)``
        builds each (see :class:`~stmgcn_tpu.train.continual
        .ContinualDaemon` — pass ``replica=str(rid)`` so its gauges are
        replica-labeled). The router only holds them for lifecycle."""
        for rid, handle in sorted(self._handles.items()):
            if handle.state != "active" or rid in self.daemons:
                continue
            self.daemons[rid] = make_daemon(rid, handle.engine)
        return dict(self.daemons)

    def close(self) -> None:
        """Tier shutdown: stop daemons, stop watchers (wedged ones are
        counted + logged by ``stop()`` itself), close engines. Bounded:
        engine closes run on daemon reaper threads with a joined grace
        window, so one wedged replica cannot hold the tier open."""
        for daemon in self.daemons.values():
            stop = getattr(daemon, "stop", None)
            if stop is not None:
                stop()
        closers = []
        for rid, handle in sorted(self._handles.items()):
            if handle.state == "dead":
                continue  # the kill path already dispatched its reaper
            handle.mark("detached")
            watcher = getattr(handle.engine, "_watcher", None)
            if watcher is not None:
                watcher.stop()
            t = threading.Thread(
                target=self._close_engine, args=(rid,),
                name=f"stmgcn-close-{rid}", daemon=True,
            )
            t.start()
            closers.append(t)
        for t in closers:
            t.join(5.0)

    def __enter__(self) -> "FederationRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
