"""Fleet serving: one engine, many cities, per-shape-class programs.

:class:`~stmgcn_tpu.serving.engine.ServingEngine` pins ONE city — its
region count, normalizer, and support stack are baked at construction,
so a two-city deployment runs two engines and concurrent requests for
different cities never coalesce. :class:`FleetServingEngine` lifts that
to a fleet: cities group into shape classes by the same rung-ladder
planner training uses (:func:`stmgcn_tpu.data.fleet.plan_shape_classes`),
each class owns per-batch-bucket AOT programs over a
``(members, M, K, rung, rung)`` support stack plus its own
micro-batcher, and a ``(city -> class)`` routing layer in front lets
requests for *different cities of one class* coalesce into single
dispatches (counted in :attr:`cross_city_dispatches`). One checkpoint's
parameters sit behind a single atomic ``(generation, params)``
reference, shared by every program — so one ``swap_params`` (or the
checkpoint watcher) re-points the entire fleet at once, and every
class's dispatches stay single-generation.

Bit-parity contract: each coalesced row selects its city's padded
support stack and real-node count *inside* the program (the gate
pooling divides by the traced count; exact-fit cities take the
plain-mean arm), normalization/denormalization touch only the city's
real-node slice, and padded node rows are stripped before return — so
results are bit-identical to per-city ``Forecaster.predict``, pinned in
tests/test_fleet.py. Cities the planner leaves unassigned (pad waste
over budget) still serve: each gets a private exact-fit class.

Overload behavior matches the single-city engine: SLO admission + typed
sheds per class queue, ``shed_policy="degrade"`` serves inline at the
degrade rung, a wedged class batcher degrades that class to the inline
path. Fault plans address each class's dispatch stream independently
(ordinals are per-batcher).

Import-leanness contract (same as engine.py): jax/numpy only at module
scope; the model stack loads lazily inside ``from_forecaster``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.serving.admission import (
    AdmissionController,
    BatcherWedged,
    ShedError,
)
from stmgcn_tpu.serving.engine import (
    _SWAP_RETRIES,
    CheckpointWatcher,
    ServingEngine,
    _check_swap_structure,
)
from stmgcn_tpu.serving.metrics import EngineStats
from stmgcn_tpu.serving.microbatch import MicroBatcher

__all__ = ["FleetServingEngine", "fleet_bucket_fn", "fleet_tiled_bucket_fn"]


def fleet_bucket_fn(model):
    """The per-class serving program: rows carry their city's slot.

    Each row gathers its city's padded support stack and real-node count
    from the class-level operands (pure index copies) and runs the
    eval-mode forward with the traced count feeding the gate pooling —
    one compiled program per (class, bucket) serves every member city.
    Params stay an explicit argument (hot-swappable, exactly like
    ``serve_bucket_fn``). Traced by the jaxpr contract pass as
    ``serve_fleet_bucket``.
    """

    def serve_fleet_bucket(params, sup_stack, n_arr, slots, history):
        def row(h, s):
            sup = jnp.take(sup_stack, s, axis=0)
            nr = jnp.take(n_arr, s)
            return model.apply(params, sup, h[None], nr)[0]

        return jax.vmap(row)(history, slots)

    return serve_fleet_bucket


def fleet_tiled_bucket_fn(tiled_model, m_graphs: int):
    """The private-class serving program for a tiled (large-N) city.

    Tiled cities always serve exact-fit (their
    :class:`~stmgcn_tpu.ops.tiling.TiledSupports` plan owns the whole
    reordered node axis — rung-sharing would mean re-planning at the
    rung), so there is no slot gather. The fleet's single vmapped
    ``(generation, params)`` reference still covers this class: the
    program converts to the loop layout *inside* (pure tree slicing,
    traced once at AOT time), so one ``swap_params`` re-points tiled and
    dense classes alike. Traced by the jaxpr contract pass as
    ``serve_tiled_bucket``.
    """
    from stmgcn_tpu.models import to_looped_params

    def serve_tiled_bucket(params, plan, history):
        return tiled_model.apply(to_looped_params(params, m_graphs), plan, history)

    return serve_tiled_bucket


class FleetServingEngine:
    """City-routed, class-coalesced serving over one hetero checkpoint.

    Build with :meth:`from_forecaster`; then::

        engine = FleetServingEngine.from_forecaster(fc, city_supports)
        pred = engine.predict(history, city=1)        # micro-batched
        pred = engine.predict_direct(history, city=0) # bypass the queue
        engine.swap_params(new_params)                # whole fleet, atomic
        engine.class_stats[engine.class_of(1)].snapshot()
        engine.cross_city_dispatches                  # coalescing proof
        engine.close()
    """

    def __init__(self, plan, groups, programs, batch_buckets, normalizers,
                 city_n, seq_len, input_dim, config, *, params_dev=None,
                 fault_plan=None, global_budget=None):
        #: the shape-class plan (extra exact-fit classes for unassigned
        #: cities appear in ``groups`` only)
        self.plan = plan
        self._groups = tuple(groups)  # (rung, (city, ...)) per class
        self._programs = programs  # cls_id -> {bucket: call(p, slots, hist)}
        self._buckets = tuple(sorted(batch_buckets))
        self._normalizers = list(normalizers)
        self._city_n = list(city_n)
        self._seq_len = seq_len
        self._input_dim = input_dim
        self.config = config
        self._city_cls: dict = {}
        self._city_slot: dict = {}
        for ci, (rung, cities) in enumerate(self._groups):
            for slot, c in enumerate(cities):
                self._city_cls[c] = ci
                self._city_slot[c] = slot
        #: dispatches whose coalesced rows spanned >1 city — the fleet
        #: engine's reason to exist; per-city engines can never coalesce
        self.cross_city_dispatches = 0
        # one (generation, params) reference for the whole fleet: every
        # class's dispatch reads it once, one swap re-points all classes
        self._current = (0, params_dev)
        self._prepare_params = None
        self._params_template = None
        self._fault_plan = (
            fault_plan if fault_plan is not None and fault_plan.active else None
        )
        self._watcher: Optional[CheckpointWatcher] = None
        #: per-class telemetry (bucket keys are batch rungs)
        self.class_stats = {
            ci: EngineStats() for ci in range(len(self._groups))
        }
        slo = (config.deadline_ms is not None or config.queue_bound_rows
               or global_budget is not None)
        self.class_admission = {
            ci: (
                AdmissionController(config, self.class_stats[ci],
                                    self._buckets,
                                    global_budget=global_budget)
                if slo else None
            )
            for ci in range(len(self._groups))
        }
        self._batchers = {
            ci: MicroBatcher(
                lambda payload, bucket, segments, k=ci: self._run_program(
                    k, payload, bucket, segments
                ),
                self._buckets,
                config.max_delay_ms,
                self.class_stats[ci],
                admission=self.class_admission[ci],
                fault_plan=self._fault_plan,
            )
            for ci in range(len(self._groups))
        }
        #: live distribution-drift monitor shared by every class (per-city
        #: sketches inside); None until :meth:`enable_drift` attaches one
        self.drift = None
        self._closed = False

    # -- construction ---------------------------------------------------

    @classmethod
    def from_forecaster(cls, fc, city_supports, *, config=None,
                        max_classes: int = 8, max_pad_waste: float = 0.5,
                        fault_plan=None, global_budget=None
                        ) -> "FleetServingEngine":
        """Engine over a heterogeneous multi-city checkpoint.

        ``city_supports``: one dense ``(M, K, n_c, n_c)`` stack per city
        (a :class:`~stmgcn_tpu.train.CitySupports` or a plain sequence).
        The checkpoint's model is rebuilt as its dense serving clone and
        every (class, batch-bucket) pair compiled AOT with the class's
        rung-padded support stack pinned device-resident and parameters
        an explicit (hot-swappable) argument.

        Cities whose entry is a :class:`~stmgcn_tpu.ops.tiling
        .TiledSupports` plan (the large-N tiled path) each get a private
        exact-fit class running the tiled serving clone
        (:func:`fleet_tiled_bucket_fn`) — they never rung-share, but
        they DO share the fleet's single param reference, checkpoint
        watcher, and SLO machinery.
        """
        from stmgcn_tpu.data.fleet import plan_shape_classes
        from stmgcn_tpu.models import to_dense_serving, to_tiled_serving
        from stmgcn_tpu.ops.tiling import TiledSupports

        cfg = ServingEngine._resolve_config(
            config if config is not None else getattr(fc.config, "serving", None)
        )
        if getattr(fc, "normalizers", None) is None:
            raise ValueError(
                "FleetServingEngine needs a heterogeneous multi-city "
                "checkpoint (per-city normalizers) — homogeneous "
                "checkpoints use ServingEngine"
            )
        n_nodes = [int(n) for n in fc.derived["n_nodes"]]
        normalizers = list(fc.normalizers)
        sups = (
            list(city_supports.per_city)
            if hasattr(city_supports, "per_city")
            else list(city_supports)
        )
        if len(sups) != len(n_nodes):
            raise ValueError(
                f"got {len(sups)} support stacks for {len(n_nodes)} cities"
            )
        m = fc.config.model.m_graphs
        model, params = to_dense_serving(fc.model, fc.params, m)
        tiled_cities = frozenset(
            c for c, s in enumerate(sups) if isinstance(s, TiledSupports)
        )
        sups_np = []
        for c, (s, n) in enumerate(zip(sups, n_nodes)):
            if c in tiled_cities:
                got = (s.m_graphs, s.n_supports, s.n)
                want = (m, model.n_supports, n)
                if got != want:
                    raise ValueError(
                        f"city {c} tiled supports must plan (M, K, N)="
                        f"{want}, got {got}"
                    )
                sups_np.append(s)
                continue
            s = np.asarray(s, dtype=np.float32)
            want = (m, model.n_supports, n, n)
            if s.shape != want:
                raise ValueError(
                    f"city {c} supports must be {want}, got {s.shape}"
                )
            sups_np.append(s)
        plan = plan_shape_classes(
            n_nodes, max_classes=max_classes, max_pad_waste=max_pad_waste
        )
        groups = []
        for sc in plan.classes:
            dense_members = tuple(c for c in sc.cities if c not in tiled_cities)
            if dense_members:
                groups.append((sc.n_nodes, dense_members))
        for c in plan.unassigned:  # serve everyone: private exact-fit class
            if c not in tiled_cities:
                groups.append((n_nodes[c], (c,)))
        for c in sorted(tiled_cities):  # tiled: always private, always exact
            groups.append((n_nodes[c], (c,)))

        params_dev = jax.tree.map(jnp.asarray, params)
        fn = fleet_bucket_fn(model)
        fn_tiled = None
        seq_len, input_dim = fc.seq_len, fc.derived["input_dim"]
        programs: dict = {}
        for ci, (rung, cities) in enumerate(groups):
            if cities[0] in tiled_cities:
                if fn_tiled is None:
                    fn_tiled = fleet_tiled_bucket_fn(
                        to_tiled_serving(model, params, m)[0], m
                    )
                plan_dev = jax.device_put(sups_np[cities[0]])
                programs[ci] = {}
                for b in cfg.buckets:
                    hist_struct = jax.ShapeDtypeStruct(
                        (b, seq_len, rung, input_dim), jnp.float32
                    )
                    compiled = (
                        jax.jit(fn_tiled)
                        .lower(params_dev, plan_dev, hist_struct)
                        .compile()
                    )
                    programs[ci][b] = (
                        lambda p, slots, h, c_=compiled, pd=plan_dev:
                        c_(p, pd, h)
                    )
                continue
            stack = np.zeros(
                (len(cities), m, model.n_supports, rung, rung), np.float32
            )
            for slot, c in enumerate(cities):
                n = n_nodes[c]
                stack[slot, :, :, :n, :n] = sups_np[c]
            stack_dev = jax.device_put(jnp.asarray(stack))
            n_arr_dev = jax.device_put(
                jnp.asarray([n_nodes[c] for c in cities], jnp.int32)
            )
            programs[ci] = {}
            for b in cfg.buckets:
                slots_struct = jax.ShapeDtypeStruct((b,), jnp.int32)
                hist_struct = jax.ShapeDtypeStruct(
                    (b, seq_len, rung, input_dim), jnp.float32
                )
                compiled = (
                    jax.jit(fn)
                    .lower(params_dev, stack_dev, n_arr_dev, slots_struct,
                           hist_struct)
                    .compile()
                )
                programs[ci][b] = (
                    lambda p, slots, h, c_=compiled, sd=stack_dev,
                    nd=n_arr_dev: c_(p, sd, nd, slots, h)
                )
        engine = cls(plan, groups, programs, cfg.buckets, normalizers,
                     n_nodes, seq_len, input_dim, cfg,
                     params_dev=params_dev, fault_plan=fault_plan,
                     global_budget=global_budget)
        engine._prepare_params = lambda p: to_dense_serving(fc.model, p, m)[1]
        engine._params_template = fc.params
        hb = getattr(fc, "health_baseline", None)
        hcfg = getattr(fc.config, "health", None)
        if hb is not None and hcfg is not None and hcfg.drift:
            engine.enable_drift(hb)
        return engine

    # -- drift ----------------------------------------------------------

    def enable_drift(self, baseline: dict, *, registry=REGISTRY):
        """Attach a :class:`stmgcn_tpu.obs.drift.DriftMonitor` comparing
        live per-city traffic against the training-time baseline blob.
        Auto-attached by ``from_forecaster`` when the checkpoint carries
        one and its config enables ``health.drift``. Returns the
        monitor."""
        from stmgcn_tpu.obs.drift import DriftMonitor

        self.drift = DriftMonitor(
            baseline, registry=registry, generation=self.generation
        )
        return self.drift

    def drift_snapshot(self) -> Optional[dict]:
        """JSON-able live drift state, or None without a monitor."""
        return None if self.drift is None else self.drift.snapshot()

    # -- hot swap --------------------------------------------------------

    @property
    def generation(self) -> int:
        """Monotonic param-generation counter (0 = construction params)."""
        return self._current[0]

    def swap_params(self, params, *, health_baseline=None) -> int:
        """Atomically re-point every shape class at new parameters;
        returns the new generation (same contract as
        :meth:`ServingEngine.swap_params` — raw checkpoint pytree in,
        one reference swap, no AOT rebuild, attached drift monitor reset
        atomically with the swap)."""
        new_dev = jax.tree.map(jnp.asarray, self._prepare_params(params))
        gen, cur_dev = self._current
        _check_swap_structure(cur_dev, new_dev)
        self._current = (gen + 1, new_dev)
        if self.drift is not None:
            self.drift.reset(gen + 1, baseline=health_baseline)
        REGISTRY.counter("serving.swaps").inc()
        REGISTRY.gauge("serving.generation").set(gen + 1)
        return gen + 1

    def watch_checkpoints(self, out_dir: str, *, poll_s: Optional[float] = None,
                          log=None) -> CheckpointWatcher:
        """Hot-swap new verified checkpoints (see
        :meth:`ServingEngine.watch_checkpoints` — identical semantics,
        fleet-wide swap)."""
        if self._watcher is not None:
            self._watcher.stop()
        self._watcher = CheckpointWatcher(self, out_dir, poll_s, log)
        return self._watcher

    # -- serving --------------------------------------------------------

    @property
    def buckets(self) -> tuple:
        return self._buckets

    @property
    def n_cities(self) -> int:
        return len(self._city_n)

    def class_of(self, city: int) -> int:
        """The shape class a city routes to."""
        self._check_city(city)
        return self._city_cls[city]

    def _check_city(self, city) -> None:
        if city not in self._city_cls:
            raise ValueError(
                f"city must be in [0, {len(self._city_n)}), got {city}"
            )

    def _run_program(self, cls_id: int, payload: np.ndarray, bucket: int,
                     segments):
        """One coalesced dispatch for a shape class; returns
        ``(predictions, generation)``.

        ``segments`` is ``((offset, n_rows, (city, pre_normalized)), ...)``
        in payload order. Normalization runs per segment over the city's
        real-node slice only (padded node rows stay zero — the forward's
        bit-parity precondition); the denormalized output keeps pad rows
        for the batcher's zero-copy scatter, and ``predict`` strips them.
        """
        from stmgcn_tpu.serving.bucketing import pad_to_bucket

        gen, params_dev = self._current  # ONE read — whole dispatch, one gen
        if all(pre for _, _, (_, pre) in segments):
            batch = payload
        else:
            batch = payload.copy()
            for ofs, n, (c, pre) in segments:
                norm = self._normalizers[c]
                if not pre and norm is not None:
                    nc = self._city_n[c]
                    batch[ofs:ofs + n, :, :nc, :] = norm.transform(
                        payload[ofs:ofs + n, :, :nc, :]
                    )
        slots = np.zeros(bucket, np.int32)
        for ofs, n, (c, _) in segments:
            slots[ofs:ofs + n] = self._city_slot[c]
        out = np.array(
            self._programs[cls_id][bucket](
                params_dev, slots, pad_to_bucket(batch, bucket)
            )
        )
        for ofs, n, (c, _) in segments:
            norm = self._normalizers[c]
            if norm is not None:
                nc = self._city_n[c]
                out[ofs:ofs + n, ..., :nc, :] = norm.inverse(
                    out[ofs:ofs + n, ..., :nc, :]
                )
        if self.drift is not None:
            # per segment, real-node slice only: padded node columns are
            # class filler, not any city's traffic
            for ofs, n, (c, _) in segments:
                nc = self._city_n[c]
                self.drift.observe_input(c, batch[ofs:ofs + n, :, :nc, :])
                self.drift.observe_prediction(
                    c, out[ofs:ofs + n, ..., :nc, :]
                )
        if len({c for _, _, (c, _) in segments}) > 1:
            self.cross_city_dispatches += 1
        return out, gen

    def _validate(self, history, city: int) -> np.ndarray:
        self._check_city(city)
        history = np.asarray(history, dtype=np.float32)
        expected = (self._seq_len, self._city_n[city], self._input_dim)
        if history.ndim != 4 or history.shape[1:] != expected:
            raise ValueError(
                f"history must be (B, seq_len={expected[0]}, "
                f"n_nodes={expected[1]}, n_feats={expected[2]}) for city "
                f"{city}, got {history.shape}"
            )
        return history

    def _pad_city(self, history: np.ndarray, city: int) -> np.ndarray:
        pad = self._groups[self._city_cls[city]][0] - self._city_n[city]
        if not pad:
            return history
        return np.pad(history, [(0, 0), (0, 0), (0, pad), (0, 0)])

    def _strip(self, out: np.ndarray, city: int) -> np.ndarray:
        nc = self._city_n[city]
        return out[..., :nc, :] if out.shape[-2] != nc else out

    def _call_batched(self, h: np.ndarray, city: int, normalized: bool):
        batcher = self._batchers[self._city_cls[city]]
        cap = self._buckets[-1]
        if h.shape[0] <= cap:
            return batcher.submit(h, tag=(city, normalized), with_info=True)
        # oversized batches split into ladder-top chunks; stale chunks
        # re-dispatch until every chunk is on one param generation
        spans = [
            (i, min(i + cap, h.shape[0])) for i in range(0, h.shape[0], cap)
        ]
        parts: list = [None] * len(spans)
        gens: list = [None] * len(spans)
        for _ in range(_SWAP_RETRIES):
            target = max((g for g in gens if g is not None), default=None)
            for k, (i, j) in enumerate(spans):
                if gens[k] is None or gens[k] != target:
                    parts[k], gens[k] = batcher.submit(
                        h[i:j], tag=(city, normalized), with_info=True
                    )
            if len(set(gens)) == 1:
                return np.concatenate(parts, axis=0), gens[0]
        raise RuntimeError(
            "could not assemble a single-generation response in "
            f"{_SWAP_RETRIES} rounds — params are swapping faster than "
            "dispatches complete"
        )

    def _dispatch_inline(self, chunk: np.ndarray, city: int, normalized: bool):
        import time

        from stmgcn_tpu.serving.bucketing import smallest_covering_bucket

        cls_id = self._city_cls[city]
        bucket = smallest_covering_bucket(chunk.shape[0], self._buckets)
        t0 = time.perf_counter()
        out, gen = self._run_program(
            cls_id, chunk, bucket, ((0, chunk.shape[0], (city, normalized)),)
        )
        device_ms = (time.perf_counter() - t0) * 1e3
        self.class_stats[cls_id].record_dispatch(
            bucket, chunk.shape[0], [0.0], device_ms
        )
        return out[:chunk.shape[0]], gen

    def _call_direct(self, h: np.ndarray, city: int, normalized: bool,
                     cap: Optional[int] = None):
        cap = cap if cap is not None else self._buckets[-1]
        spans = [
            (i, min(i + cap, h.shape[0])) for i in range(0, h.shape[0], cap)
        ]
        parts: list = [None] * len(spans)
        gens: list = [None] * len(spans)
        for _ in range(_SWAP_RETRIES):
            target = max((g for g in gens if g is not None), default=None)
            for k, (i, j) in enumerate(spans):
                if gens[k] is None or gens[k] != target:
                    parts[k], gens[k] = self._dispatch_inline(
                        h[i:j], city, normalized
                    )
            if len(set(gens)) == 1:
                out = (
                    parts[0] if len(parts) == 1
                    else np.concatenate(parts, axis=0)
                )
                return out, gens[0]
        raise RuntimeError(
            "could not assemble a single-generation response in "
            f"{_SWAP_RETRIES} rounds — params are swapping faster than "
            "dispatches complete"
        )

    def predict(self, history, *, city: int, normalized: bool = False,
                with_generation: bool = False) -> np.ndarray:
        """Micro-batched raw-units forecast for one city.

        Concurrent callers — including callers for *other cities of the
        same shape class* — coalesce into one dispatch. Bit-identical to
        ``Forecaster.predict(..., city=city)`` on the same rows. Typed
        sheds / degrade / wedged-batcher fallback behave exactly like
        :meth:`ServingEngine.predict`; ``with_generation=True`` returns
        ``(pred, generation)``.
        """
        if self._closed:
            raise RuntimeError("FleetServingEngine is closed")
        h = self._pad_city(self._validate(history, city), city)
        try:
            out, gen = self._call_batched(h, city, normalized)
        except BatcherWedged:
            out, gen = self._call_direct(h, city, normalized)
        except ShedError:
            if self.config.shed_policy != "degrade":
                raise
            self.class_stats[self._city_cls[city]].record_shed("degraded")
            out, gen = self._call_direct(
                h, city, normalized,
                cap=self.config.degrade_rung or self._buckets[0],
            )
        out = self._strip(out, city)
        return (out, gen) if with_generation else out

    def predict_direct(self, history, *, city: int, normalized: bool = False,
                       with_generation: bool = False) -> np.ndarray:
        """Bypass the queue: pad to the covering rung and dispatch inline
        (same results; no coalescing). ``with_generation=True`` returns
        ``(pred, generation)``."""
        if self._closed:
            raise RuntimeError("FleetServingEngine is closed")
        h = self._pad_city(self._validate(history, city), city)
        out, gen = self._call_direct(h, city, normalized)
        out = self._strip(out, city)
        return (out, gen) if with_generation else out

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._watcher is not None:
                self._watcher.stop()
            for b in self._batchers.values():
                b.close()

    def __enter__(self) -> "FleetServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
