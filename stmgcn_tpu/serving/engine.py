"""Shape-bucketed AOT serving engine with dynamic micro-batching.

The r05 serving numbers showed batching buying nothing: every
``ExportedForecaster.predict`` call re-dispatched through jit (and
re-uploaded the support stack), so batch 16 ran at batch-1 throughput.
This engine removes both failure modes the way the superstep PR removed
them for training:

- **shape buckets, compiled ahead of time** — at construction the engine
  lowers and compiles one program per ladder rung (``ServingConfig
  .buckets``), so serving never traces, never recompiles, and never pays
  jit dispatch: a request is one ``Compiled.__call__``.
- **device-resident operands** — the support stack (and, for the live
  path, the parameters) are placed on device once; the history window is
  the only per-request upload.
- **dynamic micro-batching** — concurrent callers coalesce into the
  smallest covering rung (:mod:`stmgcn_tpu.serving.microbatch`), with
  per-bucket latency/queue/pad-waste telemetry
  (:mod:`stmgcn_tpu.serving.metrics`).

Both predictor flavors feed the same engine: ``from_forecaster`` bakes a
live checkpoint's dense serving clone, ``from_artifact`` specializes an
exported StableHLO module's symbolic batch to each rung. Import-leanness
contract: this module may import jax/numpy only at module scope — the
model stack (flax, stmgcn_tpu.models) loads lazily inside
``from_forecaster`` so ``import stmgcn_tpu.export`` stays lean
(``tests/test_export.py::test_export_module_is_lean``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from stmgcn_tpu.serving.metrics import EngineStats
from stmgcn_tpu.serving.microbatch import MicroBatcher

__all__ = ["ServingEngine", "serve_bucket_fn"]


def serve_bucket_fn(model):
    """The per-bucket serving program (eval-mode forward, params explicit).

    The one function the live-path engine compiles per ladder rung — and
    the program the jaxpr contract pass traces as ``serve_bucket``, so a
    fusion regression in the serving forward fails ``stmgcn lint`` the
    same way a train-step regression does.
    """

    def serve_bucket(params, supports, history):
        return model.apply(params, supports, history)

    return serve_bucket


class ServingEngine:
    """Pre-compiled bucket ladder + micro-batcher over one model.

    Build with :meth:`from_forecaster` (live checkpoint) or
    :meth:`from_artifact` (exported StableHLO); then::

        engine = ServingEngine.from_forecaster(fc, supports)
        pred = engine.predict(history)          # micro-batched, raw units
        pred = engine.predict_direct(history)   # bypass the queue
        engine.stats.snapshot()                 # per-bucket telemetry
        engine.close()

    ``predict`` keeps the predictors' validate → normalize → call →
    denormalize contract (normalization vectorized once per coalesced
    dispatch), so results are bit-identical to ``Forecaster.predict`` at
    any request size (padding parity pinned in tests/test_serving.py).
    """

    def __init__(self, programs, sup_dev, supports_np, normalizer, expected,
                 config):
        self._programs = dict(programs)  # bucket -> call(history_np) -> dev arr
        self._sup_dev = sup_dev
        self._supports_np = supports_np
        self.normalizer = normalizer
        self.expected = tuple(expected)  # (seq_len, n_nodes, input_dim)
        self.config = config
        self._buckets = tuple(sorted(self._programs))
        self.stats = EngineStats()
        self._batcher = MicroBatcher(
            self._run_program, self._buckets, config.max_delay_ms, self.stats
        )
        self._closed = False

    # -- construction ---------------------------------------------------

    @staticmethod
    def _resolve_config(config):
        from stmgcn_tpu.config import ServingConfig

        cfg = config if config is not None else ServingConfig()
        bad = cfg.violations()
        if bad:
            raise ValueError("invalid serving config: " + "; ".join(bad))
        return cfg

    @staticmethod
    def _check_supports(supports, want) -> np.ndarray:
        supports_np = np.asarray(supports, dtype=np.float32)
        if supports_np.shape != tuple(want):
            raise ValueError(
                f"supports must be {tuple(want)}, got {supports_np.shape}"
            )
        return supports_np

    @classmethod
    def from_forecaster(cls, fc, supports, *, config=None, city=None
                        ) -> "ServingEngine":
        """Engine over a live :class:`~stmgcn_tpu.inference.Forecaster`.

        The checkpoint's model is rebuilt as its dense serving clone
        (``models.to_dense_serving`` — sparse/looped layouts restacked,
        pallas LSTM re-routed to xla) and each ladder rung compiled AOT
        with params and supports pinned device-resident. Heterogeneous
        multi-city checkpoints require ``city=`` exactly like
        ``export_forecaster``.
        """
        from stmgcn_tpu.models import to_dense_serving

        cfg = cls._resolve_config(
            config if config is not None else getattr(fc.config, "serving", None)
        )
        hetero = getattr(fc, "normalizers", None) is not None
        n_nodes, normalizer = fc.derived["n_nodes"], fc.normalizer
        if hetero:
            if city is None:
                raise ValueError(
                    "heterogeneous multi-city checkpoint: the engine bakes one "
                    "city's region count and normalizer — pass city="
                )
            if not 0 <= city < len(fc.normalizers):
                raise ValueError(
                    f"city must be in [0, {len(fc.normalizers)}), got {city}"
                )
            n_nodes = n_nodes[city]
            normalizer = fc.normalizers[city]
        elif city is not None:
            raise ValueError(
                "city= only applies to heterogeneous multi-city checkpoints"
            )

        m = fc.config.model.m_graphs
        model, params = to_dense_serving(fc.model, fc.params, m)
        supports_np = cls._check_supports(
            supports, (m, model.n_supports, n_nodes, n_nodes)
        )
        sup_dev = jax.device_put(jnp.asarray(supports_np))
        params_dev = jax.tree.map(jnp.asarray, params)
        expected = (fc.seq_len, n_nodes, fc.derived["input_dim"])
        fn = serve_bucket_fn(model)

        programs = {}
        for b in cfg.buckets:
            struct = jax.ShapeDtypeStruct((b,) + expected, jnp.float32)
            compiled = jax.jit(fn).lower(params_dev, sup_dev, struct).compile()
            # params/supports are the SAME resident arrays every call —
            # the numpy history batch is the only per-request upload
            # (Compiled takes it as-is; wrapping in jnp.asarray first
            # just adds a dispatch-path round trip)
            programs[b] = lambda h, c=compiled: c(params_dev, sup_dev, h)
        return cls(programs, sup_dev, supports_np, normalizer, expected, cfg)

    @classmethod
    def from_artifact(cls, source, supports, *, config=None) -> "ServingEngine":
        """Engine over an export artifact (path or loaded
        :class:`~stmgcn_tpu.export.ExportedForecaster`).

        The artifact's symbolic-batch StableHLO module is specialized and
        compiled per ladder rung. The wrapped predictor is re-routed:
        ``ex.predict(supports, history)`` now goes through the engine's
        buckets (same supports required — the engine pinned them).
        """
        from stmgcn_tpu.export import ExportedForecaster

        ex = ExportedForecaster.load(source) if isinstance(source, str) else source
        cfg = cls._resolve_config(config)
        meta = ex.meta
        supports_np = cls._check_supports(
            supports,
            (meta["m_graphs"], meta["n_supports"], meta["n_nodes"],
             meta["n_nodes"]),
        )
        sup_dev = jax.device_put(jnp.asarray(supports_np))
        expected = (meta["seq_len"], meta["n_nodes"], meta["input_dim"])

        programs = {}
        for b in cfg.buckets:
            struct = jax.ShapeDtypeStruct((b,) + expected, jnp.float32)
            compiled = jax.jit(ex.exported.call).lower(sup_dev, struct).compile()
            programs[b] = lambda h, c=compiled: c(sup_dev, h)
        engine = cls(programs, sup_dev, supports_np, ex.normalizer, expected, cfg)
        engine.exported = ex
        ex._engine = engine  # route ex.predict through the bucket ladder
        return engine

    # -- serving --------------------------------------------------------

    @property
    def buckets(self) -> tuple:
        return self._buckets

    def _run_program(self, payload: np.ndarray, bucket: int,
                     segments) -> np.ndarray:
        """One dispatch: normalize (vectorized, once per *batch* — not
        once per request), pad to the rung, run the compiled program,
        denormalize. ``segments`` is ``((offset, n_rows, pre_normalized),
        ...)`` in payload order; pre-normalized rows are kept verbatim.
        Elementwise normalization + row-independent forward keep the
        result bit-identical to the per-request flow."""
        from stmgcn_tpu.serving.bucketing import pad_to_bucket

        norm = self.normalizer
        if norm is None or all(pre for _, _, pre in segments):
            batch = payload
        else:
            batch = norm.transform(payload)
            for ofs, n, pre in segments:
                if pre:
                    batch[ofs:ofs + n] = payload[ofs:ofs + n]
        out = np.asarray(self._programs[bucket](pad_to_bucket(batch, bucket)))
        return norm.inverse(out) if norm is not None else out

    def _call_batched(self, history: np.ndarray, normalized: bool
                      ) -> np.ndarray:
        cap = self._buckets[-1]
        if history.shape[0] <= cap:
            return self._batcher.submit(history, tag=normalized)
        # oversized batches split into ladder-top chunks (never a request)
        parts = [
            self._batcher.submit(history[i:i + cap], tag=normalized)
            for i in range(0, history.shape[0], cap)
        ]
        return np.concatenate(parts, axis=0)

    def _call_direct(self, history: np.ndarray, normalized: bool
                     ) -> np.ndarray:
        import time

        from stmgcn_tpu.serving.bucketing import smallest_covering_bucket

        cap = self._buckets[-1]
        parts = []
        for i in range(0, history.shape[0], cap):
            chunk = history[i:i + cap]
            bucket = smallest_covering_bucket(chunk.shape[0], self._buckets)
            t0 = time.perf_counter()
            out = self._run_program(
                chunk, bucket, ((0, chunk.shape[0], normalized),)
            )
            device_ms = (time.perf_counter() - t0) * 1e3
            self.stats.record_dispatch(
                bucket, chunk.shape[0], [0.0], device_ms
            )
            parts.append(out[:chunk.shape[0]])
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    def _validate(self, history) -> np.ndarray:
        history = np.asarray(history, dtype=np.float32)
        if history.ndim != 4 or history.shape[1:] != self.expected:
            raise ValueError(
                f"history must be (B, seq_len={self.expected[0]}, "
                f"n_nodes={self.expected[1]}, n_feats={self.expected[2]}) "
                f"for this model, got {history.shape}"
            )
        return history

    def predict(self, history, *, normalized: bool = False) -> np.ndarray:
        """Micro-batched raw-units forecast — the concurrent-caller path.

        Blocks until this request's coalesced dispatch completes; results
        are bit-identical to ``Forecaster.predict`` on the same rows
        (parity pinned in tests/test_serving.py). Normalization happens
        inside the coalesced dispatch, vectorized over the whole bucket.
        """
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        return self._call_batched(self._validate(history), normalized)

    def predict_direct(self, history, *, normalized: bool = False) -> np.ndarray:
        """Bypass the queue: pad to the covering rung and dispatch inline
        (the latency-critical single-caller path; same results)."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        return self._call_direct(self._validate(history), normalized)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._batcher.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
