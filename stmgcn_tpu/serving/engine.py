"""Shape-bucketed AOT serving engine with dynamic micro-batching.

The r05 serving numbers showed batching buying nothing: every
``ExportedForecaster.predict`` call re-dispatched through jit (and
re-uploaded the support stack), so batch 16 ran at batch-1 throughput.
This engine removes both failure modes the way the superstep PR removed
them for training:

- **shape buckets, compiled ahead of time** — at construction the engine
  lowers and compiles one program per ladder rung (``ServingConfig
  .buckets``), so serving never traces, never recompiles, and never pays
  jit dispatch: a request is one ``Compiled.__call__``.
- **device-resident operands** — the support stack is placed on device
  once; parameters are an explicit program argument held behind one
  atomic ``(generation, params)`` reference, so the history window is
  the only per-request upload *and* a new checkpoint hot-swaps in
  between dispatches without an AOT rebuild (:meth:`ServingEngine
  .swap_params`, :meth:`ServingEngine.watch_checkpoints`). Every
  response can report the generation that produced it
  (``predict(..., with_generation=True)``) and is never mixed-generation
  — a dispatch reads the reference once.
- **dynamic micro-batching** — concurrent callers coalesce into the
  smallest covering rung (:mod:`stmgcn_tpu.serving.microbatch`), with
  per-bucket latency/queue/pad-waste telemetry
  (:mod:`stmgcn_tpu.serving.metrics`).
- **SLO admission + typed sheds** — with ``ServingConfig.deadline_ms`` /
  ``queue_bound_rows`` set, overload sheds at arrival with typed errors
  (:mod:`stmgcn_tpu.serving.admission`); ``shed_policy="degrade"``
  serves shed requests inline at a smaller rung instead, and a wedged
  batcher degrades ``predict`` to the inline path automatically.

Both predictor flavors feed the same engine: ``from_forecaster`` bakes a
live checkpoint's dense serving clone, ``from_artifact`` specializes an
exported StableHLO module's symbolic batch to each rung (that flavor
bakes params into the module, so it cannot hot-swap). Import-leanness
contract: this module may import jax/numpy only at module scope — the
model stack (flax, stmgcn_tpu.models) loads lazily inside
``from_forecaster`` so ``import stmgcn_tpu.export`` stays lean
(``tests/test_export.py::test_export_module_is_lean``).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from stmgcn_tpu.obs.registry import REGISTRY
from stmgcn_tpu.serving.admission import (
    AdmissionController,
    BatcherWedged,
    ShedError,
)
from stmgcn_tpu.serving.metrics import EngineStats
from stmgcn_tpu.serving.microbatch import MicroBatcher

__all__ = ["CheckpointWatcher", "ServingEngine", "serve_bucket_fn"]

#: bound on the re-dispatch loop that keeps multi-chunk responses on one
#: param generation — hit only under pathological swap churn (a swap per
#: dispatch, twenty dispatches in a row)
_SWAP_RETRIES = 20


def serve_bucket_fn(model):
    """The per-bucket serving program (eval-mode forward, params explicit).

    The one function the live-path engine compiles per ladder rung — and
    the program the jaxpr contract pass traces as ``serve_bucket``, so a
    fusion regression in the serving forward fails ``stmgcn lint`` the
    same way a train-step regression does. Params stay an explicit
    argument of the compiled program (never closure-captured) — that is
    what makes :meth:`ServingEngine.swap_params` possible without
    recompiling the ladder.
    """

    def serve_bucket(params, supports, history):
        return model.apply(params, supports, history)

    return serve_bucket


def _check_swap_structure(cur_dev, new_dev) -> None:
    """The compiled ladder is shape-specialized: a hot-swap must present
    the exact same pytree structure and leaf shapes/dtypes, else the
    program would crash (or silently reinterpret bytes) mid-serve."""
    cur_leaves, cur_def = jax.tree_util.tree_flatten(cur_dev)
    new_leaves, new_def = jax.tree_util.tree_flatten(new_dev)
    if cur_def != new_def:
        raise ValueError(
            "swap_params: new params have a different pytree structure "
            "than the compiled programs were built for"
        )
    for a, b in zip(cur_leaves, new_leaves):
        if a.shape != b.shape or a.dtype != b.dtype:
            raise ValueError(
                f"swap_params: leaf mismatch — compiled for "
                f"{a.shape}/{a.dtype}, got {b.shape}/{b.dtype}"
            )


class CheckpointWatcher:
    """Hot-swap poller: newest verified checkpoint → ``engine.swap_params``.

    Watches ``out_dir`` by mtime and only ever moves *forward*: a new
    checkpoint that fails verification is quarantined by
    ``load_latest_verified`` and counted in :attr:`rejected` — the
    engine keeps serving its current params rather than falling back to
    a checkpoint older than the one already live. ``poll()`` is the
    synchronous single-step (what tests drive deterministically); a
    background thread calls it every ``poll_s`` seconds when one was
    requested. The engine's :class:`~stmgcn_tpu.resilience
    .ServeFaultPlan` gets its ``corrupt-checkpoint`` shot in *before*
    each scan, so the corruption path is exercised end-to-end.
    """

    def __init__(self, engine, out_dir: str, poll_s: Optional[float] = None,
                 log=None):
        self._engine = engine
        self.out_dir = out_dir
        self.swaps = 0
        self.rejected = 0
        self.last_path: Optional[str] = None
        self._log = log if log is not None else (lambda msg: None)
        # start from the present: the engine was just built from the
        # newest checkpoint, so only *future* writes should swap
        self._seen_mtime = self._newest_mtime() or -1.0
        self._applied_mtime = self._seen_mtime
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if poll_s is not None:
            self._thread = threading.Thread(
                target=self._loop, args=(float(poll_s),),
                name="stmgcn-ckpt-watch", daemon=True,
            )
            self._thread.start()

    def _newest_mtime(self) -> Optional[float]:
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return None
        mtimes = []
        for name in names:
            if not name.endswith(".ckpt"):
                continue
            try:
                mtimes.append(os.path.getmtime(os.path.join(self.out_dir, name)))
            except OSError:
                continue  # rotated away between listdir and stat
        return max(mtimes) if mtimes else None

    def _loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.poll()
            except Exception as e:  # keep watching: one bad scan (transient
                # IO, partial write) must not end hot-swapping forever
                self._log(f"checkpoint watch: {type(e).__name__}: {e}")

    def poll(self) -> bool:
        """One scan; returns True when a swap was applied."""
        from stmgcn_tpu.train.checkpoint import load_latest_verified

        eng = self._engine
        plan = getattr(eng, "_fault_plan", None)
        if plan is not None:
            for p in plan.corrupt_checkpoints(self.out_dir):
                self._log(f"fault plan corrupted {p}")
        newest = self._newest_mtime()
        if newest is None or newest <= self._seen_mtime:
            return False
        self._seen_mtime = newest
        got = load_latest_verified(
            self.out_dir, eng._params_template, None,
            load_opt_state=False, quarantine=True, log=self._log,
        )
        if got is None:
            self.rejected += 1
            REGISTRY.counter("serving.ckpt_rejected").inc()
            return False
        path, _meta, params, _ = got
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = newest
        if mtime <= self._applied_mtime:
            # the newest file failed verification and the chain fell back
            # to something no newer than what is already serving
            self.rejected += 1
            REGISTRY.counter("serving.ckpt_rejected").inc()
            return False
        eng.swap_params(
            params, health_baseline=_meta.get("health_baseline")
        )
        self.swaps += 1
        self.last_path = path
        self._applied_mtime = mtime
        return True

    #: stop() waits this long for an in-flight poll before detaching
    JOIN_TIMEOUT_S = 5.0

    def stop(self, timeout_s: Optional[float] = None) -> bool:
        """Signal the poll loop and join it, bounded by ``timeout_s``
        (default :attr:`JOIN_TIMEOUT_S`).

        Returns True when the thread exited within the timeout; False
        when an in-flight ``poll()`` is still finishing. Either way the
        stop event guarantees no *further* scans, and the thread is
        daemon, so a straggler cannot hold the process open — close()
        must never deadlock behind slow checkpoint IO.

        A False return is not silent: the ``serving.watcher_wedged``
        counter ticks and a structured ``_event`` log line names the
        watch directory, so a federation tier drain can report *which*
        replica's watcher refused to die instead of just timing out.
        """
        self._stop.set()
        t = self._thread
        if t is None:
            return True
        t.join(self.JOIN_TIMEOUT_S if timeout_s is None else timeout_s)
        if t.is_alive():
            REGISTRY.counter("serving.watcher_wedged").inc()
            self._log(
                f"_event=watcher_wedged dir={self.out_dir} — stop() timed "
                "out joining an in-flight poll; the stop event blocks "
                "further scans and the daemon thread cannot hold the "
                "process open"
            )
            return False
        self._thread = None
        return True


class ServingEngine:
    """Pre-compiled bucket ladder + micro-batcher over one model.

    Build with :meth:`from_forecaster` (live checkpoint) or
    :meth:`from_artifact` (exported StableHLO); then::

        engine = ServingEngine.from_forecaster(fc, supports)
        pred = engine.predict(history)          # micro-batched, raw units
        pred = engine.predict_direct(history)   # bypass the queue
        pred, gen = engine.predict(history, with_generation=True)
        engine.swap_params(new_params)          # atomic, no AOT rebuild
        watcher = engine.watch_checkpoints(out_dir, poll_s=2.0)
        engine.stats.snapshot()                 # per-bucket telemetry
        engine.close()

    ``predict`` keeps the predictors' validate → normalize → call →
    denormalize contract (normalization vectorized once per coalesced
    dispatch), so results are bit-identical to ``Forecaster.predict`` at
    any request size (padding parity pinned in tests/test_serving.py).
    Under an SLO config it raises the typed sheds of
    :mod:`stmgcn_tpu.serving.admission` (or serves degraded inline when
    ``shed_policy="degrade"``).
    """

    def __init__(self, programs, sup_dev, supports_np, normalizer, expected,
                 config, *, params_dev=None, fault_plan=None,
                 global_budget=None):
        self._programs = dict(programs)  # bucket -> call(params, hist) -> dev
        self._sup_dev = sup_dev
        self._supports_np = supports_np
        self.normalizer = normalizer
        self.expected = tuple(expected)  # (seq_len, n_nodes, input_dim)
        self.config = config
        self._buckets = tuple(sorted(self._programs))
        self.stats = EngineStats()
        # ONE reference holds (generation, device params): dispatches read
        # it once, swaps replace it whole — a response is never computed
        # from a mix of generations (CPython reference reads are atomic)
        self._current = (0, params_dev)
        self._prepare_params = None   # raw ckpt params -> serving params
        self._params_template = None  # pytree template for verified loads
        self._fault_plan = (
            fault_plan if fault_plan is not None and fault_plan.active else None
        )
        self._watcher: Optional[CheckpointWatcher] = None
        self.admission = (
            AdmissionController(config, self.stats, self._buckets,
                                global_budget=global_budget)
            if (config.deadline_ms is not None or config.queue_bound_rows
                or global_budget is not None)
            else None
        )
        self._batcher = MicroBatcher(
            self._run_program, self._buckets, config.max_delay_ms, self.stats,
            admission=self.admission, fault_plan=self._fault_plan,
        )
        #: live distribution-drift monitor (obs/drift.DriftMonitor); None
        #: until :meth:`enable_drift` attaches one
        self.drift = None
        self._drift_city = "0"
        self._closed = False

    # -- construction ---------------------------------------------------

    @staticmethod
    def _resolve_config(config):
        from stmgcn_tpu.config import ServingConfig

        cfg = config if config is not None else ServingConfig()
        bad = cfg.violations()
        if bad:
            raise ValueError("invalid serving config: " + "; ".join(bad))
        return cfg

    @staticmethod
    def _check_supports(supports, want) -> np.ndarray:
        supports_np = np.asarray(supports, dtype=np.float32)
        if supports_np.shape != tuple(want):
            raise ValueError(
                f"supports must be {tuple(want)}, got {supports_np.shape}"
            )
        return supports_np

    @classmethod
    def from_forecaster(cls, fc, supports, *, config=None, city=None,
                        fault_plan=None, global_budget=None) -> "ServingEngine":
        """Engine over a live :class:`~stmgcn_tpu.inference.Forecaster`.

        The checkpoint's model is rebuilt as its dense serving clone
        (``models.to_dense_serving`` — sparse/looped layouts restacked,
        pallas LSTM re-routed to xla) and each ladder rung compiled AOT
        with the supports pinned device-resident and params an explicit
        argument (hot-swappable). Heterogeneous multi-city checkpoints
        require ``city=`` exactly like ``export_forecaster``.
        ``fault_plan`` threads a deterministic
        :class:`~stmgcn_tpu.resilience.ServeFaultPlan` through the
        batcher and checkpoint watcher (tests only; the empty plan is a
        no-op).

        A :class:`~stmgcn_tpu.ops.tiling.TiledSupports` plan instead of a
        dense stack builds the *tiled* serving clone
        (``models.to_tiled_serving``): the large-N path, where the dense
        ``(M, K, N, N)`` stack would not even be worth materializing on
        device. Same engine contract — AOT rungs, resident supports,
        hot-swappable params (swaps go through the tiled transform).
        """
        from stmgcn_tpu.models import to_dense_serving, to_tiled_serving
        from stmgcn_tpu.ops.tiling import TiledSupports

        cfg = cls._resolve_config(
            config if config is not None else getattr(fc.config, "serving", None)
        )
        hetero = getattr(fc, "normalizers", None) is not None
        n_nodes, normalizer = fc.derived["n_nodes"], fc.normalizer
        if hetero:
            if city is None:
                raise ValueError(
                    "heterogeneous multi-city checkpoint: the engine bakes one "
                    "city's region count and normalizer — pass city="
                )
            if not 0 <= city < len(fc.normalizers):
                raise ValueError(
                    f"city must be in [0, {len(fc.normalizers)}), got {city}"
                )
            n_nodes = n_nodes[city]
            normalizer = fc.normalizers[city]
        elif city is not None:
            raise ValueError(
                "city= only applies to heterogeneous multi-city checkpoints"
            )

        m = fc.config.model.m_graphs
        tiled = isinstance(supports, TiledSupports)
        if tiled:
            model, params = to_tiled_serving(fc.model, fc.params, m)
            got = (supports.m_graphs, supports.n_supports, supports.n)
            want = (m, model.n_supports, n_nodes)
            if got != want:
                raise ValueError(
                    f"tiled supports must plan (M, K, N)={want}, got {got}"
                )
            supports_np = supports  # the plan IS the host-side artifact
            sup_dev = jax.device_put(supports)
        else:
            model, params = to_dense_serving(fc.model, fc.params, m)
            supports_np = cls._check_supports(
                supports, (m, model.n_supports, n_nodes, n_nodes)
            )
            sup_dev = jax.device_put(jnp.asarray(supports_np))
        params_dev = jax.tree.map(jnp.asarray, params)
        expected = (fc.seq_len, n_nodes, fc.derived["input_dim"])
        fn = serve_bucket_fn(model)

        programs = {}
        for b in cfg.buckets:
            struct = jax.ShapeDtypeStruct((b,) + expected, jnp.float32)
            compiled = jax.jit(fn).lower(params_dev, sup_dev, struct).compile()
            # supports are the SAME resident array every call; params come
            # from the engine's (generation, params) reference — the numpy
            # history batch is the only per-request upload (Compiled takes
            # it as-is; wrapping in jnp.asarray first just adds a
            # dispatch-path round trip)
            programs[b] = lambda p, h, c=compiled: c(p, sup_dev, h)
        engine = cls(programs, sup_dev, supports_np, normalizer, expected,
                     cfg, params_dev=params_dev, fault_plan=fault_plan,
                     global_budget=global_budget)
        # hot-swap plumbing: raw checkpoint params go through the same
        # serving transform the ladder was compiled for, and verified
        # loads restore against the live checkpoint's pytree
        engine._prepare_params = (
            (lambda p: to_tiled_serving(fc.model, p, m)[1])
            if tiled
            else (lambda p: to_dense_serving(fc.model, p, m)[1])
        )
        engine._params_template = fc.params
        hb = getattr(fc, "health_baseline", None)
        hcfg = getattr(fc.config, "health", None)
        if hb is not None and hcfg is not None and hcfg.drift:
            engine.enable_drift(hb, city=city if city is not None else 0)
        return engine

    @classmethod
    def from_artifact(cls, source, supports, *, config=None, fault_plan=None
                      ) -> "ServingEngine":
        """Engine over an export artifact (path or loaded
        :class:`~stmgcn_tpu.export.ExportedForecaster`).

        The artifact's symbolic-batch StableHLO module is specialized and
        compiled per ladder rung. The wrapped predictor is re-routed:
        ``ex.predict(supports, history)`` now goes through the engine's
        buckets (same supports required — the engine pinned them).
        Artifact params are baked into the StableHLO module, so this
        flavor cannot ``swap_params`` — rebuild from a new artifact.
        """
        from stmgcn_tpu.export import ExportedForecaster

        ex = ExportedForecaster.load(source) if isinstance(source, str) else source
        cfg = cls._resolve_config(config)
        meta = ex.meta
        supports_np = cls._check_supports(
            supports,
            (meta["m_graphs"], meta["n_supports"], meta["n_nodes"],
             meta["n_nodes"]),
        )
        sup_dev = jax.device_put(jnp.asarray(supports_np))
        expected = (meta["seq_len"], meta["n_nodes"], meta["input_dim"])

        programs = {}
        for b in cfg.buckets:
            struct = jax.ShapeDtypeStruct((b,) + expected, jnp.float32)
            compiled = jax.jit(ex.exported.call).lower(sup_dev, struct).compile()
            programs[b] = lambda p, h, c=compiled: c(sup_dev, h)
        engine = cls(programs, sup_dev, supports_np, ex.normalizer, expected,
                     cfg, fault_plan=fault_plan)
        engine.exported = ex
        ex._engine = engine  # route ex.predict through the bucket ladder
        return engine

    # -- drift ----------------------------------------------------------

    def enable_drift(self, baseline: dict, *, city: int = 0,
                     registry=REGISTRY):
        """Attach a :class:`stmgcn_tpu.obs.drift.DriftMonitor` comparing
        live traffic against a training-time ``health_baseline`` blob
        (checkpoint meta). Auto-attached by ``from_forecaster`` when the
        checkpoint carries a baseline and its config enables
        ``health.drift``. Returns the monitor."""
        from stmgcn_tpu.obs.drift import DriftMonitor

        self._drift_city = str(city)
        self.drift = DriftMonitor(
            baseline, registry=registry, generation=self.generation
        )
        return self.drift

    def drift_snapshot(self) -> Optional[dict]:
        """JSON-able live drift state, or None without a monitor."""
        return None if self.drift is None else self.drift.snapshot()

    # -- hot swap --------------------------------------------------------

    @property
    def generation(self) -> int:
        """Monotonic param-generation counter (0 = construction params)."""
        return self._current[0]

    def swap_params(self, params, *, health_baseline=None) -> int:
        """Atomically replace the serving parameters; returns the new
        generation.

        ``params`` is a *raw checkpoint* pytree (``Forecaster.params``
        shape) — it goes through the same dense-serving transform the
        ladder was compiled from, is structure/shape-checked against the
        live params, placed on device, and published as one reference
        swap. In-flight dispatches finish on the generation they read at
        entry; every later dispatch sees the new one. No AOT rebuild:
        the compiled programs take params as an argument.

        An attached drift monitor resets atomically with the swap — its
        live sketches drop so gauges never mix traffic across param
        generations; ``health_baseline`` (the new checkpoint's blob, when
        the watcher has one) replaces the comparison baseline too.
        """
        if self._prepare_params is None:
            raise RuntimeError(
                "this engine was built from_artifact — params are baked "
                "into the exported StableHLO module; rebuild the engine "
                "from a new artifact to change them"
            )
        new_dev = jax.tree.map(jnp.asarray, self._prepare_params(params))
        gen, cur_dev = self._current
        _check_swap_structure(cur_dev, new_dev)
        self._current = (gen + 1, new_dev)
        if self.drift is not None:
            self.drift.reset(gen + 1, baseline=health_baseline)
        REGISTRY.counter("serving.swaps").inc()
        REGISTRY.gauge("serving.generation").set(gen + 1)
        return gen + 1

    def watch_checkpoints(self, out_dir: str, *, poll_s: Optional[float] = None,
                          log=None) -> CheckpointWatcher:
        """Hot-swap new verified checkpoints from ``out_dir`` as they land.

        ``poll_s=None`` returns a passive handle — call ``.poll()``
        yourself (deterministic; what the tests do). With ``poll_s`` a
        daemon thread polls on that period until ``.stop()`` or the
        engine closes. Corrupt checkpoints are quarantined by the
        verified-load chain and never swapped in; the engine keeps its
        current params (counted in ``watcher.rejected``).
        """
        if self._prepare_params is None:
            raise RuntimeError(
                "from_artifact engines cannot hot-swap — no checkpoint "
                "watcher"
            )
        if self._watcher is not None:
            self._watcher.stop()
        self._watcher = CheckpointWatcher(self, out_dir, poll_s, log)
        return self._watcher

    # -- serving --------------------------------------------------------

    @property
    def buckets(self) -> tuple:
        return self._buckets

    def _run_program(self, payload: np.ndarray, bucket: int, segments):
        """One dispatch: normalize (vectorized, once per *batch* — not
        once per request), pad to the rung, run the compiled program,
        denormalize. Returns ``(predictions, generation)`` — the batcher
        stamps the generation on every coalesced request, so the stamp
        is atomic with the params the dispatch actually used.
        ``segments`` is ``((offset, n_rows, pre_normalized), ...)`` in
        payload order; pre-normalized rows are kept verbatim.
        Elementwise normalization + row-independent forward keep the
        result bit-identical to the per-request flow."""
        from stmgcn_tpu.serving.bucketing import pad_to_bucket

        gen, params_dev = self._current  # ONE read — whole dispatch, one gen
        norm = self.normalizer
        if norm is None or all(pre for _, _, pre in segments):
            batch = payload
        else:
            batch = norm.transform(payload)
            for ofs, n, pre in segments:
                if pre:
                    batch[ofs:ofs + n] = payload[ofs:ofs + n]
        out = np.asarray(
            self._programs[bucket](params_dev, pad_to_bucket(batch, bucket))
        )
        out = norm.inverse(out) if norm is not None else out
        if self.drift is not None:
            # real rows only: batch is payload-sized (pre-pad) and the
            # padded prediction rows are bucket filler, not traffic
            n_rows = payload.shape[0]
            self.drift.observe_input(self._drift_city, batch[:n_rows])
            self.drift.observe_prediction(self._drift_city, out[:n_rows])
        return out, gen

    def _call_batched(self, history: np.ndarray, normalized: bool):
        """Micro-batched path; returns ``(out, generation)`` with every
        chunk of an oversized batch on the SAME generation (stale chunks
        re-dispatch until the generations agree — gen only moves forward,
        so the loop converges unless swaps outrun dispatches)."""
        cap = self._buckets[-1]
        if history.shape[0] <= cap:
            out, gen = self._batcher.submit(
                history, tag=normalized, with_info=True
            )
            return out, gen
        spans = [
            (i, min(i + cap, history.shape[0]))
            for i in range(0, history.shape[0], cap)
        ]
        parts: list = [None] * len(spans)
        gens: list = [None] * len(spans)
        for _ in range(_SWAP_RETRIES):
            target = max((g for g in gens if g is not None), default=None)
            for k, (i, j) in enumerate(spans):
                if gens[k] is None or gens[k] != target:
                    parts[k], gens[k] = self._batcher.submit(
                        history[i:j], tag=normalized, with_info=True
                    )
            if len(set(gens)) == 1:
                return np.concatenate(parts, axis=0), gens[0]
        raise RuntimeError(
            "could not assemble a single-generation response in "
            f"{_SWAP_RETRIES} rounds — params are swapping faster than "
            "dispatches complete"
        )

    def _dispatch_inline(self, chunk: np.ndarray, normalized: bool):
        import time

        from stmgcn_tpu.serving.bucketing import smallest_covering_bucket

        bucket = smallest_covering_bucket(chunk.shape[0], self._buckets)
        t0 = time.perf_counter()
        out, gen = self._run_program(
            chunk, bucket, ((0, chunk.shape[0], normalized),)
        )
        device_ms = (time.perf_counter() - t0) * 1e3
        self.stats.record_dispatch(bucket, chunk.shape[0], [0.0], device_ms)
        return out[:chunk.shape[0]], gen

    def _call_direct(self, history: np.ndarray, normalized: bool,
                     cap: Optional[int] = None):
        """Inline path; ``cap`` chunks at a smaller rung (the degrade
        policy's knob). Returns ``(out, generation)`` — same one-
        generation re-dispatch rule as the batched path."""
        cap = cap if cap is not None else self._buckets[-1]
        spans = [
            (i, min(i + cap, history.shape[0]))
            for i in range(0, history.shape[0], cap)
        ]
        parts: list = [None] * len(spans)
        gens: list = [None] * len(spans)
        for _ in range(_SWAP_RETRIES):
            target = max((g for g in gens if g is not None), default=None)
            for k, (i, j) in enumerate(spans):
                if gens[k] is None or gens[k] != target:
                    parts[k], gens[k] = self._dispatch_inline(
                        history[i:j], normalized
                    )
            if len(set(gens)) == 1:
                out = (
                    parts[0] if len(parts) == 1
                    else np.concatenate(parts, axis=0)
                )
                return out, gens[0]
        raise RuntimeError(
            "could not assemble a single-generation response in "
            f"{_SWAP_RETRIES} rounds — params are swapping faster than "
            "dispatches complete"
        )

    def _validate(self, history) -> np.ndarray:
        history = np.asarray(history, dtype=np.float32)
        if history.ndim != 4 or history.shape[1:] != self.expected:
            raise ValueError(
                f"history must be (B, seq_len={self.expected[0]}, "
                f"n_nodes={self.expected[1]}, n_feats={self.expected[2]}) "
                f"for this model, got {history.shape}"
            )
        return history

    def predict(self, history, *, normalized: bool = False,
                with_generation: bool = False) -> np.ndarray:
        """Micro-batched raw-units forecast — the concurrent-caller path.

        Blocks until this request's coalesced dispatch completes; results
        are bit-identical to ``Forecaster.predict`` on the same rows
        (parity pinned in tests/test_serving.py). Normalization happens
        inside the coalesced dispatch, vectorized over the whole bucket.

        Overload behavior (``ServingConfig`` SLO knobs set): sheds raise
        :class:`~stmgcn_tpu.serving.admission.Overloaded` /
        :class:`~stmgcn_tpu.serving.admission.DeadlineExceeded` under
        ``shed_policy="reject"``; ``"degrade"`` serves the request inline
        at ``degrade_rung`` instead. A wedged batcher (worker died) falls
        back to the inline path unconditionally — callers never hang.
        ``with_generation=True`` returns ``(pred, generation)``.
        """
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        h = self._validate(history)
        try:
            out, gen = self._call_batched(h, normalized)
        except BatcherWedged:
            out, gen = self._call_direct(h, normalized)
        except ShedError:
            if self.config.shed_policy != "degrade":
                raise
            self.stats.record_shed("degraded")
            out, gen = self._call_direct(
                h, normalized,
                cap=self.config.degrade_rung or self._buckets[0],
            )
        return (out, gen) if with_generation else out

    def predict_direct(self, history, *, normalized: bool = False,
                       with_generation: bool = False) -> np.ndarray:
        """Bypass the queue: pad to the covering rung and dispatch inline
        (the latency-critical single-caller path; same results).
        ``with_generation=True`` returns ``(pred, generation)``."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        out, gen = self._call_direct(self._validate(history), normalized)
        return (out, gen) if with_generation else out

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._watcher is not None:
                self._watcher.stop()
            self._batcher.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
