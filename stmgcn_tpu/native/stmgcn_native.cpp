// Native host-side kernels for stmgcn-tpu.
//
// The reference's host pipeline is pure Python/numpy (SURVEY.md §2: zero
// native components in the repo; its speed came from PyTorch's bundled
// kernels). These are the TPU build's host-runtime equivalents for the two
// paths that sit before device execution:
//
//   window_gather      — the sliding-window featurizer's gather
//                        (Data_Container.py:125-146 semantics, vectorized):
//                        one pass, writing straight into the output buffer
//                        instead of materializing numpy fancy-index temps.
//   nonzero_block_scan — the block-sparsity structure scan behind
//                        ops/spmm.from_dense: marks which (tile x tile)
//                        blocks of a padded (n_pad, n_pad) matrix are
//                        nonzero, without numpy's (R, R, T, T) reduction
//                        temporaries.
//
// Built as a plain C ABI shared library (ctypes binding in __init__.py);
// every function has a numpy fallback, so the library is an accelerator,
// never a requirement.

#include <cstdint>
#include <cstring>

extern "C" {

// data: (T, N, C) float32 row-major. offsets: n_off gather offsets relative
// to each target t in [burn_in, T). Writes x: (S, n_off, N, C) and
// y: (S, N, C) where S = T - burn_in.
void window_gather(const float* data, int64_t T, int64_t N, int64_t C,
                   const int64_t* offsets, int64_t n_off, int64_t burn_in,
                   float* x_out, float* y_out) {
  const int64_t frame = N * C;
  const int64_t S = T - burn_in;
  const size_t frame_bytes = static_cast<size_t>(frame) * sizeof(float);
  for (int64_t s = 0; s < S; ++s) {
    const int64_t t = burn_in + s;
    float* xrow = x_out + static_cast<size_t>(s) * n_off * frame;
    for (int64_t o = 0; o < n_off; ++o) {
      std::memcpy(xrow + static_cast<size_t>(o) * frame,
                  data + static_cast<size_t>(t + offsets[o]) * frame,
                  frame_bytes);
    }
    std::memcpy(y_out + static_cast<size_t>(s) * frame,
                data + static_cast<size_t>(t) * frame, frame_bytes);
  }
}

// mat: (nr_pad, nc_pad) float32, both dims % tile == 0. nz: (Rr, Rc) uint8
// output (Rr = nr_pad / tile, Rc = nc_pad / tile), set to 1 where the block
// holds any nonzero. Rectangular form: row strips of region-sharded
// supports are (n_local, N).
void nonzero_block_scan_rect(const float* mat, int64_t nr_pad, int64_t nc_pad,
                             int64_t tile, unsigned char* nz) {
  const int64_t Rc = nc_pad / tile;
  for (int64_t i = 0; i < nr_pad; ++i) {
    const float* row = mat + static_cast<size_t>(i) * nc_pad;
    unsigned char* nzrow = nz + (i / tile) * Rc;
    for (int64_t j = 0; j < nc_pad; ++j) {
      if (row[j] != 0.0f) {
        nzrow[j / tile] = 1;
        // skip to the next block boundary: everything until there maps to
        // the same nz entry
        j = ((j / tile) + 1) * tile - 1;
      }
    }
  }
}

// Square back-compat wrapper (the original ABI).
void nonzero_block_scan(const float* mat, int64_t n_pad, int64_t tile,
                        unsigned char* nz) {
  nonzero_block_scan_rect(mat, n_pad, n_pad, tile, nz);
}

}  // extern "C"
