"""ctypes binding for the native host kernels, with transparent fallback.

The shared library is built on demand (``g++`` via the Makefile) and
cached next to the sources; if the toolchain or binary is unavailable —
or ``STMGCN_NATIVE=0`` is set — callers get ``None``/False and use their
numpy fallbacks. The native path is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

__all__ = ["available", "window_gather", "nonzero_block_scan", "nonzero_block_scan_rect"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libstmgcn_native.so")
_SRC = os.path.join(_DIR, "stmgcn_native.cpp")

_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("STMGCN_NATIVE", "1") == "0":
        return None
    try:
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            subprocess.run(
                ["make", "-s", "-C", _DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        lib = ctypes.CDLL(_SO)
        lib.window_gather.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.window_gather.restype = None
        lib.nonzero_block_scan.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_ubyte),
        ]
        lib.nonzero_block_scan.restype = None
        lib.nonzero_block_scan_rect.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_ubyte),
        ]
        lib.nonzero_block_scan_rect.restype = None
        _lib = lib
    except (OSError, subprocess.SubprocessError):
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def window_gather(data: np.ndarray, offsets: np.ndarray, burn_in: int):
    """Native ``(x, y)`` window extraction; ``None`` when the library is absent.

    Semantics identical to the numpy gather in
    :func:`stmgcn_tpu.data.windowing.sliding_windows`.
    """
    lib = _load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, dtype=np.float32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    T, N, C = data.shape
    S = T - burn_in
    x = np.empty((S, len(offsets), N, C), dtype=np.float32)
    y = np.empty((S, N, C), dtype=np.float32)
    lib.window_gather(
        _fptr(data), T, N, C,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(offsets),
        burn_in, _fptr(x), _fptr(y),
    )
    return x, y


def nonzero_block_scan(padded: np.ndarray, tile: int):
    """Native ``(R, R)`` bool nonzero-block map; ``None`` when unavailable."""
    return nonzero_block_scan_rect(padded, tile)


def nonzero_block_scan_rect(padded: np.ndarray, tile: int):
    """Native ``(Rr, Rc)`` bool nonzero-block map of a rectangular padded
    matrix; ``None`` when unavailable."""
    lib = _load()
    if lib is None:
        return None
    padded = np.ascontiguousarray(padded, dtype=np.float32)
    nr_pad, nc_pad = padded.shape
    rr, rc = nr_pad // tile, nc_pad // tile
    nz = np.zeros((rr, rc), dtype=np.uint8)
    lib.nonzero_block_scan_rect(
        _fptr(padded), nr_pad, nc_pad, tile,
        nz.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
    )
    return nz.astype(bool)
