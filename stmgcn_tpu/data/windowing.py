"""Vectorized serial / daily-periodic / weekly-periodic window extraction.

Reference semantics (``Data_Container.py:125-146``, verified in SURVEY.md §2
C3/C5) reproduced exactly, but as **one fancy-index gather** over a
precomputed offset table instead of a Python loop over every timestep — the
reference's hottest host-side loop (SURVEY.md §3.1).

Pinned semantics:

- burn-in ``= max(serial_len, daily_len*day_steps, weekly_len*day_steps*7)``
  (``Data_Container.py:127``): the first sample's target is the first
  timestep with a full history.
- serial component: the ``serial_len`` timesteps immediately before the
  target (``Data_Container.py:129``).
- periodic components use skip stride ``p_len * period`` — i.e. the *d*-th
  daily lag sits ``d * daily_len`` days back, not ``d`` days
  (``Data_Container.py:138-140``); same for weekly with period ``7`` — and
  are emitted oldest-first (the ``[::-1]`` at ``Data_Container.py:145``).
- concatenation order along the sequence axis is
  ``[weekly | daily | serial]`` (``Data_Container.py:83-86``), with
  zero-length components skipped (the ``ndim != 2`` test at
  ``Data_Container.py:84``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["WindowSpec", "sliding_windows"]


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    """Observation-window lengths (reference CLI ``-cpt s d w``, ``Main.py:30-33``).

    ``day_timesteps`` is the number of timesteps per day (``24 // dt``,
    ``Data_Container.py:96``).
    """

    serial_len: int = 3
    daily_len: int = 1
    weekly_len: int = 1
    day_timesteps: int = 24
    #: forecast steps per sample; 1 reproduces the reference's next-step
    #: target (``Data_Container.py:132``), H>1 makes targets ``t .. t+H-1``
    horizon: int = 1

    def __post_init__(self):
        if min(self.serial_len, self.daily_len, self.weekly_len) < 0:
            raise ValueError("window lengths must be >= 0")
        if self.seq_len == 0:
            raise ValueError("at least one window component must be non-empty")
        if self.day_timesteps <= 0:
            raise ValueError("day_timesteps must be positive")
        if self.horizon < 1:
            raise ValueError("horizon must be >= 1")

    @property
    def seq_len(self) -> int:
        """Total model sequence length (reference ``sum(obs_len)``, ``Main.py:62``)."""
        return self.serial_len + self.daily_len + self.weekly_len

    @property
    def burn_in(self) -> int:
        """Timesteps of history needed before the first target.

        The reference computes ``max(s, d*day_steps, w*day_steps*7)``
        (``Data_Container.py:127``), but because the periodic skip stride is
        itself scaled by the component length (``p_steps * k`` for lag ``k``,
        ``Data_Container.py:138-144``) the deepest lag reaches
        ``p_len**2 * period`` timesteps back — for ``daily_len`` or
        ``weekly_len`` >= 2 the reference's first samples wrap to *negative*
        indices and silently read future data. Fixed here by covering the
        deepest actual lag; identical to the reference for the default
        ``(3, 1, 1)`` config (168).
        """
        return max(
            self.serial_len,
            self.daily_len**2 * self.day_timesteps,
            self.weekly_len**2 * self.day_timesteps * 7,
        )

    def n_samples(self, n_timesteps: int) -> int:
        """Windowed sample count for a ``T``-timestep series."""
        return n_timesteps - self.burn_in - (self.horizon - 1)

    @property
    def offsets(self) -> np.ndarray:
        """Gather offsets (relative to the target index) in ``[weekly|daily|serial]`` order."""
        parts = []
        if self.weekly_len:
            stride = self.weekly_len * self.day_timesteps * 7
            parts.append(-stride * np.arange(self.weekly_len, 0, -1))
        if self.daily_len:
            stride = self.daily_len * self.day_timesteps
            parts.append(-stride * np.arange(self.daily_len, 0, -1))
        if self.serial_len:
            parts.append(np.arange(-self.serial_len, 0))
        return np.concatenate(parts)

    def target_indices(self, n_timesteps: int) -> np.ndarray:
        """Target timesteps for every sample of a ``T``-step series.

        Sample ``i`` targets timestep ``burn_in + i``; its observation
        window is ``series[target + offsets]`` and its label is
        ``series[target : target + horizon]``. This is the whole sample
        enumeration — :func:`sliding_windows` is exactly the gather of
        these targets, which is what lets the window-free resident path
        ship targets + offsets instead of materialized windows.
        """
        return np.arange(self.burn_in, n_timesteps - self.horizon + 1)


def sliding_windows(data, spec: WindowSpec) -> tuple[np.ndarray, np.ndarray]:
    """Extract all ``(x_seq, y)`` samples from a ``(T, N, C)`` demand tensor.

    Returns ``x`` of shape ``(S, seq_len, N, C)`` and ``y`` of shape
    ``(S, N, C)`` for ``horizon == 1`` (reference parity) or
    ``(S, horizon, N, C)`` for multi-step forecasting, where
    ``S = T - spec.burn_in - (spec.horizon - 1)``; sample ``i``'s first
    target is timestep ``spec.burn_in + i``. Equivalent to the reference's
    ``get_feats`` + per-mode concatenation (``Data_Container.py:125-146`` and
    ``:82-86``) in a single gather.
    """
    data = np.asarray(data)
    if data.ndim < 1:
        raise ValueError("data must have a leading time axis")
    T = data.shape[0]
    h = spec.horizon
    if T <= spec.burn_in + h - 1:
        raise ValueError(
            f"need more than burn_in+horizon-1={spec.burn_in + h - 1} "
            f"timesteps, got T={T}"
        )
    if h == 1 and data.ndim == 3 and data.dtype == np.float32:
        # native single-pass gather (stmgcn_tpu/native), numpy fallback below
        from stmgcn_tpu import native

        got = native.window_gather(data, spec.offsets, spec.burn_in)
        if got is not None:
            return got
    targets = spec.target_indices(T)
    x = data[targets[:, None] + spec.offsets[None, :]]
    if h == 1:
        y = data[targets]
    else:
        y = data[targets[:, None] + np.arange(h)[None, :]]
    return x, y
