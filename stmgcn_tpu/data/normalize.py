"""Normalization with externally-storable statistics.

Reference: ``Data_Container.py:31-51`` (min-max to ``[-1, 1]`` and the unused
std pair). The reference keeps ``_min``/``_max`` as hidden attributes on the
live ``DataInput`` object, so its saved checkpoints cannot denormalize
without re-running the loader (SURVEY.md §5.d). Here the statistics are an
explicit, serializable value that travels inside the training checkpoint.

Parity notes: statistics are fit over the *entire* tensor (train and test
together), exactly like ``DataInput.load_data`` (``Data_Container.py:21``),
and the min-max transform maps to ``[-1, 1]`` via ``2x - 1``
(``Data_Container.py:34-35``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["MinMaxNormalizer", "StdNormalizer", "normalizer_from_dict"]


@dataclasses.dataclass(frozen=True)
class MinMaxNormalizer:
    """Min-max to ``[-1, 1]``; reference ``Data_Container.py:31-41``."""

    minimum: float
    maximum: float

    @classmethod
    def fit(cls, x) -> "MinMaxNormalizer":
        x = np.asarray(x)
        lo, hi = float(x.min()), float(x.max())
        if hi == lo:
            # The reference silently divides by zero here
            # (Data_Container.py:34); fail loudly instead of emitting NaN.
            raise ValueError(
                f"cannot min-max normalize constant data (min == max == {lo})"
            )
        return cls(minimum=lo, maximum=hi)

    @property
    def scale(self) -> float:
        return self.maximum - self.minimum

    def transform(self, x):
        x = (x - self.minimum) / self.scale
        return 2.0 * x - 1.0

    def inverse(self, x):
        x = (x + 1.0) / 2.0
        return self.scale * x + self.minimum

    def to_dict(self) -> dict:
        return {"kind": "minmax", "minimum": self.minimum, "maximum": self.maximum}


@dataclasses.dataclass(frozen=True)
class StdNormalizer:
    """Zero-mean unit-variance; reference ``Data_Container.py:43-51``."""

    mean: float
    std: float

    @classmethod
    def fit(cls, x) -> "StdNormalizer":
        x = np.asarray(x)
        std = float(x.std())
        if std == 0.0:
            raise ValueError("cannot std-normalize constant data (std == 0)")
        return cls(mean=float(x.mean()), std=std)

    def transform(self, x):
        return (x - self.mean) / self.std

    def inverse(self, x):
        return x * self.std + self.mean

    def to_dict(self) -> dict:
        return {"kind": "std", "mean": self.mean, "std": self.std}


def normalizer_from_dict(d: dict):
    kind = d.get("kind")
    if kind == "minmax":
        return MinMaxNormalizer(minimum=d["minimum"], maximum=d["maximum"])
    if kind == "std":
        return StdNormalizer(mean=d["mean"], std=d["std"])
    raise ValueError(f"unknown normalizer kind {kind!r}")
