"""Date-range driven train/validate/test splits, in timestep units.

Reference: ``DataGenerator.date2len`` (``Data_Container.py:102-112``) maps
``MMDD`` date strings to split *lengths* in timesteps, carves validation off
the end of train with ``val_ratio``, and places test immediately after
validation ("Test follows train", ``Main.py:27``).

Fixed here (SURVEY.md §2 quirk 3): the reference returns the train start as
a **day** index and uses it directly to index **timestep**-resolution sample
arrays, and never subtracts the windowing burn-in — correct only for the
default ``-date 0101 ...`` start. This module converts the start date to
timesteps, subtracts the burn-in, and validates that every split fits inside
the available samples.
"""

from __future__ import annotations

import dataclasses
import datetime
import warnings

__all__ = ["SplitSpec", "date_splits", "fraction_splits"]

MODES = ("train", "validate", "test")


@dataclasses.dataclass(frozen=True)
class SplitSpec:
    """Contiguous sample ranges per mode over a windowed sample array."""

    start_idx: int
    mode_len: dict  # {"train": int, "validate": int, "test": int}

    def range_for(self, mode: str) -> tuple[int, int]:
        """Half-open ``[start, stop)`` sample range for ``mode``.

        Cumulative offsets exactly as ``TaxiDataset.prepare_xy``
        (``Data_Container.py:75-80``).
        """
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        start = self.start_idx
        for m in MODES:
            if m == mode:
                break
            start += self.mode_len[m]
        return start, start + self.mode_len[mode]

    @property
    def total(self) -> int:
        return sum(self.mode_len.values())

    def validate_against(self, n_samples: int) -> "SplitSpec":
        if self.start_idx + self.total > n_samples:
            raise ValueError(
                f"splits need {self.start_idx + self.total} samples but only "
                f"{n_samples} exist"
            )
        return self


def fraction_splits(
    n_samples: int, train: float = 0.7, validate: float = 0.1
) -> SplitSpec:
    """Fractional contiguous splits for date-less (e.g. synthetic) data.

    Test takes the remainder. Same contiguous train->validate->test layout
    as the date-driven path.
    """
    if not 0 < train < 1 or not 0 <= validate < 1 or train + validate >= 1:
        raise ValueError(f"invalid fractions train={train}, validate={validate}")
    train_len = int(n_samples * train)
    val_len = int(n_samples * validate)
    test_len = n_samples - train_len - val_len
    return SplitSpec(
        start_idx=0,
        mode_len={"train": train_len, "validate": val_len, "test": test_len},
    ).validate_against(n_samples)


def _day_of_year(year: int, mmdd: str) -> int:
    d = datetime.date(year, int(mmdd[:2]), int(mmdd[2:]))
    return (d - datetime.date(year, 1, 1)).days


def date_splits(
    dates,
    *,
    burn_in: int,
    day_timesteps: int = 24,
    val_ratio: float = 0.2,
    year: int = 2017,
    n_samples: int | None = None,
) -> SplitSpec:
    """Build a :class:`SplitSpec` from ``[train_start, train_end, test_start, test_end]``.

    Lengths match the reference exactly: ``train = days * day_timesteps``
    with ``validate = int(train * val_ratio)`` carved off the end
    (``Data_Container.py:104-108``), ``test = test-days * day_timesteps``
    (``:109-111``). The start index is converted to timesteps and shifted by
    ``burn_in`` (the unit-bug fix), clamped at the first available sample:
    when the train start date falls inside the initial burn-in window (as
    the default ``0101`` start does) the split begins at the first sample
    with a full history — the position the reference's ``start_idx = 0``
    denotes. A clamp that actually moves a non-day-0 start is warned about.
    ``burn_in`` is a required keyword (pass ``WindowSpec.burn_in``) so the
    fix cannot be silently skipped. Pass ``n_samples`` to bounds-check the
    split extents.
    """
    if len(dates) != 4:
        raise ValueError("dates must be [train_start, train_end, test_start, test_end]")
    t0, t1, s0, s1 = (_day_of_year(year, d) for d in dates)
    if t1 < t0 or s1 < s0:
        raise ValueError(f"date ranges must be ascending, got {dates}")
    if s0 != t1 + 1:
        # The test dates only determine the split *length*; the test range is
        # always placed immediately after validation ("Test follows train",
        # Main.py:27-28). A gap or overlap between the ranges means the test
        # samples do not cover the dates the caller named — surface that.
        warnings.warn(
            f"test start {dates[2]} is not the day after train end {dates[1]}; "
            "the test split is placed contiguously after validation, so its "
            "samples will not correspond to the named test dates",
            stacklevel=2,
        )
    train_len = (t1 + 1 - t0) * day_timesteps
    val_len = int(train_len * val_ratio)
    train_len -= val_len
    test_len = (s1 + 1 - s0) * day_timesteps
    if 0 < t0 * day_timesteps < burn_in:
        warnings.warn(
            f"train start {dates[0]} falls inside the {burn_in}-timestep window "
            "burn-in; the split begins at the first sample with a full history, "
            f"{burn_in - t0 * day_timesteps} timesteps after the named date",
            stacklevel=2,
        )
    spec = SplitSpec(
        start_idx=max(0, t0 * day_timesteps - burn_in),
        mode_len={"train": train_len, "validate": val_len, "test": test_len},
    )
    if n_samples is not None:
        spec.validate_against(n_samples)
    return spec
