"""Synthetic demand data with realistic spatiotemporal structure.

The reference's dataset (``./data/data_dict.npz``, ``Main.py:9``) is not
shipped, so the framework generates synthetic city-demand tensors with the
same schema for tests, smoke configs, and benchmarking (BASELINE.md: the
baseline must be *established* on synthetic data of matching shape).

The generator composes daily and weekly sinusoidal cycles with per-region
phase/amplitude variation, spatially-correlated noise diffused over the
region grid, and non-negativity — enough structure that the periodic
windows carry real signal and a model can beat persistence.
"""

from __future__ import annotations

import numpy as np

__all__ = ["grid_adjacency", "synthetic_demand", "synthetic_dataset"]


def grid_adjacency(rows: int, cols: int | None = None, diagonal: bool = False) -> np.ndarray:
    """Rook (or queen, with ``diagonal=True``) adjacency of a rows x cols region grid."""
    cols = rows if cols is None else cols
    n = rows * cols
    adj = np.zeros((n, n), dtype=np.float32)
    steps = [(0, 1), (1, 0)]
    if diagonal:
        steps += [(1, 1), (1, -1)]
    for r in range(rows):
        for c in range(cols):
            i = r * cols + c
            for dr, dc in steps:
                rr, cc = r + dr, c + dc
                if 0 <= rr < rows and 0 <= cc < cols:
                    j = rr * cols + cc
                    adj[i, j] = adj[j, i] = 1.0
    return adj


def synthetic_demand(
    n_timesteps: int,
    n_nodes: int,
    n_feats: int = 1,
    day_timesteps: int = 24,
    seed: int = 0,
) -> np.ndarray:
    """``(T, N, C)`` non-negative demand with daily/weekly cycles per region."""
    rng = np.random.default_rng(seed)
    t = np.arange(n_timesteps)[:, None, None]  # (T, 1, 1)
    base = rng.gamma(shape=2.0, scale=20.0, size=(1, n_nodes, n_feats))
    day_phase = rng.uniform(0, 2 * np.pi, size=(1, n_nodes, n_feats))
    week_phase = rng.uniform(0, 2 * np.pi, size=(1, n_nodes, n_feats))
    day_amp = rng.uniform(0.3, 0.8, size=(1, n_nodes, n_feats))
    week_amp = rng.uniform(0.1, 0.4, size=(1, n_nodes, n_feats))
    daily = day_amp * np.sin(2 * np.pi * t / day_timesteps + day_phase)
    weekly = week_amp * np.sin(2 * np.pi * t / (day_timesteps * 7) + week_phase)
    noise = 0.1 * rng.standard_normal((n_timesteps, n_nodes, n_feats))
    demand = base * (1.0 + daily + weekly + noise)
    return np.maximum(demand, 0.0).astype(np.float32)


def synthetic_dataset(
    rows: int = 10,
    cols: int | None = None,
    n_timesteps: int = 24 * 7 * 6,
    n_feats: int = 1,
    m_graphs: int = 3,
    day_timesteps: int = 24,
    seed: int = 0,
):
    """A full in-memory dataset: demand + M adjacencies on a region grid.

    Graph views mirror the reference's three (``Data_Container.py:23-28``):
    spatial neighborhood (grid rook), transport connectivity (random sparse
    symmetric links), and functional similarity (similarity of mean demand
    profiles).
    """
    from stmgcn_tpu.data.loader import ADJ_KEYS, DemandData

    cols = rows if cols is None else cols
    n = rows * cols
    rng = np.random.default_rng(seed + 1)
    demand = synthetic_demand(n_timesteps, n, n_feats, day_timesteps, seed)

    adjs: dict = {}
    if m_graphs >= 1:
        adjs[ADJ_KEYS[0]] = grid_adjacency(rows, cols)
    if m_graphs >= 2:
        trans = (rng.random((n, n)) < min(1.0, 10.0 / n)).astype(np.float32)
        trans = np.maximum(trans, trans.T)
        np.fill_diagonal(trans, 0.0)
        adjs[ADJ_KEYS[1]] = trans
    if m_graphs >= 3:
        profile = demand.mean(axis=2).T  # (N, T)
        profile = profile - profile.mean(axis=1, keepdims=True)
        norms = np.linalg.norm(profile, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        sim = (profile / norms) @ (profile / norms).T
        np.fill_diagonal(sim, 0.0)
        # keep the strongest similarities as edges
        thresh = np.quantile(sim, 0.9)
        adjs[ADJ_KEYS[2]] = (sim > thresh).astype(np.float32)
    if m_graphs > 3:
        raise ValueError("synthetic_dataset supports at most 3 graphs")
    return DemandData(demand=demand, adjs=adjs)
