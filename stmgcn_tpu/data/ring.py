"""Device-resident ingest ring: the live-feed end of the closed loop.

The window-free resident path (``DemandDataset.series`` +
``gather_window_batch``) already trains and serves from a normalized
``(T, N, C)`` series that lives on device; what it lacks is a way to
*append* to that series without re-uploading full history. This module
closes that gap: :class:`SeriesRing` keeps the freshest ``capacity``
timesteps of one city's normalized series as a ring buffer updated in
place by a single jitted program (``lax.dynamic_update_slice`` with a
*traced* slot index, so ingest compiles exactly once and every
subsequent row is a compile-free device write), while the host side
keeps the monotonic-timestamp bookkeeping a real feed needs:

- **gaps** — a timestamp jump forward-fills the missing slots with the
  last observed row (counted per missing step), so the gather offsets
  of :func:`~stmgcn_tpu.train.step.make_series_superstep_fns` stay
  valid index arithmetic: logical row ``i`` is *always* timestamp
  ``origin_ts + i``.
- **out-of-order rows** — a late arrival within ``reorder_window``
  steps overwrites its (still-resident) slot in place; older than that
  it is a typed reject (:class:`StaleObservationError`), never a silent
  drop and never a corrupted timeline.
- **duplicates** — re-delivery of a timestamp that already holds a real
  observation is dropped and counted (the at-least-once transport
  case).
- **nonfinite observations** — quarantined on the host (bounded list of
  ``(ts, reason)``) and counted; the slot forward-fills so NaN never
  reaches the device buffer and the timeline still advances.

Because logical index == timestamp offset, "train on the last K hours"
is just an index range (:meth:`SeriesRing.target_indices` with
``last=K``) and a predict request shrinks from a full-history upload to
``(city, region ids, timestamp)`` — :meth:`SeriesRing.window_at`
gathers the model input for a timestamp straight from ring contents.

Ingest-stage fault drills run through
:class:`~stmgcn_tpu.resilience.IngestFaultPlan` via
:func:`ingest_stream`; an absent/empty plan is byte-for-byte the
production path.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from stmgcn_tpu.obs.registry import REGISTRY

__all__ = ["SeriesRing", "StaleObservationError", "ingest_stream"]


class StaleObservationError(ValueError):
    """A row arrived too late to place: older than the ring's reorder
    window (or before the ring's first timestamp entirely). Typed so
    feed drivers can count/route rejects without pattern-matching
    message strings."""


def _ingest_program(buf, row, slot):
    """One in-place ring write. ``slot`` is traced (a device scalar), so
    every row of a ring's lifetime reuses the single compiled program —
    the zero-recompiles-after-warmup property the smoke drill pins."""
    return jax.lax.dynamic_update_slice(buf, row[None], (slot, 0, 0))


# buf is donated: ingest really is an in-place update, not a copy chain.
_INGEST = jax.jit(_ingest_program, donate_argnums=(0,))
_ROLL = jax.jit(lambda buf, shift: jnp.roll(buf, -shift, axis=0))


class SeriesRing:
    """Ring buffer holding the freshest ``capacity`` rows of one city's
    normalized ``(T, N, C)`` series on device.

    Logical contract: :meth:`series` returns rows in time order, row
    ``i`` being timestamp ``origin_ts + i`` — bit-identical to the slice
    ``full_series[-L:]`` a host-side feed would produce (pinned against
    a numpy oracle in tests/test_ring.py). All anomaly handling
    (gap/out-of-order/duplicate/nonfinite) happens on the host *before*
    the device write, so the device buffer only ever holds finite,
    time-ordered data.
    """

    def __init__(
        self,
        capacity: int,
        n_nodes: int,
        n_feats: int,
        *,
        reorder_window: int = 4,
        start_ts: Optional[int] = None,
        city: int = 0,
        registry=None,
        max_quarantine: int = 64,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not 0 <= reorder_window < capacity:
            raise ValueError(
                f"reorder_window must be in [0, capacity), got "
                f"{reorder_window} for capacity {capacity}"
            )
        self.capacity = int(capacity)
        self.n_nodes = int(n_nodes)
        self.n_feats = int(n_feats)
        self.reorder_window = int(reorder_window)
        self.city = int(city)
        self.start_ts: Optional[int] = None if start_ts is None else int(start_ts)
        #: rows ever committed (real + forward-fills); the ts<->index map
        self.count = 0
        self.rows = 0
        self.gaps = 0
        self.out_of_order = 0
        self.duplicates = 0
        self.nonfinite = 0
        #: most recent quarantined observations, newest last
        self.quarantined: list[Tuple[int, str]] = []
        self.max_quarantine = int(max_quarantine)
        self._buf = jnp.zeros((self.capacity, n_nodes, n_feats), jnp.float32)
        self._last_row: Optional[np.ndarray] = None
        self._real: set[int] = set()
        reg = REGISTRY if registry is None else registry
        labels = {"city": str(self.city)}
        self._c_rows = reg.counter("ingest.rows", labels)
        self._c_gaps = reg.counter("ingest.gaps", labels)
        self._c_ooo = reg.counter("ingest.out_of_order", labels)
        self._c_dup = reg.counter("ingest.duplicates", labels)
        self._c_nonfinite = reg.counter("ingest.nonfinite", labels)
        self._g_occupancy = reg.gauge("ring.occupancy", labels)

    # ------------------------------------------------------------------
    # construction from an existing series (loop-off / pre-fill path)

    @classmethod
    def from_series(cls, series, *, start_ts: int = 0,
                    capacity: Optional[int] = None, **kwargs) -> "SeriesRing":
        """Pre-fill a ring from an existing ``(T, N, C)`` series.

        With ``capacity >= T`` (the default: exactly ``T``),
        :meth:`series` returns the input bit-identically — the loop-off
        parity case. With ``capacity < T`` only the freshest rows are
        resident, exactly as if every row had been ingested live.
        """
        arr = np.asarray(series, dtype=np.float32)
        if arr.ndim != 3:
            raise ValueError(f"series must be (T, N, C), got {arr.shape}")
        T, n, c = arr.shape
        cap = T if capacity is None else int(capacity)
        ring = cls(cap, n, c, start_ts=start_ts, **kwargs)
        keep = arr[-cap:]
        g0 = T - keep.shape[0]
        buf = np.zeros((cap, n, c), dtype=np.float32)
        buf[(np.arange(g0, T) % cap)] = keep
        ring._buf = jnp.asarray(buf)
        ring.count = T
        ring.rows = T
        ring._last_row = arr[-1].copy()
        last_ts = start_ts + T - 1
        ring._real = {t for t in range(last_ts - ring.reorder_window, last_ts + 1)
                      if t >= start_ts}
        ring._c_rows.inc(T)
        ring._g_occupancy.set(min(T, cap) / cap)
        return ring

    # ------------------------------------------------------------------
    # properties

    def __len__(self) -> int:
        """Logical length: resident rows (<= capacity)."""
        return min(self.count, self.capacity)

    @property
    def next_ts(self) -> Optional[int]:
        """Timestamp the next in-order row should carry."""
        return None if self.start_ts is None else self.start_ts + self.count

    @property
    def origin_ts(self) -> Optional[int]:
        """Timestamp of logical row 0 (the ring's logical origin)."""
        if self.start_ts is None:
            return None
        return self.start_ts + self.count - len(self)

    @property
    def nbytes(self) -> int:
        """Device-resident footprint of the ring buffer."""
        return self.capacity * self.n_nodes * self.n_feats * 4

    # ------------------------------------------------------------------
    # ingest

    def _commit(self, row: np.ndarray) -> None:
        # Device write first, host bookkeeping after: a SIGTERM between
        # the two leaves the new row outside the logical window (count
        # not yet advanced), so the ring's visible state stays a valid,
        # fully-written series — the mid-ingest preemption invariant.
        slot = self.count % self.capacity
        self._buf = _INGEST(self._buf, jnp.asarray(row),
                            jnp.asarray(slot, jnp.int32))
        self.count += 1

    def ingest(self, ts: int, values) -> str:
        """Feed one observation row; returns what happened to it.

        Outcomes: ``"append"`` (in-order commit), ``"gap-fill"``
        (in-order commit after forward-filling missing timestamps),
        ``"late"`` (out-of-order slot overwrite inside the reorder
        window), ``"duplicate"`` (dropped re-delivery), ``"nonfinite"``
        (quarantined, slot forward-filled). Rows older than the reorder
        window raise :class:`StaleObservationError`.
        """
        ts = int(ts)
        row = np.asarray(values, dtype=np.float32)
        if row.shape != (self.n_nodes, self.n_feats):
            raise ValueError(
                f"row must be ({self.n_nodes}, {self.n_feats}), got {row.shape}"
            )
        if self.start_ts is None:
            self.start_ts = ts
        outcome = self._place(ts, row)
        self._g_occupancy.set(len(self) / self.capacity)
        return outcome

    def _place(self, ts: int, row: np.ndarray) -> str:
        nxt = self.start_ts + self.count
        finite = bool(np.isfinite(row).all())
        if not finite:
            self.nonfinite += 1
            self._c_nonfinite.inc()
            self.quarantined.append((ts, "nonfinite"))
            del self.quarantined[: -self.max_quarantine]
            if ts < nxt:
                return "nonfinite"  # late *and* broken: nothing to place
            self._fill_to(ts + 1)  # forward-fill through the bad slot
            return "nonfinite"
        if ts >= nxt:
            missing = ts - nxt
            if missing:
                self._fill_to(ts)
                self.gaps += missing
                self._c_gaps.inc(missing)
            self._commit(row)
            self._last_row = row.copy()
            self._note_real(ts)
            self.rows += 1
            self._c_rows.inc()
            return "gap-fill" if missing else "append"
        # late arrival: staleness is decided first — beyond the reorder
        # window even a re-delivery is a typed reject (the _real set is
        # pruned to the window, so dedupe past it would be unreliable)
        if ts < self.start_ts or nxt - ts > self.reorder_window:
            raise StaleObservationError(
                f"row at ts={ts} is {nxt - ts} steps behind the ring head "
                f"(reorder window {self.reorder_window}) — too stale to place"
            )
        if ts in self._real:
            self.duplicates += 1
            self._c_dup.inc()
            return "duplicate"
        slot = (ts - self.start_ts) % self.capacity
        self._buf = _INGEST(self._buf, jnp.asarray(row),
                            jnp.asarray(slot, jnp.int32))
        self._note_real(ts)
        self.out_of_order += 1
        self._c_ooo.inc()
        self.rows += 1
        self._c_rows.inc()
        return "late"

    def _fill_to(self, ts: int) -> None:
        """Forward-fill committed slots up to (excluding) ``ts``. Fills
        beyond one full capacity are skipped device-side (they would be
        overwritten before ever becoming visible) but still advance
        ``count`` so the ts<->index map stays exact."""
        missing = ts - (self.start_ts + self.count)
        skip = max(0, missing - self.capacity)
        self.count += skip
        fill = (self._last_row if self._last_row is not None
                else np.zeros((self.n_nodes, self.n_feats), np.float32))
        for _ in range(missing - skip):
            self._commit(fill)

    def _note_real(self, ts: int) -> None:
        self._real.add(ts)
        if len(self._real) > 4 * (self.reorder_window + 1):
            head = self.start_ts + self.count
            self._real = {t for t in self._real
                          if t >= head - self.reorder_window - 1}

    # ------------------------------------------------------------------
    # reading

    def series(self, last: Optional[int] = None) -> jax.Array:
        """The resident series ``(L, N, C)`` in logical time order
        (``last=K`` trims to the freshest K rows). One device roll when
        the ring has wrapped; a plain slice before that."""
        L = len(self)
        if self.count <= self.capacity:
            view = self._buf[:L]
        else:
            view = _ROLL(self._buf, jnp.asarray(self.count % self.capacity,
                                                jnp.int32))
        if last is not None:
            view = view[-min(int(last), L):]
        return view

    def index_of(self, ts: int) -> int:
        """Logical index of timestamp ``ts`` in :meth:`series`."""
        if self.start_ts is None:
            raise ValueError("ring is empty")
        i = int(ts) - self.origin_ts
        if not 0 <= i < len(self):
            raise StaleObservationError(
                f"ts={ts} is not resident (ring spans "
                f"[{self.origin_ts}, {self.origin_ts + len(self) - 1}])"
            )
        return i

    def target_indices(self, spec, last: Optional[int] = None) -> np.ndarray:
        """Valid superstep target indices into :meth:`series` — "train on
        the last K hours" as an index range (``last=K`` keeps only the
        freshest K targets). Same enumeration as
        ``WindowSpec.target_indices`` over the resident length."""
        L = len(self)
        if L <= spec.burn_in + spec.horizon - 1:
            raise ValueError(
                f"ring holds {L} rows; need more than "
                f"burn_in+horizon-1={spec.burn_in + spec.horizon - 1}"
            )
        idx = spec.target_indices(L).astype(np.int32)
        if last is not None:
            idx = idx[-int(last):]
        return idx

    def window_at(self, spec, ts: int) -> np.ndarray:
        """Model input window ``(seq_len, N, C)`` for predicting
        timestamp ``ts`` — the shrunken predict request: the caller
        ships ``(city, ts)`` and the ring supplies the history."""
        t = self.index_of(ts)
        if t < spec.burn_in:
            raise StaleObservationError(
                f"ts={ts} has only {t} resident history rows; the window "
                f"needs {spec.burn_in}"
            )
        return np.asarray(jnp.take(self.series(), t + spec.offsets, axis=0))


def ingest_stream(ring: SeriesRing, rows: Iterable[Tuple[int, np.ndarray]],
                  fault_plan=None) -> dict:
    """Drive a feed of ``(ts, values)`` rows into ``ring``, optionally
    through an :class:`~stmgcn_tpu.resilience.IngestFaultPlan` (absent or
    empty plan = production pass-through). Stale rows are counted, not
    raised — a live feed must survive its transport. Returns
    ``{"fed", "accepted", "rejected"}``."""
    summary = {"fed": 0, "accepted": 0, "rejected": 0}
    for ts, values in rows:
        arrivals = ([(ts, values)] if fault_plan is None
                    else fault_plan.feed(ts, values))
        for ats, avalues in arrivals:
            summary["fed"] += 1
            try:
                ring.ingest(ats, avalues)
                summary["accepted"] += 1
            except StaleObservationError:
                summary["rejected"] += 1
    return summary
