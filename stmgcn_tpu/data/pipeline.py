"""End-to-end data pipeline: normalize -> window -> split -> batch.

Counterpart of the reference's ``DataGenerator.get_data_loader`` +
``TaxiDataset`` (``Data_Container.py:54-123``), redesigned for TPU:

- windows are built once, vectorized, on the host (float32 numpy);
- splits are *views* into the sample arrays (no per-mode copies);
- batching yields host numpy — device placement is the trainer's decision
  (``jax.device_put`` once for small configs, sharded placement for meshes)
  rather than an eager ``.to(device)`` inside the dataset
  (``Data_Container.py:88-89``, SURVEY.md §2 quirk 7);
- the last partial batch can be dropped or padded to keep shapes static
  under ``jit`` (the reference's DataLoader lets the tail batch ragged).

Reference parity defaults: min-max normalization over the full tensor,
``shuffle=False`` for every mode (``Data_Container.py:122``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from stmgcn_tpu.data.loader import DemandData
from stmgcn_tpu.data.normalize import MinMaxNormalizer
from stmgcn_tpu.data.splits import MODES, SplitSpec, fraction_splits
from stmgcn_tpu.data.windowing import WindowSpec, sliding_windows

__all__ = ["Batch", "DemandDataset"]


@dataclasses.dataclass(frozen=True)
class Batch:
    """One step's input: ``x`` ``(B, seq_len, N, C)``, target ``y`` ``(B, N, C)``."""

    x: np.ndarray
    y: np.ndarray
    #: number of *real* (non-padding) samples; == len(y) except for a padded tail
    n_real: int

    def __len__(self) -> int:
        return self.y.shape[0]


class DemandDataset:
    """Windowed, normalized, split demand samples with batch iteration."""

    def __init__(
        self,
        data: DemandData,
        window: WindowSpec,
        split: SplitSpec | None = None,
        normalize: bool = True,
    ):
        self.window = window
        self.normalizer = MinMaxNormalizer.fit(data.demand) if normalize else None
        demand = (
            self.normalizer.transform(data.demand) if normalize else data.demand
        ).astype(np.float32)
        self.x, self.y = sliding_windows(demand, window)
        self.split = (
            split.validate_against(self.n_samples)
            if split is not None
            else fraction_splits(self.n_samples)
        )
        self.adjs = data.adjs

    @property
    def n_samples(self) -> int:
        return self.y.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.y.shape[1]

    @property
    def n_feats(self) -> int:
        return self.y.shape[2]

    def arrays(self, mode: str) -> tuple[np.ndarray, np.ndarray]:
        """Full ``(x, y)`` views for a mode (no copy)."""
        start, stop = self.split.range_for(mode)
        return self.x[start:stop], self.y[start:stop]

    def denormalize(self, values):
        if self.normalizer is None:
            return values
        return self.normalizer.inverse(values)

    def num_batches(self, mode: str, batch_size: int, drop_last: bool = False) -> int:
        n = self.split.mode_len[mode]
        return n // batch_size if drop_last else -(-n // batch_size)

    def batches(
        self,
        mode: str,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        epoch: int = 0,
        drop_last: bool = False,
        pad_last: bool = False,
    ) -> Iterator[Batch]:
        """Yield :class:`Batch` es over a mode.

        ``pad_last`` repeats the final sample to fill the tail batch so every
        batch has the same static shape under ``jit``; ``Batch.n_real`` lets
        the loss/metrics mask the padding. ``shuffle`` reshuffles per epoch
        with a deterministic ``(seed, epoch)`` stream.
        """
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        if drop_last and pad_last:
            raise ValueError("drop_last and pad_last are mutually exclusive")
        x, y = self.arrays(mode)
        n = y.shape[0]
        order = None
        if shuffle:
            order = np.random.default_rng((seed, epoch)).permutation(n)
        stop = n - n % batch_size if drop_last else n
        for i in range(0, stop, batch_size):
            idx = slice(i, min(i + batch_size, n))
            bx, by = (x[order[idx]], y[order[idx]]) if order is not None else (x[idx], y[idx])
            n_real = by.shape[0]
            if pad_last and n_real < batch_size:
                reps = batch_size - n_real
                bx = np.concatenate([bx, np.repeat(bx[-1:], reps, axis=0)])
                by = np.concatenate([by, np.repeat(by[-1:], reps, axis=0)])
            yield Batch(x=bx, y=by, n_real=n_real)
