"""End-to-end data pipeline: normalize -> window -> split -> batch.

Counterpart of the reference's ``DataGenerator.get_data_loader`` +
``TaxiDataset`` (``Data_Container.py:54-123``), redesigned for TPU:

- the dataset's primary storage is the normalized raw ``(T, N, C)``
  series per city; materialized windows (``x`` of shape
  ``(S, seq_len, N, C)`` — a ~``seq_len``x copy of the series) are built
  lazily, vectorized, on first access, because the window-free resident
  trainer path never needs them: it gathers windows on device from the
  series via :meth:`DemandDataset.mode_targets` + ``WindowSpec.offsets``;
- splits are computed per city and the per-mode slices of every city are
  concatenated, so multi-city training (BASELINE config 4) sees both
  cities in every mode rather than one city leaking entirely into test;
- batching yields host numpy — device placement is the trainer's decision
  (``jax.device_put`` once for small configs, sharded placement for meshes)
  rather than an eager ``.to(device)`` inside the dataset
  (``Data_Container.py:88-89``, SURVEY.md §2 quirk 7);
- the last partial batch can be dropped or padded to keep shapes static
  under ``jit`` (the reference's DataLoader lets the tail batch ragged).

Reference parity defaults: min-max normalization over the full tensor,
``shuffle=False`` for every mode (``Data_Container.py:122``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence, Union

import numpy as np

from stmgcn_tpu.data.loader import DemandData
from stmgcn_tpu.data.normalize import MinMaxNormalizer, StdNormalizer
from stmgcn_tpu.data.splits import MODES, SplitSpec, fraction_splits
from stmgcn_tpu.data.windowing import WindowSpec, sliding_windows

__all__ = ["Batch", "DemandDataset"]


@dataclasses.dataclass(frozen=True)
class Batch:
    """One step's input: ``x`` ``(B, seq_len, N, C)``; target ``y`` is
    ``(B, N, C)`` for next-step forecasting or ``(B, H, N, C)`` for a
    multi-step horizon."""

    x: np.ndarray
    y: np.ndarray
    #: number of *real* (non-padding) samples; == len(y) except for a padded tail
    n_real: int
    #: which city's graphs this batch belongs to (always 0 when cities
    #: share one graph stack; batches never mix cities with differing graphs)
    city: int = 0
    #: positions of these samples in the mode's (city-relative) arrays —
    #: shuffled order and tail padding included, so a device-resident
    #: consumer can gather ``arrays(mode)[indices]`` instead of uploading
    #: ``x``/``y`` (``Trainer``'s resident data placement). With
    #: ``batches(with_arrays=False)`` the indices are the *only* payload
    #: (``x``/``y`` are None — not even materialized on the host).
    indices: np.ndarray | None = None

    def __len__(self) -> int:
        return self.y.shape[0] if self.y is not None else len(self.indices)


class DemandDataset:
    """Windowed, normalized, split demand samples with batch iteration.

    ``data`` may be a single :class:`DemandData` or a sequence of
    same-shape cities; windows never cross city boundaries, and each mode's
    samples are the concatenation of that mode's slice from every city.
    """

    #: homogeneous cities: one shared shape/normalizer/split (the
    #: heterogeneous counterpart is data.hetero.HeteroCityDataset)
    heterogeneous = False

    #: normalizer selected per ``normalize=`` kind (None = raw values)
    _NORMALIZERS = {"minmax": MinMaxNormalizer, "std": StdNormalizer, "none": None}

    def __init__(
        self,
        data: Union[DemandData, Sequence[DemandData]],
        window: WindowSpec,
        split: SplitSpec | None = None,
        normalize: Union[bool, str] = "minmax",
    ):
        # bool accepted for back-compat: True = reference-parity min-max
        # (Data_Container.py:21), False = raw values.
        if isinstance(normalize, bool):
            normalize = "minmax" if normalize else "none"
        if normalize not in self._NORMALIZERS:
            raise ValueError(
                f"normalize must be one of {sorted(self._NORMALIZERS)}, got {normalize!r}"
            )
        datas = list(data) if isinstance(data, (list, tuple)) else [data]
        if not datas:
            raise ValueError("need at least one city")
        shapes = {d.demand.shape for d in datas}
        if len(shapes) != 1:
            raise ValueError(f"cities must share (T, N, C) shape, got {shapes}")
        for d in datas[1:]:
            if list(d.adjs) != list(datas[0].adjs):
                raise ValueError(
                    f"cities must carry the same graph views (adjacency keys), "
                    f"got {list(datas[0].adjs)} vs {list(d.adjs)}"
                )
        self.window = window
        self.n_cities = len(datas)
        #: per-city adjacency dicts; real city pairs (BASELINE config 4,
        #: Chengdu+Beijing) have different graphs, so each batch carries a
        #: city index and the trainer applies that city's support stack
        self.city_adjs = [d.adjs for d in datas]
        #: whether one support stack serves every city (true for a single
        #: city or synthetic cities built over one region structure)
        self.shared_graphs = all(
            all(np.array_equal(d.adjs[k], datas[0].adjs[k]) for k in d.adjs)
            for d in datas[1:]
        )
        self.adjs = datas[0].adjs  # city 0 (the shared stack when shared_graphs)
        self._mode_cache: dict = {}

        norm_cls = self._NORMALIZERS[normalize]
        stacked = np.concatenate([d.demand for d in datas], axis=0)
        self.normalizer = norm_cls.fit(stacked) if norm_cls is not None else None

        # Primary storage: one normalized (T, N, C) series per city. The
        # materialized windows are derived lazily (see materialize()) —
        # the window-free resident path never touches them.
        self._series = [
            (
                self.normalizer.transform(d.demand)
                if self.normalizer is not None
                else d.demand
            ).astype(np.float32)
            for d in datas
        ]
        self._series_stack = None
        self._xs = self._ys = None

        T = self._series[0].shape[0]
        per_city = window.n_samples(T)
        if per_city <= 0:
            # the same error sliding_windows would raise — kept eager so a
            # too-short series fails at construction, not at first access
            raise ValueError(
                f"need more than burn_in+horizon-1="
                f"{window.burn_in + window.horizon - 1} timesteps, got T={T}"
            )
        self.split = (
            split.validate_against(per_city)
            if split is not None
            else fraction_splits(per_city)
        )

    def materialize(self) -> None:
        """Build the windowed ``(x, y)`` sample arrays from the series.

        The non-resident/hetero fallback (and the window-free path's
        parity oracle): ``x[i] == series[targets[i] + offsets]`` by
        construction, so the two representations are bit-identical views
        of the same data. Idempotent; called lazily by every accessor
        that needs host-side windows.
        """
        if self._xs is None:
            pairs = [sliding_windows(s, self.window) for s in self._series]
            self._xs = [x for x, _ in pairs]
            self._ys = [y for _, y in pairs]

    @property
    def materialized(self) -> bool:
        """Whether the windowed sample arrays have been built."""
        return self._xs is not None

    def series(self, city: int = 0) -> np.ndarray:
        """One city's normalized ``(T, N, C)`` series — the window-free
        resident payload; windows gather from it by target + offset."""
        return self._series[city]

    def series_stack(self) -> np.ndarray:
        """All cities' series concatenated along time: ``(n_cities*T, N, C)``
        (a zero-copy view for a single city).

        :meth:`mode_targets` indices with ``city=None`` address this
        tensor; window offsets never cross a city boundary because every
        offset lies within ``burn_in`` of its target and every target sits
        at least ``burn_in`` into its own city's block.
        """
        if self.n_cities == 1:
            return self._series[0]
        if self._series_stack is None:
            self._series_stack = np.concatenate(self._series, axis=0)
        return self._series_stack

    def mode_targets(self, mode: str, city: int | None = None) -> np.ndarray:
        """int32 target timesteps for a mode's samples, in ``arrays(mode)``
        order.

        ``city=None`` returns absolute indices into :meth:`series_stack`
        (cities concatenated city-major, matching the ``arrays(mode)``
        concatenation); ``city=k`` returns indices into ``series(k)``.
        Sample ``i`` of the mode satisfies
        ``arrays(mode)[0][i] == stack[targets[i] + window.offsets]`` and
        ``arrays(mode)[1][i] == stack[targets[i] (+ arange(H))]`` exactly.
        """
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        start, stop = self.split.range_for(mode)
        base = self.window.burn_in + np.arange(start, stop)
        if city is not None:
            return base.astype(np.int32)
        T = self._series[0].shape[0]
        return np.concatenate(
            [c * T + base for c in range(self.n_cities)]
        ).astype(np.int32)

    @property
    def samples_per_city(self) -> int:
        return self.window.n_samples(self._series[0].shape[0])

    @property
    def nbytes(self) -> int:
        """Bytes of the windowed sample arrays (all cities, all modes) —
        what the materialized resident path would upload. Computed
        analytically so sizing decisions never force materialization."""
        per_sample = (
            (self.window.seq_len + self.window.horizon)
            * self.n_nodes
            * self.n_feats
        )
        itemsize = self._series[0].dtype.itemsize
        return self.n_cities * self.samples_per_city * per_sample * itemsize

    @property
    def resident_nbytes(self) -> int:
        """Bytes the window-free resident path keeps on device: the raw
        normalized series plus the int32 target vectors and offset table —
        smaller than :attr:`nbytes` by ~``seq_len``x (windows overlap;
        the series stores each timestep once)."""
        series = sum(s.nbytes for s in self._series)
        targets = 4 * self.n_samples  # one int32 target per sample
        offsets = 4 * self.window.seq_len
        return series + targets + offsets

    @property
    def n_samples(self) -> int:
        return self.samples_per_city * self.n_cities

    @property
    def n_nodes(self) -> int:
        return self._series[0].shape[1]

    @property
    def n_feats(self) -> int:
        return self._series[0].shape[2]

    def mode_size(self, mode: str) -> int:
        """Total samples for a mode across all cities."""
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        return self.split.mode_len[mode] * self.n_cities

    def arrays(self, mode: str) -> tuple[np.ndarray, np.ndarray]:
        """Full ``(x, y)`` for a mode — a view for one city, a cached concat
        otherwise. Materializes the windowed arrays on first use."""
        start, stop = self.split.range_for(mode)
        self.materialize()
        if self.n_cities == 1:
            return self._xs[0][start:stop], self._ys[0][start:stop]
        if mode not in self._mode_cache:
            self._mode_cache[mode] = (
                np.concatenate([x[start:stop] for x in self._xs], axis=0),
                np.concatenate([y[start:stop] for y in self._ys], axis=0),
            )
        return self._mode_cache[mode]

    def city_arrays(self, mode: str, city: int) -> tuple[np.ndarray, np.ndarray]:
        """One city's ``(x, y)`` views for a mode."""
        start, stop = self.split.range_for(mode)
        self.materialize()
        return self._xs[city][start:stop], self._ys[city][start:stop]

    def denormalize(self, values):
        if self.normalizer is None:
            return values
        return self.normalizer.inverse(values)

    def num_batches(self, mode: str, batch_size: int, drop_last: bool = False) -> int:
        per = self.split.mode_len[mode]
        if self.shared_graphs:
            n = per * self.n_cities
            return n // batch_size if drop_last else -(-n // batch_size)
        # differing graphs: batches never span cities, so each city's tail
        # rounds (or drops) independently
        one = per // batch_size if drop_last else -(-per // batch_size)
        return one * self.n_cities

    def batches(
        self,
        mode: str,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        epoch: int = 0,
        drop_last: bool = False,
        pad_last: bool = False,
        with_arrays: bool = True,
    ) -> Iterator[Batch]:
        """Yield :class:`Batch` es over a mode.

        ``pad_last`` repeats the final sample to fill the tail batch so every
        batch has the same static shape under ``jit``; ``Batch.n_real`` lets
        the loss/metrics mask the padding. ``shuffle`` reshuffles per epoch
        with a deterministic ``(seed, epoch)`` stream.

        ``with_arrays=False`` yields index-only batches (``x``/``y`` None):
        a device-resident consumer gathers on device from ``Batch.indices``,
        so materializing host copies here would be pure waste — the
        windowed arrays are not even built (the window-free path runs a
        whole training job on indices + the raw series alone).

        With per-city graphs (``shared_graphs=False``) batches never mix
        cities — every batch carries the ``city`` whose support stack
        applies to it; shuffling permutes within each city.
        """
        if drop_last and pad_last:
            raise ValueError("drop_last and pad_last are mutually exclusive")
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        per_mode = self.split.mode_len[mode]
        if self.shared_graphs:
            yield from self._iter_arrays(
                lambda: self.arrays(mode), per_mode * self.n_cities, 0,
                batch_size, shuffle, (seed,), epoch, drop_last, pad_last,
                with_arrays,
            )
            return
        for city in range(self.n_cities):
            yield from self._iter_arrays(
                lambda c=city: self.city_arrays(mode, c), per_mode, city,
                batch_size, shuffle, (seed, city), epoch, drop_last,
                pad_last, with_arrays,
            )

    def _iter_arrays(
        self, arrays_fn, n, city, batch_size, shuffle, seed_key, epoch,
        drop_last, pad_last, with_arrays=True,
    ) -> Iterator[Batch]:
        # arrays are a thunk so index-only iteration stays window-free
        x = y = None
        order = None
        if shuffle:
            order = np.random.default_rng((*seed_key, epoch)).permutation(n)
        stop = n - n % batch_size if drop_last else n
        for i in range(0, stop, batch_size):
            idx = slice(i, min(i + batch_size, n))
            if order is not None:
                sel = order[idx]
            else:
                sel = np.arange(i, min(i + batch_size, n))
            n_real = sel.shape[0]
            if pad_last and n_real < batch_size:
                sel = np.concatenate([sel, np.repeat(sel[-1:], batch_size - n_real)])
            if not with_arrays:
                yield Batch(x=None, y=None, n_real=n_real, city=city, indices=sel)
                continue
            if x is None:
                x, y = arrays_fn()
            if order is not None:
                bx, by = x[sel[:n_real]], y[sel[:n_real]]
            else:  # contiguous: keep the zero-copy views
                bx, by = x[idx], y[idx]
            if n_real < sel.shape[0]:  # padded tail
                reps = sel.shape[0] - n_real
                bx = np.concatenate([bx, np.repeat(bx[-1:], reps, axis=0)])
                by = np.concatenate([by, np.repeat(by[-1:], reps, axis=0)])
            yield Batch(x=bx, y=by, n_real=n_real, city=city, indices=sel)
