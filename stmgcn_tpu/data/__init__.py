"""Data layer: NPZ loading, normalization, windowing, splits, batching.

TPU-native counterpart of the reference's ``Data_Container.py`` (L1 in
SURVEY.md §1): same sample semantics, but windowing is a single vectorized
gather instead of a Python loop over time, split indices are computed in
timesteps (fixing the reference's day-vs-timestep unit bug, SURVEY.md §2
quirk 3), and device placement is explicit and shardable instead of eager
``.to(device)`` at dataset construction.
"""

from stmgcn_tpu.data.loader import ADJ_KEYS, DemandData, load_npz
from stmgcn_tpu.data.normalize import MinMaxNormalizer, StdNormalizer, normalizer_from_dict
from stmgcn_tpu.data.pipeline import DemandDataset, Batch
from stmgcn_tpu.data.hetero import HeteroCityDataset
from stmgcn_tpu.data.ring import SeriesRing, StaleObservationError, ingest_stream
from stmgcn_tpu.data.fleet import FleetPlan, ShapeClass, plan_shape_classes
from stmgcn_tpu.data.splits import SplitSpec, date_splits
from stmgcn_tpu.data.synthetic import synthetic_demand, grid_adjacency, synthetic_dataset
from stmgcn_tpu.data.windowing import WindowSpec, sliding_windows

__all__ = [
    "ADJ_KEYS",
    "Batch",
    "DemandData",
    "DemandDataset",
    "FleetPlan",
    "HeteroCityDataset",
    "MinMaxNormalizer",
    "SeriesRing",
    "ShapeClass",
    "StaleObservationError",
    "StdNormalizer",
    "SplitSpec",
    "WindowSpec",
    "date_splits",
    "grid_adjacency",
    "ingest_stream",
    "load_npz",
    "normalizer_from_dict",
    "plan_shape_classes",
    "sliding_windows",
    "synthetic_dataset",
    "synthetic_demand",
]
