"""Fleet shape-class planner: bucket cities by padded node count.

A heterogeneous dataset carries one graph per city, so naively every
city gets its own compiled program (and the trainer falls back to the
materialized per-step loop). The planner here groups cities into a
bounded set of *shape classes* — each class a node-count rung ``N_c``
every member is padded up to — so that ONE jitted window-free superstep
program (training) or ONE bucket ladder of AOT programs (serving) covers
every member city. Rung selection reuses the serving ladder's covering
rule (:func:`stmgcn_tpu.serving.bucketing.smallest_covering_bucket`):
greedy descending — the largest unassigned city opens a rung, and every
city whose node padding would waste at most ``max_pad_waste`` of the
rung joins it. Cities left over once ``max_classes`` rungs exist are
returned as ``unassigned`` and keep the per-city fallback path.

Padded rows are provably inert in training and serving alike: supports
are zero in padded rows/cols, the contextual gate pools over a traced
real-node count, and the ``(B, N)`` loss mask zeroes padded regions —
pinned bit-exact by ``tests/test_fleet.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from stmgcn_tpu.serving.bucketing import smallest_covering_bucket

__all__ = ["FleetPlan", "ShapeClass", "plan_shape_classes"]


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


@dataclasses.dataclass(frozen=True)
class ShapeClass:
    """One compiled shape: cities padded to a shared ``(n_nodes, nnz)``."""

    #: rung node count every member is padded up to
    n_nodes: int
    #: member city indices, in dataset order
    cities: tuple
    #: members' real node counts, aligned with ``cities``
    city_n_nodes: tuple
    #: dense support entries at the rung (per graph view x hop) — the
    #: padded supports are materialized dense, so nnz == n_nodes**2
    nnz: int
    #: members' real support nnz (``None`` entries when not measured)
    city_nnz: tuple

    def pad_for(self, city: int) -> int:
        return self.n_nodes - self.city_n_nodes[self.cities.index(city)]

    @property
    def node_waste(self) -> float:
        """Worst member's padded-node fraction of the rung."""
        return max(1.0 - n / self.n_nodes for n in self.city_n_nodes)

    @property
    def nnz_waste(self) -> float:
        """Worst member's padded fraction of the rung's dense support."""
        known = [z for z in self.city_nnz if z is not None]
        if not known:
            return self.node_waste
        return max(1.0 - z / self.nnz for z in known)


@dataclasses.dataclass(frozen=True)
class FleetPlan:
    """Shape classes covering a city fleet (+ the cities that fit none)."""

    classes: tuple
    #: city indices that fit no class (per-city fallback path)
    unassigned: tuple

    @property
    def class_of(self) -> dict:
        return {c: i for i, cls in enumerate(self.classes) for c in cls.cities}

    @property
    def slot_of(self) -> dict:
        """city -> position inside its class's stacked support tensor."""
        return {c: s for cls in self.classes for s, c in enumerate(cls.cities)}

    def pad_for(self, city: int) -> Optional[int]:
        i = self.class_of.get(city)
        return None if i is None else self.classes[i].pad_for(city)

    @property
    def node_waste(self) -> float:
        return max((cls.node_waste for cls in self.classes), default=0.0)


def plan_shape_classes(
    city_n_nodes: Sequence[int],
    *,
    city_nnz: Optional[Sequence[int]] = None,
    max_classes: int = 8,
    max_pad_waste: float = 0.5,
    node_multiple: int = 1,
) -> FleetPlan:
    """Group cities into at most ``max_classes`` node-count rungs.

    Greedy descending: the largest not-yet-covered city opens a rung at
    its (``node_multiple``-rounded) node count; membership is then
    resolved through :func:`smallest_covering_bucket` over the final
    rung ladder, so a small city joins the tightest rung that wastes at
    most ``max_pad_waste`` of its nodes. Cities that no rung covers
    within the waste budget land in ``unassigned``.
    """
    if max_classes < 1:
        raise ValueError(f"max_classes must be >= 1, got {max_classes}")
    if not 0.0 <= max_pad_waste < 1.0:
        raise ValueError(f"max_pad_waste must be in [0, 1), got {max_pad_waste}")
    sizes = [int(n) for n in city_n_nodes]
    if any(n <= 0 for n in sizes):
        raise ValueError(f"city node counts must be positive, got {sizes}")
    nnzs = list(city_nnz) if city_nnz is not None else [None] * len(sizes)
    if len(nnzs) != len(sizes):
        raise ValueError("city_nnz must align with city_n_nodes")

    # Pass 1 — open rungs largest-first until every city is covered or
    # the class budget runs out. A rung covers city n when the pad
    # fraction (rung - n) / rung stays within budget.
    rungs: list = []
    uncovered = sorted(set(sizes), reverse=True)
    while uncovered and len(rungs) < max_classes:
        rung = _round_up(uncovered[0], node_multiple)
        rungs.append(rung)
        uncovered = [n for n in uncovered if rung - n > max_pad_waste * rung]
    ladder = sorted(rungs)

    # Pass 2 — final membership via the serving ladder's covering rule.
    members: dict = {r: [] for r in ladder}
    unassigned = []
    for city, n in enumerate(sizes):
        # the first pass-1 rung comes from the largest city, so the
        # ladder top always covers every n and this cannot raise
        rung = smallest_covering_bucket(n, ladder)
        if rung - n > max_pad_waste * rung:
            unassigned.append(city)
        else:
            members[rung].append(city)

    classes = tuple(
        ShapeClass(
            n_nodes=rung,
            cities=tuple(cs),
            city_n_nodes=tuple(sizes[c] for c in cs),
            nnz=rung * rung,
            city_nnz=tuple(nnzs[c] for c in cs),
        )
        for rung, cs in members.items()
        if cs
    )
    return FleetPlan(classes=classes, unassigned=tuple(unassigned))
