"""NPZ demand/adjacency loading.

Reference: ``DataInput`` (``Data_Container.py:8-29``). The archive holds a
``taxi`` demand tensor of shape ``(T, N, C)`` plus up to three adjacency
matrices gated by the graph count M, in the fixed priority order
``neighbor_adj`` -> ``trans_adj`` -> ``semantic_adj``
(``Data_Container.py:23-28``). Normalization is *not* fused into loading
here (the reference normalizes inside ``load_data``,
``Data_Container.py:21``) — the pipeline owns it so the statistics can be
checkpointed.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["ADJ_KEYS", "DemandData", "load_npz"]

#: Adjacency key priority, mirroring ``Data_Container.py:23-28``.
ADJ_KEYS = ("neighbor_adj", "trans_adj", "semantic_adj")


@dataclasses.dataclass
class DemandData:
    """Raw (un-normalized) demand plus M adjacency matrices."""

    demand: np.ndarray  # (T, N, C)
    adjs: dict  # key -> (N, N), insertion-ordered

    @property
    def n_graphs(self) -> int:
        return len(self.adjs)

    @property
    def n_nodes(self) -> int:
        return self.demand.shape[1]

    @property
    def n_feats(self) -> int:
        return self.demand.shape[2]

    def adj_list(self) -> list:
        return list(self.adjs.values())


def load_npz(path: str, m_graphs: int = 3, demand_key: str = "taxi") -> DemandData:
    """Load a demand archive; take the first ``m_graphs`` adjacency keys.

    Unknown ``*_adj`` keys beyond the canonical three are accepted after
    them, in file order, so multi-city archives can carry extra graphs.
    """
    with np.load(path) as npz:
        keys = list(npz.keys())
        if demand_key not in keys:
            raise KeyError(f"{path} has no {demand_key!r} array; keys: {keys}")
        demand = np.asarray(npz[demand_key], dtype=np.float32)
        if demand.ndim == 2:  # (T, N) -> (T, N, 1)
            demand = demand[..., None]
        if demand.ndim != 3:
            raise ValueError(f"demand must be (T, N, C), got {demand.shape}")
        ordered = [k for k in ADJ_KEYS if k in keys]
        ordered += [k for k in keys if k.endswith("_adj") and k not in ADJ_KEYS]
        if len(ordered) < m_graphs:
            raise ValueError(
                f"need {m_graphs} adjacency arrays but {path} only has {ordered}"
            )
        adjs = {}
        for k in ordered[:m_graphs]:
            a = np.asarray(npz[k], dtype=np.float32)
            if a.shape != (demand.shape[1], demand.shape[1]):
                raise ValueError(
                    f"{k} has shape {a.shape}, expected "
                    f"({demand.shape[1]}, {demand.shape[1]})"
                )
            adjs[k] = a
    return DemandData(demand=demand, adjs=adjs)
