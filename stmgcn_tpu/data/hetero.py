"""Heterogeneous multi-city dataset: cities with differing shapes.

The homogeneous :class:`~stmgcn_tpu.data.pipeline.DemandDataset` requires
its cities to share one ``(T, N, C)`` shape and fits one normalizer on
their concatenation — right for synthetic twins, wrong for real pairs:
BASELINE config 4's "Chengdu + Beijing" differ in region count, series
span, and demand scale (a shared min-max would train the low-demand city
compressed into a corner of the unit scale). The reference framework is
single-city outright (``Data_Container.py:8-29``); this subsystem has no
counterpart there.

:class:`HeteroCityDataset` keeps one full :class:`DemandDataset` per
city — its own windowed arrays, its own normalizer (fitted on that city
alone), its own split calendar — behind the same batch protocol the
:class:`~stmgcn_tpu.train.trainer.Trainer` already speaks. One parameter
set serves every city because every ST-MGCN parameter is
region-count-agnostic: gate FCs are ``seq_len``-sized (``STMGCN.py:20``),
graph-conv weights are ``(K*F_in, F_out)`` (``GCN.py:18``), and the LSTM
is feature-space. What cities MUST share is the :class:`WindowSpec`
(``seq_len`` sizes the gate parameters) and the channel count ``C``
(sizes the LSTM input projection); everything else — ``T``, ``N``,
graphs, demand scale — is per-city. Under ``jit`` each distinct city
shape compiles once and is cached thereafter (XLA's shape-keyed cache),
so a two-city run carries exactly two compiled steps.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Sequence

import numpy as np

from stmgcn_tpu.data.loader import DemandData
from stmgcn_tpu.data.pipeline import Batch, DemandDataset
from stmgcn_tpu.data.splits import MODES, SplitSpec
from stmgcn_tpu.data.windowing import WindowSpec

__all__ = ["HeteroCityDataset"]


class HeteroCityDataset:
    """Per-city windows/normalizers/splits behind the Trainer's protocol.

    ``splits`` is an optional per-city sequence of :class:`SplitSpec`
    (``None`` entries fall back to fraction splits on that city's own
    sample count — cities with different spans get different split
    boundaries, as a calendar would give them).
    """

    #: consumers branch per-city metric/normalizer handling on this
    heterogeneous = True
    #: per-city graphs always (differing N cannot share a support stack)
    shared_graphs = False

    def __init__(
        self,
        datas: Sequence[DemandData],
        window: WindowSpec,
        splits: Optional[Sequence[Optional[SplitSpec]]] = None,
        normalize="minmax",
    ):
        datas = list(datas)
        if not datas:
            raise ValueError("need at least one city")
        feats = {d.demand.shape[-1] for d in datas}
        if len(feats) != 1:
            raise ValueError(
                "cities must share the feature/channel count C (it sizes the "
                f"LSTM input projection), got {sorted(feats)}"
            )
        for d in datas[1:]:
            if list(d.adjs) != list(datas[0].adjs):
                raise ValueError(
                    f"cities must carry the same graph views (adjacency keys), "
                    f"got {list(datas[0].adjs)} vs {list(d.adjs)}"
                )
        if splits is None:
            splits = [None] * len(datas)
        if len(splits) != len(datas):
            raise ValueError(
                f"got {len(splits)} splits for {len(datas)} cities — pass one "
                "SplitSpec (or None) per city"
            )
        self.window = window
        self.cities = [
            DemandDataset(d, window, s, normalize) for d, s in zip(datas, splits)
        ]

    # -- structure -------------------------------------------------------
    @property
    def n_cities(self) -> int:
        return len(self.cities)

    @property
    def city_adjs(self) -> list:
        return [c.adjs for c in self.cities]

    @property
    def adjs(self):
        """City 0's graphs (the protocol slot; per-city consumers use
        :attr:`city_adjs`)."""
        return self.cities[0].adjs

    @property
    def normalizer(self):
        """Always ``None``: normalization is per-city (:attr:`normalizers`)."""
        return None

    @property
    def normalizers(self) -> list:
        return [c.normalizer for c in self.cities]

    @property
    def n_feats(self) -> int:
        return self.cities[0].n_feats

    @property
    def city_n_nodes(self) -> list:
        return [c.n_nodes for c in self.cities]

    @property
    def n_nodes(self) -> int:
        raise ValueError(
            "heterogeneous cities have per-city region counts — use "
            "city_n_nodes"
        )

    @property
    def nbytes(self) -> int:
        return sum(c.nbytes for c in self.cities)

    @property
    def n_samples(self) -> int:
        return sum(c.n_samples for c in self.cities)

    # -- window-free protocol (per-city delegation) ----------------------
    # Mirrors DemandDataset's resident-series surface so the trainer's
    # window-free gather (and the fleet superstep built on it) treats a
    # hetero fleet like any resident dataset — one (T, N_c, C) series per
    # city, target vectors per (mode, city), no window materialization.
    def series(self, city: int = 0) -> np.ndarray:
        return self.cities[city].series(0)

    def series_stack(self, city: int = 0) -> np.ndarray:
        return self.cities[city].series_stack()

    def mode_targets(self, mode: str, city: int = 0) -> np.ndarray:
        return self.cities[city].mode_targets(mode, 0)

    @property
    def resident_nbytes(self) -> int:
        return sum(c.resident_nbytes for c in self.cities)

    @property
    def materialized(self) -> bool:
        return any(c.materialized for c in self.cities)

    # -- samples ---------------------------------------------------------
    def mode_size(self, mode: str) -> int:
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        return sum(c.mode_size(mode) for c in self.cities)

    def num_batches(self, mode: str, batch_size: int, drop_last: bool = False) -> int:
        return sum(c.num_batches(mode, batch_size, drop_last) for c in self.cities)

    def arrays(self, mode: str):
        raise ValueError(
            "heterogeneous cities cannot concatenate into one array — use "
            "city_arrays(mode, city)"
        )

    def city_arrays(self, mode: str, city: int):
        return self.cities[city].arrays(mode)

    def denormalize(self, values, city: Optional[int] = None):
        """Per-city inverse transform; ``city`` may be omitted only when a
        single city makes it unambiguous."""
        if city is None:
            if self.n_cities != 1:
                raise ValueError(
                    "denormalize needs city= with heterogeneous cities (each "
                    "has its own normalizer)"
                )
            city = 0
        return self.cities[city].denormalize(values)

    def batches(
        self,
        mode: str,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        epoch: int = 0,
        drop_last: bool = False,
        pad_last: bool = False,
        with_arrays: bool = True,
    ) -> Iterator[Batch]:
        """City-sequential batches; every batch carries its city index.

        Batches never mix cities (their shapes differ). City 0 streams
        with the unmodified ``seed`` so a city-0-only run reproduces the
        single-city iteration order exactly; later cities decorrelate
        their shuffle streams with a per-city offset.
        """
        for city, ds in enumerate(self.cities):
            for b in ds.batches(
                mode,
                batch_size,
                shuffle=shuffle,
                seed=seed + city * 7919,
                epoch=epoch,
                drop_last=drop_last,
                pad_last=pad_last,
                with_arrays=with_arrays,
            ):
                yield dataclasses.replace(b, city=city) if b.city != city else b
