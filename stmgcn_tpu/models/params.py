"""Branch-parameter layout conversion.

The flagship stores its M branch parameters in one of two layouts:

- **vmapped** (``vmap_branches=True``, all-dense supports): one
  ``branches`` subtree whose every leaf carries a leading ``(M, ...)``
  axis (``nn.vmap`` with ``variable_axes={'params': 0}``);
- **looped** (sparse / routed / ``vmap_branches=False``): subtrees
  ``branch_0 .. branch_{M-1}`` with per-branch leaves.

The layouts are informationally identical — these converters make
checkpoints interchangeable across them (e.g. continue a GSPMD-trained
vmapped run under the banded region strategy, or serve a sparse-trained
checkpoint with the vmapped dense model). Non-branch subtrees (the
``head``) pass through untouched.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = [
    "compute_cast",
    "leaf_dtype_census",
    "sr_cast_bf16",
    "to_dense_serving",
    "to_looped_params",
    "to_tiled_serving",
    "to_vmapped_params",
]


def leaf_dtype_census(tree):
    """Per-dtype ``{"leaves": n, "bytes": n}`` census of a pytree.

    Works on concrete arrays and abstract ``ShapeDtypeStruct``-likes
    alike (anything with ``shape``/``dtype``), so the precision lint and
    the bench rider can census a parameter tree without materializing
    it. Leaves without a dtype (e.g. Python scalars) count under their
    numpy-inferred dtype name.
    """
    import numpy as np

    census: dict = {}
    for leaf in jax.tree.leaves(tree):
        dt = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        shape = getattr(leaf, "shape", ())
        entry = census.setdefault(dt.name, {"leaves": 0, "bytes": 0})
        entry["leaves"] += 1
        entry["bytes"] += int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    return census

def _round_to_bf16_stochastic(x, noise):
    """Truncate ``f32 -> bf16`` after adding uniform mantissa noise.

    bf16 is f32 with the low 16 mantissa bits dropped; adding
    ``U[0, 2^16)`` to the raw bits before masking them off makes the
    truncation round up with probability proportional to the discarded
    fraction — an unbiased rounding whose *expected* value is the f32
    input (plain round-to-nearest is biased toward representable
    values, which a long optimizer trajectory can integrate into drift).
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    rounded = (bits + noise) & jnp.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(rounded, jnp.float32).astype(jnp.bfloat16)


@jax.custom_vjp
def sr_cast_bf16(x, noise):
    """Stochastically-rounded ``f32 -> bf16`` cast with straight-through grad.

    ``noise`` is a ``uint32`` array of ``x``'s shape holding
    ``U[0, 2^16)`` draws (``jax.random.randint``). The backward pass is
    the plain cast's: cotangents convert to f32 (identity/straight-
    through), ``None`` for the noise.
    """
    return _round_to_bf16_stochastic(x, noise)


def _sr_cast_fwd(x, noise):
    return _round_to_bf16_stochastic(x, noise), None


def _sr_cast_bwd(_res, g):
    return (g.astype(jnp.float32), None)


sr_cast_bf16.defvjp(_sr_cast_fwd, _sr_cast_bwd)


def compute_cast(tree, dtype, rng=None):
    """Cast the float leaves of a pytree to the compute ``dtype``.

    The master/compute split of mixed-precision training: the optimizer
    holds f32 masters and each step regenerates this low-precision
    shadow inside the loss closure, so autodiff returns f32 cotangents
    at the cast boundary. Non-float leaves (index tables, counters)
    pass through untouched. With ``rng`` (and ``dtype=bfloat16``) the
    cast is stochastically rounded via :func:`sr_cast_bf16`, one
    ``fold_in``-derived noise stream per leaf.
    """
    dtype = jnp.dtype(dtype)

    def _is_float(leaf):
        return jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)

    if rng is None:
        return jax.tree.map(
            lambda leaf: leaf.astype(dtype) if _is_float(leaf) else leaf, tree
        )
    if dtype != jnp.bfloat16:
        raise ValueError(
            f"stochastic rounding is defined for bfloat16 only, got {dtype}"
        )
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for i, leaf in enumerate(leaves):
        if _is_float(leaf):
            noise = jax.random.randint(
                jax.random.fold_in(rng, i), jnp.shape(leaf), 0, 1 << 16,
                dtype=jnp.uint32,
            )
            leaf = sr_cast_bf16(leaf, noise)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


_VMAPPED_KEY = "branches"


def _branch_keys(m_graphs: int):
    return [f"branch_{m}" for m in range(m_graphs)]


def to_vmapped_params(variables, m_graphs: int):
    """Looped ``branch_0..branch_{M-1}`` layout -> vmapped ``branches``."""
    params = dict(variables["params"])
    keys = _branch_keys(m_graphs)
    missing = [k for k in keys if k not in params]
    if missing:
        raise ValueError(
            f"not a looped-layout checkpoint: missing subtree(s) {missing}"
        )
    per_branch = [params.pop(k) for k in keys]
    params[_VMAPPED_KEY] = jax.tree.map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_branch
    )
    return {**variables, "params": params}


def to_dense_serving(model, variables, m_graphs: int):
    """Rebuild ``(model, params)`` as the dense vmapped XLA serving clone.

    Serving (the export artifact and :class:`stmgcn_tpu.serving.engine
    .ServingEngine`) always consumes dense ``(M, K, N, N)`` support
    stacks on a single device: sparse/banded layouts, per-branch looping,
    shard bindings and the Pallas LSTM kernel are training-side
    representations. Sparse/looped checkpoints are restacked to the
    vmapped layout (same modules, same math — round-trip + forward
    equality pinned in tests/test_param_layouts.py); a Pallas-backend
    model is re-routed to the xla scan of the same params
    (tests/test_pallas_lstm.py). Already-dense models pass through
    untouched.
    """
    if any(mode != "dense" for mode in model.branch_modes()) or not model.vmap_branches:
        model = dataclasses.replace(
            model,
            sparse=False,
            support_modes=None,
            shard_spec=None,
            vmap_branches=True,
            n_real_nodes=None,
        )
        variables = to_vmapped_params(variables, m_graphs)
    if model.lstm_backend != "xla":
        model = dataclasses.replace(model, lstm_backend="xla", lstm_pallas_mesh=None)
    return model, variables


def to_tiled_serving(model, variables, m_graphs: int):
    """Rebuild ``(model, params)`` as the tiled-sparse serving clone.

    The tiled twin of :func:`to_dense_serving`: serving a large-N city
    on its :class:`~stmgcn_tpu.ops.tiling.TiledSupports` plan needs the
    loop-layout model with ``support_modes=("tiled",) * M`` — a
    dense/vmapped-trained checkpoint is unstacked to ``branch_0..
    branch_{M-1}``; sparse/banded/tiled-trained (already looped)
    checkpoints pass through. Shard bindings drop and a Pallas-backend
    LSTM re-routes to the xla scan, exactly like the dense clone.
    """
    if all(mode == "dense" for mode in model.branch_modes()) and model.vmap_branches:
        variables = to_looped_params(variables, m_graphs)
    model = dataclasses.replace(
        model,
        sparse=False,
        support_modes=("tiled",) * m_graphs,
        shard_spec=None,
        vmap_branches=False,
        n_real_nodes=None,
    )
    if model.lstm_backend != "xla":
        model = dataclasses.replace(model, lstm_backend="xla", lstm_pallas_mesh=None)
    return model, variables


def to_looped_params(variables, m_graphs: int):
    """Vmapped ``branches`` layout -> looped ``branch_0..branch_{M-1}``."""
    params = dict(variables["params"])
    if _VMAPPED_KEY not in params:
        raise ValueError(
            f"not a vmapped-layout checkpoint: no {_VMAPPED_KEY!r} subtree"
        )
    stacked = params.pop(_VMAPPED_KEY)
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stacked)}
    if leading != {m_graphs}:
        raise ValueError(
            f"stacked branch axis is {sorted(leading)}, expected {{{m_graphs}}}"
        )
    for m, key in enumerate(_branch_keys(m_graphs)):
        params[key] = jax.tree.map(lambda leaf, m=m: leaf[m], stacked)
    return {**variables, "params": params}
