"""Branch-parameter layout conversion.

The flagship stores its M branch parameters in one of two layouts:

- **vmapped** (``vmap_branches=True``, all-dense supports): one
  ``branches`` subtree whose every leaf carries a leading ``(M, ...)``
  axis (``nn.vmap`` with ``variable_axes={'params': 0}``);
- **looped** (sparse / routed / ``vmap_branches=False``): subtrees
  ``branch_0 .. branch_{M-1}`` with per-branch leaves.

The layouts are informationally identical — these converters make
checkpoints interchangeable across them (e.g. continue a GSPMD-trained
vmapped run under the banded region strategy, or serve a sparse-trained
checkpoint with the vmapped dense model). Non-branch subtrees (the
``head``) pass through untouched.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = [
    "leaf_dtype_census",
    "to_dense_serving",
    "to_looped_params",
    "to_tiled_serving",
    "to_vmapped_params",
]


def leaf_dtype_census(tree):
    """Per-dtype ``{"leaves": n, "bytes": n}`` census of a pytree.

    Works on concrete arrays and abstract ``ShapeDtypeStruct``-likes
    alike (anything with ``shape``/``dtype``), so the precision lint and
    the bench rider can census a parameter tree without materializing
    it. Leaves without a dtype (e.g. Python scalars) count under their
    numpy-inferred dtype name.
    """
    import numpy as np

    census: dict = {}
    for leaf in jax.tree.leaves(tree):
        dt = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        shape = getattr(leaf, "shape", ())
        entry = census.setdefault(dt.name, {"leaves": 0, "bytes": 0})
        entry["leaves"] += 1
        entry["bytes"] += int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    return census

_VMAPPED_KEY = "branches"


def _branch_keys(m_graphs: int):
    return [f"branch_{m}" for m in range(m_graphs)]


def to_vmapped_params(variables, m_graphs: int):
    """Looped ``branch_0..branch_{M-1}`` layout -> vmapped ``branches``."""
    params = dict(variables["params"])
    keys = _branch_keys(m_graphs)
    missing = [k for k in keys if k not in params]
    if missing:
        raise ValueError(
            f"not a looped-layout checkpoint: missing subtree(s) {missing}"
        )
    per_branch = [params.pop(k) for k in keys]
    params[_VMAPPED_KEY] = jax.tree.map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_branch
    )
    return {**variables, "params": params}


def to_dense_serving(model, variables, m_graphs: int):
    """Rebuild ``(model, params)`` as the dense vmapped XLA serving clone.

    Serving (the export artifact and :class:`stmgcn_tpu.serving.engine
    .ServingEngine`) always consumes dense ``(M, K, N, N)`` support
    stacks on a single device: sparse/banded layouts, per-branch looping,
    shard bindings and the Pallas LSTM kernel are training-side
    representations. Sparse/looped checkpoints are restacked to the
    vmapped layout (same modules, same math — round-trip + forward
    equality pinned in tests/test_param_layouts.py); a Pallas-backend
    model is re-routed to the xla scan of the same params
    (tests/test_pallas_lstm.py). Already-dense models pass through
    untouched.
    """
    if any(mode != "dense" for mode in model.branch_modes()) or not model.vmap_branches:
        model = dataclasses.replace(
            model,
            sparse=False,
            support_modes=None,
            shard_spec=None,
            vmap_branches=True,
            n_real_nodes=None,
        )
        variables = to_vmapped_params(variables, m_graphs)
    if model.lstm_backend != "xla":
        model = dataclasses.replace(model, lstm_backend="xla", lstm_pallas_mesh=None)
    return model, variables


def to_tiled_serving(model, variables, m_graphs: int):
    """Rebuild ``(model, params)`` as the tiled-sparse serving clone.

    The tiled twin of :func:`to_dense_serving`: serving a large-N city
    on its :class:`~stmgcn_tpu.ops.tiling.TiledSupports` plan needs the
    loop-layout model with ``support_modes=("tiled",) * M`` — a
    dense/vmapped-trained checkpoint is unstacked to ``branch_0..
    branch_{M-1}``; sparse/banded/tiled-trained (already looped)
    checkpoints pass through. Shard bindings drop and a Pallas-backend
    LSTM re-routes to the xla scan, exactly like the dense clone.
    """
    if all(mode == "dense" for mode in model.branch_modes()) and model.vmap_branches:
        variables = to_looped_params(variables, m_graphs)
    model = dataclasses.replace(
        model,
        sparse=False,
        support_modes=("tiled",) * m_graphs,
        shard_spec=None,
        vmap_branches=False,
        n_real_nodes=None,
    )
    if model.lstm_backend != "xla":
        model = dataclasses.replace(model, lstm_backend="xla", lstm_pallas_mesh=None)
    return model, variables


def to_looped_params(variables, m_graphs: int):
    """Vmapped ``branches`` layout -> looped ``branch_0..branch_{M-1}``."""
    params = dict(variables["params"])
    if _VMAPPED_KEY not in params:
        raise ValueError(
            f"not a vmapped-layout checkpoint: no {_VMAPPED_KEY!r} subtree"
        )
    stacked = params.pop(_VMAPPED_KEY)
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stacked)}
    if leading != {m_graphs}:
        raise ValueError(
            f"stacked branch axis is {sorted(leading)}, expected {{{m_graphs}}}"
        )
    for m, key in enumerate(_branch_keys(m_graphs)):
        params[key] = jax.tree.map(lambda leaf, m=m: leaf[m], stacked)
    return {**variables, "params": params}
