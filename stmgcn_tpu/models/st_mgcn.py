"""ST-MGCN: the multi-graph flagship model.

TPU-native counterpart of the reference's ``ST_MGCN``
(``/root/reference/STMGCN.py:61-119``). Architectural difference by design:
the reference keeps M (CG_LSTM, GCN) pairs in ``nn.ModuleList`` s and runs
the branches *sequentially* in a Python loop (``STMGCN.py:69-77,112-115``);
here the branch is a single module vmapped over the leading graph axis of a
stacked ``(M, K, N, N)`` support tensor — all M shape-identical branches
execute as one batched computation (one MXU-sized einsum per op instead of
M small ones), with per-branch parameters stacked on axis 0.

Fusion and head match the reference: sum over the M branch outputs
(``STMGCN.py:116``) then a final ``Dense(gcn_hidden -> input_dim)``
(``STMGCN.py:78,118``), producing the ``(B, N, C)`` next-step prediction.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from stmgcn_tpu.models.cg_lstm import CGLSTM
from stmgcn_tpu.ops.chebconv import accum_dot_general, make_conv

__all__ = ["STMGCN", "Branch"]


class Branch(nn.Module):
    """One graph view's encoder: CGLSTM -> graph conv on the LSTM state."""

    n_supports: int
    seq_len: int
    lstm_hidden_dim: int
    lstm_num_layers: int
    gcn_hidden_dim: int
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    shared_gate_fc: bool = True
    #: support representation this branch consumes: "dense" | "sparse" |
    #: "banded" (stmgcn_tpu.ops.chebconv.conv_cls)
    support_mode: str = "dense"
    shard_spec: Any = None
    n_real_nodes: Optional[int] = None
    remat: bool = False
    lstm_unroll: int = 1
    lstm_fused_scan: bool = False
    lstm_backend: str = "xla"
    lstm_pallas_mesh: Any = None
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, obs_seq: jnp.ndarray, n_real=None) -> jnp.ndarray:
        rnn_out = CGLSTM(
            n_supports=self.n_supports,
            seq_len=self.seq_len,
            lstm_hidden_dim=self.lstm_hidden_dim,
            lstm_num_layers=self.lstm_num_layers,
            use_bias=self.use_bias,
            activation=self.activation,
            shared_gate_fc=self.shared_gate_fc,
            support_mode=self.support_mode,
            shard_spec=self.shard_spec,
            n_real_nodes=self.n_real_nodes,
            remat=self.remat,
            lstm_unroll=self.lstm_unroll,
            lstm_fused_scan=self.lstm_fused_scan,
            lstm_backend=self.lstm_backend,
            lstm_pallas_mesh=self.lstm_pallas_mesh,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="cg_lstm",
        )(supports, obs_seq, n_real)
        return make_conv(
            self.support_mode,
            shard_spec=self.shard_spec,
            n_supports=self.n_supports,
            features=self.gcn_hidden_dim,
            use_bias=self.use_bias,
            activation=self.activation,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="gcn",
        )(supports, rnn_out)


class STMGCN(nn.Module):
    """Multi-graph spatiotemporal model; ``(B, T, N, C) -> (B, N, C)``.

    With ``horizon > 1`` the head forecasts H steps jointly and the output
    is ``(B, H, N, C)`` — a seq2seq extension the single-step reference
    (``STMGCN.py:118``) does not have.
    """

    m_graphs: int
    n_supports: int
    seq_len: int
    input_dim: int
    horizon: int = 1
    lstm_hidden_dim: int = 64
    lstm_num_layers: int = 3
    gcn_hidden_dim: int = 64
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    shared_gate_fc: bool = True
    #: sparse mode: supports are an M-tuple of K-tuples of BlockSparse and
    #: branches run as a Python loop (the Pallas SpMM is not vmappable over
    #: the graph axis); params live under branch_0..branch_{M-1} instead of
    #: a stacked axis
    sparse: bool = False
    #: per-branch support representations, e.g. ``("banded", "dense",
    #: "dense")`` — branches with banded (grid-structured) supports take
    #: the explicit halo-exchange plan while the rest stay on GSPMD.
    #: ``None`` derives a uniform tuple from ``sparse``. Any non-dense
    #: entry forces the loop path (params under branch_0..branch_{M-1}),
    #: EXCEPT a uniformly banded/sparse tuple whose supports arrive
    #: branch-stacked (BandedSupports strips / ShardedBlockSparse with a
    #: leading M axis) + vmap_branches=True: that runs ONE vmapped Branch
    #: whose branch axis a mesh can shard.
    support_modes: Optional[tuple] = None
    #: static mesh/axis routing for "banded" branches and mesh-sharded
    #: "sparse" branches
    shard_spec: Any = None
    #: real node count when the node axis carries mesh-divisibility
    #: padding (None = no padding); gate pooling and nothing else depends
    #: on it — padded rows are excluded from the loss by the (B, N) mask
    n_real_nodes: Optional[int] = None
    vmap_branches: bool = True
    remat: bool = False
    #: lax.scan unroll factor / single-scan-all-layers for the shared LSTM
    #: (pure XLA scheduling levers; numerically identical either way)
    lstm_unroll: int = 1
    lstm_fused_scan: bool = False
    #: "xla" (scan) or "pallas" (hand-written fused kernel, ops/pallas_lstm.py)
    lstm_backend: str = "xla"
    #: with lstm_backend="pallas" on a >1-device mesh: launch the kernel
    #: per-shard over this Mesh (ops/pallas_lstm.py:sharded_fused_lstm)
    #: instead of asking GSPMD to partition the Mosaic custom call
    lstm_pallas_mesh: Any = None
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    def branch_modes(self) -> tuple:
        """Effective per-branch support modes."""
        if self.support_modes is not None:
            if self.sparse:
                raise ValueError("pass either sparse=True or support_modes, not both")
            if len(self.support_modes) != self.m_graphs:
                raise ValueError(
                    f"support_modes needs {self.m_graphs} entries, "
                    f"got {len(self.support_modes)}"
                )
            return tuple(self.support_modes)
        return ("sparse" if self.sparse else "dense",) * self.m_graphs

    def _branch_kwargs(self, mode: str = "dense") -> dict:
        return dict(
            n_supports=self.n_supports,
            seq_len=self.seq_len,
            lstm_hidden_dim=self.lstm_hidden_dim,
            lstm_num_layers=self.lstm_num_layers,
            gcn_hidden_dim=self.gcn_hidden_dim,
            use_bias=self.use_bias,
            activation=self.activation,
            shared_gate_fc=self.shared_gate_fc,
            support_mode=mode,
            shard_spec=self.shard_spec if mode in ("banded", "sparse") else None,
            n_real_nodes=self.n_real_nodes,
            remat=self.remat,
            lstm_unroll=self.lstm_unroll,
            lstm_fused_scan=self.lstm_fused_scan,
            lstm_backend=self.lstm_backend,
            lstm_pallas_mesh=self.lstm_pallas_mesh,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )

    @nn.compact
    def __call__(self, supports_stack, obs_seq: jnp.ndarray, n_real=None) -> jnp.ndarray:
        """``supports_stack``: dense ``(M, K, N, N)`` array; or, when any
        branch mode is non-dense, an M-sequence whose ``m``-th entry matches
        branch ``m``'s mode — dense ``(K, N, N)`` array, K-sequence of
        ``BlockSparse``, or ``BandedSupports``; ``obs_seq`` ``(B, T, N, C)``.

        ``n_real``: optional traced int32 real-node count forwarded to the
        gate pooling (fleet shape classes share one program over cities of
        differing real N); ``None`` keeps the static ``n_real_nodes``."""
        modes = self.branch_modes()
        all_dense = all(m == "dense" for m in modes)
        from stmgcn_tpu.parallel.banded import BandedSupports
        from stmgcn_tpu.parallel.sparse import ShardedBlockSparse

        branch_stacked = (
            self.vmap_branches
            and isinstance(supports_stack, (BandedSupports, ShardedBlockSparse))
            and supports_stack.branch_stacked
        )
        if branch_stacked:
            want = "banded" if isinstance(supports_stack, BandedSupports) else "sparse"
            if modes != (want,) * self.m_graphs:
                raise ValueError(
                    f"branch-stacked supports need support_modes "
                    f"('{want}',) * {self.m_graphs}, got {modes}"
                )
            leading = jax.tree_util.tree_leaves(supports_stack)[0].shape[0]
            if leading != self.m_graphs:
                raise ValueError(
                    f"branch-stacked supports carry {leading} branches, "
                    f"model has {self.m_graphs}"
                )
        elif not all_dense:
            if len(supports_stack) != self.m_graphs:
                raise ValueError(
                    f"need {self.m_graphs} per-branch support groups, "
                    f"got {len(supports_stack)}"
                )
        else:
            supports_stack = jnp.asarray(supports_stack)  # accept an M-sequence
            if supports_stack.ndim != 4 or supports_stack.shape[0] != self.m_graphs:
                raise ValueError(
                    f"supports_stack must be ({self.m_graphs}, K, N, N), "
                    f"got {supports_stack.shape}"
                )  # STMGCN.py:107
        if branch_stacked:
            # branch-parallel loop-layout supports (banded strips or
            # block-CSR): ONE vmapped Branch over the stacked operand.
            # spmd_axis_name tells the inner shard_maps (ring halo
            # exchange / sharded SpMM) that the vmapped axis is the
            # mesh's branch axis, so each branch group runs its own
            # region collectives while the branch dim shards away (no
            # kernel batching rule needed). Only at apply time: flax's
            # rng-split machinery during init rejects spmd_axis_name's
            # axis tree, and the created params are identical either way
            # (placement shards them afterwards).
            spmd = (
                "branch"
                if not self.is_initializing()
                and self.shard_spec is not None
                and self.shard_spec.mesh.shape.get("branch", 1) > 1
                else None
            )
            branches = nn.vmap(
                Branch,
                in_axes=(0, None, None),
                out_axes=0,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                spmd_axis_name=spmd,
            )(**self._branch_kwargs(modes[0]), name="branches")
            feats = branches(supports_stack, obs_seq, n_real)  # (M, B, N, gcn_hidden)
            # aggregation (STMGCN.py:116); f32 reduction island (no-op on fp32)
            fused = feats.sum(axis=0, dtype=jnp.float32).astype(feats.dtype)
        elif not all_dense or not self.vmap_branches:
            feats = [
                Branch(**self._branch_kwargs(modes[m]), name=f"branch_{m}")(
                    supports_stack[m], obs_seq, n_real
                )
                for m in range(self.m_graphs)
            ]
            # aggregation (STMGCN.py:116); f32 reduction island (no-op on fp32)
            fused = sum(f.astype(jnp.float32) for f in feats).astype(feats[0].dtype)
        else:
            branches = nn.vmap(
                Branch,
                in_axes=(0, None, None),
                out_axes=0,
                variable_axes={"params": 0},
                split_rngs={"params": True},
            )(**self._branch_kwargs(), name="branches")
            feats = branches(supports_stack, obs_seq, n_real)  # (M, B, N, gcn_hidden)
            # aggregation (STMGCN.py:116); f32 reduction island (no-op on fp32)
            fused = feats.sum(axis=0, dtype=jnp.float32).astype(feats.dtype)
        out = nn.Dense(
            self.horizon * self.input_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            dot_general=accum_dot_general(self.dtype),
            name="head",
        )(fused)
        if self.dtype is not None:
            # the head's dot_general hands back its f32 accumulator (bias
            # add included); the prediction leaves in the module compute
            # dtype — a no-op convert on fp32, bf16 at the serve boundary
            out = out.astype(self.dtype)
        if self.horizon == 1:
            return out  # (B, N, C) — reference-shaped next-step prediction
        batch, n_nodes = out.shape[:2]
        return out.reshape(batch, n_nodes, self.horizon, self.input_dim).transpose(
            0, 2, 1, 3
        )  # (B, H, N, C)
