"""Model layer: contextual-gated LSTM branches and the ST-MGCN flagship."""

from stmgcn_tpu.models.cg_lstm import CGLSTM, ContextualGate
from stmgcn_tpu.models.params import (
    to_dense_serving,
    to_looped_params,
    to_tiled_serving,
    to_vmapped_params,
)
from stmgcn_tpu.models.st_mgcn import STMGCN, Branch

__all__ = [
    "Branch",
    "CGLSTM",
    "ContextualGate",
    "STMGCN",
    "to_dense_serving",
    "to_looped_params",
    "to_tiled_serving",
    "to_vmapped_params",
]
