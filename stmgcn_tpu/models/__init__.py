"""Model layer: contextual-gated LSTM branches and the ST-MGCN flagship."""

from stmgcn_tpu.models.cg_lstm import CGLSTM, ContextualGate
from stmgcn_tpu.models.st_mgcn import STMGCN, Branch

__all__ = ["CGLSTM", "ContextualGate", "STMGCN", "Branch"]
