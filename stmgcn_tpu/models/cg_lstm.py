"""Contextual-gated LSTM (CGRNN) — one graph branch's recurrent encoder.

TPU-native counterpart of the reference's ``CG_LSTM``
(``/root/reference/STMGCN.py:7-57``), implementing paper eqs. 6-9:

1. each region's length-T history is treated as its feature vector and
   graph-convolved over the support stack (eq. 6 with residual,
   ``STMGCN.py:40-41``);
2. global average pooling over *nodes* then an FC -> ReLU -> FC -> sigmoid
   produces per-timestep attention weights (eqs. 7-8, ``STMGCN.py:42-43``);
3. the observation sequence is reweighted per timestep (eq. 9,
   ``STMGCN.py:44``) and fed through a globally-shared LSTM with nodes
   folded into the batch axis (``STMGCN.py:47-50``), keeping the last
   timestep's hidden state.

Reference quirk 1 (SURVEY.md §2): the reference applies the *same*
``nn.Linear`` twice in eq. 8 (``s = sigmoid(fc(relu(fc(z))))``,
``STMGCN.py:20,43``) where the paper has two distinct layers.
``shared_gate_fc=True`` (default) reproduces the reference; ``False`` gives
the paper's two-layer gate.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from flax import linen as nn

from stmgcn_tpu.ops.chebconv import accum_dot_general, make_conv
from stmgcn_tpu.ops.lstm import StackedLSTM

__all__ = ["CGLSTM", "ContextualGate"]


class ContextualGate(nn.Module):
    """Per-timestep sigmoid attention from graph-convolved temporal features."""

    n_supports: int
    seq_len: int
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    shared_gate_fc: bool = True
    #: "dense" | "sparse" | "banded" — the support representation this
    #: gate's graph conv consumes (see stmgcn_tpu.ops.chebconv.conv_cls)
    support_mode: str = "dense"
    shard_spec: Any = None
    #: when the node axis carries mesh-divisibility padding, the number of
    #: real nodes — eq. 7's node pooling then excludes padded rows (whose
    #: conv bias would otherwise shift the gate), keeping the padded model
    #: numerically identical to the unpadded one
    n_real_nodes: Optional[int] = None
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, obs_seq: jnp.ndarray, n_real=None) -> jnp.ndarray:
        """``obs_seq`` ``(B, T, N, C)`` -> gated ``(B, T, N, C)``.

        ``n_real`` is an optional *traced* int32 real-node count: one
        compiled program can then serve cities with differing real N
        inside one padded shape class (fleet training/serving), where
        the static ``n_real_nodes`` attribute would force a program per
        city. ``None`` keeps the static-attribute behavior.
        """
        # collapse features (STMGCN.py:36); reduce in f32 (mandatory-f32
        # reduction under the precision policy — no-op jaxpr-wise on fp32)
        x_seq = obs_seq.sum(axis=-1, dtype=jnp.float32).astype(obs_seq.dtype)
        x_nt = x_seq.transpose(0, 2, 1)  # (B, N, T): history as node features
        g = make_conv(
            self.support_mode,
            shard_spec=self.shard_spec,
            n_supports=self.n_supports,
            features=self.seq_len,
            use_bias=self.use_bias,
            activation=self.activation,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="temporal_gconv",
        )(supports, x_nt)
        x_hat = x_nt + g  # eq. 6 residual
        n_nodes = x_hat.shape[1]
        if n_real is not None:
            # eq. 7 over real nodes only with a *traced* count; the
            # exact-fit arm goes through the same plain mean as the
            # unpadded model so exact-fit cities stay bit-identical to it
            nr = jnp.asarray(n_real)
            node_mask = (jnp.arange(n_nodes) < nr).astype(x_hat.dtype)
            masked = (x_hat * node_mask[None, :, None]).sum(
                axis=1, dtype=jnp.float32
            ) / nr.astype(jnp.float32)
            z = jnp.where(
                nr == n_nodes, x_hat.mean(axis=1, dtype=jnp.float32), masked
            ).astype(x_hat.dtype)
        elif self.n_real_nodes is not None and self.n_real_nodes != n_nodes:
            # eq. 7 over real nodes only (masked mean; a static slice would
            # fight the region sharding, a broadcast-multiply does not)
            node_mask = (jnp.arange(n_nodes) < self.n_real_nodes).astype(x_hat.dtype)
            z = (
                (x_hat * node_mask[None, :, None]).sum(axis=1, dtype=jnp.float32)
                / self.n_real_nodes
            ).astype(x_hat.dtype)
        else:
            # eq. 7: average pool over nodes -> (B, T); f32 reduction island
            z = x_hat.mean(axis=1, dtype=jnp.float32).astype(x_hat.dtype)

        fc = nn.Dense(
            self.seq_len, dtype=self.dtype, param_dtype=self.param_dtype,
            dot_general=accum_dot_general(self.dtype), name="gate_fc"
        )
        inner = fc(z)
        second = (
            fc
            if self.shared_gate_fc
            else nn.Dense(
                self.seq_len, dtype=self.dtype, param_dtype=self.param_dtype,
                dot_general=accum_dot_general(self.dtype), name="gate_fc2"
            )
        )
        s = nn.sigmoid(second(nn.relu(inner)))  # eq. 8
        return obs_seq * s[:, :, None, None]  # eq. 9


class CGLSTM(nn.Module):
    """Contextual gate + globally-shared LSTM; returns ``(B, N, lstm_hidden)``."""

    n_supports: int
    seq_len: int
    lstm_hidden_dim: int
    lstm_num_layers: int
    use_bias: bool = True
    activation: Optional[Callable] = nn.relu
    shared_gate_fc: bool = True
    support_mode: str = "dense"
    shard_spec: Any = None
    n_real_nodes: Optional[int] = None
    remat: bool = False
    lstm_unroll: int = 1
    lstm_fused_scan: bool = False
    lstm_backend: str = "xla"
    #: Mesh for per-shard pallas kernel launch (ops/lstm.py:StackedLSTM)
    lstm_pallas_mesh: Any = None
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, supports, obs_seq: jnp.ndarray, n_real=None) -> jnp.ndarray:
        batch, seq_len, n_nodes, n_feats = obs_seq.shape
        gated = ContextualGate(
            n_supports=self.n_supports,
            seq_len=self.seq_len,
            use_bias=self.use_bias,
            activation=self.activation,
            shared_gate_fc=self.shared_gate_fc,
            support_mode=self.support_mode,
            shard_spec=self.shard_spec,
            n_real_nodes=self.n_real_nodes,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="gate",
        )(supports, obs_seq, n_real)

        # Fold nodes into batch for the shared recurrence (STMGCN.py:47).
        folded = gated.transpose(0, 2, 1, 3).reshape(batch * n_nodes, seq_len, n_feats)
        outputs, _ = StackedLSTM(
            hidden_dim=self.lstm_hidden_dim,
            num_layers=self.lstm_num_layers,
            remat=self.remat,
            unroll=self.lstm_unroll,
            fused_scan=self.lstm_fused_scan,
            backend=self.lstm_backend,
            pallas_mesh=self.lstm_pallas_mesh,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="lstm",
        )(folded)
        last = outputs[:, -1, :]  # (B*N, H) — keep final timestep (STMGCN.py:50)
        return last.reshape(batch, n_nodes, self.lstm_hidden_dim)
