"""Experiment assembly: config -> data, supports, model, trainer.

The wiring the reference does inline in ``Main.py:36-88`` (load data, build
per-graph supports, construct model with hard-coded widths, train, test),
as composable builders. Everything downstream (CLI, bench, graft entry,
distributed runners) assembles experiments through these functions.
"""

from __future__ import annotations


from stmgcn_tpu.config import ExperimentConfig
from stmgcn_tpu.data import (
    DemandDataset,
    WindowSpec,
    date_splits,
    load_npz,
    synthetic_dataset,
)
from stmgcn_tpu.data.splits import fraction_splits
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.train import Trainer

__all__ = ["build_dataset", "build_supports", "build_model", "build_trainer", "run"]


def build_dataset(cfg: ExperimentConfig) -> DemandDataset:
    """Load or synthesize demand data and window/split it per config."""
    d = cfg.data
    window = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps, horizon=d.horizon
    )
    if d.path is not None:
        paths = [p for p in d.path.split(",") if p]
        if d.n_cities > 1 and len(paths) != d.n_cities:
            raise ValueError(
                f"n_cities={d.n_cities} needs {d.n_cities} comma-separated "
                f"archives in data.path, got {len(paths)}"
            )
        cities = [load_npz(p, m_graphs=cfg.model.m_graphs) for p in paths]
    else:
        cities = [
            synthetic_dataset(
                rows=d.rows,
                cols=d.cols,
                n_timesteps=d.n_timesteps,
                m_graphs=cfg.model.m_graphs,
                day_timesteps=d.day_timesteps,
                seed=d.seed + c,
            )
            for c in range(d.n_cities)
        ]
        # One support stack serves all branches, so synthetic cities share the
        # region-graph structure (distinct demand, common graphs) — the DP
        # mesh axis is what the multicity config exercises.
        for c in cities[1:]:
            c.adjs = cities[0].adjs
    n_samples = window.n_samples(cities[0].demand.shape[0])
    if d.dates is not None:
        split = date_splits(
            list(d.dates),
            burn_in=window.burn_in,
            day_timesteps=d.day_timesteps,
            val_ratio=d.val_ratio,
            year=d.year,
            n_samples=n_samples,
        )
    else:
        split = fraction_splits(n_samples, train=d.train_frac, validate=d.val_frac)
    return DemandDataset(
        cities if len(cities) > 1 else cities[0], window, split, normalize=d.normalize
    )


def build_supports(cfg: ExperimentConfig, dataset: DemandDataset):
    """Supports from the dataset's graphs.

    Dense mode: one stacked ``(M, n_supports, N, N)`` array. Sparse mode:
    an M-tuple of K-tuples of :class:`~stmgcn_tpu.ops.spmm.BlockSparse`
    for the Pallas SpMM path.
    """
    dense = cfg.model.support_config.build_all(dataset.adjs.values())
    if not cfg.model.sparse:
        return dense
    from stmgcn_tpu.ops.spmm import from_dense

    return tuple(
        tuple(from_dense(dense[m, k]) for k in range(dense.shape[1]))
        for m in range(dense.shape[0])
    )


def build_model(cfg: ExperimentConfig, input_dim: int) -> STMGCN:
    """Model from config + the one data-derived scalar (feature count)."""
    m = cfg.model
    return STMGCN(
        m_graphs=m.m_graphs,
        n_supports=m.n_supports,
        seq_len=cfg.data.seq_len,
        input_dim=input_dim,
        horizon=cfg.data.horizon,
        lstm_hidden_dim=m.lstm_hidden_dim,
        lstm_num_layers=m.lstm_num_layers,
        gcn_hidden_dim=m.gcn_hidden_dim,
        use_bias=m.use_bias,
        shared_gate_fc=m.shared_gate_fc,
        sparse=m.sparse,
        remat=m.remat,
        dtype=m.compute_dtype if m.dtype != "float32" else None,
    )


def build_trainer(
    cfg: ExperimentConfig,
    placement=None,
    verbose: bool = True,
) -> Trainer:
    """Assemble a trainer; a >1-device mesh config gets sharded placement.

    If the config asks for a mesh and fewer devices are visible, this
    raises — silent fallback to one device would misreport the benchmark
    configs (3/4) as sharded.
    """
    if placement is None and cfg.model.sparse and cfg.mesh.n_devices > 1:
        raise ValueError(
            "sparse mode does not support mesh sharding yet — use dense "
            "supports for multi-device configs"
        )
    if placement is None and cfg.mesh.n_devices > 1:
        # Fail fast (before data/support construction) if the mesh can't exist.
        from stmgcn_tpu.parallel import MeshPlacement, mesh_from_config

        placement = MeshPlacement(mesh_from_config(cfg.mesh))
    dataset = build_dataset(cfg)
    supports = build_supports(cfg, dataset)
    model = build_model(cfg, dataset.n_feats)
    if placement is not None and hasattr(placement, "check_divisibility"):
        placement.check_divisibility(cfg.train.batch_size, dataset.n_nodes)
    t = cfg.train
    return Trainer(
        model,
        dataset,
        supports,
        lr=t.lr,
        weight_decay=t.weight_decay,
        loss=t.loss,
        n_epochs=t.epochs,
        batch_size=t.batch_size,
        patience=t.patience,
        top_k=t.top_k,
        shuffle=t.shuffle,
        seed=t.seed,
        out_dir=t.out_dir,
        placement=placement,
        extra_meta={
            "config": cfg.to_dict(),
            # data-derived model facts a checkpoint consumer needs to rebuild
            # the model without the dataset
            "derived": {"input_dim": dataset.n_feats, "n_nodes": dataset.n_nodes},
        },
        verbose=verbose,
    )


def run(cfg: ExperimentConfig, verbose: bool = True) -> dict:
    """Train then test (the reference's ``Main.py:78-88`` flow)."""
    trainer = build_trainer(cfg, verbose=verbose)
    history = trainer.train()
    results = trainer.test(modes=("train", "test"))
    return {"history": history, "results": results}
