"""Experiment assembly: config -> data, supports, model, trainer.

The wiring the reference does inline in ``Main.py:36-88`` (load data, build
per-graph supports, construct model with hard-coded widths, train, test),
as composable builders. Everything downstream (CLI, bench, graft entry,
distributed runners) assembles experiments through these functions.
"""

from __future__ import annotations


from stmgcn_tpu.config import ExperimentConfig
from stmgcn_tpu.data import (
    DemandDataset,
    WindowSpec,
    date_splits,
    load_npz,
    synthetic_dataset,
)
from stmgcn_tpu.data.splits import fraction_splits
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.train import Trainer

__all__ = [
    "build_dataset",
    "build_model",
    "build_supports",
    "build_trainer",
    "route_supports",
    "run",
]


def _split_for(d, window: WindowSpec, n_timesteps: int):
    """One split spec over a series of ``n_timesteps`` per the data config."""
    n_samples = window.n_samples(n_timesteps)
    if d.dates is not None:
        return date_splits(
            list(d.dates),
            burn_in=window.burn_in,
            day_timesteps=d.day_timesteps,
            val_ratio=d.val_ratio,
            year=d.year,
            n_samples=n_samples,
        )
    return fraction_splits(n_samples, train=d.train_frac, validate=d.val_frac)


def build_dataset(cfg: ExperimentConfig):
    """Load or synthesize demand data and window/split it per config.

    Returns a :class:`DemandDataset` for same-shape cities, or a
    :class:`~stmgcn_tpu.data.HeteroCityDataset` when city shapes differ
    (or ``data.hetero`` forces per-city treatment) — each city then keeps
    its own normalizer and split calendar.
    """
    d = cfg.data
    window = WindowSpec(
        d.serial_len, d.daily_len, d.weekly_len, d.day_timesteps, horizon=d.horizon
    )
    for name, per_city in (("city_rows", d.city_rows), ("city_timesteps", d.city_timesteps)):
        if per_city is not None and len(per_city) != d.n_cities:
            raise ValueError(
                f"data.{name} must list one value per city "
                f"(n_cities={d.n_cities}), got {per_city}"
            )
    if d.path is not None:
        paths = [p for p in d.path.split(",") if p]
        if d.n_cities > 1 and len(paths) != d.n_cities:
            raise ValueError(
                f"n_cities={d.n_cities} needs {d.n_cities} comma-separated "
                f"archives in data.path, got {len(paths)}"
            )
        cities = [load_npz(p, m_graphs=cfg.model.m_graphs) for p in paths]
    else:
        cities = [
            synthetic_dataset(
                rows=d.city_rows[c] if d.city_rows is not None else d.rows,
                cols=d.cols,
                n_timesteps=(
                    d.city_timesteps[c] if d.city_timesteps is not None else d.n_timesteps
                ),
                m_graphs=cfg.model.m_graphs,
                day_timesteps=d.day_timesteps,
                seed=d.seed + c,
            )
            for c in range(d.n_cities)
        ]
        if d.shared_graphs:
            # optionally collapse to one region-graph structure (distinct
            # demand, common graphs) — lets every support representation
            # (banded/sparse mesh routing) apply across cities
            if len({c.demand.shape[1] for c in cities}) > 1:
                raise ValueError(
                    "shared_graphs needs cities with one region count — "
                    "a graph stack cannot be shared across differing N"
                )
            for c in cities[1:]:
                c.adjs = cities[0].adjs
    hetero = len(cities) > 1 and (
        d.hetero or len({c.demand.shape for c in cities}) > 1
    )
    if hetero:
        from stmgcn_tpu.data import HeteroCityDataset

        splits = [_split_for(d, window, c.demand.shape[0]) for c in cities]
        return HeteroCityDataset(cities, window, splits, normalize=d.normalize)
    split = _split_for(d, window, cities[0].demand.shape[0])
    return DemandDataset(
        cities if len(cities) > 1 else cities[0], window, split, normalize=d.normalize
    )


def node_pad_target(cfg: ExperimentConfig, n_nodes: int):
    """Padded node count for a region mesh that does not divide ``N``
    (``None`` when no padding is needed).

    Supports are built at the true ``N`` and then zero-padded — padding the
    *adjacency* instead would change the Laplacian spectrum (the
    ``2L/λmax − I`` rescale, ``GCN.py:113-123``) and silently alter the
    model at real nodes. Padded rows are isolated: zero support rows/cols,
    zero inputs, excluded from the gate pooling (``STMGCN.n_real_nodes``)
    and from the loss via the ``(B, N)`` mask.
    """
    region = cfg.mesh.region
    if cfg.mesh.n_devices > 1 and region > 1 and n_nodes % region:
        return -(-n_nodes // region) * region
    return None


def _pad_support_nodes(dense, n_pad: int):
    """Zero-pad the trailing two (node) axes of a dense support stack."""
    import numpy as np

    dense = np.asarray(dense)
    extra = n_pad - dense.shape[-1]
    if extra <= 0:
        return dense
    widths = [(0, 0)] * (dense.ndim - 2) + [(0, extra), (0, extra)]
    return np.pad(dense, widths)


def _dense_supports(cfg: ExperimentConfig, adjs):
    """One city's dense support stack, node-padded iff the mesh needs it —
    the single padding site every support representation derives from.
    ``N`` comes from the adjacencies themselves (heterogeneous cities
    have per-city region counts)."""
    n_nodes = next(iter(adjs.values())).shape[0]
    dense = cfg.model.support_config.build_all(adjs.values())
    n_pad = node_pad_target(cfg, n_nodes)
    return _pad_support_nodes(dense, n_pad) if n_pad is not None else dense


def build_supports(cfg: ExperimentConfig, dataset: DemandDataset):
    """Supports from the dataset's graphs.

    Dense mode: one stacked ``(M, n_supports, N, N)`` array. Sparse mode:
    an M-tuple of :class:`~stmgcn_tpu.ops.spmm.BlockSparseStack` — each
    branch's K supports in one fused-launch block-CSR structure. Tiled
    mode: one :class:`~stmgcn_tpu.ops.tiling.TiledSupports` plan per city
    (offline reorder + condense covering all M x K supports). When the
    dataset's cities carry differing graphs, the result is a
    :class:`~stmgcn_tpu.train.CitySupports` of one such stack per city.
    On a region mesh that does not divide ``N``, the node axes carry zero
    padding (see :func:`node_pad_target`).
    """
    if cfg.model.tiled and cfg.model.sparse:
        raise ValueError(
            "model.tiled and model.sparse are mutually exclusive — each is "
            "a complete support representation; pick one"
        )

    def one(adjs):
        dense = _dense_supports(cfg, adjs)
        if cfg.model.tiled:
            from stmgcn_tpu.ops.tiling import plan_tiling

            plan = plan_tiling(dense, tile=cfg.model.tile_size)
            stats = plan.tile_stats()
            stored = (
                plan.m_graphs * plan.n_supports * plan.block_rows * plan.block_cols
            )
            waste = 1.0 - stats["blocks_kept"] / max(stored, 1)
            if waste > cfg.model.tile_waste_budget:
                raise ValueError(
                    f"tiled condensation wastes {waste:.3f} of stored blocks "
                    f"on all-zero padding (> model.tile_waste_budget="
                    f"{cfg.model.tile_waste_budget}) — the graph's nonzeros "
                    "do not cluster under the reorder; use dense/sparse "
                    "supports, a smaller model.tile_size, or raise the budget"
                )
            return plan
        if not cfg.model.sparse:
            return dense
        from stmgcn_tpu.ops.spmm import stack_from_dense

        return tuple(stack_from_dense(dense[m]) for m in range(dense.shape[0]))

    if not dataset.shared_graphs:
        from stmgcn_tpu.train import CitySupports

        return CitySupports(one(adjs) for adjs in dataset.city_adjs)
    return one(dataset.adjs)


def _strategy_active(cfg: ExperimentConfig) -> bool:
    """Whether the mesh's region strategy replaces GSPMD's automatic plan."""
    s = cfg.mesh.region_strategy
    if s not in ("gspmd", "banded", "auto"):
        raise ValueError(
            f"mesh.region_strategy must be gspmd|banded|auto, got {s!r}"
        )
    # (round 5: mesh.branch > 1 composes with BOTH loop-layout support
    # families now — banded via branch-stacked strips, sparse via
    # branch-stacked block-CSR; route_supports builds the stacked forms)
    return s != "gspmd" and cfg.mesh.region > 1 and not cfg.model.sparse


def route_supports(cfg: ExperimentConfig, dataset: DemandDataset, supports=None):
    """Route each branch's supports per the mesh's region strategy.

    Returns ``(supports, modes)`` where ``modes`` is ``None`` when GSPMD
    (or single-device sparse) handles everything, else a per-branch tuple:

    - dense + active region strategy: ``"banded" | "dense"`` per branch —
      branches whose supports are banded enough (max Chebyshev-support
      bandwidth within the halo budget, default ``n_local // 2``) get
      strip form for the explicit halo-exchange plan; the rest stay dense
      under GSPMD. ``region_strategy="banded"`` demands every branch
      qualify and raises otherwise.
    - sparse on a >1-device mesh: ``("sparse",) * M`` with each branch's
      supports as :class:`~stmgcn_tpu.parallel.sparse.ShardedBlockSparse`
      row strips over the region axis.
    - active strategy + ``mesh.branch > 1``: a single branch-stacked
      :class:`~stmgcn_tpu.parallel.banded.BandedSupports` (all branches'
      strips at one common halo) with ``("banded",) * M`` — the vmapped
      branch axis shards it; if any branch exceeds the budget, ``auto``
      falls back to all-dense GSPMD (``modes=None``) and ``banded``
      raises.
    """
    _strategy_active(cfg)  # validates strategy / branch-axis combinations
    if cfg.model.tiled:
        # tiled-sparse supports are a single-device representation: the
        # gathered-tiles/Pallas kernels own the full node axis (the offline
        # permutation has no sharded form), and branches run the loop
        # layout — no vmapped branch axis for a mesh to shard
        if cfg.mesh.n_devices > 1:
            raise ValueError(
                "model.tiled does not compose with a >1-device mesh — the "
                "reordered tile plan owns the whole node axis; use dense "
                "GSPMD or sharded sparse supports for multi-device configs"
            )
        supports = build_supports(cfg, dataset) if supports is None else supports
        return supports, ("tiled",) * cfg.model.m_graphs
    if not dataset.shared_graphs and (
        (cfg.model.sparse and cfg.mesh.n_devices > 1) or _strategy_active(cfg)
    ):
        raise ValueError(
            "per-city graphs currently compose with dense GSPMD or "
            "single-device sparse supports only — set "
            "data.shared_graphs=True, region_strategy='gspmd', or dense "
            "mode for multi-city mesh configs"
        )
    if cfg.model.sparse and cfg.mesh.n_devices > 1:
        from stmgcn_tpu.parallel.sparse import branch_stack_sparse, sharded_from_dense

        dense = _dense_supports(cfg, dataset.adjs)
        if cfg.mesh.branch > 1:
            # branch parallelism needs ONE stacked operand: all branches'
            # strips at a common block-column width, vmapped branch axis
            # sharded over the mesh (same shape trade as banded's common
            # halo — see parallel.sparse.branch_stack_sparse)
            return (
                branch_stack_sparse(dense, cfg.mesh.region),
                ("sparse",) * dense.shape[0],
            )
        routed = tuple(
            sharded_from_dense(dense[m], cfg.mesh.region)
            for m in range(dense.shape[0])
        )
        return routed, ("sparse",) * dense.shape[0]
    supports = build_supports(cfg, dataset) if supports is None else supports
    if not _strategy_active(cfg):
        return supports, None
    import numpy as np

    from stmgcn_tpu.parallel.banded import banded_decompose, bandwidth

    region = cfg.mesh.region
    n = supports.shape[-1]  # node-padded when the mesh required it
    if n % region:
        raise ValueError(f"n_nodes {n} not divisible by region={region}")
    n_local = n // region
    budget = min(cfg.mesh.halo if cfg.mesh.halo is not None else n_local // 2, n_local)
    bws = [
        max(bandwidth(supports[m, k]) for k in range(supports.shape[1]))
        for m in range(supports.shape[0])
    ]
    if cfg.mesh.branch > 1:
        # branch parallelism needs ONE stacked operand the vmapped branch
        # axis can shard — every branch must fit the banded plan at a
        # common halo (mixed banded/dense routing has no stacked form)
        from stmgcn_tpu.parallel.banded import branch_stack

        over = [m for m, bw in enumerate(bws) if bw > budget]
        if over and cfg.mesh.region_strategy == "banded":
            raise ValueError(
                "mesh.branch > 1 with region_strategy='banded' needs every "
                f"branch banded, but branches {over} have support bandwidth "
                f"> halo budget {budget} (shard size {n_local}) — use "
                "'auto' (falls back to GSPMD), raise mesh.halo, or reorder "
                "nodes to reduce bandwidth"
            )
        if over:
            # 'auto' keeps its contract: when the halo plan can't cover
            # every branch, the whole (still fully supported) dense
            # branch-parallel plan stays on GSPMD
            return supports, None
        stacked = branch_stack(
            [np.asarray(supports[m]) for m in range(supports.shape[0])],
            region,
            halo=max(bws),
        )
        return stacked, ("banded",) * supports.shape[0]
    routed, modes = [], []
    for m in range(supports.shape[0]):
        bw = bws[m]
        if bw <= budget:
            routed.append(banded_decompose(np.asarray(supports[m]), region, halo=bw))
            modes.append("banded")
        elif cfg.mesh.region_strategy == "banded":
            raise ValueError(
                f"region_strategy='banded' but branch {m}'s supports have "
                f"bandwidth {bw} > halo budget {budget} (shard size {n_local}) "
                "— use 'auto' to keep non-banded branches on GSPMD, raise "
                "mesh.halo, or reorder nodes to reduce bandwidth"
            )
        else:
            routed.append(supports[m])
            modes.append("dense")
    return tuple(routed), tuple(modes)


def build_model(
    cfg: ExperimentConfig,
    input_dim: int,
    support_modes=None,
    shard_spec=None,
    n_real_nodes=None,
    lstm_pallas_mesh=None,
) -> STMGCN:
    """Model from config + the one data-derived scalar (feature count).

    ``support_modes``/``shard_spec`` come from :func:`route_supports` +
    the live mesh. Whenever the config's region strategy is active the
    branch parameters use the loop layout (``branch_0..branch_{M-1}``)
    regardless of how many branches actually routed banded — EXCEPT
    ``mesh.branch > 1``, whose branch-stacked banded supports keep the
    vmapped stacked layout (the mesh shards its branch axis). Either
    way the checkpoint layout is a function of the config alone — a
    single-device rebuild (e.g. :class:`~stmgcn_tpu.inference.Forecaster`)
    reconstructs the same layout with plain dense supports. (Sparse mode
    uses the loop layout — except under ``mesh.branch > 1``, which is
    vmapped like everything branch-parallel. Tiled mode always uses the
    loop layout: ``support_modes=("tiled",) * M`` is derived from the
    config here whenever the caller passed none, so a checkpoint rebuild
    without :func:`route_supports` still gets the trained layout.)
    """
    m = cfg.model
    if m.tiled and support_modes is None:
        support_modes = ("tiled",) * m.m_graphs
    return STMGCN(
        m_graphs=m.m_graphs,
        n_supports=m.n_supports,
        seq_len=cfg.data.seq_len,
        input_dim=input_dim,
        horizon=cfg.data.horizon,
        lstm_hidden_dim=m.lstm_hidden_dim,
        lstm_num_layers=m.lstm_num_layers,
        gcn_hidden_dim=m.gcn_hidden_dim,
        use_bias=m.use_bias,
        shared_gate_fc=m.shared_gate_fc,
        # support_modes carries the routing when set (e.g. sharded sparse);
        # sparse=True alongside it would be rejected by the model. A
        # branch>1 sparse config trains in the vmapped stacked layout
        # (branch-stacked block-CSR), so its mesh-less rebuild (Forecaster
        # with dense supports) must use the vmapped dense path too — NOT
        # the sparse loop layout — or the param trees would not match.
        sparse=m.sparse and support_modes is None and cfg.mesh.branch == 1,
        support_modes=support_modes,
        shard_spec=shard_spec,
        n_real_nodes=n_real_nodes,
        # active region strategies use the per-branch loop layout — except
        # branch-parallel meshes, whose branch-stacked banded supports
        # shard the vmapped branch axis (route_supports guarantees the
        # uniform stacked form whenever mesh.branch > 1)
        vmap_branches=not _strategy_active(cfg) or cfg.mesh.branch > 1,
        remat=m.remat,
        lstm_unroll=m.lstm_unroll,
        lstm_fused_scan=m.lstm_fused_scan,
        lstm_backend=m.lstm_backend,
        lstm_pallas_mesh=lstm_pallas_mesh,
        dtype=m.compute_dtype if m.dtype != "float32" else None,
    )


def build_trainer(
    cfg: ExperimentConfig,
    placement=None,
    verbose: bool = True,
    fault_plan=None,
    dataset=None,
) -> Trainer:
    """Assemble a trainer; a >1-device mesh config gets sharded placement.

    If the config asks for a mesh and fewer devices are visible, this
    raises — silent fallback to one device would misreport the benchmark
    configs (3/4) as sharded.

    ``fault_plan`` (a :class:`~stmgcn_tpu.resilience.FaultPlan`) threads
    deterministic fault injection through the trainer's hot loop — the
    fault-drill tests' entry point; ``None`` is the no-op production plan.

    ``dataset`` overrides the config-built dataset (same config, edited
    data — e.g. :mod:`~stmgcn_tpu.parallel.compose` swaps in banded
    adjacencies before routing); ``None`` builds from ``cfg``.
    """
    if placement is None and cfg.mesh.n_devices > 1:
        # Fail fast (before data/support construction) if the mesh can't exist.
        from stmgcn_tpu.parallel import MeshPlacement, mesh_from_config

        placement = MeshPlacement(mesh_from_config(cfg.mesh))
    if dataset is None:
        dataset = build_dataset(cfg)
    supports, support_modes = route_supports(cfg, dataset)
    shard_spec = None
    if support_modes is not None and {"banded", "sparse"} & set(support_modes):
        from stmgcn_tpu.parallel.banded import ShardSpec

        if placement is None or not hasattr(placement, "mesh"):
            raise ValueError(
                "mesh-routed supports (banded/sharded-sparse) need a mesh "
                "placement (mesh.n_devices > 1 with visible devices)"
            )
        shard_spec = ShardSpec(mesh=placement.mesh)
    hetero = getattr(dataset, "heterogeneous", False)
    if hetero:
        # per-city padding: each city's N rounds up to the region extent
        # independently (jit compiles one step per city shape anyway);
        # cities whose padded shape differs from true N get their own
        # gate-pooling divisor via Trainer's city_n_real
        targets = [node_pad_target(cfg, n) for n in dataset.city_n_nodes]
        city_pads = tuple(
            (t - n) if t is not None else 0
            for t, n in zip(targets, dataset.city_n_nodes)
        )
        n_pad, node_pad_arg = None, city_pads
        padded_city_nodes = [
            n + p for n, p in zip(dataset.city_n_nodes, city_pads)
        ]
    else:
        n_pad = node_pad_target(cfg, dataset.n_nodes)
        node_pad_arg = (n_pad - dataset.n_nodes) if n_pad is not None else 0
        padded_city_nodes = [n_pad if n_pad is not None else dataset.n_nodes]
    lstm_pallas_mesh = None
    if cfg.model.lstm_backend == "pallas" and hasattr(placement, "mesh"):
        if cfg.mesh.branch > 1:
            # the per-shard launch shards rows over (dp, region); under a
            # branch axis the LSTM runs inside GSPMD-sharded vmapped
            # branches, a manual/auto mix sharded_fused_lstm doesn't do
            raise ValueError(
                "lstm_backend='pallas' does not compose with mesh.branch > 1 "
                "— use the xla backend for branch-parallel meshes"
            )
        lstm_pallas_mesh = placement.mesh
    model = build_model(
        cfg,
        dataset.n_feats,
        support_modes,
        shard_spec,
        n_real_nodes=dataset.n_nodes if not hetero and n_pad is not None else None,
        lstm_pallas_mesh=lstm_pallas_mesh,
    )
    if placement is not None and hasattr(placement, "check_divisibility"):
        for n_nodes in padded_city_nodes:
            placement.check_divisibility(
                cfg.train.batch_size, n_nodes, m_graphs=cfg.model.m_graphs
            )
    t = cfg.train
    return Trainer(
        model,
        dataset,
        supports,
        node_pad=node_pad_arg,
        lr=t.lr,
        weight_decay=t.weight_decay,
        lr_schedule=t.lr_schedule,
        warmup_epochs=t.warmup_epochs,
        min_lr_fraction=t.min_lr_fraction,
        grad_clip_norm=t.grad_clip_norm,
        loss=t.loss,
        checks=t.checks,
        precision=t.precision,
        sr_seed=t.sr_seed,
        n_epochs=t.epochs,
        batch_size=t.batch_size,
        patience=t.patience,
        top_k=t.top_k,
        prefetch=t.prefetch,
        data_placement=t.data_placement,
        window_free=t.window_free,
        steps_per_superstep=t.steps_per_superstep,
        fleet=t.fleet,
        fleet_max_classes=t.fleet_max_classes,
        fleet_max_pad_waste=t.fleet_max_pad_waste,
        async_checkpoint=t.async_checkpoint,
        checkpoint_every_steps=t.checkpoint_every_steps,
        divergence_guard=t.divergence_guard,
        divergence_action=t.divergence_action,
        divergence_patience=t.divergence_patience,
        divergence_lr_cut=t.divergence_lr_cut,
        fault_plan=fault_plan,
        health=cfg.health.enabled,
        health_every_k=cfg.health.every_k,
        health_out=cfg.health.out,
        health_baseline=cfg.health.baseline,
        health_sketch_size=cfg.health.sketch_size,
        shuffle=t.shuffle,
        seed=t.seed,
        out_dir=t.out_dir,
        placement=placement,
        extra_meta={
            "config": cfg.to_dict(),
            # data-derived model facts a checkpoint consumer needs to rebuild
            # the model without the dataset
            "derived": {
                "input_dim": dataset.n_feats,
                "n_nodes": dataset.city_n_nodes if hetero else dataset.n_nodes,
            },
        },
        verbose=verbose,
    )


def run(cfg: ExperimentConfig, verbose: bool = True) -> dict:
    """Train then test (the reference's ``Main.py:78-88`` flow)."""
    trainer = build_trainer(cfg, verbose=verbose)
    history = trainer.train()
    results = trainer.test(modes=("train", "test"))
    return {"history": history, "results": results}
