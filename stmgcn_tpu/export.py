"""Ahead-of-time export: a trained forecaster as one serving artifact.

:class:`~stmgcn_tpu.inference.Forecaster` serves from a checkpoint but
needs the framework (flax model code, config reconstruction) at load
time. This module goes one step further down the deployment path the
reference doesn't have at all (its checkpoints can't even denormalize —
``Model_Trainer.py:52-53``, SURVEY.md §5.d): ``export_forecaster``
lowers the jitted forward — **parameters baked in as constants** — to
serialized StableHLO via :mod:`jax.export` and writes a single file
carrying the compiled-function bytes plus the normalizer statistics and
shape contract. ``ExportedForecaster.load`` rebuilds a raw-units
predictor from that file alone: no model classes, no config machinery,
no flax — just JAX's export runtime plus the numpy-only data layer
(normalizer statistics) and :mod:`stmgcn_tpu.serving`. The batch
dimension is exported symbolically, so one artifact serves any batch
size.

Scope: artifacts always take dense ``(M, K, N, N)`` support stacks (the
serving-side representation). Sparse/banded-trained checkpoints export
transparently: their per-branch param layout is restacked to the dense
vmapped layout (``models.to_vmapped_params``) and the model rebuilt
dense — sparsity is a training-side optimization, not part of the
serving contract.
"""

from __future__ import annotations

import json
import os
import struct

import jax
import numpy as np
from jax import export as jax_export

from stmgcn_tpu.data.normalize import normalizer_from_dict
from stmgcn_tpu.serving import serve_predict

__all__ = ["ExportedForecaster", "export_forecaster"]

_MAGIC = b"STMGX1\n"


def _write_blobs(path: str, blobs: list[bytes]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        for blob in blobs:
            f.write(struct.pack("<Q", len(blob)))
            f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_blobs(path: str, n: int) -> list[bytes]:
    file_size = os.stat(path).st_size
    with open(path, "rb") as f:
        if f.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path} is not an stmgcn-tpu export artifact")
        blobs = []
        for _ in range(n):
            header = f.read(8)
            if len(header) != 8:
                raise ValueError(f"truncated export artifact: {path}")
            (size,) = struct.unpack("<Q", header)
            # Bound against the bytes actually present BEFORE allocating:
            # a corrupt length field must fail cleanly, not attempt a
            # multi-GB read.
            if size > file_size - f.tell():
                raise ValueError(f"truncated export artifact: {path}")
            blob = f.read(size)
            if len(blob) != size:
                raise ValueError(f"truncated export artifact: {path}")
            blobs.append(blob)
        if f.tell() != file_size:
            raise ValueError(
                f"trailing garbage after final blob in export artifact: {path}"
            )
    return blobs


def export_forecaster(fc, path: str, *, platforms=("cpu", "tpu"), city=None) -> None:
    """Write ``fc`` (a :class:`~stmgcn_tpu.inference.Forecaster`) to
    ``path`` as a self-contained serving artifact.

    ``platforms`` lists the backends the artifact must run on (compiled
    for all of them; JAX picks the matching lowering at call time). The
    exported program must be pure XLA: a forecaster whose LSTM uses the
    Pallas kernel backend (TPU-only custom call) is exported through an
    ``lstm_backend="xla"`` clone of the model — checkpoints are
    backend-agnostic (same params, same math, equality-tested), so this
    changes nothing about the numbers. Sparse/banded-trained checkpoints
    are restacked to the dense vmapped layout automatically (see the
    module docstring).

    A heterogeneous multi-city forecaster bakes ONE city's shape contract
    and normalizer per artifact (the artifact's signature is
    fixed-``N``): pass ``city`` to pick which; export each city to its
    own file to serve them all.
    """
    import jax.numpy as jnp

    hetero = getattr(fc, "normalizers", None) is not None
    if hetero and city is None:
        raise ValueError(
            "heterogeneous multi-city checkpoint: the artifact bakes one "
            "city's region count and normalizer — pass city= (export each "
            "city to its own artifact to serve them all)"
        )
    if not hetero and city is not None:
        raise ValueError("city= only applies to heterogeneous multi-city checkpoints")
    from stmgcn_tpu.models import to_dense_serving

    m = fc.config.model.m_graphs
    model, params = to_dense_serving(fc.model, fc.params, m)

    n_nodes = fc.derived["n_nodes"]
    normalizer = fc.normalizer
    if hetero:
        if not 0 <= city < len(fc.normalizers):
            raise ValueError(f"city must be in [0, {len(fc.normalizers)}), got {city}")
        n_nodes = n_nodes[city]
        normalizer = fc.normalizers[city]
    input_dim = fc.derived["input_dim"]
    k = model.n_supports

    def fn(supports, history):
        return model.apply(params, supports, history)

    (b,) = jax_export.symbolic_shape("b")
    sup_t = jax.ShapeDtypeStruct((m, k, n_nodes, n_nodes), jnp.float32)
    hist_t = jax.ShapeDtypeStruct((b, fc.seq_len, n_nodes, input_dim), jnp.float32)
    exported = jax_export.export(jax.jit(fn), platforms=tuple(platforms))(sup_t, hist_t)

    meta = {
        "version": 1,
        "platforms": list(platforms),
        "n_nodes": n_nodes,
        "input_dim": input_dim,
        "seq_len": fc.seq_len,
        "horizon": fc.horizon,
        "m_graphs": m,
        "n_supports": k,
        "normalizer": normalizer.to_dict() if normalizer is not None else None,
    }
    if hetero:
        meta["city"] = city
    _write_blobs(path, [json.dumps(meta).encode("utf-8"), exported.serialize()])


class ExportedForecaster:
    """A serving artifact loaded back into a callable predictor.

    Same raw-units contract as ``Forecaster.predict`` — normalize input,
    run the baked-in compiled forward, denormalize output — but rebuilt
    from serialized StableHLO: the framework's model code is not touched.
    """

    def __init__(self, exported, meta: dict):
        self._exported = exported
        self.meta = meta
        self.normalizer = (
            normalizer_from_dict(meta["normalizer"]) if meta["normalizer"] else None
        )
        # Per-history-shape AOT program cache: ``Exported.call`` re-traces
        # per invocation, and even ``jit(call)`` pays dispatch + a support
        # re-upload every call (the r05 batch-scaling inversion). Each
        # distinct history shape is lowered+compiled once; the support
        # stack is pinned device-resident at first predict (identity fast
        # path; a genuinely different stack re-pins and clears the cache).
        self._programs: dict = {}
        self._sup_src = None   # last supports object (identity check)
        self._sup_np = None    # its float32 numpy view (value check)
        self._sup_dev = None   # the device-resident pinned copy
        self._engine = None    # set by ServingEngine.from_artifact

    @classmethod
    def load(cls, path: str) -> "ExportedForecaster":
        meta_blob, fn_blob = _read_blobs(path, 2)
        meta = json.loads(meta_blob.decode("utf-8"))
        if meta.get("version") != 1:
            raise ValueError(f"unsupported export version {meta.get('version')!r}")
        return cls(jax_export.deserialize(fn_blob), meta)

    @property
    def seq_len(self) -> int:
        return self.meta["seq_len"]

    @property
    def horizon(self) -> int:
        return self.meta["horizon"]

    @property
    def exported(self):
        """The deserialized :mod:`jax.export` module (symbolic batch dim)
        — what :meth:`ServingEngine.from_artifact` specializes per rung."""
        return self._exported

    def _pin_supports(self, supports, supports_np: np.ndarray) -> None:
        import jax.numpy as jnp

        if self._sup_dev is not None and (
            supports is self._sup_src or np.array_equal(supports_np, self._sup_np)
        ):
            return
        self._sup_src = supports
        self._sup_np = supports_np
        self._sup_dev = jax.device_put(jnp.asarray(supports_np))
        self._programs.clear()  # programs bake the pinned stack's placement

    def _call(self, history: np.ndarray):
        import jax.numpy as jnp

        prog = self._programs.get(history.shape)
        if prog is None:
            prog = (
                jax.jit(self._exported.call)
                .lower(
                    self._sup_dev,
                    jax.ShapeDtypeStruct(history.shape, jnp.float32),
                )
                .compile()
            )
            self._programs[history.shape] = prog
        # Compiled takes the numpy batch as-is — wrapping it in
        # jnp.asarray first just adds a dispatch-path round trip
        return prog(self._sup_dev, history)

    def predict(self, supports, history, *, normalized: bool = False) -> np.ndarray:
        supports_np = np.asarray(supports, dtype=np.float32)
        want = (
            self.meta["m_graphs"],
            self.meta["n_supports"],
            self.meta["n_nodes"],
            self.meta["n_nodes"],
        )
        if supports_np.shape != want:
            raise ValueError(f"supports must be {want}, got {supports_np.shape}")
        if self._engine is not None:
            # a ServingEngine wraps this artifact: requests route through
            # its bucket ladder (and its pinned support stack)
            if not (
                supports is self._engine._supports_np
                or np.array_equal(supports_np, self._engine._supports_np)
            ):
                raise ValueError(
                    "this artifact is wrapped by a ServingEngine pinned to a "
                    "different support stack — build a new engine to serve a "
                    "different graph"
                )
            return self._engine.predict(history, normalized=normalized)
        self._pin_supports(supports, supports_np)
        expected = (self.meta["seq_len"], self.meta["n_nodes"], self.meta["input_dim"])
        return serve_predict(
            self._call, self.normalizer, expected, history, normalized
        )
