"""Pass 2i: health-overhead contracts — numeric-health config math.

The health layer carries the same "never become the thing you measure"
obligation as tracing (:mod:`.obs_check`): a preset whose drift monitor
has no baseline to compare against silently gauges nothing, a moment
sketch or reservoir sized past ``config.OBS_RESERVOIR_BUDGET`` regresses
a long-lived process, and a non-positive cadence makes the sampling
arithmetic in the trainer undefined. The per-config arithmetic is
``HealthConfig.violations()``; this pass evaluates it per preset. Pure
config math — no JAX, no trainer.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_health_overhead"]


def check_health_overhead(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate every preset's numeric-health knobs.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. One finding per violation string.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="health-overhead",
                path=f"<contract:health:{name}>",
                line=0,
                message=message,
                severity=RULES["health-overhead"].severity,
            )
        )

    for name, cfg in configs:
        health = getattr(cfg, "health", None)
        if health is None:
            continue
        for violation in health.violations():
            emit(name, f"{name}: {violation}")
    return findings
