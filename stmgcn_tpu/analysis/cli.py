"""``stmgcn lint``: run both analysis passes and gate on errors.

Usage::

    stmgcn lint                      # lint the shipped package + contracts
    stmgcn lint path/to/code ...     # lint specific files/dirs (AST only)
    stmgcn lint --format json        # machine-readable report (CI)
    stmgcn lint --no-contracts       # AST pass only (no JAX import/trace)
    stmgcn lint --list-rules         # rule table
    stmgcn lint --rebaseline         # rewrite PRIMITIVE_BUDGETS from
                                     # measured counts (+~2x headroom)

Exit code 1 when any *error*-severity finding survives suppression;
warnings are reported but do not gate. The contract pass (jaxpr +
sharding) runs only for the default whole-package target — explicit path
arguments mean "lint this code", which contracts don't apply to.

The default whole-package AST pass also runs the static concurrency
rules (:mod:`stmgcn_tpu.analysis.concurrency_check`) repo-wide off the
program database's class model: ``unguarded-attr``,
``lock-order-cycle``, ``condvar-discipline``, ``thread-lifecycle``.
``--no-whole-program`` skips them along with cross-module reachability.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

__all__ = ["build_lint_parser", "main"]


def build_lint_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="stmgcn lint",
        description="JAX-aware static analysis: AST lint + jaxpr/sharding "
        "contract checks (stmgcn_tpu.analysis)",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the installed "
                        "stmgcn_tpu package, plus contract checks)")
    p.add_argument("--format", choices=("text", "json", "sarif"), default="text",
                   help="'sarif' emits one SARIF 2.1.0 document on stdout "
                        "(code-scanning upload); 'json' the native report")
    p.add_argument("--no-contracts", action="store_true",
                   help="skip the jaxpr/sharding contract pass (pure-AST "
                        "mode: fast, no JAX initialization)")
    p.add_argument("--no-whole-program", action="store_true",
                   help="per-module AST lint only — skip the repo-wide "
                        "program database and cross-module jit-reachability "
                        "(the escape hatch; whole-program is the default)")
    p.add_argument("--include-suppressed", action="store_true",
                   help="keep `# stmgcn: ignore`-suppressed findings in the "
                        "report, marked suppressed and never counted/gating")
    p.add_argument("--preset", default="smoke",
                   help="config preset the contract pass traces (default: "
                        "smoke)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--rebaseline", action="store_true",
                   help="measure the step programs' primitive counts and "
                        "rewrite PRIMITIVE_BUDGETS (measured x ~2 headroom) "
                        "in stmgcn_tpu/analysis/jaxpr_check.py, and measure "
                        "the spmd probe programs' collective bytes-on-wire "
                        "and rewrite WIRE_BUDGETS in analysis/spmd_check.py, "
                        "and measure the per-program dtype census and rewrite "
                        "PRECISION_BASELINES in analysis/precision_check.py, "
                        "then exit — the deliberate-rebaseline command for "
                        "features that move a step's op count, wire volume, "
                        "or precision census")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_lint_parser().parse_args(argv)

    from stmgcn_tpu.analysis.rules import RULES

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for rule in RULES.values():
            print(f"{rule.id:<{width}}  {rule.severity:<7}  {rule.summary}")
        return 0

    if args.rebaseline:
        import json

        from stmgcn_tpu.analysis.jaxpr_check import rebaseline
        from stmgcn_tpu.analysis.precision_check import rebaseline_precision
        from stmgcn_tpu.analysis.spmd_check import rebaseline_wire
        from stmgcn_tpu.utils.platform import force_host_platform

        # never queue on (or wake) an accelerator; 8 virtual host devices
        # so the spmd probe programs can lower on every preset's mesh
        force_host_platform("cpu", n_devices=8)
        result = rebaseline(preset_name=args.preset)
        wire = rebaseline_wire()
        precision = rebaseline_precision(preset_name=args.preset)
        if args.format == "json":
            print(json.dumps({**result, "wire": wire, "precision": precision}))
        else:
            for name, count in result["counts"].items():
                print(
                    f"{name}: measured {count} primitives -> "
                    f"budget {result['budgets'][name]}"
                )
            print(f"rewrote PRIMITIVE_BUDGETS in {result['path']}")
            for name, total in wire["totals"].items():
                print(
                    f"{name}: measured {total} collective bytes -> "
                    f"budget {wire['budgets'][name]}"
                )
            print(f"rewrote WIRE_BUDGETS in {wire['path']}")
            for name, census in precision["census"].items():
                floats = sorted(census["bytes"])
                print(
                    f"{name}: dtype census {floats}, "
                    f"{census['casts']} cast(s)"
                )
            print(f"rewrote PRECISION_BASELINES in {precision['path']}")
        return 0

    from stmgcn_tpu.analysis.lint import lint_package, lint_paths
    from stmgcn_tpu.analysis.report import render_json, render_sarif, render_text

    if args.paths:
        findings = lint_paths(
            args.paths, include_suppressed=args.include_suppressed
        )
        run_contracts = False
    else:
        findings = lint_package(
            whole_program=not args.no_whole_program,
            include_suppressed=args.include_suppressed,
        )
        run_contracts = not args.no_contracts

    if run_contracts:
        # force CPU *before* the contract pass initializes the backend —
        # lint must never queue on (or wake) an accelerator
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.analysis.continual_check import check_continual_config
        from stmgcn_tpu.analysis.federation_check import check_federation_config
        from stmgcn_tpu.analysis.fleet_check import check_fleet_shape_classes
        from stmgcn_tpu.analysis.health_check import check_health_overhead
        from stmgcn_tpu.analysis.jaxpr_check import check_step_contracts
        from stmgcn_tpu.analysis.obs_check import check_obs_overhead
        from stmgcn_tpu.analysis.pallas_check import check_pallas_kernels
        from stmgcn_tpu.analysis.precision_check import check_precision
        from stmgcn_tpu.analysis.resident_check import check_resident_memory
        from stmgcn_tpu.analysis.serving_check import (
            check_serving_buckets,
            check_serving_slo,
        )
        from stmgcn_tpu.analysis.sharding_check import check_partition_specs
        from stmgcn_tpu.analysis.spmd_check import check_spmd_contracts
        from stmgcn_tpu.analysis.tiling_check import check_tile_plan
        from stmgcn_tpu.utils.platform import force_host_platform

        # 8 virtual host devices: the spmd contract pass lowers the real
        # sharded step programs on every preset's mesh (dp x region x
        # branch extents all fit in 8) without touching an accelerator
        force_host_platform("cpu", n_devices=8)
        findings.extend(check_partition_specs())
        findings.extend(check_collective_contracts())
        findings.extend(check_resident_memory())
        findings.extend(check_fleet_shape_classes())
        findings.extend(check_serving_buckets())
        findings.extend(check_serving_slo())
        findings.extend(check_obs_overhead())
        findings.extend(check_health_overhead())
        findings.extend(check_continual_config())
        findings.extend(check_federation_config())
        findings.extend(check_tile_plan())
        # static Pallas checks ride the contract section: deriving the
        # kernel's real block sizes imports ops.pallas_lstm (jax), which
        # --no-contracts' no-JAX promise must not do
        findings.extend(check_pallas_kernels())
        findings.extend(check_step_contracts(args.preset))
        findings.extend(check_spmd_contracts())
        # precision pass reuses the step-contract traces (one walk per
        # program via the shared program_flows cache)
        findings.extend(check_precision(args.preset))
    elif not args.paths:
        from stmgcn_tpu.analysis.sharding_check import check_partition_specs

        findings.extend(check_partition_specs())

    renderers = {"json": render_json, "sarif": render_sarif, "text": render_text}
    print(renderers[args.format](findings))
    return 1 if any(
        f.severity == "error" and not f.suppressed for f in findings
    ) else 0


if __name__ == "__main__":
    sys.exit(main())
