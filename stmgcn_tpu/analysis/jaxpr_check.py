"""Pass 2a: jaxpr contract checks on the step functions.

Abstractly traces the smoke-preset train/eval steps on the host
(``jax.eval_shape`` for parameter shapes, ``jax.make_jaxpr`` for the step
bodies — no FLOPs execute, so this runs in seconds on CPU) and asserts
invariants that only show up at trace level:

- **fp64-promotion** — no ``convert_element_type`` to float64 and no
  float64 aval anywhere in the jaxpr. TPUs have no fp64 MXU path; a
  stray numpy float64 constant silently doubles memory traffic and, on
  hardware, falls off the fast path entirely.
- **weak-type-output** — no weak-typed output aval where the inputs were
  strongly typed. A weak output fed back as the next step's input (the
  params/opt-state loop) re-traces and recompiles on step 2 — the classic
  "first two steps compile" hazard.
- **primitive-budget** — the recursive primitive count of each step stays
  under a recorded budget. Fusion breakage (a rematerialized subgraph, an
  accidentally unrolled scan, a transpose that stopped fusing) shows up
  as op-count growth long before it shows up in a profile; the budget
  makes it a test failure. Rebaseline ``PRIMITIVE_BUDGETS`` deliberately
  when a real feature moves the count — ``stmgcn lint --rebaseline``
  (:func:`rebaseline`) measures the current counts and rewrites the
  budgets with headroom in one command.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

import numpy as np

from stmgcn_tpu.analysis.dtype_flow import sub_jaxprs, walk_eqns
from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = [
    "PRIMITIVE_BUDGETS",
    "check_step_contracts",
    "count_primitives",
    "measured_primitive_counts",
    "rebaseline",
]

# the walk helpers moved to dtype_flow (the shared engine); the old
# private names stay importable for existing callers
_sub_jaxprs = sub_jaxprs
_walk_eqns = walk_eqns

#: measured counts x ~2 headroom for legitimate feature growth (see the
#: trailer comment) — the guard is against order-of-magnitude
#: fusion/unroll regressions (an accidentally unrolled scan multiplies
#: the count by seq_len), not single-op drift. Keep this a single-line
#: literal: ``stmgcn lint --rebaseline`` rewrites it in place from the
#: measured counts (:func:`rebaseline`).
PRIMITIVE_BUDGETS = {"serve_bucket": 170, "train_step": 860, "eval_step": 190, "train_superstep": 890, "train_series_superstep": 910, "train_series_superstep_health": 1310, "train_fleet_superstep": 970, "serve_fleet_bucket": 270, "train_step_checked": 3290, "train_step_bf16": 1030, "train_superstep_bf16": 1060, "train_series_superstep_bf16": 1080, "train_fleet_superstep_bf16": 1130}


def count_primitives(jaxpr) -> int:
    return sum(1 for _ in walk_eqns(jaxpr))


def _check_one(
    name: str,
    closed,
    n_strong_inputs: bool,
    budget: Optional[int],
    fp64_events: Optional[list] = None,
):
    findings: List[Finding] = []
    path = f"<contract:{name}>"

    def emit(rule: str, message: str) -> None:
        findings.append(
            Finding(rule=rule, path=path, line=0, message=message,
                    severity=RULES[rule].severity)
        )

    # fp64 detection is one job of the shared dtype walk
    # (dtype_flow.flow_program); the events come pre-ordered exactly as
    # the old two-branch eqn scan emitted them, so messages are
    # byte-identical whether the caller hands in a cached flow or we
    # walk here
    if fp64_events is None:
        from stmgcn_tpu.analysis.dtype_flow import flow_program

        fp64_events = flow_program(name, closed).fp64_events
    for ev in fp64_events:
        if ev["kind"] == "convert":
            emit(
                "fp64-promotion",
                f"{name}: convert_element_type to float64 "
                f"(source: {ev['source']})"[:500],
            )
        else:
            emit(
                "fp64-promotion",
                f"{name}: {ev['primitive']} produces a float64 value",
            )

    if n_strong_inputs:
        for i, aval in enumerate(closed.out_avals):
            if getattr(aval, "weak_type", False):
                emit(
                    "weak-type-output",
                    f"{name}: output {i} is weak-typed "
                    f"({aval.str_short()}) with strongly-typed inputs — "
                    "feeding it back recompiles the step",
                )

    if budget is not None:
        n = count_primitives(closed)
        if n > budget:
            emit(
                "primitive-budget",
                f"{name}: {n} primitives > budget {budget} — fusion/unroll "
                "regression, or rebaseline PRIMITIVE_BUDGETS with the "
                "feature that moved it",
            )
    return findings


#: per-preset trace cache: tracing is the expensive half of the
#: contract pass, and three consumers (the contract checks, the dtype
#: flows, the precision summary) now share one trace per process
_TRACE_CACHE: Dict[str, Dict[str, dict]] = {}


def _expand_roles(roles, sizes: Dict[str, int], total: int, name: str):
    """Expand per-argument precision roles to per-leaf labels.

    ``param``/``opt_state`` expand to their pytree leaf counts, a
    trailing-``*`` role absorbs whatever leaf count remains (checkify
    error payloads, health stats), everything else is one leaf.
    """
    wild = [r for r in roles if r.endswith("*")]
    if len(wild) > 1:
        raise ValueError(f"{name}: more than one wildcard role in {roles}")
    fixed = sum(
        sizes.get(r, 1) for r in roles if not r.endswith("*")
    )
    labels: List[str] = []
    for r in roles:
        if r.endswith("*"):
            labels.extend([r[:-1]] * (total - fixed))
        else:
            labels.extend([r] * sizes.get(r, 1))
    if len(labels) != total:
        raise ValueError(
            f"{name}: precision roles {roles} expand to {len(labels)} "
            f"labels for {total} leaves"
        )
    return tuple(labels)


def _trace_step_jaxprs(preset_name: str = "smoke") -> Dict[str, object]:
    """Abstractly trace every checked step program of a preset.

    CPU-only and concrete-data-free past dataset synthesis: parameter
    shapes come from ``jax.eval_shape`` over the jitted init, the step
    jaxprs from ``jax.make_jaxpr`` over ``ShapeDtypeStruct`` inputs. The
    superstep traces at S=4 over a small abstract resident pool — its
    primitive count is S-invariant (the S steps are one scan sub-jaxpr),
    so any fixed S>1 guards the fused program.
    """
    return {
        name: rec["jaxpr"]
        for name, rec in _trace_step_programs(preset_name).items()
    }


def _trace_step_programs(preset_name: str = "smoke") -> Dict[str, dict]:
    """The traced registry with per-leaf precision labels attached.

    Returns ``{name: {"jaxpr": ClosedJaxpr, "in_labels": tuple,
    "out_labels": tuple}}`` — the labels expand
    :data:`stmgcn_tpu.train.step.PRECISION_ROLES` over the actual
    flattened arities, seeding the dtype-flow pass's provenance chains
    and its master-param/loss boundary checks. Cached per preset.
    """
    cached = _TRACE_CACHE.get(preset_name)
    if cached is not None:
        return cached
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.config import preset
    from stmgcn_tpu.experiment import build_dataset, build_model, route_supports
    from stmgcn_tpu.serving.engine import serve_bucket_fn
    from stmgcn_tpu.serving.fleet import fleet_bucket_fn
    from stmgcn_tpu.train import (
        make_fleet_superstep_fns,
        make_optimizer,
        make_series_superstep_fns,
        make_step_fns,
        make_superstep_fns,
    )
    from stmgcn_tpu.train.step import make_checked_raw_train_step

    cfg = preset(preset_name)
    dataset = build_dataset(cfg)
    supports, modes = route_supports(cfg, dataset)
    model = build_model(cfg, dataset.n_feats, modes)
    optimizer = make_optimizer(cfg.train.lr, cfg.train.weight_decay)
    fns = make_step_fns(model, optimizer, loss=cfg.train.loss)
    sfns = make_superstep_fns(model, optimizer, loss=cfg.train.loss)
    wfns = make_series_superstep_fns(
        model, optimizer, loss=cfg.train.loss, horizon=cfg.data.horizon
    )
    hfns = make_series_superstep_fns(
        model, optimizer, loss=cfg.train.loss, horizon=cfg.data.horizon,
        health=True,
    )
    ffns = make_fleet_superstep_fns(
        model, optimizer, loss=cfg.train.loss, horizon=cfg.data.horizon
    )
    # the mixed-precision twins: same factories at precision="bf16"
    # (f32 master params, bf16 compute shadows — train/step.py). Traced
    # with stochastic rounding OFF: SR adds rng primitives per leaf and
    # is a training-run knob, not part of the checked program contract.
    fns_bf16 = make_step_fns(
        model, optimizer, loss=cfg.train.loss, precision="bf16"
    )
    sfns_bf16 = make_superstep_fns(
        model, optimizer, loss=cfg.train.loss, precision="bf16"
    )
    wfns_bf16 = make_series_superstep_fns(
        model, optimizer, loss=cfg.train.loss, horizon=cfg.data.horizon,
        precision="bf16",
    )
    ffns_bf16 = make_fleet_superstep_fns(
        model, optimizer, loss=cfg.train.loss, horizon=cfg.data.horizon,
        precision="bf16",
    )

    b = cfg.train.batch_size
    t = cfg.data.serial_len + cfg.data.daily_len + cfg.data.weekly_len
    n, c = dataset.n_nodes, dataset.n_feats
    f32 = jnp.float32
    sup = jax.ShapeDtypeStruct(np.shape(supports), f32)
    x = jax.ShapeDtypeStruct((b, t, n, c), f32)
    y = jax.ShapeDtypeStruct((b, n, c), f32)
    mask = jax.ShapeDtypeStruct((b,), f32)
    s_steps, pool = 4, 4 * b
    x_all = jax.ShapeDtypeStruct((pool, t, n, c), f32)
    y_all = jax.ShapeDtypeStruct((pool, n, c), f32)
    idx_block = jax.ShapeDtypeStruct((s_steps, b), jnp.int32)
    mask_block = jax.ShapeDtypeStruct((s_steps, b), f32)
    # the window-free superstep's resident inputs: the raw series plus the
    # int32 index vectors the on-device gather runs over
    series = jax.ShapeDtypeStruct((cfg.data.n_timesteps, n, c), f32)
    targets = jax.ShapeDtypeStruct((pool,), jnp.int32)
    offsets = jax.ShapeDtypeStruct((t,), jnp.int32)
    # the fleet superstep's per-class operands: a 2-member support stack
    # plus per-step slot / real-node vectors and node-crossed masks (the
    # smoke preset is homogeneous; the fleet program's contract shape is
    # class-size-invariant the same way the scan is S-invariant)
    members = 2
    sup_stack = jax.ShapeDtypeStruct((members,) + np.shape(supports), f32)
    n_arr = jax.ShapeDtypeStruct((members,), jnp.int32)
    slot_block = jax.ShapeDtypeStruct((s_steps,), jnp.int32)
    nr_block = jax.ShapeDtypeStruct((s_steps,), jnp.int32)
    mask_nodes_block = jax.ShapeDtypeStruct((s_steps, b, n), f32)

    # one serving bucket program (a mid-ladder rung): the engine compiles
    # exactly this function per rung, so its fusion health is a serving
    # contract just like the train step's
    ladder = cfg.serving.buckets
    bucket = ladder[len(ladder) // 2]
    hist_bucket = jax.ShapeDtypeStruct((bucket, t, n, c), f32)

    params, opt_state = jax.eval_shape(fns.init, jax.random.PRNGKey(0), sup, x)
    programs = {
        "serve_bucket": jax.make_jaxpr(serve_bucket_fn(model))(
            params, sup, hist_bucket
        ),
        "train_step": jax.make_jaxpr(fns.train_step)(
            params, opt_state, sup, x, y, mask
        ),
        "eval_step": jax.make_jaxpr(fns.eval_step)(params, sup, x, y, mask),
        "train_superstep": jax.make_jaxpr(sfns.train_superstep)(
            params, opt_state, sup, x_all, y_all, idx_block, mask_block
        ),
        # the window-free default: each scan step gathers its batch from
        # the resident series on device (gather_window_batch) before the
        # same shared raw train step
        "train_series_superstep": jax.make_jaxpr(wfns.train_superstep)(
            params, opt_state, sup, series, targets, offsets, idx_block, mask_block
        ),
        # the health-instrumented window-free superstep (health=True):
        # same math plus on-device grad/update statistics as extra scan
        # outputs — a checked program of its own so the "bit-identical
        # when on" variant cannot rot unnoticed
        "train_series_superstep_health": jax.make_jaxpr(hfns.train_superstep)(
            params, opt_state, sup, series, targets, offsets, idx_block, mask_block
        ),
        # the per-class fleet superstep: scanned steps select the city's
        # support stack by slot and feed the traced real-node count to
        # the gate pooling — the heterogeneous fast path's one program
        "train_fleet_superstep": jax.make_jaxpr(ffns.train_superstep)(
            params, opt_state, sup_stack, series, targets, offsets,
            idx_block, mask_nodes_block, slot_block, nr_block,
        ),
        # the fleet serving program: one compiled (class, bucket) pair
        # serves every member city (per-row slot gather + traced count)
        "serve_fleet_bucket": jax.make_jaxpr(fleet_bucket_fn(model))(
            params, sup_stack, n_arr,
            jax.ShapeDtypeStruct((bucket,), jnp.int32), hist_bucket,
        ),
        # the checkify-wrapped step --checkify nan actually runs (the
        # divergence-guard diagnostic path) — checked like the production
        # programs so the debug tool cannot silently rot
        "train_step_checked": jax.make_jaxpr(
            make_checked_raw_train_step(
                model, optimizer, loss=cfg.train.loss, checks="nan"
            )
        )(params, opt_state, sup, x, y, mask),
        # bf16 twins of the four train programs, traced over the SAME
        # f32 operand structs as their fp32 counterparts — the program
        # boundary (master params, optimizer state, data, loss) is f32
        # by contract; the compute dtype changes inside the jaxpr, where
        # the dtype-flow pass certifies the f32 accumulation islands
        "train_step_bf16": jax.make_jaxpr(fns_bf16.train_step)(
            params, opt_state, sup, x, y, mask
        ),
        "train_superstep_bf16": jax.make_jaxpr(sfns_bf16.train_superstep)(
            params, opt_state, sup, x_all, y_all, idx_block, mask_block
        ),
        "train_series_superstep_bf16": jax.make_jaxpr(wfns_bf16.train_superstep)(
            params, opt_state, sup, series, targets, offsets, idx_block, mask_block
        ),
        "train_fleet_superstep_bf16": jax.make_jaxpr(ffns_bf16.train_superstep)(
            params, opt_state, sup_stack, series, targets, offsets,
            idx_block, mask_nodes_block, slot_block, nr_block,
        ),
    }

    from stmgcn_tpu.train.step import PRECISION_ROLES

    sizes = {
        "param": len(jax.tree.leaves(params)),
        "opt_state": len(jax.tree.leaves(opt_state)),
    }
    records: Dict[str, dict] = {}
    for name, closed in programs.items():
        in_roles, out_roles = PRECISION_ROLES[name]
        records[name] = {
            "jaxpr": closed,
            "in_labels": _expand_roles(
                in_roles, sizes, len(closed.jaxpr.invars), name
            ),
            "out_labels": _expand_roles(
                out_roles, sizes, len(closed.jaxpr.outvars), name
            ),
        }
    _TRACE_CACHE[preset_name] = records
    return records


def check_step_contracts(preset_name: str = "smoke") -> List[Finding]:
    """Trace the preset's step programs abstractly and check contracts."""
    from stmgcn_tpu.analysis.dtype_flow import program_flows

    findings: List[Finding] = []
    flows = program_flows(preset_name)
    for name, closed in _trace_step_jaxprs(preset_name).items():
        # checkify's error-payload outputs are weak-typed by construction
        # and never feed back into the step inputs, so the weak-type
        # contract does not apply to the checked program
        strong = name != "train_step_checked"
        flow = flows.get(name)
        findings += _check_one(
            name, closed, strong, PRIMITIVE_BUDGETS.get(name),
            fp64_events=flow.fp64_events if flow is not None else None,
        )
    return findings


def measured_primitive_counts(preset_name: str = "smoke") -> Dict[str, int]:
    """The current recursive primitive count of every checked program."""
    return {
        name: count_primitives(closed)
        for name, closed in _trace_step_jaxprs(preset_name).items()
    }


def rebaseline(
    path: Optional[str] = None,
    preset_name: str = "smoke",
    headroom: float = 2.0,
) -> dict:
    """Measure primitive counts and rewrite :data:`PRIMITIVE_BUDGETS`.

    The budget-regression guard needs a deliberate rebaseline whenever a
    real feature moves a step's op count; doing that by hand means
    re-deriving the counts and editing this file. This measures every
    checked program at ``preset_name``, applies ``headroom`` (default the
    standing ~2x policy, rounded up to the next 10), rewrites the
    single-line ``PRIMITIVE_BUDGETS = {...}`` literal in this module's
    source (``path`` overrides the target for tests), and updates the
    in-process dict so subsequent contract checks see the new budgets.

    Returns ``{"counts": ..., "budgets": ..., "path": ...}``.
    """
    if headroom < 1.0:
        raise ValueError(f"headroom must be >= 1.0, got {headroom}")
    counts = measured_primitive_counts(preset_name)
    budgets = {
        name: int(math.ceil(c * headroom / 10.0) * 10) for name, c in counts.items()
    }
    path = path or __file__
    with open(path) as f:
        src = f.read()
    literal = "{" + ", ".join(f'"{k}": {v}' for k, v in budgets.items()) + "}"
    new_src, n_subs = re.subn(
        r"PRIMITIVE_BUDGETS = \{[^}]*\}",
        "PRIMITIVE_BUDGETS = " + literal,
        src,
        count=1,
    )
    if n_subs != 1:
        raise RuntimeError(
            f"could not find the PRIMITIVE_BUDGETS literal in {path}"
        )
    with open(path, "w") as f:
        f.write(new_src)
    PRIMITIVE_BUDGETS.clear()
    PRIMITIVE_BUDGETS.update(budgets)
    return {"counts": counts, "budgets": budgets, "path": path}
