"""Compiled-HLO collective extraction with mesh-axis attribution.

:mod:`stmgcn_tpu.utils.comm` tallies collective kinds and bytes; this
module additionally recovers *which mesh axes* each collective spans, by
parsing the op's ``replica_groups`` (or ``source_target_pairs``) and
matching the observed device grouping against the partitions a
``(dp, region[, branch])`` mesh induces. That attribution is what turns
"the program all-gathers 2 KiB" into "the program all-gathers the node
axis over ``region``" — the unit the :mod:`.spmd_check` manifests are
declared in.

Partition ids in a jit-compiled SPMD module index the mesh's device
array in row-major axis order (``build_mesh`` constructs ``Mesh(devs
.reshape(dp, region[, branch]), names)`` and XLA's device assignment is
that array flattened), so axis membership is pure arithmetic on the ids
— no devices touched. Both ``replica_groups`` syntaxes XLA prints are
handled: the explicit form ``{{0,4},{1,5}}`` and the iota form
``[4,2]<=[2,4]T(1,0)`` (group shape ``<=`` iota dims with an optional
transpose; reshape of the transposed iota yields the groups).

Byte counts are per-op *output* shapes (an all-gather's output is the
gathered tensor, a permute's the shifted block) — the same wire-volume
proxy :func:`stmgcn_tpu.utils.comm.collective_stats` uses, and async
``-start``/``-done`` pairs count once with the start tuple's result
element only.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import re
from typing import List, Optional, Sequence, Tuple

from stmgcn_tpu.utils.comm import COLLECTIVES

__all__ = ["CollectiveOp", "collect_collectives", "infer_axes"]

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_OP_RE = re.compile(
    r"%(\S+?)\s*=\s*(?:\(([^)]*)\)|(\w+)\[([\d,]*)\][^ ]*)\s+("
    + "|".join(COLLECTIVES)
    + r")(-start)?\("
)
_TUPLE_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})?\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")
_WHILE_RE = re.compile(r"=\s*(\([^)]*\)|\S+)\s+while\(")


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in a compiled module, attributed to mesh axes.

    ``axes`` is ``"dp"`` / ``"region"`` / ``"branch"`` / a ``"+"``-joined
    combination, or ``"?"`` when the grouping matches no axis subset of
    the mesh (an op the plan has no vocabulary for — always a finding).
    """

    kind: str
    axes: str
    out_bytes: int
    name: str  # HLO op name, e.g. "all-gather.1"


def _shape_bytes(dtype: str, dims: str) -> int:
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        return 0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * size


def _parse_groups(line: str, n_devices: int) -> Optional[List[Tuple[int, ...]]]:
    """Replica groups as id tuples, or None when the line carries none."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        gshape = [int(d) for d in m.group(1).split(",")]
        idims = [int(d) for d in m.group(2).split(",")]
        ids = list(range(math.prod(idims)))
        if m.group(3):
            perm = [int(d) for d in m.group(3).split(",")]
            # transpose the iota array: id at multi-index i goes to i[perm]
            strides = [0] * len(idims)
            acc = 1
            for ax in reversed(range(len(idims))):
                strides[ax] = acc
                acc *= idims[ax]
            out = []
            for idx in itertools.product(*[range(idims[p]) for p in perm]):
                out.append(sum(idx[k] * strides[perm[k]] for k in range(len(perm))))
            ids = out
        size = gshape[-1] if len(gshape) > 1 else gshape[0]
        n_groups = math.prod(gshape) // size if len(gshape) > 1 else 1
        return [
            tuple(ids[g * size:(g + 1) * size]) for g in range(max(1, n_groups))
        ]
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        if not m.group(1):  # replica_groups={} — every device, one group
            return [tuple(range(n_devices))]
        return [
            tuple(int(x) for x in grp.split(","))
            for grp in re.findall(r"\{([\d,]+)\}", m.group(1))
        ]
    return None


def _parse_pairs(line: str) -> Optional[List[Tuple[int, int]]]:
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return [
        (int(a), int(b))
        for a, b in re.findall(r"\{(\d+),(\d+)\}", m.group(1))
    ]


def _coords(pid: int, shape: Sequence[int]) -> Tuple[int, ...]:
    out = []
    for extent in reversed(shape):
        out.append(pid % extent)
        pid //= extent
    return tuple(reversed(out))


def infer_axes(
    line: str, mesh_shape: Sequence[int], axis_names: Sequence[str]
) -> str:
    """Mesh axes a collective op line spans, from its groups/pairs.

    For grouped collectives the observed groups must equal the partition
    induced by some non-empty subset of mesh axes (vary the subset, fix
    the rest); for ``collective-permute`` every source→target pair must
    differ in exactly one (common) axis coordinate. ``"?"`` otherwise.
    """
    n = math.prod(mesh_shape)
    pairs = _parse_pairs(line)
    if pairs is not None:
        axes = set()
        for a, b in pairs:
            ca, cb = _coords(a, mesh_shape), _coords(b, mesh_shape)
            diff = [i for i in range(len(mesh_shape)) if ca[i] != cb[i]]
            if len(diff) != 1:
                return "?"
            axes.add(diff[0])
        return axis_names[axes.pop()] if len(axes) == 1 else "?"
    groups = _parse_groups(line, n)
    if groups is None:  # no grouping printed — spans every device
        groups = [tuple(range(n))]
    if all(len(g) == 1 for g in groups):
        # singleton groups: a degenerate collective over an extent-1 axis
        # partition — no device exchanges data with any other
        return ""
    observed = {frozenset(g) for g in groups}
    n_axes = len(mesh_shape)
    for r in range(1, n_axes + 1):
        for subset in itertools.combinations(range(n_axes), r):
            expect: dict = {}
            for pid in range(n):
                c = _coords(pid, mesh_shape)
                key = tuple(c[i] for i in range(n_axes) if i not in subset)
                expect.setdefault(key, []).append(pid)
            if {frozenset(g) for g in expect.values()} == observed:
                return "+".join(axis_names[i] for i in subset)
    return "?"


def collect_collectives(
    hlo_text: str, mesh_shape: Sequence[int], axis_names: Sequence[str]
) -> Tuple[List[CollectiveOp], int]:
    """All collectives in a compiled module with axis attribution.

    Returns ``(ops, while_count)``; a nonzero ``while_count`` means the
    static per-op counts under-report runtime volume (loop trip counts
    don't multiply through), same caveat as ``collective_stats``.
    """
    ops: List[CollectiveOp] = []
    while_count = 0
    for line in hlo_text.splitlines():
        if _WHILE_RE.search(line):
            while_count += 1
        m = _OP_RE.search(line)
        if not m:
            continue
        name, tuple_shape, dtype, dims, kind, is_start = m.groups()
        axes = infer_axes(line, mesh_shape, axis_names)
        if axes == "":  # degenerate singleton grouping: zero bytes on wire
            continue
        if dtype is not None:
            nbytes = _shape_bytes(dtype, dims)
        else:
            elems = _TUPLE_SHAPE_RE.findall(tuple_shape)
            if is_start:
                nonscalar = [e for e in elems if e[1]]
                elems = (nonscalar or elems)[-1:]
            nbytes = sum(_shape_bytes(dt, dm) for dt, dm in elems)
        ops.append(
            CollectiveOp(kind=kind, axes=axes, out_bytes=nbytes, name=name)
        )
    return ops, while_count
