"""Pass 2d: collective-shape contracts — static mesh/operand math.

The sharded step programs move data through three collectives whose
operand shapes are fully determined by the config: the ``ppermute`` halo
exchange sends ``halo`` boundary rows per shard (:mod:`stmgcn_tpu.
parallel.halo`), the data-parallel loss ``psum``/gather sees per-device
batch slices, and branch model parallelism ``psum``s over equal branch
shards. A config whose extents don't divide its operands fails only at
runtime — on the mesh, possibly hours into a run (``strip_decompose``
raises at decomposition time; GSPMD raggedness surfaces as a sharding
error inside jit). This pass re-derives the shapes from the config alone
— no data build, no trace — and flags the mismatches up front for every
preset whose mesh spans more than one device.

For the halo plan the check estimates the grid (neighborhood) branch's
support bandwidth a priori: a rows x cols rook grid in row-major order
has adjacency bandwidth ``cols``, and a K-hop kernel (``chebyshev`` /
``random_walk_diffusion`` order K) reaches ``K * cols``; ``localpool``
is one hop. Only the grid branch has such an a-priori bound — the
transport/similarity branches' bandwidths are data-dependent, which is
exactly why ``region_strategy="auto"`` routes them per-branch at
decomposition time and why this check stays silent about them.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

from stmgcn_tpu.analysis.report import Finding
from stmgcn_tpu.analysis.rules import RULES

__all__ = ["check_collective_contracts", "grid_bandwidth_estimate"]

_K_HOP_KERNELS = ("chebyshev", "random_walk_diffusion")


def grid_bandwidth_estimate(kernel_type: str, K: int, cols: int) -> int:
    """A-priori support bandwidth of the rook-grid branch.

    Row-major rook adjacency has bandwidth ``cols`` (the vertical
    neighbor); a K-hop kernel's highest-order support reaches K such
    steps. ``localpool`` is the one-hop Kipf support.
    """
    hops = K if kernel_type in _K_HOP_KERNELS else 1
    return hops * cols


def _city_grids(cfg) -> List[Tuple[int, int]]:
    """Every city's (rows, cols) synthetic grid shape."""
    d = cfg.data
    if d.city_rows is not None:
        return [(r, r) for r in d.city_rows]
    cols = d.cols if d.cols is not None else d.rows
    return [(d.rows, cols)] * max(1, d.n_cities)


def check_collective_contracts(
    configs: Optional[Iterable[Tuple[str, object]]] = None,
) -> List[Finding]:
    """Validate collective operand shapes against mesh extents.

    ``configs`` is ``(name, ExperimentConfig)`` pairs; default is every
    registered preset. Pure config math — safe without a JAX backend.
    """
    from stmgcn_tpu.config import PRESETS

    if configs is None:
        configs = [(name, build()) for name, build in PRESETS.items()]

    findings: List[Finding] = []

    def emit(name: str, message: str) -> None:
        findings.append(
            Finding(
                rule="collective-shape",
                path=f"<contract:collective:{name}>",
                line=0,
                message=message,
                severity=RULES["collective-shape"].severity,
            )
        )

    for name, cfg in configs:
        mesh = cfg.mesh
        if mesh.n_devices <= 1:
            continue

        if mesh.dp > 1 and cfg.train.batch_size % mesh.dp:
            emit(
                name,
                f"{name}: batch_size {cfg.train.batch_size} is not "
                f"divisible by dp={mesh.dp} — the data-parallel loss "
                "psum/gather would see ragged per-device batch shards",
            )

        if mesh.branch > 1 and cfg.model.m_graphs % mesh.branch:
            emit(
                name,
                f"{name}: m_graphs {cfg.model.m_graphs} is not divisible "
                f"by branch={mesh.branch} — the branch-sum psum needs "
                "equal branch shards on every device",
            )

        halo_active = (
            mesh.region > 1
            and mesh.region_strategy in ("banded", "auto")
            and not cfg.model.sparse
        )
        if not halo_active:
            continue
        for rows, cols in _city_grids(cfg):
            n = rows * cols
            padded = -(-n // mesh.region) * mesh.region
            n_local = padded // mesh.region
            budget = min(
                mesh.halo if mesh.halo is not None else n_local // 2, n_local
            )
            if mesh.halo is not None and mesh.halo > n_local:
                emit(
                    name,
                    f"{name}: mesh.halo {mesh.halo} exceeds the shard size "
                    f"{n_local} ({padded} padded nodes / region="
                    f"{mesh.region}) — the ppermute exchange operand "
                    "cannot hold more rows than the shard",
                )
            bw = grid_bandwidth_estimate(
                cfg.model.kernel_type, cfg.model.K, cols
            )
            if bw > n_local:
                emit(
                    name,
                    f"{name}: grid-branch support bandwidth ~{bw} "
                    f"({cfg.model.kernel_type} K={cfg.model.K} on a "
                    f"{rows}x{cols} grid) exceeds the shard size {n_local} "
                    "— no halo fits; shrink mesh.region or reorder nodes",
                )
            elif bw > budget and mesh.region_strategy == "banded":
                emit(
                    name,
                    f"{name}: region_strategy='banded' but the grid "
                    f"branch's support bandwidth ~{bw} exceeds the halo "
                    f"budget {budget} (shard size {n_local}) — "
                    "strip_decompose would drop boundary neighbors; use "
                    "'auto' or raise mesh.halo",
                )
    return findings
